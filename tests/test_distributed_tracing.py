"""Distributed tracing + fleet aggregation tests (ISSUE 17).

The load-bearing contracts:

- ONE compact ``TraceContext`` round-trips every transport encoding the
  serving plane uses — HTTP header, wire-frame v2 ``trace:ctx`` column,
  worker-IPC ``meta:trace`` column, shm slot-header words, fleet router
  hop — with the SAME trace id and the sampling verdict intact (parity:
  a request is traced everywhere or nowhere);
- the head-sampling verdict is a pure function of the trace id, so
  every hop that re-derives it agrees, and tail retention under a fake
  clock is deterministic: an unsampled hop slower than the SLO emits
  its span tagged ``tail``, a fast one emits nothing;
- one request through FleetRouter -> LocalHost -> process worker yields
  ONE stitched trace: the worker's ``serving.batch`` span carries the
  router's trace id and an ``rparent`` link resolving to the
  ``serving.http_score`` span's global id, across a REAL process
  boundary, and every per-process trace.json is Perfetto-loadable;
- the fleet aggregator degrades a host that drops mid-scrape
  (``telemetry.scrape`` chaos seam) to its last-seen snapshot — counts
  the failure, gauges the staleness, never wedges — and the multi-window
  burn evaluator fires exactly one edge-triggered alert per excursion.
"""

import glob
import json
import os
import struct
import types

import numpy as np
import pytest

from photon_ml_tpu import chaos
from photon_ml_tpu import telemetry
from photon_ml_tpu.serving import wire
from photon_ml_tpu.serving.batcher import BatcherConfig
from photon_ml_tpu.serving.fleet import FleetRouter, LocalHost
from photon_ml_tpu.serving.procpool import WorkerPool
from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
from photon_ml_tpu.serving.service import ScoringService
from photon_ml_tpu.serving.shm_ingress import _TRACE_WORDS
from photon_ml_tpu.serving.supervisor import ReplicaSupervisor
from photon_ml_tpu.serving.synthetic import SyntheticWorkload
from photon_ml_tpu.telemetry import (
    TRACE_HEADER,
    FleetAggregator,
    SloPolicy,
    Telemetry,
    TraceContext,
)


@pytest.fixture(scope="module")
def workload():
    return SyntheticWorkload(n_entities=32, seed=7)


RT_CFG = dict(max_batch_size=8, hot_entities=8)


@pytest.fixture(scope="module")
def runtime(workload):
    return ScoringRuntime(
        workload.model, workload.index_maps, RuntimeConfig(**RT_CFG)
    )


def _ctx() -> TraceContext:
    """A context with every field non-trivial: parity tests must prove
    all three survive, not just the trace id."""
    return TraceContext(
        f"{0xDEADBEEF12345678:016x}",
        span_id=0x0123456789ABCDEF,
        sampled=True,
    )


# ---------------------------------------------------------------------------
# TraceContext: encodings + head sampling
# ---------------------------------------------------------------------------

class TestTraceContext:
    def test_header_round_trip(self):
        for sampled in (True, False):
            ctx = TraceContext("00ab" * 4, 0x1234, sampled)
            parsed = TraceContext.parse(ctx.header_value())
            assert parsed == ctx

    def test_parse_rejects_malformed(self):
        bad = [
            None, "", 123, b"bytes",
            "deadbeef",                         # one part
            "deadbeef-0-1",                     # trace id not 16 hex
            "deadbeefdeadbeef-0",               # two parts
            "deadbeefdeadbeef-0-1-9",           # four parts
            "zzzzzzzzzzzzzzzz-0-1",             # not hex
            "deadbeefdeadbeef-0-x",             # flag not int
            "0000000000000000-0-1",             # zero trace word
        ]
        for text in bad:
            assert TraceContext.parse(text) is None, text

    def test_words_round_trip(self):
        ctx = _ctx()
        assert TraceContext.from_words(*ctx.to_words()) == ctx
        # Zero trace word means "untraced" on every binary transport.
        assert TraceContext.from_words(0, 5, 1) is None

    def test_head_sampling_is_pure_function_of_trace_id(self):
        hub = Telemetry(enabled=True, sinks=[])
        hub.configure_tracing(sample_every=4)
        contexts = [hub.new_trace() for _ in range(256)]
        for ctx in contexts:
            expected = int(ctx.trace_id, 16) % 4 == 0
            assert ctx.sampled is expected
            # The verdict RIDES the wire — a downstream hop parses it
            # back rather than re-rolling the dice.
            assert TraceContext.parse(ctx.header_value()).sampled \
                is expected
        # ~1/4 of random ids sample; all-or-nothing would be a bug.
        n = sum(c.sampled for c in contexts)
        assert 0 < n < len(contexts)
        hub.configure_tracing(sample_every=1)
        assert all(hub.new_trace().sampled for _ in range(16))

    def test_trace_word_never_zero(self):
        hub = Telemetry(enabled=True, sinks=[])
        assert all(
            int(hub.new_trace().trace_id, 16) != 0 for _ in range(512)
        )


# ---------------------------------------------------------------------------
# Propagation parity: the five transports
# ---------------------------------------------------------------------------

class TestPropagationParity:
    """Each transport encodes the SAME context and decodes it intact —
    same trace id, same remote-parent span id, same sampling verdict."""

    def test_http_header_transport(self):
        ctx = _ctx()
        headers = {TRACE_HEADER: ctx.header_value()}
        assert TRACE_HEADER == "X-Photon-Trace"
        assert TraceContext.parse(headers[TRACE_HEADER]) == ctx

    def test_wire_frame_v2_trace_column(self, workload):
        ctx = _ctx()
        frame = wire.encode_request(
            [workload.request(0)], trace=ctx.header_value()
        )
        _rows, trace = wire.decode_request_ex(frame)
        assert TraceContext.parse(trace) == ctx
        # Untraced frames carry no column at all — and still decode.
        _rows, trace = wire.decode_request_ex(
            wire.encode_request([workload.request(0)])
        )
        assert trace is None

    def test_worker_ipc_score_and_result_frames(self, workload, runtime):
        ctx = _ctx()
        row = runtime.parse_request(workload.request(0))
        msg = wire.decode_score_ipc(
            wire.encode_score_ipc(7, row, trace=ctx.header_value())
        )
        assert TraceContext.parse(msg["trace"]) == ctx
        assert "trace" not in wire.decode_score_ipc(
            wire.encode_score_ipc(7, row)
        )
        value = {"score": 1.5, "mean": 0.25, "latency_ms": 2.0}
        out = wire.decode_result_ipc(
            wire.encode_result_ipc(7, value, trace=ctx.header_value())
        )
        assert TraceContext.parse(out["trace"]) == ctx
        assert "trace" not in wire.decode_result_ipc(
            wire.encode_result_ipc(7, value)
        )

    def test_shm_slot_header_words(self):
        ctx = _ctx()
        buf = bytearray(_TRACE_WORDS.size)
        _TRACE_WORDS.pack_into(buf, 0, *ctx.to_words())
        assert TraceContext.from_words(
            *_TRACE_WORDS.unpack_from(buf, 0)
        ) == ctx
        # All-zero words (a fresh slot) decode to "untraced".
        assert TraceContext.from_words(
            *_TRACE_WORDS.unpack_from(bytes(_TRACE_WORDS.size), 0)
        ) is None
        # The words fit the fixed slot-header field exactly.
        assert _TRACE_WORDS.size == struct.calcsize("<QQI")

    def test_fleet_hop_shares_trace_and_links_parent(
        self, workload, tmp_path
    ):
        """JSON-path fleet hop, in-process: the router's routing span
        and the host's handler span land in one trace with a resolvable
        parent link — the cross-HOST half of the stitched chain (the
        cross-PROCESS half is TestStitchedFleetTrace)."""
        cfg = RuntimeConfig(**RT_CFG)
        service = ScoringService(
            ScoringRuntime(workload.model, workload.index_maps, cfg),
            BatcherConfig(max_batch_size=8, max_wait_us=1000,
                          max_queue=256),
        )
        with Telemetry(output_dir=str(tmp_path), run_name="hop") as hub:
            hub.configure_tracing(sample_every=1)
            host = LocalHost("h0", service).start()
            router = FleetRouter(
                [host.base_url], probe_interval_s=0.05,
                wire_format="json",
            ).start()
            try:
                result = router.score(workload.request(0))
                assert np.isfinite(result["score"])
            finally:
                router.stop()
                host.stop()
        spans = _spans(os.path.join(tmp_path, "trace.json"))
        routes = [s for s in spans if s["name"] == "serving.fleet_route"]
        scores = [s for s in spans if s["name"] == "serving.http_score"]
        assert len(routes) == 1 and len(scores) >= 1
        trace_id = routes[0]["args"]["trace"]
        for s in scores:
            assert s["args"]["trace"] == trace_id
            assert s["args"]["rparent"] == routes[0]["args"]["gid"]


def _spans(trace_path: str) -> list:
    """Chrome-trace complete events ("X") from one trace.json —
    asserting Perfetto-loadability on the way (array of events, each
    with the keys the UI requires)."""
    with open(trace_path) as f:
        events = json.load(f)
    assert isinstance(events, list) and events
    for ev in events:
        assert isinstance(ev, dict)
        for key in ("name", "ph", "ts", "pid"):
            assert key in ev, (trace_path, ev)
    return [ev for ev in events if ev.get("ph") == "X"]


# ---------------------------------------------------------------------------
# Tail sampling: deterministic under a fake clock
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self, t0: float = 1000.0):
        self.t = t0

    def perf_counter(self) -> float:
        return self.t


class TestTailSampling:
    @pytest.fixture()
    def clocked(self, tmp_path, monkeypatch):
        import time as real_time

        from photon_ml_tpu.telemetry import core as core_mod

        clock = _Clock()
        # Patch only core's module reference, not the global time
        # module — nothing outside the hub sees the fake clock.
        monkeypatch.setattr(
            core_mod, "time",
            types.SimpleNamespace(
                perf_counter=clock.perf_counter,
                time=real_time.time,
                sleep=real_time.sleep,
                monotonic=real_time.monotonic,
            ),
        )
        hub = Telemetry(output_dir=str(tmp_path), run_name="tail")
        return types.SimpleNamespace(
            hub=hub, clock=clock, path=str(tmp_path / "events.jsonl")
        )

    def _records(self, path: str) -> list:
        with open(path) as f:
            return [json.loads(line) for line in f]

    def test_slow_unsampled_hop_emits_tail_span(self, clocked):
        hub, clock = clocked.hub, clocked.clock
        hub.configure_tracing(tail_slo_s=0.05)
        ctx = TraceContext("ab" * 8, span_id=0x99, sampled=False)
        with hub:
            with hub.adopt(ctx):
                with hub.span("hop.fast"):
                    clock.t += 0.049  # just under the SLO: dropped
                with hub.span("hop.slow"):
                    clock.t += 0.051  # just over: retained, tagged
        spans = [r for r in self._records(clocked.path)
                 if r.get("type") == "span"]
        assert [s["name"] for s in spans] == ["hop.slow"]
        span = spans[0]
        assert span["tail"] is True
        assert span["trace"] == ctx.trace_id
        assert span["rparent"] == f"{ctx.span_id:016x}"
        assert span["dur"] == pytest.approx(0.051)

    def test_verdicts_are_deterministic(self, clocked):
        """Same durations, same verdicts, run twice — retention depends
        only on the clock, never on wall-time jitter."""
        hub, clock = clocked.hub, clocked.clock
        hub.configure_tracing(tail_slo_s=0.05)
        ctx = TraceContext("cd" * 8, sampled=False)
        # No duration sits exactly ON the 50ms boundary: accumulated
        # float error there would test rounding, not retention.
        durations = [0.01, 0.2, 0.06, 0.04, 0.5, 0.002]
        with hub:
            for _ in range(2):
                with hub.adopt(ctx):
                    for i, dur in enumerate(durations):
                        with hub.span(f"hop.{i}"):
                            clock.t += dur
        names = [r["name"] for r in self._records(clocked.path)
                 if r.get("type") == "span"]
        kept = [f"hop.{i}" for i, d in enumerate(durations) if d >= 0.05]
        assert names == kept * 2

    def test_sampled_hop_always_emits(self, clocked):
        hub, clock = clocked.hub, clocked.clock
        hub.configure_tracing(tail_slo_s=0.05)
        ctx = TraceContext("ef" * 8, sampled=True)
        with hub:
            with hub.adopt(ctx), hub.span("hop.sampled"):
                clock.t += 0.001  # fast, but head-sampled: kept
        spans = [r for r in self._records(clocked.path)
                 if r.get("type") == "span"]
        assert [s["name"] for s in spans] == ["hop.sampled"]
        assert "tail" not in spans[0]

    def test_unsampled_without_tail_slo_elides_span_entirely(self):
        """With tail retention off, the 255-in-256 unsampled path takes
        the null-span fast path: no record, no bookkeeping."""
        recorder = telemetry.FlightRecorder()
        hub = Telemetry(enabled=True, sinks=[recorder])
        ctx = TraceContext("12" * 8, sampled=False)
        with hub.adopt(ctx):
            a = hub.span("x")
            b = hub.span("y")
        assert a is b  # the shared null-span singleton
        with hub.adopt(TraceContext("34" * 8, sampled=True)):
            with hub.span("x"):
                pass
        names = [r.get("name") for r in recorder.snapshot()
                 if r.get("type") == "span"]
        assert names == ["x"]  # only the sampled hop reached a sink


# ---------------------------------------------------------------------------
# The acceptance contract: one request, one stitched trace, real processes
# ---------------------------------------------------------------------------

class TestStitchedFleetTrace:
    def test_one_request_one_stitched_trace_across_processes(
        self, workload, tmp_path, monkeypatch
    ):
        """A live 2-host fleet, each host backed by a real worker
        PROCESS: one traced request's spans — router routing span, host
        HTTP span, worker batch span — share one trace id and chain
        through resolvable global-id parent links, merged from three
        independently written Perfetto-loadable trace files."""
        worker_dir = tmp_path / "workers"
        monkeypatch.setenv("PHOTON_TRACE_DIR", str(worker_dir))
        n_requests = 4
        with Telemetry(
            output_dir=str(tmp_path / "router"), run_name="router"
        ) as hub:
            hub.configure_tracing(sample_every=1)
            hosts, router = [], None
            try:
                for i in range(2):
                    pool = WorkerPool(
                        workload.model, workload.index_maps,
                        runtime_config=RuntimeConfig(**RT_CFG), version=1,
                    )
                    supervisor = ReplicaSupervisor(
                        pool=pool, n_replicas=1, probe_interval_s=0.05,
                        probe_timeout_s=60.0, probe_failure_threshold=5,
                    )
                    service = ScoringService(supervisor, BatcherConfig(
                        max_batch_size=8, max_wait_us=2_000,
                        max_queue=256,
                    ))
                    hosts.append(LocalHost(f"h{i}", service).start())
                # Binary wire format: the trace context rides the v2
                # trace:ctx frame column on this hop, not the header.
                router = FleetRouter(
                    [h.base_url for h in hosts], probe_interval_s=0.05,
                    wire_format="binary",
                ).start()
                results = [
                    router.score(workload.request(i))
                    for i in range(n_requests)
                ]
                assert all(np.isfinite(r["score"]) for r in results)
            finally:
                if router is not None:
                    router.stop()
                for h in hosts:
                    h.stop()  # graceful: workers flush their sinks

        router_spans = _spans(
            os.path.join(tmp_path, "router", "trace.json")
        )
        routes = {
            s["args"]["trace"]: s for s in router_spans
            if s["name"] == "serving.fleet_route"
        }
        scores = [s for s in router_spans
                  if s["name"] == "serving.http_score"
                  and "trace" in s.get("args", {})]
        assert len(routes) == n_requests  # one distinct trace each
        # Host hop: every HTTP span stitches to its request's routing
        # span (LocalHost handlers run in the router's process, so both
        # hops land in the router's trace file).
        assert len(scores) == n_requests
        score_gids = {}
        for s in scores:
            trace_id = s["args"]["trace"]
            assert trace_id in routes
            assert s["args"]["rparent"] == \
                routes[trace_id]["args"]["gid"]
            score_gids[s["args"]["gid"]] = trace_id

        # Worker hop: the REAL process boundary.  Each worker wrote its
        # own trace file; its serving.batch spans carry the router's
        # trace ids and parent to the host's HTTP spans by global id.
        worker_files = sorted(
            glob.glob(str(worker_dir / "trace-worker-*.trace.json"))
        )
        assert len(worker_files) == 2, worker_files
        stitched_traces = set()
        for path in worker_files:
            for s in _spans(path):
                args = s.get("args", {})
                if s["name"] != "serving.batch" or "trace" not in args:
                    continue
                assert args["trace"] in routes, path
                assert args["rparent"] in score_gids, path
                assert score_gids[args["rparent"]] == args["trace"]
                stitched_traces.add(args["trace"])
        # At least one request's chain crosses all three hops — ONE
        # stitched trace spanning two processes (batching can coalesce
        # neighbors into a shared batch span, so not necessarily all 4).
        assert stitched_traces, (
            "no worker batch span carried a router trace id"
        )


# ---------------------------------------------------------------------------
# Fleet aggregation: scrape chaos + burn alerting
# ---------------------------------------------------------------------------

def _host_hub() -> Telemetry:
    return Telemetry(enabled=True, sinks=[])


def _snapshot_fetch(hubs: dict):
    """Injectable fetch mapping base URLs back to live hubs — the same
    snapshot shape MetricsExporter serves, no sockets needed."""
    def fetch(url: str, timeout_s: float) -> dict:
        hid = url.split("//", 1)[1].split("/", 1)[0]
        hub = hubs[hid]
        return {
            "transport": hub.metrics.transport_snapshot(),
            "host": {"host_id": hid, "pid": os.getpid()},
        }
    return fetch


class TestFleetAggregation:
    def test_scrape_chaos_degrades_to_last_seen_and_recovers(self):
        hub = _host_hub()
        lat = hub.histogram("serving_request_latency_seconds")
        for _ in range(10):
            lat.observe(0.001)
        agg = FleetAggregator(
            {"h0": "http://h0"}, fetch=_snapshot_fetch({"h0": hub})
        )
        report = agg.poll_once(now=10.0)
        assert report["hosts"]["h0"]["stale"] is False

        # The host drops off the network mid-scrape (the
        # "telemetry.scrape" chaos seam): the aggregator counts the
        # failure, marks the host stale, and keeps serving the
        # last-seen fold — it must never wedge or raise.
        with chaos.FaultPlan([chaos.FaultSpec(
            site="telemetry.scrape", at=0, count=1,
        )]):
            report = agg.poll_once(now=25.0)
        assert report["hosts"]["h0"]["stale"] is True
        counters = agg.registry.snapshot()["counters"]
        assert counters["fleet_scrape_failures_total"] == 1
        gauges = agg.registry.snapshot()["gauges"]
        assert gauges["fleet_scrape_staleness_seconds"] == \
            pytest.approx(15.0)
        # Last-seen state survives the outage: the fold still carries
        # the 10 observations scraped while the host was up.
        parsed = telemetry.parse_prometheus_text(agg.prometheus_text())
        assert parsed[(
            "serving_request_latency_seconds_count", '{host="h0"}'
        )] == 10.0

        report = agg.poll_once(now=30.0)  # the host comes back
        assert report["hosts"]["h0"]["stale"] is False
        assert report["hosts"]["h0"]["staleness_s"] == 0.0

    def test_burn_alert_fires_once_per_excursion(self):
        hub = _host_hub()
        lat = hub.histogram("serving_request_latency_seconds")
        agg = FleetAggregator(
            {"h0": "http://h0"},
            fetch=_snapshot_fetch({"h0": hub}),
            policies=[SloPolicy(
                name="latency-p99", p99_s=0.05, error_budget=0.01,
            )],
        )
        for _ in range(100):
            lat.observe(0.002)
        report = agg.poll_once(now=1000.0)
        policy = report["policies"][0]
        assert policy["alerting"] is False and policy["alerts"] == 0

        for _ in range(20):
            lat.observe(1.0)  # way past the 50ms target
        for now in (1060.0, 1120.0):  # two rounds inside one excursion
            report = agg.poll_once(now=now)
        policy = report["policies"][0]
        assert policy["alerting"] is True
        assert policy["alerts"] == 1  # edge-triggered, not per-round
        assert policy["fast"]["burn"] >= 1.0
        counters = agg.registry.snapshot()["counters"]
        assert counters["slo_burn_alerts_total"] == 1

        # The excursion ends (a quiet window): the alert re-arms, and a
        # second excursion fires a SECOND alert.
        report = agg.poll_once(now=1120.0 + 7200.0)
        assert report["policies"][0]["alerting"] is False
        for _ in range(20):
            lat.observe(1.0)
        report = agg.poll_once(now=1120.0 + 7260.0)
        assert report["policies"][0]["alerts"] == 2

    def test_fleet_fold_is_host_labeled_and_parseable(self):
        hubs = {"h0": _host_hub(), "h1": _host_hub()}
        for i, hub in enumerate(hubs.values()):
            hub.counter("serving_requests_total").inc(10 * (i + 1))
        agg = FleetAggregator(
            {hid: f"http://{hid}" for hid in hubs},
            fetch=_snapshot_fetch(hubs),
        )
        agg.poll_once(now=1.0)
        parsed = telemetry.parse_prometheus_text(agg.prometheus_text())
        assert parsed[("serving_requests_total", "")] == 30.0  # fold
        assert parsed[
            ("serving_requests_total", '{host="h0"}')] == 10.0
        assert parsed[
            ("serving_requests_total", '{host="h1"}')] == 20.0
        assert parsed[("fleet_hosts_count", "")] == 2.0

    def test_membership_sync_marks_departed_then_drops(self):
        """Satellite: the scraped host set FOLLOWS membership — a
        departed host is marked stale immediately, stops being
        scraped, and is DROPPED from the exposition after
        ``stale_drop_s``; a returner resumes under the same host
        label.  A dead host's last-seen numbers never sum forever."""
        t = {"now": 0.0}
        hubs = {"h0": _host_hub(), "h1": _host_hub()}
        for hub in hubs.values():
            hub.counter("serving_requests_total").inc(5)
        agg = FleetAggregator(
            {hid: f"http://{hid}" for hid in hubs},
            fetch=_snapshot_fetch(hubs),
            clock=lambda: t["now"],
            stale_drop_s=30.0,
        )
        agg.poll_once()
        assert ('fleet_host_stale_count{host="h1"} 0'
                in agg.prometheus_text())

        out = agg.sync_membership({"h0": "http://h0"})
        assert out == {"added": [], "departed": ["h1"], "returned": []}
        t["now"] = 10.0
        report = agg.poll_once()
        assert report["hosts"]["h1"]["departed"] is True
        # Departed hosts stop being scraped...
        assert report["hosts"]["h1"]["scrapes"] == 1
        # ...and their series are flagged stale in the exposition.
        assert ('fleet_host_stale_count{host="h1"} 1'
                in agg.prometheus_text())
        counters = agg.registry.snapshot()["counters"]
        assert counters["fleet_membership_changes_total"] == 1

        # A returner is re-adopted in place, under the same label.
        out = agg.sync_membership({"h0": "http://h0",
                                   "h1": "http://h1"})
        assert out["returned"] == ["h1"]
        t["now"] = 20.0
        report = agg.poll_once()
        assert report["hosts"]["h1"]["departed"] is False
        assert report["hosts"]["h1"]["stale"] is False

        # Departed past stale_drop_s: dropped from the exposition
        # entirely — bounded aging, not forever-sums.
        agg.sync_membership({"h0": "http://h0"})
        t["now"] = 60.0
        report = agg.poll_once()
        assert "h1" not in report["hosts"]
        assert 'host="h1"' not in agg.prometheus_text()
        counters = agg.registry.snapshot()["counters"]
        assert counters["fleet_hosts_dropped_total"] == 1
