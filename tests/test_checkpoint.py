"""Checkpoint/resume + incremental training (SURVEY.md §5.3/§5.4).

The bar (VERDICT round 1, item 5): a killed-and-resumed run reproduces the
uninterrupted result BIT-FOR-BIT on CPU — resumed state is the accumulated
float values, not a recomputation.
"""

import json
import os

import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.game.estimator import (
    FixedEffectCoordinateConfig,
    GameEstimator,
    GameTransformer,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.io.checkpoint import (
    CoordinateDescentCheckpointer,
    GridCheckpointer,
)
from photon_ml_tpu.optim.problem import (
    GlmOptimizationConfig,
    GlmOptimizationProblem,
    OptimizerConfig,
)
from photon_ml_tpu.optim.regularization import RegularizationContext


def _game_data(seed=13, n=400, n_users=12):
    rng = np.random.default_rng(seed)
    user_effect = rng.normal(scale=2.0, size=n_users)
    Xg = rng.normal(size=(n, 3)).astype(np.float32)
    users = rng.integers(n_users, size=n)
    margin = 1.3 * Xg[:, 0] - 0.7 * Xg[:, 1] + user_effect[users]
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(np.float32)
    shards = {
        "global": sp.csr_matrix(Xg),
        "userFeatures": sp.csr_matrix(np.ones((n, 1), np.float32)),
    }
    ids = {"userId": np.array([f"u{u}" for u in users])}
    return shards, ids, y


def _configs():
    opt = GlmOptimizationConfig(
        optimizer=OptimizerConfig(max_iters=30, tolerance=1e-7),
        regularization=RegularizationContext.l2(),
    )
    return {
        "fixed": FixedEffectCoordinateConfig(
            feature_shard="global", optimization=opt, reg_weight=0.5
        ),
        "per_user": RandomEffectCoordinateConfig(
            feature_shard="userFeatures", entity_key="userId",
            optimization=opt, reg_weight=0.5,
        ),
    }


class TestCheckpointerRoundTrip:
    def test_cd_checkpointer(self, tmp_path):
        ck = CoordinateDescentCheckpointer(str(tmp_path / "ck"))
        assert ck.load() is None
        total = np.arange(5, dtype=np.float32)
        scores = {"a": np.ones(5, np.float32), "b": np.zeros(5, np.float32)}
        states = {
            "a": np.arange(3, dtype=np.float32),
            "b": [np.ones((2, 2), np.float32), np.zeros((1, 4), np.float32)],
        }
        history = [{"iteration": 0, "coordinate": "a", "train_metric": 0.5}]
        ck.save(2, total, scores, states, history)
        got = ck.load()
        assert got["iteration"] == 2
        np.testing.assert_array_equal(got["total"], total)
        np.testing.assert_array_equal(got["scores"]["a"], scores["a"])
        assert isinstance(got["states"]["b"], list)
        np.testing.assert_array_equal(got["states"]["b"][0], states["b"][0])
        assert got["history"] == history

    def test_grid_checkpointer(self, tmp_path):
        ck = GridCheckpointer(str(tmp_path / "g"))
        assert ck.load() == {}
        solved = {
            10.0: np.arange(4, dtype=np.float32),
            0.1: np.ones(4, np.float32),
        }
        ck.save(solved)
        got = ck.load()
        assert list(got) == [10.0, 0.1]  # solve order preserved
        np.testing.assert_array_equal(got[10.0], solved[10.0])

    def test_grid_checkpointer_extra_meta(self, tmp_path):
        """Run-configuration metadata (the driver's bounds fingerprint)
        rides the checkpoint and is absent-not-crashing on old files."""
        ck = GridCheckpointer(str(tmp_path / "g"))
        assert ck.load_meta() == {}  # no checkpoint yet
        solved = {1.0: np.ones(3, np.float32)}
        ck.save(solved, extra_meta={"bounds_fingerprint": "abc123"})
        meta = ck.load_meta()
        assert meta["bounds_fingerprint"] == "abc123"
        assert meta["lambdas"] == [1.0]
        # A save without extra_meta (pre-fingerprint writer) reads back
        # with the key simply missing.
        ck.save(solved)
        assert ck.load_meta().get("bounds_fingerprint") is None
        np.testing.assert_array_equal(ck.load()[1.0], solved[1.0])


class TestKillAndResume:
    def test_cd_resume_bit_for_bit(self, tmp_path):
        """Interrupted-after-iteration-1 + resume == uninterrupted, exactly."""
        shards, ids, y = _game_data()

        # Uninterrupted 3-iteration run.
        est = GameEstimator("logistic", _configs(), n_iterations=3)
        model_full, hist_full = est.fit(shards, ids, y)

        # "Killed" run: 1 iteration with a checkpointer, then a resumed
        # 3-iteration run against the same checkpoint.
        ck = CoordinateDescentCheckpointer(str(tmp_path / "ck"))
        est1 = GameEstimator("logistic", _configs(), n_iterations=1)
        est1.fit(shards, ids, y, checkpointer=ck)
        est3 = GameEstimator("logistic", _configs(), n_iterations=3)
        model_res, hist_res = est3.fit(shards, ids, y, checkpointer=ck)

        w_full = np.asarray(model_full["fixed"].model.coefficients.means)
        w_res = np.asarray(model_res["fixed"].model.coefficients.means)
        np.testing.assert_array_equal(w_full, w_res)
        cf = model_full["per_user"].coefficients
        cr = model_res["per_user"].coefficients
        assert set(cf) == set(cr)
        for k in cf:
            np.testing.assert_array_equal(cf[k][0], cr[k][0])
            np.testing.assert_array_equal(cf[k][1], cr[k][1])
        # History: resumed run restores iteration-0 entries then continues.
        assert len(hist_res) == len(hist_full)
        assert [h["coordinate"] for h in hist_res] == [
            h["coordinate"] for h in hist_full
        ]

    def test_glm_grid_resume_bit_for_bit(self):
        rng = np.random.default_rng(5)
        X = sp.csr_matrix(rng.normal(size=(300, 10)).astype(np.float32))
        w_true = rng.normal(size=10).astype(np.float32)
        y = (X @ w_true + 0.1 * rng.normal(size=300) > 0).astype(np.float32)
        from photon_ml_tpu.data.dataset import make_glm_data

        data = make_glm_data(X, y)
        problem = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(max_iters=50),
                regularization=RegularizationContext.l2(),
            ),
        )
        lams = [10.0, 1.0, 0.1]
        solved_log: dict = {}
        full = problem.run_grid(
            data, lams, on_solved=lambda lam, w: solved_log.update(
                {lam: np.asarray(w)}
            )
        )
        # Resume with the first TWO λs (solve order: descending) restored.
        partial = {
            lam: solved_log[lam] for lam in sorted(lams, reverse=True)[:2]
        }
        resumed = problem.run_grid(data, lams, solved=partial)
        for (lam_f, model_f, _), (lam_r, model_r, res_r) in zip(full, resumed):
            assert lam_f == lam_r
            np.testing.assert_array_equal(
                np.asarray(model_f.coefficients.means),
                np.asarray(model_r.coefficients.means),
            )
        assert resumed[0][2] is None and resumed[1][2] is None  # restored
        assert resumed[2][2] is not None  # freshly solved


class TestIncrementalTraining:
    def test_initial_states_round_trip(self):
        """model → initial states → finalize reproduces the model exactly."""
        shards, ids, y = _game_data()
        est = GameEstimator("logistic", _configs(), n_iterations=2)
        coords = est.build_coordinates(shards, ids, y)
        model, _ = est.fit_coordinates(coords, y)
        states = GameEstimator.initial_states_from_model(coords, model)
        # Fixed effect: exact vector.
        np.testing.assert_array_equal(
            np.asarray(states["fixed"]),
            np.asarray(model["fixed"].model.coefficients.means),
        )
        # Random effect: finalizing the projected states reproduces the
        # per-entity coefficient table.
        re_model2 = coords[1].finalize(states["per_user"])
        c1, c2 = model["per_user"].coefficients, re_model2.coefficients
        assert set(c1) == set(c2)
        for k in c1:
            np.testing.assert_array_equal(c1[k][0], c2[k][0])
            np.testing.assert_array_equal(c1[k][1], c2[k][1])

    def test_warm_start_seeds_scores(self):
        """First CD update of an incremental fit trains against the prior
        model's residuals — its train metric starts at prior-model level."""
        shards, ids, y = _game_data()
        est = GameEstimator("logistic", _configs(), n_iterations=2)
        model, hist_cold = est.fit(shards, ids, y)
        est2 = GameEstimator("logistic", _configs(), n_iterations=1)
        _, hist_warm = est2.fit(shards, ids, y, initial_model=model)
        # Cold run's first update is fixed-effect-only; the warm run's first
        # entry already includes the prior random effect.
        assert hist_warm[0]["train_metric"] > hist_cold[0]["train_metric"]


class TestDriverResume:
    def test_game_driver_kill_and_resume(self, tmp_path):
        from photon_ml_tpu.data.game_reader import write_game_avro
        from photon_ml_tpu.drivers import game_training_driver
        from photon_ml_tpu.io.game_store import load_game_model

        rng = np.random.default_rng(2)
        user_effect = {f"u{u}": rng.normal(scale=2.0) for u in range(10)}
        rows = []
        for i in range(300):
            u = f"u{rng.integers(10)}"
            xg = rng.normal(size=3)
            margin = 1.5 * xg[0] + user_effect[u]
            yv = float(rng.uniform() < 1 / (1 + np.exp(-margin)))
            rows.append({
                "uid": f"r{i}", "response": yv, "weight": None, "offset": None,
                "ids": {"userId": u},
                "features": {
                    "global": [
                        {"name": f"g{j}", "term": "", "value": float(xg[j])}
                        for j in range(3)
                    ],
                    "userFeatures": [{"name": "b", "term": "", "value": 1.0}],
                },
            })
        train = str(tmp_path / "train.avro")
        write_game_avro(train, rows)

        def cfg(iters):
            c = {
                "task": "logistic", "iterations": iters,
                "coordinates": [
                    {"name": "fixed", "type": "fixed",
                     "feature_shard": "global", "optimizer": "lbfgs",
                     "max_iters": 30, "reg_type": "l2", "reg_weight": 0.5},
                    {"name": "per_user", "type": "random",
                     "feature_shard": "userFeatures", "entity_key": "userId",
                     "optimizer": "lbfgs", "max_iters": 20,
                     "reg_type": "l2", "reg_weight": 0.5},
                ],
            }
            path = str(tmp_path / f"cfg{iters}.json")
            with open(path, "w") as f:
                json.dump(c, f)
            return path

        out_full = str(tmp_path / "full")
        game_training_driver.run([
            "--train-data", train, "--config", cfg(3),
            "--output-dir", out_full,
        ])
        # "Kill" after 1 iteration, then resume to 3 in the same output dir.
        out_res = str(tmp_path / "resumed")
        game_training_driver.run([
            "--train-data", train, "--config", cfg(1),
            "--output-dir", out_res,
        ])
        assert os.path.exists(
            os.path.join(out_res, "checkpoints", "cd_checkpoint.npz")
        )
        game_training_driver.run([
            "--train-data", train, "--config", cfg(3),
            "--output-dir", out_res, "--resume",
        ])

        m_full, imaps = load_game_model(os.path.join(out_full, "models"))
        m_res, _ = load_game_model(os.path.join(out_res, "models"))
        from photon_ml_tpu.data.game_reader import read_game_avro

        shards, ids, *_ = read_game_avro(train, index_maps=imaps)
        s_full = GameTransformer(m_full).transform(shards, ids)
        s_res = GameTransformer(m_res).transform(shards, ids)
        np.testing.assert_array_equal(s_full, s_res)

    def test_game_driver_incremental(self, tmp_path):
        # Reuses the kill-and-resume data shape; the point here is just that
        # --initial-model round-trips through the driver CLI.
        from photon_ml_tpu.data.game_reader import write_game_avro
        from photon_ml_tpu.drivers import game_training_driver

        rng = np.random.default_rng(4)
        rows = []
        for i in range(200):
            xg = rng.normal(size=2)
            yv = float(rng.uniform() < 1 / (1 + np.exp(-1.2 * xg[0])))
            rows.append({
                "uid": f"r{i}", "response": yv, "weight": None, "offset": None,
                "ids": {"userId": f"u{i % 8}"},
                "features": {
                    "global": [
                        {"name": f"g{j}", "term": "", "value": float(xg[j])}
                        for j in range(2)
                    ],
                    "userFeatures": [{"name": "b", "term": "", "value": 1.0}],
                },
            })
        train = str(tmp_path / "t.avro")
        write_game_avro(train, rows)
        config = {
            "task": "logistic", "iterations": 1,
            "coordinates": [
                {"name": "fixed", "type": "fixed", "feature_shard": "global",
                 "optimizer": "lbfgs", "max_iters": 20, "reg_type": "l2",
                 "reg_weight": 0.5},
                {"name": "per_user", "type": "random",
                 "feature_shard": "userFeatures", "entity_key": "userId",
                 "optimizer": "lbfgs", "max_iters": 15, "reg_type": "l2",
                 "reg_weight": 0.5},
            ],
        }
        cfgp = str(tmp_path / "c.json")
        with open(cfgp, "w") as f:
            json.dump(config, f)
        out1 = str(tmp_path / "m1")
        r1 = game_training_driver.run([
            "--train-data", train, "--config", cfgp, "--output-dir", out1,
        ])
        out2 = str(tmp_path / "m2")
        r2 = game_training_driver.run([
            "--train-data", train, "--config", cfgp, "--output-dir", out2,
            "--initial-model", os.path.join(out1, "models"),
        ])
        # Warm-started training must not regress the train metric.
        assert r2["train_metric"] >= r1["train_metric"] - 1e-6


class TestGlmDriverResume:
    def test_glm_driver_resume_and_initial_model(self, tmp_path):
        from photon_ml_tpu.data import libsvm
        from photon_ml_tpu.drivers import glm_driver

        rng = np.random.default_rng(9)
        n, d = 400, 30
        X = sp.random(n, d, density=0.2, random_state=1, format="csr")
        w_true = rng.normal(size=d)
        y = np.where(
            rng.uniform(size=n) < 1 / (1 + np.exp(-(X @ w_true))), 1.0, -1.0
        )
        train = str(tmp_path / "t.libsvm")
        libsvm.write_libsvm(train, X, y)
        base_args = [
            "--train-data", train, "--task", "logistic",
            "--reg-type", "l2", "--reg-weights", "0.1,1.0,10.0",
            "--n-features", str(d), "--max-iters", "40",
            "--normalization", "standardization",
        ]
        out1 = str(tmp_path / "o1")
        r1 = glm_driver.run(base_args + ["--output-dir", out1])
        assert os.path.exists(
            os.path.join(out1, "checkpoints", "grid_checkpoint.npz")
        )
        # Resume over a fully-solved grid: every λ restores, results match.
        r1b = glm_driver.run(base_args + ["--output-dir", out1, "--resume"])
        assert r1b["best_lambda"] == r1["best_lambda"]
        assert r1b["metrics"] == r1["metrics"]
        # Incremental training from the saved best model (exercises the
        # original→scaled-space warm-start mapping under normalization).
        model_path = os.path.join(
            out1, f"model_lambda_{r1['best_lambda']:g}.avro"
        )
        out2 = str(tmp_path / "o2")
        r2 = glm_driver.run(
            base_args + ["--output-dir", out2, "--initial-model", model_path]
        )
        best = lambda r: r["metrics"][str(r["best_lambda"])]
        assert best(r2) >= best(r1) - 1e-6


class TestCheckpointFormatCompat:
    def test_old_bucketed_checkpoint_refused_vector_only_loads(
        self, tmp_path
    ):
        """Round-4 tight bucket padding changed random-effect state
        SHAPES: a pre-generation checkpoint carrying per-bucket (list)
        states must be refused with a warning (restoring it would
        shape-crash deep inside the rebuilt coordinates' vmapped
        solvers), while a bare-vector-only checkpoint — whose shapes
        are padding-independent — still loads."""
        import json

        import numpy as np

        from photon_ml_tpu.io.checkpoint import (
            CoordinateDescentCheckpointer,
            _atomic_savez,
        )

        ck = CoordinateDescentCheckpointer(str(tmp_path))
        arrays = {
            "total": np.arange(4, dtype=np.float32),
            "score__fixed": np.ones(4, np.float32),
            "score__re": np.zeros(4, np.float32),
            "state__fixed": np.arange(3, dtype=np.float32),
            "state__re__0": np.ones((2, 2), np.float32),
            "state__re__1": np.ones((1, 2), np.float32),
            "__meta__": np.asarray(json.dumps({
                "iteration": 1,
                "coordinates": ["fixed", "re"],
                "list_states": {"re": 2},  # pre-nesting format, gen 1
                "history": [],
            })),
        }
        import os

        os.makedirs(str(tmp_path), exist_ok=True)
        _atomic_savez(ck.path, arrays)
        assert ck.load() is None  # bucketed states from gen 1: refused

        vec_only = {
            "total": np.arange(4, dtype=np.float32),
            "score__fixed": np.ones(4, np.float32),
            "state__fixed": np.arange(3, dtype=np.float32),
            "__meta__": np.asarray(json.dumps({
                "iteration": 2,
                "coordinates": ["fixed"],
                "state_specs": {"fixed": "array"},
                "history": [],
            })),
        }
        _atomic_savez(ck.path, vec_only)
        loaded = ck.load()
        assert loaded is not None and loaded["iteration"] == 2
        np.testing.assert_array_equal(
            loaded["states"]["fixed"], np.arange(3, dtype=np.float32)
        )

    def test_current_roundtrip_carries_padding_gen(self, tmp_path):
        import numpy as np

        from photon_ml_tpu.io.checkpoint import (
            CoordinateDescentCheckpointer,
        )

        ck = CoordinateDescentCheckpointer(str(tmp_path))
        ck.save(
            3, np.zeros(4, np.float32),
            {"re": np.zeros(4, np.float32)},
            {"re": [np.ones((2, 2), np.float32)]},
            [],
        )
        loaded = ck.load()  # same generation: bucketed states load fine
        assert loaded is not None and loaded["iteration"] == 3
        assert loaded["states"]["re"][0].shape == (2, 2)


class TestCheckpointHardening:
    """ISSUE 6 satellite: torn/corrupt checkpoints raise a pointed
    CheckpointCorruptError instead of a raw zipfile/OSError, payloads
    carry sha256 checksums, and keep-last-K retention falls back to the
    newest verifiable generation."""

    def _grid(self, tmp_path, **kw):
        ck = GridCheckpointer(str(tmp_path), **kw)
        ck.save({1.0: np.ones(4, np.float32)})
        ck.save({1.0: np.ones(4, np.float32),
                 0.5: np.full(4, 2.0, np.float32)})
        return ck

    def test_truncated_file_raises_pointed_error(self, tmp_path):
        from photon_ml_tpu.io.checkpoint import CheckpointCorruptError

        ck = GridCheckpointer(str(tmp_path), keep_last=1)
        ck.save({1.0: np.ones(4, np.float32)})
        with open(ck.path, "r+b") as f:
            f.truncate(16)
        with pytest.raises(CheckpointCorruptError) as ei:
            ck.load()
        assert ck.path in str(ei.value)
        assert "truncated or torn" in str(ei.value)

    def test_checksum_mismatch_raises_pointed_error(self, tmp_path):
        """Flip payload bytes INSIDE an otherwise well-formed npz: the
        zip layer stays readable, only the sha256 catches it."""
        from photon_ml_tpu.io.checkpoint import (
            CheckpointCorruptError,
            _CHECKSUM_KEY,
            _atomic_savez,
        )

        ck = GridCheckpointer(str(tmp_path), keep_last=1)
        # Re-save with a tampered array but the ORIGINAL digest.
        ck.save({1.0: np.ones(4, np.float32)})
        with np.load(ck.path) as z:
            arrays = {k: z[k] for k in z.files}
        digest = arrays.pop(_CHECKSUM_KEY)
        arrays["w__0"] = arrays["w__0"] + 1.0  # bit rot
        arrays[_CHECKSUM_KEY] = digest
        import io as io_mod

        buf = io_mod.BytesIO()
        np.savez(buf, **arrays)
        with open(ck.path, "wb") as f:
            f.write(buf.getvalue())
        with pytest.raises(CheckpointCorruptError) as ei:
            ck.load()
        assert "checksum mismatch" in str(ei.value)
        assert ck.path in str(ei.value)

    def test_retention_rotates_and_falls_back(self, tmp_path):
        ck = self._grid(tmp_path, keep_last=2)
        assert os.path.exists(ck.path + ".1")
        # Newest torn -> previous generation loads (one interval lost).
        with open(ck.path, "r+b") as f:
            f.truncate(8)
        assert sorted(ck.load()) == [1.0]

    def test_retention_depth_respected(self, tmp_path):
        ck = GridCheckpointer(str(tmp_path), keep_last=3)
        for i in range(5):
            ck.save({float(i): np.full(2, i, np.float32)})
        retained = sorted(os.listdir(str(tmp_path)))
        assert retained == [
            "grid_checkpoint.npz", "grid_checkpoint.npz.1",
            "grid_checkpoint.npz.2",
        ]
        # Generations are newest-first: path=4, .1=3, .2=2.
        assert list(ck.load()) == [4.0]
        with open(ck.path, "r+b") as f:
            f.truncate(8)
        assert list(ck.load()) == [3.0]

    def test_clear_removes_all_generations(self, tmp_path):
        ck = self._grid(tmp_path, keep_last=2)
        ck.clear()
        assert os.listdir(str(tmp_path)) == []
        assert ck.load() == {}

    def test_cd_checkpointer_fallback(self, tmp_path):
        ck = CoordinateDescentCheckpointer(str(tmp_path), keep_last=2)
        total = np.arange(4, dtype=np.float32)
        ck.save(1, total, {"a": np.ones(4, np.float32)},
                {"a": np.arange(2, dtype=np.float32)}, [])
        ck.save(2, total, {"a": np.ones(4, np.float32)},
                {"a": np.arange(2, dtype=np.float32)}, [])
        with open(ck.path, "r+b") as f:
            f.truncate(8)
        got = ck.load()
        assert got is not None and got["iteration"] == 1

    def test_legacy_unchecksummed_file_loads(self, tmp_path):
        """Files written before the checksum era (no __checksum__ entry)
        still load — unverified, not rejected."""
        ck = GridCheckpointer(str(tmp_path), keep_last=1)
        import json as json_mod

        arrays = {
            "w__0": np.ones(3, np.float32),
            "__meta__": np.asarray(json_mod.dumps({"lambdas": [1.0]})),
        }
        os.makedirs(str(tmp_path), exist_ok=True)
        with open(ck.path, "wb") as f:
            np.savez(f, **arrays)
        np.testing.assert_array_equal(ck.load()[1.0], arrays["w__0"])


class TestGameGridCheckpointer:
    def _mini_model_and_maps(self):
        import jax.numpy as jnp

        from photon_ml_tpu.data.index_map import IndexMap
        from photon_ml_tpu.game.model import FixedEffectModel, GameModel
        from photon_ml_tpu.models.glm import (
            Coefficients,
            GeneralizedLinearModel,
        )

        glm = GeneralizedLinearModel(
            Coefficients(jnp.asarray(np.array([0.5, -1.0], np.float32))),
            "logistic",
        )
        model = GameModel({"fixed": FixedEffectModel(glm, "global")},
                          task="logistic")
        imaps = {"global": IndexMap.build({"f0": 0, "f1": 1})}
        return model, imaps

    def _configs(self, **overrides):
        from photon_ml_tpu.game.estimator import FixedEffectCoordinateConfig

        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(
                max_iters=overrides.pop("max_iters", 10)
            ),
            regularization=RegularizationContext.l2(),
        )
        return {"fixed": FixedEffectCoordinateConfig(
            feature_shard="global", optimization=opt,
            reg_weight=overrides.pop("reg_weight", 1.0),
        )}

    def test_roundtrip_and_fingerprint_covers_full_config(self, tmp_path):
        from photon_ml_tpu.io.checkpoint import GameGridCheckpointer

        model, imaps = self._mini_model_and_maps()
        ck = GameGridCheckpointer(str(tmp_path), imaps)
        configs = self._configs()
        ck.save_point(0, configs, model, 0.8, "validation_metric",
                      [{"train_metric": 0.7}])
        loaded = ck.load_point(0, configs, "validation_metric")
        assert loaded is not None
        m2, metric, history = loaded
        assert metric == 0.8
        assert history == [{"train_metric": 0.7}]
        np.testing.assert_allclose(
            np.asarray(m2.models["fixed"].model.coefficients.means),
            [0.5, -1.0],
        )
        # ANY config change invalidates the point — not just reg_weight
        # (the round-4 review finding: a changed optimizer silently served
        # stale models under the 3-field fingerprint).
        assert ck.load_point(
            0, self._configs(max_iters=99), "validation_metric"
        ) is None
        assert ck.load_point(
            0, self._configs(reg_weight=2.0), "validation_metric"
        ) is None

    def test_metric_kind_mismatch_rejected(self, tmp_path):
        """A point selected by train metric must not resume into a run
        selecting by validation metric (different kind/direction)."""
        from photon_ml_tpu.io.checkpoint import GameGridCheckpointer

        model, imaps = self._mini_model_and_maps()
        ck = GameGridCheckpointer(str(tmp_path), imaps)
        configs = self._configs()
        ck.save_point(0, configs, model, 0.69, "train_metric", [])
        assert ck.load_point(0, configs, "validation_metric") is None
        assert ck.load_point(0, configs, "train_metric") is not None
