"""Multi-tenant serving isolation tests (ISSUE 13).

The load-bearing contracts:

- a zero-quota (suspended) tenant admits NOTHING — not even the token
  bucket's initial fill — while a neighbor on the same batcher scores
  normally;
- an unknown tenant id rides the default spec and the default model
  route: it can never starve a registered tenant, and it scores
  bit-identically to an untagged request;
- a tenant-scoped hot swap moves ONE tenant's route; every other
  tenant's scores — and the default route's — stay bitwise untouched,
  and a one-step rollback restores exactly the previous route;
- the ``serving.tenant`` chaos site fires only for tenant-routed
  dispatch groups and degrades exactly that tenant;
- the ``noisy_neighbor`` scenario is registered and its replay harness
  (``run_noisy_neighbor``) proves containment: the aggressor bursting
  10x its quota sheds alone, the victim completes with ZERO failures —
  in thread mode and in process mode with a mid-burst worker SIGKILL.
"""

import threading
import time
import types

import numpy as np
import pytest

from photon_ml_tpu import chaos
from photon_ml_tpu import telemetry
from photon_ml_tpu.io.game_store import save_game_model
from photon_ml_tpu.serving import loadgen, shm_model
from photon_ml_tpu.serving.batcher import BatcherConfig, RejectedError
from photon_ml_tpu.serving.procpool import WorkerPool
from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
from photon_ml_tpu.serving.service import ScoringService
from photon_ml_tpu.serving.supervisor import ReplicaSupervisor
from photon_ml_tpu.serving.synthetic import SyntheticWorkload
from photon_ml_tpu.serving.tenancy import (
    TenancyConfig,
    TenantRouter,
    TenantSpec,
    TokenBucket,
    tenant_slug,
)


@pytest.fixture(scope="module")
def workload():
    return SyntheticWorkload(n_entities=32, seed=7)


@pytest.fixture(scope="module")
def workload_v2():
    return SyntheticWorkload(n_entities=32, seed=8)


@pytest.fixture(scope="module")
def workload_v3():
    return SyntheticWorkload(n_entities=32, seed=9)


RT_CFG = dict(max_batch_size=8, hot_entities=8)


def _runtime(workload):
    return ScoringRuntime(
        workload.model, workload.index_maps, RuntimeConfig(**RT_CFG)
    )


def _reference(workload, requests):
    runtime = _runtime(workload)
    return np.asarray(
        [
            runtime.score_rows([runtime.parse_request(r)])[0][0]
            for r in requests
        ],
        np.float32,
    )


def _tagged(workload, i, tenant):
    obj = dict(workload.request(i))
    if tenant is not None:
        obj["tenant"] = tenant
    return obj


def _wait_until(predicate, timeout_s=60.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


# ---------------------------------------------------------------------------
# Policy primitives
# ---------------------------------------------------------------------------

class TestTenancyPrimitives:
    def test_slug_folds_to_metric_alphabet(self):
        assert tenant_slug("Acme Corp.") == "acme_corp"
        assert tenant_slug("__x__") == "x"
        assert tenant_slug("!!!") == "tenant"

    def test_spec_validation(self):
        with pytest.raises(ValueError, match="non-empty"):
            TenantSpec(name="")
        with pytest.raises(ValueError, match="quota_rps"):
            TenantSpec(name="t", quota_rps=-1.0)
        with pytest.raises(ValueError, match="burst"):
            TenantSpec(name="t", burst=0.0)
        with pytest.raises(ValueError, match="watermark"):
            TenantSpec(name="t", shed_watermark=0.9, reject_watermark=0.5)
        with pytest.raises(ValueError, match="duplicate"):
            TenancyConfig(tenants=(
                TenantSpec(name="a"), TenantSpec(name="a"),
            ))
        with pytest.raises(ValueError, match="slug"):
            TenancyConfig(tenants=(
                TenantSpec(name="a b"), TenantSpec(name="a.b"),
            ))

    def test_spec_for_unknown_is_default(self):
        cfg = TenancyConfig(tenants=(TenantSpec(name="a", max_queue=8),))
        assert cfg.spec_for("a").max_queue == 8
        assert cfg.spec_for("stranger") is cfg.default
        assert cfg.spec_for(None) is cfg.default
        assert cfg.is_known("a") and not cfg.is_known("stranger")
        assert cfg.partition_total == 8 + cfg.default.max_queue

    def test_zero_quota_bucket_denies_first_request(self):
        bucket = TokenBucket(rate_rps=0.0, burst=5.0, clock=lambda: 0.0)
        # The initial fill must NOT grant a suspended tenant a burst.
        assert not bucket.try_acquire()
        assert bucket.denied == 1 and bucket.admitted == 0

    def test_unlimited_bucket_always_admits(self):
        bucket = TokenBucket(rate_rps=None)
        assert all(bucket.try_acquire() for _ in range(100))
        assert bucket.denied == 0

    def test_bucket_refills_at_rate_up_to_burst(self):
        t = [0.0]
        bucket = TokenBucket(rate_rps=2.0, burst=4.0, clock=lambda: t[0])
        assert [bucket.try_acquire() for _ in range(5)] == [
            True, True, True, True, False,
        ]
        t[0] = 1.0  # 2 tokens refilled
        assert bucket.try_acquire() and bucket.try_acquire()
        assert not bucket.try_acquire()
        t[0] = 100.0  # refill clamps at burst, not elapsed * rate
        assert bucket.tokens <= bucket.burst
        assert [bucket.try_acquire() for _ in range(5)] == [
            True, True, True, True, False,
        ]


# ---------------------------------------------------------------------------
# Admission: quotas and bulkheads in a live batcher
# ---------------------------------------------------------------------------

class TestTenantAdmission:
    def _service(self, workload, tenancy):
        return ScoringService(_runtime(workload), BatcherConfig(
            max_batch_size=8, max_wait_us=1_000, max_queue=256,
            tenancy=tenancy,
        ))

    def test_zero_quota_tenant_shed_neighbor_unaffected(self, workload):
        tenancy = TenancyConfig(tenants=(
            TenantSpec(name="suspended", quota_rps=0.0),
            TenantSpec(name="paying"),
        ))
        with telemetry.Telemetry(sinks=[]) as tel:
            with self._service(workload, tenancy) as service:
                for i in range(8):
                    with pytest.raises(RejectedError, match="over quota"):
                        service.submit(_tagged(workload, i, "suspended"))
                results = [
                    service.score(_tagged(workload, i, "paying"))
                    for i in range(8)
                ]
                assert all(np.isfinite(r["score"]) for r in results)
            snap = tel.snapshot()
        counters = snap["counters"]
        assert counters["serving_tenant_suspended_shed_total"] == 8
        assert counters["serving_shed_quota_total"] == 8
        assert counters["serving_tenant_paying_requests_total"] == 8
        assert "serving_tenant_paying_shed_total" not in counters

    def test_unknown_tenant_scores_on_default_route(self, workload):
        tenancy = TenancyConfig(tenants=(TenantSpec(name="paying"),))
        requests = [workload.request(i) for i in range(8)]
        expected = _reference(workload, requests)
        with telemetry.Telemetry(sinks=[]):
            with self._service(workload, tenancy) as service:
                got = np.asarray(
                    [
                        np.float32(
                            service.score(
                                _tagged(workload, i, "stranger")
                            )["score"]
                        )
                        for i in range(8)
                    ],
                    np.float32,
                )
                stats = service.batcher.stats()
        assert got.tobytes() == expected.tobytes()
        # The stranger rode the default partition, not a tenant's.
        assert "stranger" not in stats["tenants"]
        assert stats["tenants"]["default"]["quota"]["admitted"] >= 8

    def test_stats_expose_per_tenant_state(self, workload):
        tenancy = TenancyConfig(tenants=(
            TenantSpec(name="a", quota_rps=100.0, max_queue=16),
        ))
        with telemetry.Telemetry(sinks=[]):
            with self._service(workload, tenancy) as service:
                service.score(_tagged(workload, 0, "a"))
                stats = service.batcher.stats()
        entry = stats["tenants"]["a"]
        assert entry["max_queue"] == 16
        assert entry["quota"]["admitted"] >= 1
        assert entry["breaker"]["state"] in ("closed", "half_open", "open")
        assert entry["routed_version"] is None  # no tenant route yet


# ---------------------------------------------------------------------------
# Tenant-scoped routing: swap + rollback, bitwise neighbors
# ---------------------------------------------------------------------------

class TestTenantRouting:
    @pytest.fixture()
    def dirs(self, tmp_path, workload, workload_v2, workload_v3):
        out = {}
        for name, w in (
            ("v1", workload), ("v2", workload_v2), ("v3", workload_v3),
        ):
            d = str(tmp_path / name)
            save_game_model(w.model, w.index_maps, d)
            out[name] = d
        return out

    def test_swap_isolates_and_rollback_restores_bitwise(
        self, dirs, workload, workload_v2, workload_v3
    ):
        requests = [workload.request(i) for i in range(8)]
        want = {
            "v1": _reference(workload, requests),
            "v2": _reference(workload_v2, requests),
            "v3": _reference(workload_v3, requests),
        }

        def scores(service, tenant):
            return np.asarray(
                [
                    np.float32(
                        service.score(_tagged(workload, i, tenant))["score"]
                    )
                    for i in range(8)
                ],
                np.float32,
            )

        tenancy = TenancyConfig(tenants=(
            TenantSpec(name="acme"), TenantSpec(name="beta"),
        ))
        with telemetry.Telemetry(sinks=[]):
            service = ScoringService(_runtime(workload), BatcherConfig(
                max_batch_size=8, max_wait_us=1_000, max_queue=256,
                tenancy=tenancy,
            ))
            with service:
                result = service.reload(dirs["v2"], tenant="acme")
                assert result.status == "swapped", result
                assert result.tenant == "acme"
                # acme scores v2; beta, untagged, and unknown all v1.
                assert scores(service, "acme").tobytes() \
                    == want["v2"].tobytes()
                assert scores(service, "beta").tobytes() \
                    == want["v1"].tobytes()
                assert scores(service, None).tobytes() \
                    == want["v1"].tobytes()
                assert scores(service, "stranger").tobytes() \
                    == want["v1"].tobytes()

                # First-swap rollback: acme returns to the DEFAULT
                # route, bitwise.  (A successful MANUAL rollback
                # reports status "rolled_back" with live targets;
                # a refusal has targets == 0.)
                rb = service.reload(rollback=True, tenant="acme")
                assert rb.status == "rolled_back" and rb.targets > 0, rb
                assert scores(service, "acme").tobytes() \
                    == want["v1"].tobytes()
                assert service.swapper.tenant_versions() == {}

                # Two tenants routed: the second swap leaves the first
                # bitwise untouched.
                assert service.reload(
                    dirs["v2"], tenant="acme"
                ).status == "swapped"
                assert service.reload(
                    dirs["v3"], tenant="beta"
                ).status == "swapped"
                assert scores(service, "beta").tobytes() \
                    == want["v3"].tobytes()
                assert scores(service, "acme").tobytes() \
                    == want["v2"].tobytes()
                assert scores(service, None).tobytes() \
                    == want["v1"].tobytes()
                assert set(service.swapper.tenant_versions()) \
                    == {"acme", "beta"}

                # The rollback token is one-step and belongs to the
                # LAST tenant swap: rolling back acme now refuses…
                stale = service.reload(rollback=True, tenant="acme")
                assert stale.targets == 0, stale
                assert "no prior tenant swap" in stale.reason
                # …while rolling back beta restores it to the default
                # route with acme's route bitwise untouched.
                rb2 = service.reload(rollback=True, tenant="beta")
                assert rb2.status == "rolled_back" and rb2.targets > 0, rb2
                assert scores(service, "beta").tobytes() \
                    == want["v1"].tobytes()
                assert scores(service, "acme").tobytes() \
                    == want["v2"].tobytes()
                assert set(service.swapper.tenant_versions()) == {"acme"}

    def test_router_view_tracks_routes(self, dirs, workload):
        tenancy = TenancyConfig(tenants=(TenantSpec(name="acme"),))
        with telemetry.Telemetry(sinks=[]):
            service = ScoringService(_runtime(workload), BatcherConfig(
                max_batch_size=8, max_wait_us=1_000, max_queue=256,
                tenancy=tenancy,
            ))
            with service:
                router = TenantRouter(service.swapper)
                before = router.route("acme")
                assert before["default_route"] is True
                assert before["version"] == service.swapper.version
                assert service.reload(
                    dirs["v2"], tenant="acme"
                ).status == "swapped"
                after = router.route("acme")
                assert after["default_route"] is False
                assert after["version"] > before["version"]
                # Unknown tenants still resolve to the default route.
                assert router.route("stranger")["default_route"] is True
                routes = router.routes()
                assert routes["acme"]["version"] == after["version"]
                assert routes["*default*"]["default_route"] is True

    def test_chaos_site_degrades_one_tenant(self, dirs, workload):
        tenancy = TenancyConfig(tenants=(TenantSpec(name="acme"),))
        with telemetry.Telemetry(sinks=[]) as tel:
            service = ScoringService(_runtime(workload), BatcherConfig(
                max_batch_size=8, max_wait_us=1_000, max_queue=256,
                tenancy=tenancy,
            ))
            with service:
                assert service.reload(
                    dirs["v2"], tenant="acme"
                ).status == "swapped"
                plan = chaos.FaultPlan([
                    chaos.FaultSpec(site="serving.tenant", at=0),
                ])
                with plan:
                    future = service.submit(_tagged(workload, 0, "acme"))
                    with pytest.raises(Exception, match="chaos-injected"):
                        future.result(timeout=30)
                assert [f["site"] for f in plan.fired] \
                    == ["serving.tenant"]
                assert plan.fired[0]["tenant"] == "acme"
                # The fault degraded acme alone: the default route
                # scores untouched afterwards.
                result = service.score(workload.request(1))
                assert np.isfinite(result["score"])
            snap = tel.snapshot()
        assert snap["counters"][
            "serving_tenant_acme_failed_requests_total"
        ] == 1


# ---------------------------------------------------------------------------
# Noisy neighbor: the containment proof
# ---------------------------------------------------------------------------

def _short_scenario():
    return loadgen.Scenario(
        name="noisy_neighbor",
        description="test-sized replay",
        phases=[
            loadgen.ScenarioPhase("baseline", 0.3),
            loadgen.ScenarioPhase("burst", 0.8, rate_multiplier=10.0),
            loadgen.ScenarioPhase("recovery", 0.3),
        ],
    )


class TestNoisyNeighbor:
    def test_scenario_registered(self):
        scenario = loadgen.SCENARIOS["noisy_neighbor"]
        assert [p.name for p in scenario.phases] \
            == ["baseline", "burst", "recovery"]
        burst = scenario.phases[1]
        assert burst.rate_multiplier == 10.0
        assert "aggressor" in scenario.description

    def test_thread_mode_isolation(self, workload):
        tenancy = TenancyConfig(tenants=(
            TenantSpec(name="victim", max_queue=128, p99_slo_ms=500.0),
            TenantSpec(name="aggressor", quota_rps=5.0, burst=2.0),
        ))
        with telemetry.Telemetry(sinks=[]) as tel:
            service = ScoringService(_runtime(workload), BatcherConfig(
                max_batch_size=8, max_wait_us=2_000, max_queue=256,
                tenancy=tenancy,
            ))
            with service:
                report = loadgen.run_noisy_neighbor(
                    service.submit,
                    lambda i, phase, tenant: _tagged(workload, i, tenant),
                    victim_rate_rps=30.0, aggressor_rate_rps=30.0,
                    scenario=_short_scenario(),
                )
            snap = tel.snapshot()
        gate = report.isolation(500.0)
        assert gate["pass"], gate
        assert report.victim.failed == 0
        assert report.victim.completed > 0
        assert report.aggressor.shed > 0
        counters = snap["counters"]
        assert counters["serving_tenant_aggressor_shed_total"] \
            == report.aggressor.shed
        assert counters["serving_tenant_victim_requests_total"] \
            >= report.victim.completed

    def test_process_mode_burst_with_midstream_sigkill(self, workload):
        # The aggressor bursts 10x its quota while a worker is
        # SIGKILLed mid-burst: the supervisor resubmits the dead
        # worker's in-flight rows, so the victim STILL finishes with
        # zero failures and only the aggressor sheds.
        tenancy = TenancyConfig(tenants=(
            TenantSpec(name="victim", max_queue=256),
            TenantSpec(name="aggressor", quota_rps=3.0, burst=2.0),
        ))
        with telemetry.Telemetry(sinks=[]):
            pool = WorkerPool(
                workload.model, workload.index_maps,
                runtime_config=RuntimeConfig(**RT_CFG), version=1,
            )
            supervisor = ReplicaSupervisor(
                pool=pool, n_replicas=2, probe_interval_s=0.05,
                probe_timeout_s=60.0, probe_failure_threshold=5,
            )
            service = ScoringService(supervisor, BatcherConfig(
                max_batch_size=8, max_wait_us=2_000, max_queue=256,
                tenancy=tenancy,
            ))
            with service:
                assert _wait_until(lambda: supervisor.healthy_count == 2)
                restarts_before = sum(
                    r["restarts"]
                    for r in supervisor.stats()["replicas"]
                )
                killer = threading.Timer(
                    0.5, lambda: supervisor.kill_replica(0)
                )
                killer.start()
                report = loadgen.run_noisy_neighbor(
                    service.submit,
                    lambda i, phase, tenant: _tagged(workload, i, tenant),
                    victim_rate_rps=30.0, aggressor_rate_rps=30.0,
                    scenario=_short_scenario(), timeout_s=60.0,
                )
                killer.join()
                assert report.victim.failed == 0, report.snapshot()
                assert report.victim.completed > 0
                assert report.aggressor.shed > 0, report.snapshot()
                assert _wait_until(
                    lambda: supervisor.healthy_count == 2
                ), supervisor.stats()
                assert sum(
                    r["restarts"]
                    for r in supervisor.stats()["replicas"]
                ) == restarts_before + 1
        assert shm_model.live_segments() == []
