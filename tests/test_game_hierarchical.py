"""Hierarchical GAME execution (ISSUE 20): the parity matrix.

Three claims, each pinned here:

- **Sharded is bitwise single-device.**  The bucket-shard plan
  (game/hierarchical.py) moves WHERE each block's program runs, never
  the shapes or the math, and the score scatter re-runs on one device
  in global block order — so the mesh-sharded coordinate (resident AND
  out-of-core) must reproduce the single-device coordinate bit for bit
  across per_user / per_item / per_context shapes.
- **Pipelined is bitwise serial.**  The overlap schedule
  (game/descent.py ``pipeline=True``) prestages only offset-independent
  host work; the Gauss-Seidel trajectory is untouched.
- **Repacked is numerical, NOT bitwise.**  The cost-model repacker
  (game/data.py) changes realized block shapes, and f32 reductions are
  not bitwise-stable under padding-length changes — so the repacked
  model is asserted allclose, while the PLAN itself is asserted fully
  deterministic and budget-feasible.

Plus the chaos seams: a kill at "game.bucket_shard" (mid-update device
dispatch) or "game.repack" (plan construction) must retry/resume to the
uninterrupted result bitwise (docs/robustness.md contract).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

from photon_ml_tpu import chaos
from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.game.coordinates import RandomEffectCoordinate
from photon_ml_tpu.game.data import (
    build_random_effect_dataset,
    plan_entity_buckets,
)
from photon_ml_tpu.game.descent import CoordinateDescent
from photon_ml_tpu.game.hierarchical import (
    ShardedBucketRandomEffectCoordinate,
    plan_bucket_shards,
)
from photon_ml_tpu.game.ooc_random import OutOfCoreRandomEffectCoordinate
from photon_ml_tpu.optim.problem import (
    GlmOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.optim.regularization import RegularizationContext
from photon_ml_tpu.parallel.distributed import data_mesh
from photon_ml_tpu.utils.watchdog import (
    RetryPolicy,
    RetryStats,
    run_with_retries,
)


def _bitwise(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return a.shape == b.shape and a.tobytes() == b.tobytes()


def _zipf_data(seed, n_entities=60, d=5, max_rows=40):
    """Long-tailed per-entity row counts: a multi-rung bucket ladder
    with a big head bucket (splits over the mesh) and a long tail
    (packs whole) — the shape mix the shard plan exists for."""
    rng = np.random.default_rng(seed)
    keys, rows, labels = [], [], []
    true_w = rng.normal(size=(n_entities, d))
    for e in range(n_entities):
        n_e = int(np.clip(rng.zipf(1.7), 1, max_rows))
        for _ in range(n_e):
            x = np.zeros(d, np.float32)
            nz = rng.choice(d, size=rng.integers(1, d + 1), replace=False)
            x[nz] = rng.normal(size=len(nz)).astype(np.float32)
            m = float(x @ true_w[e])
            keys.append(f"e{e}")
            rows.append(x)
            labels.append(float(rng.uniform() < 1 / (1 + np.exp(-m))))
    X = sp.csr_matrix(np.asarray(rows, np.float32))
    y = np.asarray(labels, np.float32)
    return keys, X, y, np.ones_like(y)


def _config():
    return GlmOptimizationConfig(
        optimizer=OptimizerConfig(max_iters=25, tolerance=1e-7),
        regularization=RegularizationContext.l2(),
    )


#: the parity matrix's coordinate axis — three entity populations with
#: different seeds/shapes (per_user wide tail, per_item narrower
#: features, per_context more features).
COORD_GRID = [
    ("per_user", dict(seed=3, n_entities=120, d=5)),
    ("per_item", dict(seed=5, n_entities=90, d=4)),
    ("per_context", dict(seed=9, n_entities=80, d=6)),
]


def _assert_states_match(st_ref, st_sharded, ref_blocks):
    """Sharded split blocks carry entity-padding lanes (appended); the
    real lanes must be bitwise the single-device state."""
    assert len(st_ref) == len(st_sharded)
    for a, b, blk in zip(st_ref, st_sharded, ref_blocks):
        a, b = np.asarray(a), np.asarray(b)
        assert b.shape[0] >= blk.n_entities
        assert _bitwise(a, b[: blk.n_entities])


# ---------------------------------------------------------------------------
# The shard plan itself
# ---------------------------------------------------------------------------

class TestBucketShardPlan:
    def test_plan_mixes_split_and_packed(self):
        keys, X, y, w = _zipf_data(seed=3, n_entities=120)
        ds = build_random_effect_dataset(keys, X, y, w, device=False)
        plan = plan_bucket_shards(ds.blocks, 8)
        assert len(plan.placements) == len(ds.blocks)
        assert plan.n_split >= 1, "head bucket should split"
        assert plan.n_packed >= 1, "tail buckets should pack"
        assert plan.imbalance_ratio >= 1.0
        # split blocks have at least one entity lane per device
        for p, b in zip(plan.placements, ds.blocks):
            if p[0] == "split":
                assert b.n_entities >= 8
            else:
                assert 0 <= p[1] < 8

    def test_plan_deterministic(self):
        keys, X, y, w = _zipf_data(seed=5)
        ds = build_random_effect_dataset(keys, X, y, w, device=False)
        p1 = plan_bucket_shards(ds.blocks, 8, split_factor=0.5)
        p2 = plan_bucket_shards(ds.blocks, 8, split_factor=0.5)
        assert p1 == p2

    def test_single_device_packs_everything(self):
        keys, X, y, w = _zipf_data(seed=3)
        ds = build_random_effect_dataset(keys, X, y, w, device=False)
        plan = plan_bucket_shards(ds.blocks, 1)
        assert plan.n_split == 0
        assert all(p == ("pack", 0) for p in plan.placements)

    def test_rejects_bad_device_count(self):
        with pytest.raises(ValueError, match="n_devices"):
            plan_bucket_shards([], 0)


# ---------------------------------------------------------------------------
# Sharded-vs-single bitwise parity: resident and out-of-core
# ---------------------------------------------------------------------------

class TestShardedParity:
    @pytest.mark.parametrize("name,shape", COORD_GRID)
    def test_resident_bitwise(self, name, shape, eight_devices):
        keys, X, y, w = _zipf_data(**shape)
        mesh = data_mesh(eight_devices)
        ref = RandomEffectCoordinate(
            name, build_random_effect_dataset(keys, X, y, w),
            "logistic", _config(), reg_weight=0.7,
        )
        sharded = ShardedBucketRandomEffectCoordinate(
            name, build_random_effect_dataset(keys, X, y, w, device=False),
            mesh, "logistic", _config(), reg_weight=0.7,
        )
        assert sharded.plan.n_split >= 1 and sharded.plan.n_packed >= 1
        offsets = jnp.asarray(
            np.random.default_rng(0).normal(size=len(y)).astype(np.float32)
        )
        st_ref = ref.train(offsets)
        st_sh = sharded.train(offsets)
        _assert_states_match(st_ref, st_sh, ref.dataset.blocks)
        assert _bitwise(ref.score(st_ref), sharded.score(st_sh))
        # warm-started second round: same contract
        st_ref2 = ref.train(offsets, warm_state=st_ref)
        st_sh2 = sharded.train(offsets, warm_state=st_sh)
        _assert_states_match(st_ref2, st_sh2, ref.dataset.blocks)
        assert _bitwise(ref.score(st_ref2), sharded.score(st_sh2))

    @pytest.mark.parametrize("name,shape", COORD_GRID)
    def test_out_of_core_bitwise(self, name, shape, eight_devices):
        keys, X, y, w = _zipf_data(**shape)
        mesh = data_mesh(eight_devices)
        ds = build_random_effect_dataset(keys, X, y, w, device=False)
        budget = 1 << 20  # far below the dataset: several pass groups

        def coord(m):
            return OutOfCoreRandomEffectCoordinate(
                name, ds, "logistic", _config(), reg_weight=0.7,
                device_budget_bytes=budget, mesh=m,
            )

        single, sharded = coord(None), coord(mesh)
        assert sharded.bucket_plan is not None
        offsets = jnp.asarray(
            np.random.default_rng(1).normal(size=len(y)).astype(np.float32)
        )
        st_s = single.train(offsets)
        st_m = sharded.train(offsets)
        assert len(st_s) == len(st_m)
        for a, b in zip(st_s, st_m):
            assert _bitwise(a, b)
        assert _bitwise(single.score(st_s), sharded.score(st_m))
        # warm round
        st_s2 = single.train(offsets, warm_state=st_s)
        st_m2 = sharded.train(offsets, warm_state=st_m)
        for a, b in zip(st_s2, st_m2):
            assert _bitwise(a, b)

    def test_sharded_coordinate_finalize_exact_entities(self, eight_devices):
        keys, X, y, w = _zipf_data(seed=3, n_entities=120)
        mesh = data_mesh(eight_devices)
        sharded = ShardedBucketRandomEffectCoordinate(
            "re", build_random_effect_dataset(keys, X, y, w, device=False),
            mesh, "logistic", _config(), reg_weight=0.7, entity_key="uid",
        )
        assert sharded.plan.n_split >= 1  # padded lanes exist to drop
        model = sharded.finalize(
            sharded.train(jnp.zeros(len(y), jnp.float32))
        )
        assert model.n_entities == 120  # padding lanes dropped

    def test_shard_imbalance_gauge_set(self, eight_devices):
        keys, X, y, w = _zipf_data(seed=3)
        mesh = data_mesh(eight_devices)
        with telemetry_mod.Telemetry(enabled=True, sinks=[]) as tel:
            sharded = ShardedBucketRandomEffectCoordinate(
                "re",
                build_random_effect_dataset(keys, X, y, w, device=False),
                mesh, "logistic", _config(),
            )
            g = tel.gauge("game_shard_imbalance_ratio").value
        assert g == sharded.plan.imbalance_ratio >= 1.0


# ---------------------------------------------------------------------------
# Cost-model repacker: deterministic plan, numerical-only model parity
# ---------------------------------------------------------------------------

class TestRepacker:
    def _counts(self, seed=7, n=400):
        rng = np.random.default_rng(seed)
        rows = np.clip(rng.zipf(1.6, size=n), 1, 200).astype(np.int64)
        cols = rng.integers(1, 30, size=n).astype(np.int64)
        return rows, cols

    def test_plan_deterministic(self):
        rows, cols = self._counts()
        p1 = plan_entity_buckets(rows, cols, program_budget=8, seed=0)
        p2 = plan_entity_buckets(rows, cols, program_budget=8, seed=0)
        assert _bitwise(p1.shapes, p2.shapes)
        assert _bitwise(p1.assignment, p2.assignment)
        assert p1.padded_flops == p2.padded_flops
        assert p1.exact_flops == p2.exact_flops

    def test_budget_and_fit_invariants(self):
        rows, cols = self._counts(seed=11)
        for budget in (1, 4, 16):
            plan = plan_entity_buckets(rows, cols, program_budget=budget)
            assert 1 <= len(plan.shapes) <= budget
            assert plan.padded_flops >= plan.exact_flops
            assert plan.assignment.shape == rows.shape
            assert plan.assignment.min() >= 0
            assert plan.assignment.max() < len(plan.shapes)
            # every entity fits the bucket it was assigned
            assert np.all(plan.shapes[plan.assignment, 0] >= rows)
            assert np.all(plan.shapes[plan.assignment, 1] >= cols)

    def test_more_budget_never_pads_more(self):
        # greedy agglomeration: a larger budget stops the merge sequence
        # earlier, and every merge only adds padding.
        rows, cols = self._counts(seed=13)
        padded = [
            plan_entity_buckets(rows, cols, program_budget=b).padded_flops
            for b in (2, 4, 8, 16)
        ]
        assert padded == sorted(padded, reverse=True)

    def test_dataset_block_count_within_budget(self):
        keys, X, y, w = _zipf_data(seed=3)
        ds = build_random_effect_dataset(
            keys, X, y, w, device=False, repack="cost_model",
            program_budget=4,
        )
        assert 1 <= len(ds.blocks) <= 4

    def test_dataset_build_deterministic(self):
        keys, X, y, w = _zipf_data(seed=5)
        kw = dict(device=False, repack="cost_model", program_budget=6)
        a = build_random_effect_dataset(keys, X, y, w, **kw)
        b = build_random_effect_dataset(keys, X, y, w, **kw)
        assert len(a.blocks) == len(b.blocks)
        for ba, bb in zip(a.blocks, b.blocks):
            for la, lb in zip(jax.tree.leaves(ba), jax.tree.leaves(bb)):
                assert _bitwise(la, lb)

    def test_repacked_model_matches_numerically(self):
        # The repacker changes realized block shapes, and f32 reductions
        # are not bitwise-stable under padding-length changes — so the
        # contract is NUMERICAL equivalence, not bitwise (contrast the
        # shard plan above).
        keys, X, y, w = _zipf_data(seed=3)
        offsets = jnp.asarray(
            np.random.default_rng(2).normal(size=len(y)).astype(np.float32)
        )
        scores = {}
        for repack in ("geometric", "cost_model"):
            ds = build_random_effect_dataset(
                keys, X, y, w, repack=repack, program_budget=8
            )
            coord = RandomEffectCoordinate(
                "re", ds, "logistic", _config(), reg_weight=0.7
            )
            scores[repack] = np.asarray(coord.score(coord.train(offsets)))
        np.testing.assert_allclose(
            scores["geometric"], scores["cost_model"], atol=1e-4
        )

    def test_padding_gauge_and_bad_policy(self):
        keys, X, y, w = _zipf_data(seed=5)
        with telemetry_mod.Telemetry(enabled=True, sinks=[]) as tel:
            build_random_effect_dataset(
                keys, X, y, w, device=False, repack="cost_model",
                program_budget=8,
            )
            ratio = tel.gauge("game_bucket_padding_ratio").value
        assert ratio >= 1.0
        with pytest.raises(ValueError, match="repack"):
            build_random_effect_dataset(
                keys, X, y, w, device=False, repack="bogus"
            )


# ---------------------------------------------------------------------------
# Pipelined coordinate descent: bitwise the serial schedule
# ---------------------------------------------------------------------------

def _two_coordinate_problem():
    """Two random effects over the same rows — one resident, one
    out-of-core (the prestage beneficiary) — so the pipelined schedule
    has real host work to overlap."""
    rng = np.random.default_rng(17)
    n, d = 400, 4
    X = sp.random(n, d, density=0.6, random_state=4, format="csr",
                  dtype=np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    w = np.ones(n, np.float32)
    users = [f"u{u}" for u in rng.integers(12, size=n)]
    items = [f"i{i}" for i in rng.integers(25, size=n)]
    resident = RandomEffectCoordinate(
        "per_item", build_random_effect_dataset(items, X, y, w),
        "logistic", _config(), reg_weight=0.5,
    )
    ooc = OutOfCoreRandomEffectCoordinate(
        "per_user",
        build_random_effect_dataset(users, X, y, w, device=False),
        "logistic", _config(), reg_weight=0.5,
        device_budget_bytes=1 << 16,  # several pass groups
    )
    return [resident, ooc], n


class TestPipelinedDescent:
    def test_trajectory_bitwise_identical_to_serial(self):
        def run(pipeline):
            coords, n = _two_coordinate_problem()
            return CoordinateDescent(coords, pipeline=pipeline).run(
                jnp.zeros(n, jnp.float32), n_iterations=3
            )

        serial, piped = run(False), run(True)
        for name in serial.states:
            assert _bitwise(serial.scores[name], piped.scores[name])
            for a, b in zip(serial.states[name], piped.states[name]):
                assert _bitwise(a, b)
        assert len(serial.history) == len(piped.history)
        for es, ep in zip(serial.history, piped.history):
            assert es["iteration"] == ep["iteration"]
            assert es["coordinate"] == ep["coordinate"]
            assert _bitwise(es["score_norm"], ep["score_norm"])

    def test_overlap_counter_accumulates(self):
        coords, n = _two_coordinate_problem()
        with telemetry_mod.Telemetry(enabled=True, sinks=[]) as tel:
            CoordinateDescent(coords, pipeline=True).run(
                jnp.zeros(n, jnp.float32), n_iterations=2
            )
            overlap = tel.counter("game_coordinate_overlap_seconds").value
        assert overlap > 0.0

    def test_estimator_pipeline_flag_bitwise(self):
        from photon_ml_tpu.game.estimator import (
            FixedEffectCoordinateConfig,
            GameEstimator,
            RandomEffectCoordinateConfig,
        )

        rng = np.random.default_rng(13)
        n, n_users = 300, 10
        Xg = rng.normal(size=(n, 3)).astype(np.float32)
        users = rng.integers(n_users, size=n)
        margin = 1.3 * Xg[:, 0] - 0.7 * Xg[:, 1]
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(
            np.float32
        )
        shards = {
            "global": sp.csr_matrix(Xg),
            "userFeatures": sp.csr_matrix(np.ones((n, 1), np.float32)),
        }
        ids = {"userId": np.array([f"u{u}" for u in users])}
        configs = {
            "fixed": FixedEffectCoordinateConfig(
                feature_shard="global", optimization=_config(),
                reg_weight=0.5,
            ),
            "per_user": RandomEffectCoordinateConfig(
                feature_shard="userFeatures", entity_key="userId",
                optimization=_config(), reg_weight=0.5,
                device_budget_bytes=1 << 14,  # out-of-core: prestage real
            ),
        }

        def fit(pipeline):
            return GameEstimator(
                "logistic", configs, n_iterations=2, pipeline=pipeline
            ).fit(shards, ids, y)

        (m_serial, _), (m_piped, _) = fit(False), fit(True)
        assert _bitwise(
            m_serial["fixed"].model.coefficients.means,
            m_piped["fixed"].model.coefficients.means,
        )
        cs, cp = (m["per_user"].coefficients for m in (m_serial, m_piped))
        assert set(cs) == set(cp)
        for k in cs:
            assert _bitwise(cs[k][1], cp[k][1])


# ---------------------------------------------------------------------------
# Chaos seams: kill at the dispatch/plan sites, resume bitwise
# ---------------------------------------------------------------------------

class TestChaosSites:
    def test_bucket_shard_kill_midupdate_retry_bitwise_resident(
        self, eight_devices
    ):
        keys, X, y, w = _zipf_data(seed=3)
        mesh = data_mesh(eight_devices)
        sharded = ShardedBucketRandomEffectCoordinate(
            "re", build_random_effect_dataset(keys, X, y, w, device=False),
            mesh, "logistic", _config(), reg_weight=0.7,
        )
        offsets = jnp.asarray(
            np.random.default_rng(3).normal(size=len(y)).astype(np.float32)
        )
        clean = sharded.train(offsets)
        # kill at the SECOND dispatch group: the first group's device
        # programs are already in flight when the update aborts.
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="game.bucket_shard", at=1),
        ])
        with plan:
            with pytest.raises(chaos.InjectedFault):
                sharded.train(offsets)
            retried = sharded.train(offsets)
        assert len(plan.fired_at("game.bucket_shard")) == 1
        for a, b in zip(clean, retried):
            assert _bitwise(a, b)

    def test_bucket_shard_kill_retry_bitwise_out_of_core(
        self, eight_devices
    ):
        keys, X, y, w = _zipf_data(seed=5)
        mesh = data_mesh(eight_devices)
        ooc = OutOfCoreRandomEffectCoordinate(
            "re", build_random_effect_dataset(keys, X, y, w, device=False),
            "logistic", _config(), reg_weight=0.7,
            device_budget_bytes=1 << 20, mesh=mesh,
        )
        offsets = jnp.zeros(len(y), jnp.float32)
        clean = ooc.train(offsets)
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="game.bucket_shard", at=0),
        ])
        with plan:
            with pytest.raises(chaos.InjectedFault):
                ooc.train(offsets)
            retried = ooc.train(offsets)
        assert len(plan.fired_at("game.bucket_shard")) == 1
        for a, b in zip(clean, retried):
            assert _bitwise(a, b)

    def test_repack_kill_rebuild_bitwise(self):
        keys, X, y, w = _zipf_data(seed=9, n_entities=30, d=6)
        kw = dict(device=False, repack="cost_model", program_budget=6)
        clean = build_random_effect_dataset(keys, X, y, w, **kw)
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="game.repack", at=0),
        ])
        with plan:
            with pytest.raises(chaos.InjectedFault):
                build_random_effect_dataset(keys, X, y, w, **kw)
            rebuilt = build_random_effect_dataset(keys, X, y, w, **kw)
        fired = plan.fired_at("game.repack")
        assert len(fired) == 1 and fired[0]["n_entities"] == 30
        assert len(clean.blocks) == len(rebuilt.blocks)
        for ba, bb in zip(clean.blocks, rebuilt.blocks):
            for la, lb in zip(jax.tree.leaves(ba), jax.tree.leaves(bb)):
                assert _bitwise(la, lb)

    def test_estimator_survives_bucket_shard_kill(self, eight_devices):
        # the full kill/resume loop: a watchdog retry after a fault in
        # the sharded dispatch must land on the unfaulted model bitwise.
        from photon_ml_tpu.game.estimator import (
            FixedEffectCoordinateConfig,
            GameEstimator,
            RandomEffectCoordinateConfig,
        )

        rng = np.random.default_rng(23)
        n, n_users = 240, 9
        Xg = rng.normal(size=(n, 3)).astype(np.float32)
        users = rng.integers(n_users, size=n)
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        shards = {
            "global": sp.csr_matrix(Xg),
            "userFeatures": sp.csr_matrix(np.ones((n, 1), np.float32)),
        }
        ids = {"userId": np.array([f"u{u}" for u in users])}
        configs = {
            "fixed": FixedEffectCoordinateConfig(
                feature_shard="global", optimization=_config(),
                reg_weight=0.5,
            ),
            "per_user": RandomEffectCoordinateConfig(
                feature_shard="userFeatures", entity_key="userId",
                optimization=_config(), reg_weight=0.5,
            ),
        }
        mesh = data_mesh(eight_devices)

        def fit():
            return GameEstimator(
                "logistic", configs, n_iterations=2, mesh=mesh
            ).fit(shards, ids, y)

        model_full, _ = fit()
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="game.bucket_shard", at=0),
        ])
        stats = RetryStats()
        with plan:
            model_res, _ = run_with_retries(
                lambda a: fit(), RetryPolicy(max_retries=1),
                sleep=lambda s: None, stats=stats,
            )
        assert stats.retries == 1
        assert _bitwise(
            model_full["fixed"].model.coefficients.means,
            model_res["fixed"].model.coefficients.means,
        )
        cf = model_full["per_user"].coefficients
        cr = model_res["per_user"].coefficients
        assert set(cf) == set(cr)
        for k in cf:
            assert _bitwise(cf[k][1], cr[k][1])
