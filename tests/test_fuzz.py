"""Seeded randomized sweeps (deterministic, not flaky).

Mirrors the reference's breadth of integration coverage with generated
shapes instead of hand-picked ones: the Pallas layout against the COO
oracle across adversarial sparsity structures, and full GAME
fit→score→save→load round trips across random coordinate configurations.
"""

import os
import zlib

import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

os.environ.setdefault("PHOTON_PALLAS_INTERPRET", "1")

from photon_ml_tpu.ops.sparse import from_coo
from photon_ml_tpu.ops.sparse_pallas import build_pallas_matrix


def _rel(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return np.abs(a - b).max() / max(1e-6, np.abs(b).max())


def _layout_case(rng, case):
    """One adversarial sparsity structure per case id."""
    n = int(rng.integers(64, 5000))
    d = int(rng.integers(50, 4500))
    base = int(rng.integers(1, 30)) * n // 4
    rows = rng.integers(0, n, size=base).astype(np.int64)
    cols = rng.integers(0, d, size=base).astype(np.int64)
    vals = rng.normal(size=base).astype(np.float32)
    if case == "zipf_cols":  # popularity-skewed columns
        cols = np.minimum((rng.zipf(1.3, base) - 1), d - 1).astype(np.int64)
    elif case == "dense_col":
        rows = np.concatenate([rows, np.arange(n, dtype=np.int64)])
        cols = np.concatenate([cols, np.full(n, d // 2, np.int64)])
        vals = np.concatenate([vals, rng.normal(size=n).astype(np.float32)])
    elif case == "dense_row":
        k = min(d, 600)
        rows = np.concatenate([rows, np.full(k, n // 3, np.int64)])
        cols = np.concatenate([cols, np.arange(k, dtype=np.int64)])
        vals = np.concatenate([vals, rng.normal(size=k).astype(np.float32)])
    elif case == "duplicates":  # repeated coordinates must sum
        take = rng.integers(0, base, size=base // 2)
        rows = np.concatenate([rows, rows[take]])
        cols = np.concatenate([cols, cols[take]])
        vals = np.concatenate([vals, rng.normal(size=len(take)).astype(np.float32)])
    elif case == "banded":  # clustered diagonal structure
        rows = np.arange(base, dtype=np.int64) % n
        cols = ((rows * d) // n + rng.integers(-3, 4, size=base)) % d
    elif case == "explicit_zeros":
        vals[rng.uniform(size=len(vals)) < 0.3] = 0.0
    return rows, cols, vals, n, d


class TestPallasLayoutFuzz:
    @pytest.mark.parametrize(
        "case",
        ["uniform", "zipf_cols", "dense_col", "dense_row", "duplicates",
         "banded", "explicit_zeros"],
    )
    def test_all_four_ops_match_coo(self, case):
        rng = np.random.default_rng(zlib.crc32(case.encode()))
        rows, cols, vals, n, d = _layout_case(rng, case)
        P = build_pallas_matrix(rows, cols, vals, n, d, depth_cap=64)
        C = from_coo(rows, cols, vals, n, d)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        u = jnp.asarray(rng.normal(size=n).astype(np.float32))
        assert _rel(P.matvec(w), C.matvec(w)) < 1e-4, case
        assert _rel(P.rmatvec(u), C.rmatvec(u)) < 1e-4, case
        assert _rel(P.row_sq_matvec(w), C.row_sq_matvec(w)) < 1e-4, case
        assert _rel(P.sq_rmatvec(u), C.sq_rmatvec(u)) < 1e-4, case
        # Cold paths (host-side) agree too.
        mask = jnp.asarray((rng.uniform(size=n) > 0.1).astype(np.float32)) > 0
        np.testing.assert_array_equal(
            np.asarray(P.col_nnz(mask)), np.asarray(C.col_nnz(mask)), case
        )


class TestGameConfigFuzz:
    @pytest.mark.parametrize(
        "seed,force_factored",
        [(101, False), (202, False), (303, False), (404, True), (505, True)],
    )
    def test_random_config_end_to_end(self, seed, force_factored, tmp_path):
        from photon_ml_tpu.data.index_map import IndexMap
        from photon_ml_tpu.game.estimator import (
            FixedEffectCoordinateConfig,
            GameEstimator,
            GameTransformer,
            RandomEffectCoordinateConfig,
        )
        from photon_ml_tpu.io.game_store import (
            load_game_model,
            save_game_model,
        )
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            OptimizerConfig,
            OptimizerType,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        rng = np.random.default_rng(seed)
        task = rng.choice(["logistic", "squared", "poisson"])
        n = int(rng.integers(150, 500))
        d_global = int(rng.integers(2, 8))
        n_users = int(rng.integers(4, 25))
        n_items = int(rng.integers(3, 12))

        Xg = rng.normal(size=(n, d_global)).astype(np.float32)
        users = rng.integers(n_users, size=n)
        items = rng.integers(n_items, size=n)
        margin = Xg[:, 0] + 0.5 * rng.normal(scale=1.0, size=n_users)[users]
        if task == "logistic":
            y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(
                np.float32
            )
        elif task == "poisson":
            y = rng.poisson(np.exp(np.clip(margin, -3, 2))).astype(np.float32)
        else:
            y = margin.astype(np.float32)

        shards = {
            "global": sp.csr_matrix(Xg),
            "userFeatures": sp.csr_matrix(np.ones((n, 1), np.float32)),
            "itemFeatures": sp.csr_matrix(
                rng.normal(size=(n, 2)).astype(np.float32)
            ),
        }
        ids = {
            "userId": np.array([f"u{u}" for u in users]),
            "itemId": np.array([f"i{i}" for i in items]),
        }

        def rand_opt():
            opt_type = rng.choice(["lbfgs", "owlqn", "tron"])
            reg = rng.choice(["none", "l1", "l2", "elastic_net"])
            if opt_type == "tron" and reg in ("l1", "elastic_net"):
                reg = "l2"  # static routing would send it to OWL-QN anyway
            return GlmOptimizationConfig(
                optimizer=OptimizerConfig(
                    optimizer=OptimizerType(opt_type),
                    max_iters=int(rng.integers(5, 25)),
                ),
                regularization={
                    "none": RegularizationContext.none(),
                    "l1": RegularizationContext.l1(),
                    "l2": RegularizationContext.l2(),
                    "elastic_net": RegularizationContext.elastic_net(0.5),
                }[reg],
            )

        configs = {
            "fixed": FixedEffectCoordinateConfig(
                "global", rand_opt(), float(rng.uniform(0.1, 2.0)),
                down_sampling_rate=(
                    float(rng.uniform(0.5, 1.0)) if task == "logistic" else 1.0
                ),
            ),
            "per_user": RandomEffectCoordinateConfig(
                "userFeatures", "userId", rand_opt(),
                float(rng.uniform(0.1, 2.0)),
                max_rows_per_entity=(
                    int(rng.integers(4, 64)) if rng.uniform() < 0.5 else None
                ),
                bucket_growth=float(rng.choice([2.0, 3.0, 4.0])),
            ),
        }
        if force_factored or rng.uniform() < 0.5:
            from photon_ml_tpu.game.estimator import (
                FactoredRandomEffectCoordinateConfig,
            )

            # Sometimes the item effect is FACTORED (w_e = V u_e) — it
            # must compose with every optimizer/regularization draw and
            # round-trip through the standard model store.  Two seeds
            # force it so coverage doesn't depend on the draws.
            if force_factored or rng.uniform() < 0.5:
                configs["per_item"] = FactoredRandomEffectCoordinateConfig(
                    "itemFeatures", "itemId",
                    rank=int(rng.integers(1, 3)),
                    optimization=rand_opt(),
                    reg_weight=float(rng.uniform(0.1, 2.0)),
                    alternations=int(rng.integers(1, 3)),
                )
            else:
                configs["per_item"] = RandomEffectCoordinateConfig(
                    "itemFeatures", "itemId", rand_opt(),
                    float(rng.uniform(0.1, 2.0)),
                )

        est = GameEstimator(
            str(task), configs, n_iterations=int(rng.integers(1, 3))
        )
        model, history = est.fit(shards, ids, y)
        assert all(np.isfinite(h["train_metric"]) for h in history)

        scores = GameTransformer(model).transform(shards, ids)
        assert np.all(np.isfinite(scores))

        imaps = {
            "global": IndexMap.build([f"g{j}" for j in range(d_global)]),
            "userFeatures": IndexMap.build(["ub"]),
            "itemFeatures": IndexMap.build(["i0", "i1"]),
        }
        out = str(tmp_path / "m")
        save_game_model(model, imaps, out)
        loaded, _ = load_game_model(out)
        scores2 = GameTransformer(loaded).transform(shards, ids)
        np.testing.assert_allclose(scores2, scores, atol=1e-5)


class TestStreamingFuzz:
    """Seeded sweeps over the out-of-core surface: random chunk
    geometry × optimizer × accumulation × layout, each fit pinned
    against the resident solver on the same data."""

    @pytest.mark.parametrize("seed", [11, 22, 33, 44])
    def test_random_stream_fit_matches_resident(self, seed, tmp_path):
        from photon_ml_tpu.data.dataset import make_glm_data
        from photon_ml_tpu.data.streaming import make_streaming_glm_data
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            GlmOptimizationProblem,
            OptimizerConfig,
            OptimizerType,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext
        from photon_ml_tpu.optim.streaming import streaming_run_grid

        rng = np.random.default_rng(seed)
        n = int(rng.integers(200, 1200))
        d = int(rng.integers(8, 60))
        density = float(rng.uniform(0.05, 0.4))
        X = sp.random(n, d, density=density, random_state=seed,
                      format="csr", dtype=np.float32)
        w_true = rng.normal(size=d).astype(np.float32)
        logits = np.asarray(X @ w_true).ravel()
        task = rng.choice(["logistic", "linear", "poisson"])
        if task == "logistic":
            y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(
                np.float32
            )
        elif task == "linear":
            y = (logits + rng.normal(size=n) * 0.1).astype(np.float32)
        else:
            y = rng.poisson(np.exp(np.clip(logits, -4, 3))).astype(
                np.float32
            )
        optimizer = rng.choice([
            OptimizerType.LBFGS, OptimizerType.TRON, OptimizerType.OWLQN
        ])
        reg = (
            RegularizationContext.l1()
            if optimizer is OptimizerType.OWLQN
            else RegularizationContext.l2()
        )
        problem = GlmOptimizationProblem(
            task,
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(
                    optimizer=optimizer, max_iters=80, tolerance=1e-8
                ),
                regularization=reg,
            ),
        )
        lam = float(rng.choice([0.3, 1.0, 4.0]))
        grid_r = problem.run_grid(make_glm_data(X, y), [lam])
        chunk_rows = int(rng.integers(50, n + 50))
        stream = make_streaming_glm_data(
            X, y, chunk_rows=chunk_rows,
            use_pallas=bool(rng.integers(2)),
            depth_cap=32,
            # Disk-backed residency is a pure re-residency of the same
            # arrays — same tolerances, coin-flipped into the sweep.
            storage_dir=(
                str(tmp_path / "spill") if rng.integers(2) else None
            ),
        )
        grid_s = streaming_run_grid(
            problem, stream, [lam],
            accumulate=str(rng.choice(["f32", "kahan"])),
        )
        w_r = np.asarray(grid_r[0][1].coefficients.means)
        w_s = np.asarray(grid_s[0][1].coefficients.means)
        scale = max(1.0, float(np.abs(w_r).max()))
        np.testing.assert_allclose(
            w_s, w_r, atol=6e-3 * scale,
            err_msg=f"task={task} opt={optimizer} chunk_rows={chunk_rows}",
        )


class TestOutOfCoreRandomEffectFuzz:
    """Seeded sweeps over the OOC random-effect surface: random entity
    geometry × budget × plain/factored, each trained against the
    resident coordinate on the same data (same solvers, different
    residency — parity is the whole contract)."""

    @pytest.mark.parametrize("seed", [5, 17, 29])
    def test_random_geometry_matches_resident(self, seed, tmp_path):
        from photon_ml_tpu.data.streaming import spill_random_effect_dataset
        from photon_ml_tpu.game.coordinates import RandomEffectCoordinate
        from photon_ml_tpu.game.data import build_random_effect_dataset
        from photon_ml_tpu.game.factored import (
            FactoredRandomEffectCoordinate,
        )
        from photon_ml_tpu.game.ooc_factored import (
            OutOfCoreFactoredRandomEffectCoordinate,
        )
        from photon_ml_tpu.game.ooc_random import (
            OutOfCoreRandomEffectCoordinate,
        )
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        rng = np.random.default_rng(seed)
        n_entities = int(rng.integers(20, 80))
        d = int(rng.integers(2, 10))
        cap = int(rng.integers(6, 40)) if rng.integers(2) else None
        keys, rows_l, y_l = [], [], []
        for e in range(n_entities):
            n_e = int(np.clip(rng.zipf(1.6), 1, 60))
            Xe = rng.normal(size=(n_e, d)).astype(np.float32)
            m = Xe @ rng.normal(size=d).astype(np.float32)
            keys.extend([f"e{e}"] * n_e)
            rows_l.append(Xe)
            y_l.append(
                (rng.uniform(size=n_e) < 1 / (1 + np.exp(-m))).astype(
                    np.float32
                )
            )
        X = sp.csr_matrix(np.concatenate(rows_l))
        y = np.concatenate(y_l)
        w = np.ones_like(y)
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=20, tolerance=1e-7),
            regularization=RegularizationContext.l2(),
        )
        kw = dict(max_rows_per_entity=cap, bucket_growth=2.0)
        resident_ds = build_random_effect_dataset(keys, X, y, w, **kw)
        host_ds = build_random_effect_dataset(
            keys, X, y, w, device=False, **kw
        )
        if rng.integers(2):  # coin-flip the disk rung into the sweep
            host_ds = spill_random_effect_dataset(
                host_ds, str(tmp_path / "re")
            )
        budget = int(rng.integers(6_000, 60_000))
        offsets = jnp.asarray(
            rng.normal(size=len(y)).astype(np.float32) * 0.3
        )
        factored = bool(rng.integers(2))
        if factored:
            rank = int(rng.integers(1, min(d, 3) + 1))
            res = FactoredRandomEffectCoordinate(
                "re", resident_ds, "logistic", opt, rank=rank,
                reg_weight=0.5, alternations=2, entity_key="k",
            )
            ooc = OutOfCoreFactoredRandomEffectCoordinate(
                "re", host_ds, "logistic", opt, rank=rank,
                reg_weight=0.5, alternations=2, entity_key="k",
                device_budget_bytes=budget,
            )
            tol = dict(rtol=1e-2, atol=1e-2)
        else:
            res = RandomEffectCoordinate(
                "re", resident_ds, "logistic", opt, reg_weight=0.5,
            )
            ooc = OutOfCoreRandomEffectCoordinate(
                "re", host_ds, "logistic", opt, reg_weight=0.5,
                device_budget_bytes=budget,
            )
            tol = dict(atol=1e-4)
        st_r = res.train(offsets)
        st_o = ooc.train(offsets)
        np.testing.assert_allclose(
            np.asarray(res.score(st_r)), np.asarray(ooc.score(st_o)),
            err_msg=(
                f"factored={factored} budget={budget} cap={cap} "
                f"entities={n_entities} d={d}"
            ),
            **tol,
        )
