"""Distributed solver subsystem tests (ISSUE 18).

Three pillars:

1. **Registry** — dispatch mechanics, legacy-routing reproduction (an
   unset ``OptimizerConfig.solver`` must be BITWISE identical to the
   pre-registry static if-chains on the resident, streamed, and
   distributed paths), and the static compatibility guards.
2. **Host-kind solvers** — consensus-ADMM (L-BFGS and cached-eigh ridge
   x-updates, logical shards AND the 8-virtual-device mesh) and
   drift-corrected distributed block CD converge to the same optimum as
   the resident reference solvers.
3. **Chaos** — a kill at ``admm.consensus`` (the outer-iteration
   boundary) or ``distributed.allreduce`` (the reduce seam) resumes
   BITWISE through the GridCheckpointer + watchdog, mirroring
   test_chaos's crash-at-every-boundary bar.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu import chaos
from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.data.dataset import make_glm_data
from photon_ml_tpu.io.checkpoint import GridCheckpointer
from photon_ml_tpu.optim.problem import (
    GlmOptimizationConfig,
    GlmOptimizationProblem,
    OptimizerConfig,
    OptimizerType,
)
from photon_ml_tpu.optim.regularization import RegularizationContext
from photon_ml_tpu.parallel.distributed import (
    data_mesh,
    run_grid_distributed,
    shard_glm_data,
)
from photon_ml_tpu.solvers import registry
from photon_ml_tpu.solvers import sharded as solvers_sharded
from photon_ml_tpu.utils.watchdog import RetryPolicy, run_with_retries


def _bitwise_equal(a, b):
    a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
    return a.shape == b.shape and a.tobytes() == b.tobytes()


def _make_xy(rng, n=240, d=10, task="logistic"):
    X = (rng.normal(size=(n, d)) / np.sqrt(d)).astype(np.float32)
    w_true = (rng.normal(size=d) * (rng.uniform(size=d) < 0.5)).astype(
        np.float32
    )
    if task == "logistic":
        p = 1.0 / (1.0 + np.exp(-3.0 * (X @ w_true)))
        y = (rng.uniform(size=n) < p).astype(np.float32)
    else:
        y = (X @ w_true + 0.1 * rng.normal(size=n)).astype(np.float32)
    return X, y


def _make_problem(
    task="logistic",
    reg=None,
    solver=None,
    solver_options=(),
    optimizer=OptimizerType.LBFGS,
    max_iters=150,
):
    return GlmOptimizationProblem(task, GlmOptimizationConfig(
        optimizer=OptimizerConfig(
            optimizer=optimizer, max_iters=max_iters, tolerance=1e-8,
            solver=solver, solver_options=solver_options,
        ),
        regularization=(
            reg if reg is not None else RegularizationContext.l2()
        ),
    ))


def _objective_value(problem, data, w, lam):
    cfg = problem.config
    l1 = cfg.regularization.l1_weight(lam)
    l2 = cfg.regularization.l2_weight(lam)
    m = data.features.matvec(jnp.asarray(w, jnp.float32)) + data.offsets
    loss = jnp.sum(
        data.weights * problem.objective.loss.value(m, data.labels)
    )
    return float(
        loss + l1 * jnp.sum(jnp.abs(jnp.asarray(w)))
        + 0.5 * l2 * jnp.vdot(jnp.asarray(w), jnp.asarray(w))
    )


# ---------------------------------------------------------------------------
# Registry mechanics
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_builtins_registered(self):
        assert {"lbfgs", "owlqn", "tron", "spg", "admm", "block_cd"} <= set(
            registry.names()
        )

    def test_duplicate_refused_replace_allowed(self):
        defn = registry.SolverDef(
            name="scratch_test_solver", kind="jit",
            description="test double", resident=lambda ctx: None,
        )
        registry.register(defn)
        with pytest.raises(ValueError, match="already registered"):
            registry.register(defn)
        registry.register(defn, replace=True)  # tests may swap doubles
        assert registry.get("scratch_test_solver") is defn

    def test_def_validation(self):
        with pytest.raises(ValueError, match="kind"):
            registry.SolverDef(name="x", kind="weird", description="")
        with pytest.raises(ValueError, match="resident"):
            registry.SolverDef(name="x", kind="jit", description="")
        with pytest.raises(ValueError, match="sharded"):
            registry.SolverDef(name="x", kind="host", description="")

    def test_unknown_name(self):
        with pytest.raises(KeyError, match="unknown solver"):
            registry.get("levenberg")

    def test_legacy_routing(self):
        opt = OptimizerConfig(optimizer=OptimizerType.TRON)
        assert registry.resolve(opt, l1_frac=0.0).name == "tron"
        assert registry.resolve(opt, l1_frac=0.5).name == "owlqn"
        assert registry.resolve(
            opt, l1_frac=0.0, has_bounds=True
        ).name == "spg"

    def test_explicit_name_guards(self):
        lbfgs = OptimizerConfig(solver="lbfgs")
        with pytest.raises(ValueError, match="no L1 subgradient"):
            registry.resolve(lbfgs, l1_frac=0.5)
        with pytest.raises(ValueError, match="box constraints"):
            registry.resolve(lbfgs, l1_frac=0.0, has_bounds=True)
        with pytest.raises(ValueError, match="needs box constraints"):
            registry.resolve(
                OptimizerConfig(solver="spg"), l1_frac=0.0
            )
        admm = OptimizerConfig(solver="admm")
        assert registry.resolve(admm, l1_frac=0.5).name == "admm"
        with pytest.raises(ValueError, match="box constraints"):
            registry.resolve(admm, l1_frac=0.0, has_bounds=True)

    def test_solver_options_dict(self):
        opt = OptimizerConfig(
            solver="admm", solver_options=(("rho", "0.5"), ("shards", "4"))
        )
        assert registry.solver_options_dict(opt) == {
            "rho": "0.5", "shards": "4"
        }
        assert registry.solver_options_dict(OptimizerConfig()) == {}

    def test_host_kind_rejected_in_traced_solve(self, rng):
        X, y = _make_xy(rng)
        data = make_glm_data(X, y)
        problem = _make_problem(
            reg=RegularizationContext.elastic_net(0.5), solver="admm"
        )
        with pytest.raises(ValueError, match="host-side outer loop"):
            problem.solve(data, 0.1)


# ---------------------------------------------------------------------------
# Registry dispatch = pre-registry routing, bitwise
# ---------------------------------------------------------------------------

class TestDispatchParity:
    """An EXPLICIT solver name must be bitwise identical to the implicit
    legacy routing on every execution path (the registry builds exactly
    the closures the static if-chains built)."""

    @pytest.mark.parametrize("name,optimizer,reg", [
        ("lbfgs", OptimizerType.LBFGS, RegularizationContext.l2()),
        ("tron", OptimizerType.TRON, RegularizationContext.l2()),
        ("owlqn", OptimizerType.LBFGS,
         RegularizationContext.elastic_net(0.5)),
    ])
    def test_resident_bitwise(self, rng, name, optimizer, reg):
        X, y = _make_xy(rng)
        data = make_glm_data(X, y)
        implicit = _make_problem(reg=reg, optimizer=optimizer)
        explicit = _make_problem(reg=reg, optimizer=optimizer, solver=name)
        res_i = implicit.solve_single_device(data, 0.3)
        res_e = explicit.solve_single_device(data, 0.3)
        assert _bitwise_equal(res_i.w, res_e.w)
        assert int(res_i.iterations) == int(res_e.iterations)

    @pytest.mark.parametrize("name,reg", [
        ("lbfgs", RegularizationContext.l2()),
        ("owlqn", RegularizationContext.elastic_net(0.5)),
    ])
    def test_streamed_bitwise(self, rng, name, reg):
        from photon_ml_tpu.data.streaming import make_streaming_glm_data
        from photon_ml_tpu.optim.streaming import streaming_run_grid

        X, y = _make_xy(rng)
        stream = make_streaming_glm_data(X, y, chunk_rows=64)
        grid = [1.0, 0.1]
        imp = streaming_run_grid(_make_problem(reg=reg), stream, grid)
        exp = streaming_run_grid(
            _make_problem(reg=reg, solver=name), stream, grid
        )
        for (lam_i, m_i, _), (lam_e, m_e, _) in zip(imp, exp):
            assert lam_i == lam_e
            assert _bitwise_equal(
                m_i.coefficients.means, m_e.coefficients.means
            )

    def test_distributed_bitwise(self, rng, eight_devices):
        X, y = _make_xy(rng)
        mesh = data_mesh(eight_devices)
        dist = shard_glm_data(X, y, mesh)
        reg = RegularizationContext.elastic_net(0.5)
        grid = [0.1]
        imp = run_grid_distributed(
            _make_problem(reg=reg), dist, mesh, grid
        )
        exp = run_grid_distributed(
            _make_problem(reg=reg, solver="owlqn"), dist, mesh, grid
        )
        for (_, m_i, _), (_, m_e, _) in zip(imp, exp):
            assert _bitwise_equal(
                m_i.coefficients.means, m_e.coefficients.means
            )


# ---------------------------------------------------------------------------
# Consensus ADMM
# ---------------------------------------------------------------------------

class TestADMM:
    def test_logical_shards_match_owlqn(self, rng):
        """ADMM over 4 logical shards lands within 1e-5 relative objective
        of the resident OWL-QN optimum on an elastic-net logistic fit."""
        X, y = _make_xy(rng, n=256, d=10)
        reg = RegularizationContext.elastic_net(0.5)
        data = make_glm_data(X, y)
        ref_problem = _make_problem(reg=reg)
        grid = [0.3, 0.1]
        ref = {
            lam: np.asarray(m.coefficients.means)
            for lam, m, _ in ref_problem.run_grid(data, grid)
        }
        admm_problem = _make_problem(
            reg=reg, solver="admm",
            solver_options=(("reltol", "1e-6"), ("shards", "4")),
        )
        dist = shard_glm_data(X, y, None, n_shards=4)
        results = solvers_sharded.run_grid_sharded(
            admm_problem, dist, None, grid
        )
        for lam, model, res in results:
            w = np.asarray(model.coefficients.means)
            f_ref = _objective_value(ref_problem, data, ref[lam], lam)
            f_admm = _objective_value(ref_problem, data, w, lam)
            gap = abs(f_admm - f_ref) / max(1.0, abs(f_ref))
            assert gap <= 1e-5, f"λ={lam}: relative gap {gap:.2e}"
            assert bool(res.converged)

    def test_ridge_closed_form_path(self, rng):
        """Squared-loss task takes the cached-eigendecomposition x-update;
        the local L-BFGS path must agree with it (same consensus optimum)."""
        X, y = _make_xy(rng, n=200, d=6, task="linear")
        reg = RegularizationContext.elastic_net(0.5)
        data = make_glm_data(X, y)
        dist = shard_glm_data(X, y, None, n_shards=4)
        ws = {}
        for local in ("ridge", "lbfgs"):
            problem = _make_problem(
                task="linear", reg=reg, solver="admm",
                solver_options=(
                    ("reltol", "1e-6"), ("local_solver", local),
                    ("max_outer", "400"),
                ),
            )
            [(_, model, res)] = solvers_sharded.run_grid_sharded(
                problem, dist, None, [0.2]
            )
            assert bool(res.converged)
            ws[local] = np.asarray(model.coefficients.means)
        f_r = _objective_value(problem, data, ws["ridge"], 0.2)
        f_l = _objective_value(problem, data, ws["lbfgs"], 0.2)
        assert abs(f_r - f_l) / max(1.0, abs(f_l)) < 1e-5

    def test_mesh_matches_logical(self, rng, eight_devices):
        """The shard_map/psum step and the vmap/axis-sum step are the same
        math: an 8-device mesh solve must agree with 8 logical shards."""
        X, y = _make_xy(rng, n=256, d=6)
        reg = RegularizationContext.elastic_net(0.5)
        opts = (("reltol", "1e-6"),)
        mesh = data_mesh(eight_devices)
        problem = _make_problem(reg=reg, solver="admm", solver_options=opts)
        dist_mesh = shard_glm_data(X, y, mesh)
        [(_, m_mesh, _)] = run_grid_distributed(
            problem, dist_mesh, mesh, [0.2]
        )
        dist_log = shard_glm_data(X, y, None, n_shards=8)
        [(_, m_log, _)] = solvers_sharded.run_grid_sharded(
            problem, dist_log, None, [0.2]
        )
        # psum vs axis-0 sum reduce in different orders, so the runs are
        # close-not-bitwise; both must land on the same consensus optimum.
        np.testing.assert_allclose(
            np.asarray(m_mesh.coefficients.means),
            np.asarray(m_log.coefficients.means),
            rtol=0, atol=5e-4,
        )
        f_mesh = _objective_value(
            problem, make_glm_data(X, y), m_mesh.coefficients.means, 0.2
        )
        f_log = _objective_value(
            problem, make_glm_data(X, y), m_log.coefficients.means, 0.2
        )
        assert abs(f_mesh - f_log) / max(1.0, abs(f_log)) < 1e-5

    def test_option_validation(self):
        from photon_ml_tpu.solvers.admm import ADMMOptions

        with pytest.raises(ValueError, match="unknown admm solver_options"):
            ADMMOptions.from_options({"momentum": "0.9"})
        with pytest.raises(ValueError, match="over_relaxation"):
            ADMMOptions.from_options({"over_relaxation": "2.5"})
        with pytest.raises(ValueError, match="local_solver"):
            ADMMOptions.from_options({"local_solver": "newton"})

    def test_telemetry_counters(self, rng):
        X, y = _make_xy(rng, n=128, d=5)
        problem = _make_problem(
            reg=RegularizationContext.elastic_net(0.5), solver="admm"
        )
        dist = shard_glm_data(X, y, None, n_shards=2)
        tel = telemetry_mod.Telemetry(enabled=True, sinks=[])
        prev = telemetry_mod.set_current(tel)
        try:
            [(_, _, res)] = solvers_sharded.run_grid_sharded(
                problem, dist, None, [0.1]
            )
        finally:
            telemetry_mod.set_current(prev)
        rounds = int(res.iterations)
        assert rounds > 0
        assert tel.counter(
            "solver_outer_iterations_total"
        ).value == rounds
        # One reduce per outer round + the final exact evaluation.
        assert tel.counter("solver_allreduce_count").value == rounds + 1
        d = X.shape[1]
        assert tel.counter("solver_allreduce_bytes_total").value == (
            rounds * (2 * d + 4) * 4 + (d + 1) * 4
        )
        assert tel.counter("solvers_sharded_solves_total").value == 1
        assert tel.gauge("solver_consensus_residual").value >= 0.0


# ---------------------------------------------------------------------------
# Distributed block coordinate descent
# ---------------------------------------------------------------------------

class TestBlockCD:
    @pytest.mark.parametrize("reg", [
        RegularizationContext.l2(),
        RegularizationContext.elastic_net(0.5),
    ])
    def test_matches_resident_reference(self, rng, reg):
        """Drift-corrected block CD over 4 shards reaches the resident
        reference optimum (the correction's fixed point is EXACT global
        prox-stationarity, not the biased delta-averaging one)."""
        X, y = _make_xy(rng, n=240, d=9)
        data = make_glm_data(X, y)
        ref_problem = _make_problem(reg=reg)
        [(lam, ref_model, _)] = ref_problem.run_grid(data, [0.1])
        problem = _make_problem(
            reg=reg, solver="block_cd",
            solver_options=(
                ("n_blocks", "3"), ("sweeps", "2"),
                ("tolerance", "1e-10"), ("max_rounds", "400"),
            ),
        )
        dist = shard_glm_data(X, y, None, n_shards=4)
        [(_, model, res)] = solvers_sharded.run_grid_sharded(
            problem, dist, None, [0.1]
        )
        f_ref = _objective_value(
            ref_problem, data, ref_model.coefficients.means, lam
        )
        f_cd = _objective_value(
            ref_problem, data, model.coefficients.means, lam
        )
        gap = abs(f_cd - f_ref) / max(1.0, abs(f_ref))
        assert gap <= 1e-5, f"relative gap {gap:.2e}"

    def test_mesh_matches_logical(self, rng, eight_devices):
        X, y = _make_xy(rng, n=256, d=6)
        reg = RegularizationContext.elastic_net(0.5)
        opts = (("n_blocks", "2"), ("max_rounds", "50"))
        mesh = data_mesh(eight_devices)
        problem = _make_problem(
            reg=reg, solver="block_cd", solver_options=opts
        )
        dist_mesh = shard_glm_data(X, y, mesh)
        [(_, m_mesh, _)] = run_grid_distributed(
            problem, dist_mesh, mesh, [0.2]
        )
        dist_log = shard_glm_data(X, y, None, n_shards=8)
        [(_, m_log, _)] = solvers_sharded.run_grid_sharded(
            problem, dist_log, None, [0.2]
        )
        np.testing.assert_allclose(
            np.asarray(m_mesh.coefficients.means),
            np.asarray(m_log.coefficients.means),
            rtol=0, atol=5e-5,
        )

    def test_option_validation(self):
        from photon_ml_tpu.solvers.block_cd import BlockCDOptions

        with pytest.raises(ValueError, match="unknown block_cd"):
            BlockCDOptions.from_options({"rho": "1.0"})

    def test_dense_features_required(self, rng):
        import scipy.sparse as sp

        X, y = _make_xy(rng, n=100, d=6)
        problem = _make_problem(
            reg=RegularizationContext.l2(), solver="block_cd"
        )
        dist = shard_glm_data(sp.csr_matrix(X), y, None, n_shards=2)
        with pytest.raises(ValueError, match="[Dd]ense"):
            solvers_sharded.run_grid_sharded(problem, dist, None, [0.1])


# ---------------------------------------------------------------------------
# Sharded-data builders + grid runner guards
# ---------------------------------------------------------------------------

class TestShardedRunner:
    def test_jit_kind_rejected(self, rng):
        X, y = _make_xy(rng, n=80, d=4)
        dist = shard_glm_data(X, y, None, n_shards=2)
        with pytest.raises(ValueError, match="jit-kind"):
            solvers_sharded.run_grid_sharded(
                _make_problem(solver="lbfgs"), dist, None, [0.1]
            )

    def test_variances_rejected(self, rng):
        X, y = _make_xy(rng, n=80, d=4)
        dist = shard_glm_data(X, y, None, n_shards=2)
        problem = GlmOptimizationProblem("logistic", GlmOptimizationConfig(
            optimizer=OptimizerConfig(solver="admm"),
            regularization=RegularizationContext.l2(),
            compute_variances=True,
        ))
        with pytest.raises(ValueError, match="compute_variances"):
            solvers_sharded.run_grid_sharded(problem, dist, None, [0.1])

    def test_stack_resident_pads_with_zero_weight(self, rng):
        X, y = _make_xy(rng, n=103, d=5)  # 103 % 4 != 0 → padding
        data = make_glm_data(X, y)
        dist = solvers_sharded.stack_resident(data, 4)
        assert dist.n_shards == 4
        assert dist.data.labels.shape[0] == 4
        total = dist.data.labels.shape[0] * dist.data.labels.shape[1]
        pad = total - 103
        assert pad > 0
        flat_w = np.asarray(dist.data.weights).reshape(-1)
        assert np.all(flat_w[103:] == 0.0)

    def test_resolve_shard_count(self):
        opt = OptimizerConfig(solver="admm", solver_options=(("shards", "6"),))
        assert solvers_sharded.resolve_shard_count(opt) == 6
        assert solvers_sharded.resolve_shard_count(OptimizerConfig()) == 2


# ---------------------------------------------------------------------------
# Chaos: kill + bitwise resume at the new sites
# ---------------------------------------------------------------------------

class TestChaosKillResume:
    def _admm_setup(self, rng):
        X, y = _make_xy(rng, n=160, d=6)
        problem = _make_problem(
            reg=RegularizationContext.elastic_net(0.5), solver="admm",
            solver_options=(("reltol", "1e-4"),),
        )
        dist = shard_glm_data(X, y, None, n_shards=2)
        lams = [0.3, 0.1]
        return problem, dist, lams

    def test_consensus_kill_resumes_bitwise(self, rng, tmp_path):
        """Kill at the admm.consensus boundary mid-λ; the watchdog
        re-enters the grid through the GridCheckpointer and the resumed
        result must be bitwise identical to the uninterrupted run (the
        warm dual + every update is deterministic in the checkpointed
        warm start)."""
        problem, dist, lams = self._admm_setup(rng)
        full = solvers_sharded.run_grid_sharded(problem, dist, None, lams)
        ref = {lam: np.asarray(m.coefficients.means) for lam, m, _ in full}

        ckpt = GridCheckpointer(str(tmp_path / "admm"))
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="admm.consensus", at=3),
        ])

        def train(attempt):
            solved = ckpt.load() if attempt else {}
            acc = dict(solved)

            def on_solved(lam, w):
                acc[lam] = np.asarray(w)
                ckpt.save(acc)

            return solvers_sharded.run_grid_sharded(
                problem, dist, None, lams,
                solved=solved, on_solved=on_solved,
            )

        with plan:
            resumed = run_with_retries(
                train, RetryPolicy(max_retries=1), sleep=lambda s: None
            )
        assert len(plan.fired_at("admm.consensus")) == 1
        for lam, model, _ in resumed:
            assert _bitwise_equal(ref[lam], model.coefficients.means), (
                f"λ={lam}: resumed ADMM grid diverged"
            )

    def test_allreduce_kill_resumes_bitwise(self, rng, tmp_path):
        """Same bar at the distributed.allreduce seam (fires BEFORE the
        round's step program dispatches)."""
        problem, dist, lams = self._admm_setup(rng)
        full = solvers_sharded.run_grid_sharded(problem, dist, None, lams)
        ref = {lam: np.asarray(m.coefficients.means) for lam, m, _ in full}

        ckpt = GridCheckpointer(str(tmp_path / "ar"))
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="distributed.allreduce", at=5),
        ])

        def train(attempt):
            solved = ckpt.load() if attempt else {}
            acc = dict(solved)

            def on_solved(lam, w):
                acc[lam] = np.asarray(w)
                ckpt.save(acc)

            return solvers_sharded.run_grid_sharded(
                problem, dist, None, lams,
                solved=solved, on_solved=on_solved,
            )

        with plan:
            resumed = run_with_retries(
                train, RetryPolicy(max_retries=1), sleep=lambda s: None
            )
        assert len(plan.fired_at("distributed.allreduce")) == 1
        for lam, model, _ in resumed:
            assert _bitwise_equal(ref[lam], model.coefficients.means)

    def test_block_cd_allreduce_kill_resumes_bitwise(self, rng, tmp_path):
        X, y = _make_xy(rng, n=128, d=6)
        problem = _make_problem(
            reg=RegularizationContext.l2(), solver="block_cd",
            solver_options=(("n_blocks", "2"), ("max_rounds", "30")),
        )
        dist = shard_glm_data(X, y, None, n_shards=2)
        full = solvers_sharded.run_grid_sharded(problem, dist, None, [0.1])
        ref = np.asarray(full[0][1].coefficients.means)

        ckpt = GridCheckpointer(str(tmp_path / "cd"))
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="distributed.allreduce", at=2),
        ])

        def train(attempt):
            solved = ckpt.load() if attempt else {}
            return solvers_sharded.run_grid_sharded(
                problem, dist, None, [0.1],
                solved=solved,
                on_solved=lambda lam, w: ckpt.save({lam: np.asarray(w)}),
            )

        with plan:
            resumed = run_with_retries(
                train, RetryPolicy(max_retries=1), sleep=lambda s: None
            )
        assert _bitwise_equal(ref, resumed[0][1].coefficients.means)


# ---------------------------------------------------------------------------
# Streamed pass counters (satellite: existing solvers publish reduces)
# ---------------------------------------------------------------------------

class TestStreamedReduceCounter:
    def test_streamed_passes_counted(self, rng):
        """Every streamed objective pass is one logical all-reduce; the
        counter puts OWL-QN/L-BFGS on the same instrument as the
        distributed solvers (bench.py BENCH_ONLY=solvers)."""
        from photon_ml_tpu.data.streaming import make_streaming_glm_data
        from photon_ml_tpu.optim.streaming import streaming_run_grid

        X, y = _make_xy(rng, n=128, d=6)
        stream = make_streaming_glm_data(X, y, chunk_rows=32)
        problem = _make_problem(reg=RegularizationContext.l2())
        tel = telemetry_mod.Telemetry(enabled=True, sinks=[])
        prev = telemetry_mod.set_current(tel)
        try:
            streaming_run_grid(problem, stream, [0.1])
        finally:
            telemetry_mod.set_current(prev)
        count = tel.counter("solver_allreduce_count").value
        assert count > 0
        # Each logical reduce moves (d+1) f32 partials per chunk batch.
        assert tel.counter("solver_allreduce_bytes_total").value >= (
            count * (X.shape[1] + 1) * 4
        )


# ---------------------------------------------------------------------------
# GAME integration: spec keys + host-kind fixed-effect trainer
# ---------------------------------------------------------------------------

class TestGameIntegration:
    def test_spec_solver_keys_parse(self):
        from photon_ml_tpu.drivers.game_training_driver import (
            parse_coordinate_config,
        )

        name, cfg = parse_coordinate_config({
            "name": "global",
            "type": "fixed",
            "feature_shard": "global",
            "solver": "admm",
            "solver_options": {"rho": "0.5", "shards": "2"},
            "reg_type": "elastic_net",
            "elastic_net_alpha": 0.5,
            "reg_weight": 0.1,
        })
        assert name == "global"
        assert cfg.optimization.optimizer.solver == "admm"
        assert dict(cfg.optimization.optimizer.solver_options) == {
            "rho": "0.5", "shards": "2"
        }

    def test_fixed_effect_trainer_matches_reference(self, rng):
        """make_fixed_effect_trainer (the GAME fixed-effect coordinate's
        host-kind path) reaches the resident optimum with re-slotted
        offsets."""
        X, y = _make_xy(rng, n=160, d=6)
        offsets = rng.normal(scale=0.3, size=160).astype(np.float32)
        reg = RegularizationContext.elastic_net(0.5)
        data = make_glm_data(X, y)
        problem = _make_problem(
            reg=reg, solver="admm", solver_options=(("reltol", "1e-6"),)
        )
        trainer = solvers_sharded.make_fixed_effect_trainer(
            problem, data, n_shards=2
        )
        w = trainer(offsets, jnp.zeros(6, jnp.float32), 0.1)

        ref_problem = _make_problem(reg=reg)
        data_off = dataclasses.replace(
            data, offsets=jnp.asarray(offsets)
        )
        ref = ref_problem.solve_single_device(data_off, 0.1)
        f_ref = _objective_value(ref_problem, data_off, ref.w, 0.1)
        f_admm = _objective_value(ref_problem, data_off, w, 0.1)
        assert abs(f_admm - f_ref) / max(1.0, abs(f_ref)) <= 1e-5
