"""Named BASELINE workloads: real a1a and MovieLens-20M when staged.

These are the reference's actual benchmark configs (BASELINE.json configs
1 and 3; SURVEY.md §4 resource datasets).  They run against the REAL files
when staged under ``datasets/`` (see its README for curl commands) and skip
with a loud reason otherwise — synthetic stand-ins live in other test files
and never masquerade as these.
"""

import os

import numpy as np
import pytest

from photon_ml_tpu.data.datasets import resolve_dataset, skip_reason


def _require(name: str) -> str:
    path = resolve_dataset(name)
    if path is None:
        pytest.skip(skip_reason(name))
    return path


class TestA1a:
    def test_a1a_l2_logistic_auc_floor(self, tmp_path):
        """BASELINE config 1: L2 logistic regression on a1a.  The
        liblinear-class result is ~0.90 validation AUC; assert a 0.88
        floor so numerical drift fails loudly without being flaky."""
        train = _require("a1a")
        test = _require("a1a.t")
        from photon_ml_tpu.drivers import glm_driver

        result = glm_driver.run([
            "--train-data", train,
            "--validate-data", test,
            "--output-dir", str(tmp_path / "out"),
            "--task", "logistic",
            "--reg-type", "l2",
            "--reg-weights", "0.01,0.1,1.0,10.0",
            "--n-features", "123",
        ])
        best_auc = result["metrics"][str(result["best_lambda"])]
        assert best_auc >= 0.88, f"a1a AUC regressed: {best_auc}"


class TestMovieLens:
    MAX_ROWS = 200_000  # subsample cap: keep the integration test minutes-fast

    def test_movielens_per_user_random_effect(self, tmp_path):
        """BASELINE config 3 shape: fixed effect + per-user random effect on
        MovieLens ratings.  The per-user effect must improve validation RMSE
        over the fixed effect alone."""
        path = _require("ml-20m-ratings.csv")
        import scipy.sparse as sp

        from photon_ml_tpu.evaluation.evaluators import RMSEEvaluator
        from photon_ml_tpu.game.estimator import (
            FixedEffectCoordinateConfig,
            GameEstimator,
            GameTransformer,
            RandomEffectCoordinateConfig,
        )
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        users, movies, ratings = [], [], []
        with open(path) as f:
            header = f.readline()
            assert header.strip().startswith("userId")
            for i, line in enumerate(f):
                if i >= self.MAX_ROWS:
                    break
                u, m, r, _ = line.rstrip("\n").split(",")
                users.append(u)
                movies.append(int(m))
                ratings.append(float(r))
        n = len(ratings)
        users = np.asarray(users)
        ratings = np.asarray(ratings, np.float32)

        # Global shard: bias + one-hot of the most-rated movies.
        movies = np.asarray(movies)
        top, counts = np.unique(movies, return_counts=True)
        top = top[np.argsort(-counts)][:500]
        movie_col = {m: j + 1 for j, m in enumerate(top)}
        rows_i, cols_i = [], []
        for i, m in enumerate(movies):
            rows_i.append(i)
            cols_i.append(0)  # bias
            j = movie_col.get(m)
            if j is not None:
                rows_i.append(i)
                cols_i.append(j)
        Xg = sp.csr_matrix(
            (np.ones(len(rows_i), np.float32), (rows_i, cols_i)),
            shape=(n, len(top) + 1),
        )
        Xu = sp.csr_matrix(np.ones((n, 1), np.float32))  # per-user bias

        rng = np.random.default_rng(0)
        val_mask = rng.uniform(size=n) < 0.2
        tr, va = ~val_mask, val_mask
        shards_tr = {"global": Xg[tr], "userFeatures": Xu[tr]}
        ids_tr = {"userId": users[tr]}
        shards_va = {"global": Xg[va], "userFeatures": Xu[va]}
        ids_va = {"userId": users[va]}

        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=40),
            regularization=RegularizationContext.l2(),
        )
        rmse = RMSEEvaluator()

        fixed_only = GameEstimator("squared", {
            "fixed": FixedEffectCoordinateConfig("global", opt, 1.0),
        }, n_iterations=1)
        m0, _ = fixed_only.fit(shards_tr, ids_tr, ratings[tr])
        rmse0 = rmse.evaluate(
            GameTransformer(m0).transform(shards_va, ids_va), ratings[va]
        )

        game = GameEstimator("squared", {
            "fixed": FixedEffectCoordinateConfig("global", opt, 1.0),
            "per_user": RandomEffectCoordinateConfig(
                "userFeatures", "userId", opt, 5.0,
                max_rows_per_entity=256,
            ),
        }, n_iterations=2)
        m1, _ = game.fit(shards_tr, ids_tr, ratings[tr])
        rmse1 = rmse.evaluate(
            GameTransformer(m1).transform(shards_va, ids_va), ratings[va]
        )
        assert rmse1 < rmse0, (
            f"per-user random effect must improve RMSE: {rmse1} vs {rmse0}"
        )
        assert rmse1 < 1.0  # MovieLens per-user models land well under 1.0
