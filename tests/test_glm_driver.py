"""End-to-end driver tests.

Mirrors the reference's integTest driver pattern (SURVEY.md §4): invoke the
Driver with full param lists against a small dataset, then assert on the
written model files and metrics (AUC above a floor, model round-trip)."""

import json
import os

import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.data import libsvm
from photon_ml_tpu.drivers import glm_driver
from photon_ml_tpu.io.model_store import load_glm_model


@pytest.fixture(scope="module")
def a1a_like(tmp_path_factory):
    """Synthetic a1a-shaped dataset: 123 binary features, ±1 labels, sparse."""
    rng = np.random.default_rng(42)
    n, d = 800, 123
    X = sp.random(n, d, density=0.11, random_state=3, format="csr")
    X.data[:] = 1.0  # a1a features are binary
    w_true = rng.normal(size=d) * (rng.uniform(size=d) < 0.3)
    logits = X @ w_true - 0.5
    y = np.where(rng.uniform(size=n) < 1 / (1 + np.exp(-logits)), 1.0, -1.0)
    root = tmp_path_factory.mktemp("a1a")
    train, test = str(root / "train.libsvm"), str(root / "test.libsvm")
    libsvm.write_libsvm(train, X[:600], y[:600])
    libsvm.write_libsvm(test, X[600:], y[600:])
    return train, test, d


class TestGlmDriver:
    def test_l2_logistic_end_to_end(self, a1a_like, tmp_path):
        train, test, d = a1a_like
        out = str(tmp_path / "out")
        result = glm_driver.run([
            "--train-data", train,
            "--validate-data", test,
            "--output-dir", out,
            "--task", "LOGISTIC_REGRESSION",
            "--optimizer", "lbfgs",
            "--reg-type", "l2",
            "--reg-weights", "0.1,1.0,10.0",
            "--n-features", str(d),
            "--compute-variances",
        ])
        # AUC floor, as the reference's driver tests assert.
        best_auc = result["metrics"][str(result["best_lambda"])]
        assert best_auc > 0.70
        # Artifacts exist.
        assert os.path.exists(os.path.join(out, "training_result.json"))
        assert os.path.exists(os.path.join(out, "feature_summary.json"))
        model_path = os.path.join(
            out, f"model_lambda_{result['best_lambda']:g}.avro"
        )
        model, imap = load_glm_model(model_path)
        assert model.task == "logistic"
        assert model.coefficients.variances is not None

    def test_output_mode_all_and_owlqn_sparsity(self, a1a_like, tmp_path):
        train, test, d = a1a_like
        out = str(tmp_path / "out_l1")
        result = glm_driver.run([
            "--train-data", train,
            "--output-dir", out,
            "--task", "logistic",
            "--optimizer", "owlqn",
            "--reg-type", "l1",
            "--reg-weights", "1.0,5.0",
            "--n-features", str(d),
            "--output-mode", "all",
        ])
        files = [
            f for f in os.listdir(out)
            if f.startswith("model_lambda_") and f.endswith(".avro")
        ]
        assert len(files) == 2
        # Stronger L1 ⇒ sparser model file (zero coefficients not written).
        from photon_ml_tpu.io import avro
        sizes = {}
        for f in files:
            _, recs = avro.read_container(os.path.join(out, f))
            lam = float(f.replace("model_lambda_", "").replace(".avro", ""))
            sizes[lam] = len(recs[0]["means"])
        assert sizes[5.0] < sizes[1.0]

    def test_linear_regression_with_normalization(self, tmp_path, rng):
        n, d = 300, 10
        X = rng.normal(loc=5.0, scale=3.0, size=(n, d))
        w_true = rng.normal(size=d)
        y = X @ w_true + 0.1 * rng.normal(size=n)
        train = str(tmp_path / "reg.libsvm")
        libsvm.write_libsvm(train, sp.csr_matrix(X), y)
        out = str(tmp_path / "out_reg")
        result = glm_driver.run([
            "--train-data", train,
            "--output-dir", out,
            "--task", "linear",
            "--reg-type", "l2",
            "--reg-weights", "0.01",
            "--normalization", "standardization",
            "--n-features", str(d),
        ])
        # Near-perfect fit ⇒ tiny RMSE on train.
        assert result["metrics"][str(result["best_lambda"])] < 0.5


class TestStreamingDriver:
    def test_streamed_grid_matches_resident(self, a1a_like, tmp_path):
        """--stream-chunk-rows: the out-of-core path must select the same
        model as the resident run on the same grid."""
        train, test, d = a1a_like
        out_r = str(tmp_path / "resident")
        out_s = str(tmp_path / "streamed")
        common = [
            "--train-data", train,
            "--validate-data", test,
            "--task", "logistic",
            "--reg-type", "l2",
            "--reg-weights", "0.1,1.0",
            "--n-features", str(d),
        ]
        res_r = glm_driver.run(common + ["--output-dir", out_r])
        res_s = glm_driver.run(
            common + ["--output-dir", out_s, "--stream-chunk-rows", "150"]
        )
        assert res_s["best_lambda"] == res_r["best_lambda"]
        for lam in ("0.1", "1.0"):
            assert res_s["metrics"][lam] == pytest.approx(
                res_r["metrics"][lam], abs=1e-3
            )
        # The selected model round-trips and scores like the resident one.
        from photon_ml_tpu.io.model_store import load_glm_model
        from photon_ml_tpu.data.index_map import IndexMap

        lam = res_s["best_lambda"]
        m_s, _ = load_glm_model(
            os.path.join(out_s, f"model_lambda_{lam:g}.avro"),
            IndexMap.load(out_s),
        )
        m_r, _ = load_glm_model(
            os.path.join(out_r, f"model_lambda_{lam:g}.avro"),
            IndexMap.load(out_r),
        )
        np.testing.assert_allclose(
            np.asarray(m_s.coefficients.means),
            np.asarray(m_r.coefficients.means),
            atol=5e-3,
        )

    def test_streamed_resume(self, a1a_like, tmp_path):
        """Checkpoint/resume works through the streamed grid too."""
        train, _, d = a1a_like
        out = str(tmp_path / "out")
        common = [
            "--train-data", train,
            "--output-dir", out,
            "--task", "logistic",
            "--reg-type", "l2",
            "--n-features", str(d),
            "--stream-chunk-rows", "200",
        ]
        glm_driver.run(common + ["--reg-weights", "1.0"])
        # Second run resumes: λ=1.0 restored, only λ=0.1 solved fresh.
        res = glm_driver.run(
            common + ["--reg-weights", "0.1,1.0", "--resume"]
        )
        assert set(res["metrics"]) == {"0.1", "1.0"}

    def test_streamed_l1_matches_resident(self, a1a_like, tmp_path):
        """Streamed OWL-QN through the driver: same model (incl. the
        sparsity pattern and the unpenalized intercept) as the resident
        L1 run."""
        train, _, d = a1a_like
        common = [
            "--train-data", train,
            "--task", "logistic",
            "--reg-type", "l1",
            "--reg-weights", "2.0",
            "--n-features", str(d),
        ]
        out_r = str(tmp_path / "resident")
        res_r = glm_driver.run(common + ["--output-dir", out_r])
        out_s = str(tmp_path / "streamed")
        res_s = glm_driver.run(
            common + ["--output-dir", out_s, "--stream-chunk-rows", "200"]
        )
        from photon_ml_tpu.data.index_map import IndexMap
        from photon_ml_tpu.io.model_store import load_glm_model

        m_r, _ = load_glm_model(
            os.path.join(out_r, "model_lambda_2.avro"), IndexMap.load(out_r)
        )
        m_s, _ = load_glm_model(
            os.path.join(out_s, "model_lambda_2.avro"), IndexMap.load(out_s)
        )
        w_r = np.asarray(m_r.coefficients.means)
        w_s = np.asarray(m_s.coefficients.means)
        np.testing.assert_allclose(w_s, w_r, atol=5e-3)
        assert np.sum(w_r == 0.0) > 10  # L1 sparsified
        np.testing.assert_array_equal(w_s == 0.0, w_r == 0.0)
        assert res_s["metrics"]["2.0"] == pytest.approx(
            res_r["metrics"]["2.0"], abs=1e-3
        )


class TestCoefficientBounds:
    def test_resume_refused_on_bounds_mismatch(self, a1a_like, tmp_path):
        """A λ-grid checkpoint records a fingerprint of the resolved
        --coefficient-bounds arrays; --resume under different bounds
        must refuse instead of warm-starting the remaining grid from
        incompatibly-constrained coefficients (the CD locked-set
        guard's discipline, ADVICE r5)."""
        import pytest

        train, test, d = a1a_like
        bounds_file = str(tmp_path / "bounds.json")
        with open(bounds_file, "w") as f:
            json.dump({"f0": [-0.05, 0.05]}, f)
        out = str(tmp_path / "out")
        base = [
            "--train-data", train,
            "--output-dir", out,
            "--task", "LOGISTIC_REGRESSION",
            "--reg-type", "l2",
            "--reg-weights", "1.0",
            "--n-features", str(d),
            "--max-iters", "5",
        ]
        glm_driver.run(base + ["--coefficient-bounds", bounds_file])
        # Same bounds: resume is allowed (and skips the solved λ).
        res = glm_driver.run(
            base + ["--coefficient-bounds", bounds_file, "--resume"]
        )
        assert res["best_lambda"] == 1.0
        # Dropping the bounds on resume must refuse...
        with pytest.raises(SystemExit, match="coefficient-bounds"):
            glm_driver.run(base + ["--resume"])
        # ...as must resuming a bound-less checkpoint WITH bounds.
        glm_driver.run(base)  # fresh run, no bounds (clears checkpoint)
        with pytest.raises(SystemExit, match="coefficient-bounds"):
            glm_driver.run(
                base + ["--coefficient-bounds", bounds_file, "--resume"]
            )

    def test_box_constrained_driver_end_to_end(self, a1a_like, tmp_path):
        """--coefficient-bounds clamps named coefficients into their box
        and matches a scipy L-BFGS-B oracle on the same objective."""
        import json

        import scipy.optimize

        from photon_ml_tpu.data import libsvm as libsvm_mod

        train, test, d = a1a_like
        cap = 0.05
        bounds_map = {f"f{j}": [-cap, cap] for j in range(10)}
        bounds_file = str(tmp_path / "bounds.json")
        with open(bounds_file, "w") as f:
            json.dump(bounds_map, f)
        out = str(tmp_path / "out")
        result = glm_driver.run([
            "--train-data", train,
            "--validate-data", test,
            "--output-dir", out,
            "--task", "LOGISTIC_REGRESSION",
            "--reg-type", "l2",
            "--reg-weights", "1.0",
            "--n-features", str(d),
            "--max-iters", "300",
            "--tolerance", "1e-10",
            "--coefficient-bounds", bounds_file,
        ])
        model_path = os.path.join(out, "model_lambda_1.avro")
        model, imap = load_glm_model(model_path)
        w = np.asarray(model.coefficients.means)
        for j in range(10):
            idx = imap.get_index(f"f{j}")
            assert -cap - 1e-6 <= w[idx] <= cap + 1e-6

        # Oracle on the identical data matrix (intercept column appended).
        X, y01 = libsvm_mod.read_libsvm(
            train, n_features=d, add_intercept=True
        )
        Xd = X.toarray()
        y = np.asarray(y01, np.float64)
        lo = np.full(X.shape[1], -np.inf)
        hi = np.full(X.shape[1], np.inf)
        for key, (l_, h_) in bounds_map.items():
            lo[imap.get_index(key)] = l_
            hi[imap.get_index(key)] = h_

        def f(wv):
            m = Xd @ wv
            val = np.sum(np.logaddexp(0, m) - y * m) + 0.5 * 1.0 * wv @ wv
            g = Xd.T @ (1 / (1 + np.exp(-m)) - y) + 1.0 * wv
            return val, g

        res = scipy.optimize.minimize(
            f, np.zeros(X.shape[1]), jac=True, method="L-BFGS-B",
            bounds=list(zip(lo, hi)),
            options={"maxiter": 1000, "ftol": 1e-14, "gtol": 1e-10},
        )
        # f32 driver solve vs f64 oracle: coefficients agree to f32
        # limits (flat directions allow ~5e-3 wiggle); the OBJECTIVE is
        # the robust comparison — the driver's constrained optimum must
        # match the oracle's to a relative whisker, and feasibility was
        # asserted above.
        np.testing.assert_allclose(w, res.x, atol=1e-2)
        f_driver, _ = f(np.asarray(w, np.float64))
        f_oracle, _ = f(res.x)
        assert f_driver <= f_oracle * (1 + 1e-5) + 1e-6, (f_driver, f_oracle)
        assert result["metrics"]["1.0"] > 0.5
