"""End-to-end driver tests.

Mirrors the reference's integTest driver pattern (SURVEY.md §4): invoke the
Driver with full param lists against a small dataset, then assert on the
written model files and metrics (AUC above a floor, model round-trip)."""

import json
import os

import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.data import libsvm
from photon_ml_tpu.drivers import glm_driver
from photon_ml_tpu.io.model_store import load_glm_model


@pytest.fixture(scope="module")
def a1a_like(tmp_path_factory):
    """Synthetic a1a-shaped dataset: 123 binary features, ±1 labels, sparse."""
    rng = np.random.default_rng(42)
    n, d = 800, 123
    X = sp.random(n, d, density=0.11, random_state=3, format="csr")
    X.data[:] = 1.0  # a1a features are binary
    w_true = rng.normal(size=d) * (rng.uniform(size=d) < 0.3)
    logits = X @ w_true - 0.5
    y = np.where(rng.uniform(size=n) < 1 / (1 + np.exp(-logits)), 1.0, -1.0)
    root = tmp_path_factory.mktemp("a1a")
    train, test = str(root / "train.libsvm"), str(root / "test.libsvm")
    libsvm.write_libsvm(train, X[:600], y[:600])
    libsvm.write_libsvm(test, X[600:], y[600:])
    return train, test, d


class TestGlmDriver:
    def test_l2_logistic_end_to_end(self, a1a_like, tmp_path):
        train, test, d = a1a_like
        out = str(tmp_path / "out")
        result = glm_driver.run([
            "--train-data", train,
            "--validate-data", test,
            "--output-dir", out,
            "--task", "LOGISTIC_REGRESSION",
            "--optimizer", "lbfgs",
            "--reg-type", "l2",
            "--reg-weights", "0.1,1.0,10.0",
            "--n-features", str(d),
            "--compute-variances",
        ])
        # AUC floor, as the reference's driver tests assert.
        best_auc = result["metrics"][str(result["best_lambda"])]
        assert best_auc > 0.70
        # Artifacts exist.
        assert os.path.exists(os.path.join(out, "training_result.json"))
        assert os.path.exists(os.path.join(out, "feature_summary.json"))
        model_path = os.path.join(
            out, f"model_lambda_{result['best_lambda']:g}.avro"
        )
        model, imap = load_glm_model(model_path)
        assert model.task == "logistic"
        assert model.coefficients.variances is not None

    def test_output_mode_all_and_owlqn_sparsity(self, a1a_like, tmp_path):
        train, test, d = a1a_like
        out = str(tmp_path / "out_l1")
        result = glm_driver.run([
            "--train-data", train,
            "--output-dir", out,
            "--task", "logistic",
            "--optimizer", "owlqn",
            "--reg-type", "l1",
            "--reg-weights", "1.0,5.0",
            "--n-features", str(d),
            "--output-mode", "all",
        ])
        files = [
            f for f in os.listdir(out)
            if f.startswith("model_lambda_") and f.endswith(".avro")
        ]
        assert len(files) == 2
        # Stronger L1 ⇒ sparser model file (zero coefficients not written).
        from photon_ml_tpu.io import avro
        sizes = {}
        for f in files:
            _, recs = avro.read_container(os.path.join(out, f))
            lam = float(f.replace("model_lambda_", "").replace(".avro", ""))
            sizes[lam] = len(recs[0]["means"])
        assert sizes[5.0] < sizes[1.0]

    def test_linear_regression_with_normalization(self, tmp_path, rng):
        n, d = 300, 10
        X = rng.normal(loc=5.0, scale=3.0, size=(n, d))
        w_true = rng.normal(size=d)
        y = X @ w_true + 0.1 * rng.normal(size=n)
        train = str(tmp_path / "reg.libsvm")
        libsvm.write_libsvm(train, sp.csr_matrix(X), y)
        out = str(tmp_path / "out_reg")
        result = glm_driver.run([
            "--train-data", train,
            "--output-dir", out,
            "--task", "linear",
            "--reg-type", "l2",
            "--reg-weights", "0.01",
            "--normalization", "standardization",
            "--n-features", str(d),
        ])
        # Near-perfect fit ⇒ tiny RMSE on train.
        assert result["metrics"][str(result["best_lambda"])] < 0.5


class TestStreamingDriver:
    def test_streamed_grid_matches_resident(self, a1a_like, tmp_path):
        """--stream-chunk-rows: the out-of-core path must select the same
        model as the resident run on the same grid."""
        train, test, d = a1a_like
        out_r = str(tmp_path / "resident")
        out_s = str(tmp_path / "streamed")
        common = [
            "--train-data", train,
            "--validate-data", test,
            "--task", "logistic",
            "--reg-type", "l2",
            "--reg-weights", "0.1,1.0",
            "--n-features", str(d),
        ]
        res_r = glm_driver.run(common + ["--output-dir", out_r])
        res_s = glm_driver.run(
            common + ["--output-dir", out_s, "--stream-chunk-rows", "150"]
        )
        assert res_s["best_lambda"] == res_r["best_lambda"]
        for lam in ("0.1", "1.0"):
            assert res_s["metrics"][lam] == pytest.approx(
                res_r["metrics"][lam], abs=1e-3
            )
        # The selected model round-trips and scores like the resident one.
        from photon_ml_tpu.io.model_store import load_glm_model
        from photon_ml_tpu.data.index_map import IndexMap

        lam = res_s["best_lambda"]
        m_s, _ = load_glm_model(
            os.path.join(out_s, f"model_lambda_{lam:g}.avro"),
            IndexMap.load(out_s),
        )
        m_r, _ = load_glm_model(
            os.path.join(out_r, f"model_lambda_{lam:g}.avro"),
            IndexMap.load(out_r),
        )
        np.testing.assert_allclose(
            np.asarray(m_s.coefficients.means),
            np.asarray(m_r.coefficients.means),
            atol=5e-3,
        )

    def test_streamed_resume(self, a1a_like, tmp_path):
        """Checkpoint/resume works through the streamed grid too."""
        train, _, d = a1a_like
        out = str(tmp_path / "out")
        common = [
            "--train-data", train,
            "--output-dir", out,
            "--task", "logistic",
            "--reg-type", "l2",
            "--n-features", str(d),
            "--stream-chunk-rows", "200",
        ]
        glm_driver.run(common + ["--reg-weights", "1.0"])
        # Second run resumes: λ=1.0 restored, only λ=0.1 solved fresh.
        res = glm_driver.run(
            common + ["--reg-weights", "0.1,1.0", "--resume"]
        )
        assert set(res["metrics"]) == {"0.1", "1.0"}

    def test_streamed_l1_matches_resident(self, a1a_like, tmp_path):
        """Streamed OWL-QN through the driver: same model (incl. the
        sparsity pattern and the unpenalized intercept) as the resident
        L1 run."""
        train, _, d = a1a_like
        common = [
            "--train-data", train,
            "--task", "logistic",
            "--reg-type", "l1",
            "--reg-weights", "2.0",
            "--n-features", str(d),
        ]
        out_r = str(tmp_path / "resident")
        res_r = glm_driver.run(common + ["--output-dir", out_r])
        out_s = str(tmp_path / "streamed")
        res_s = glm_driver.run(
            common + ["--output-dir", out_s, "--stream-chunk-rows", "200"]
        )
        from photon_ml_tpu.data.index_map import IndexMap
        from photon_ml_tpu.io.model_store import load_glm_model

        m_r, _ = load_glm_model(
            os.path.join(out_r, "model_lambda_2.avro"), IndexMap.load(out_r)
        )
        m_s, _ = load_glm_model(
            os.path.join(out_s, "model_lambda_2.avro"), IndexMap.load(out_s)
        )
        w_r = np.asarray(m_r.coefficients.means)
        w_s = np.asarray(m_s.coefficients.means)
        np.testing.assert_allclose(w_s, w_r, atol=5e-3)
        assert np.sum(w_r == 0.0) > 10  # L1 sparsified
        np.testing.assert_array_equal(w_s == 0.0, w_r == 0.0)
        assert res_s["metrics"]["2.0"] == pytest.approx(
            res_r["metrics"]["2.0"], abs=1e-3
        )
