"""Fleet serving tests (ISSUE 15): partition-tolerant multi-host
router + lease-based fleet-wide quota coordination.

The load-bearing contracts:

- a host killed as it picks up a request is marked DOWN and the request
  resubmits to a peer: a host kill under load costs ZERO failed
  requests (the ``serving.host`` chaos seam);
- reconnect backoff is a decorrelated walk that RESETS after sustained
  health — a host that flaps through repeated kill bursts re-escalates
  from base each time, it does not inherit the previous burst's delay;
- the coordinator never grants more than the budget across all live
  leases, rebalances to observed demand within one renewal round per
  host, and reclaims a dead host's share the moment its lease expires;
- a host that cannot reach the coordinator (the ``quota.lease`` seam or
  the scripted ``partitioned`` flag) degrades to its LAST lease — never
  unlimited, never zero — so a partition bounds fleet over-admission to
  one lease window.
"""

import threading
import time

import numpy as np
import pytest

from photon_ml_tpu import chaos
from photon_ml_tpu.serving.batcher import BatcherConfig, RejectedError
from photon_ml_tpu.serving.fleet import (
    FleetBudget,
    FleetRouter,
    LeaseClient,
    LocalHost,
    QuotaCoordinator,
)
from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
from photon_ml_tpu.serving.service import ScoringService
from photon_ml_tpu.serving.synthetic import SyntheticWorkload
from photon_ml_tpu.serving.tenancy import TokenBucket


@pytest.fixture(scope="module")
def workload():
    return SyntheticWorkload(n_entities=32, seed=7)


def _service(workload):
    cfg = RuntimeConfig(max_batch_size=8, hot_entities=8)
    runtime = ScoringRuntime(workload.model, workload.index_maps, cfg)
    return ScoringService(runtime, BatcherConfig(
        max_batch_size=8, max_wait_us=1000, max_queue=256,
    ))


def _wait_until(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class _Fleet:
    """n hosts + one router, torn down in reverse order."""

    def __init__(self, workload, n_hosts=2, **router_kwargs):
        self.hosts = [
            LocalHost(f"h{i}", _service(workload)).start()
            for i in range(n_hosts)
        ]
        kwargs = {"probe_interval_s": 0.05, **router_kwargs}
        self.router = FleetRouter(
            [h.base_url for h in self.hosts], **kwargs
        ).start()

    def __enter__(self) -> "_Fleet":
        return self

    def __exit__(self, *exc) -> bool:
        self.router.stop()
        for h in self.hosts:
            h.stop()
        return False


class TestFleetRouter:
    def test_scores_and_balances_across_hosts(self, workload):
        with _Fleet(workload) as fleet:
            results = [
                fleet.router.score(workload.request(i))
                for i in range(8)
            ]
            assert all(np.isfinite(r["score"]) for r in results)
            hz = fleet.router.healthz()
            assert hz["status"] == "ok"
            assert all(h["requests"] > 0 for h in hz["hosts"])

    def test_host_kill_under_load_costs_zero_failures(self, workload):
        with _Fleet(workload) as fleet:
            futures = [
                fleet.router.submit(workload.request(i))
                for i in range(16)
            ]
            fleet.hosts[0].kill()
            futures += [
                fleet.router.submit(workload.request(i))
                for i in range(16, 48)
            ]
            results = [f.result(timeout=30) for f in futures]
            assert all(np.isfinite(r["score"]) for r in results)
            # The killed host's listener rebinds and rejoins.
            fleet.hosts[0].restart()
            assert _wait_until(
                lambda: fleet.router.healthy_count == 2
            ), fleet.router.healthz()

    def test_chaos_host_site_marks_down_and_resubmits(self, workload):
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="serving.host", at=0),
        ])
        with _Fleet(workload, probe_interval_s=10.0) as fleet:
            with plan:
                result = fleet.router.score(workload.request(0))
            assert np.isfinite(result["score"])
            assert plan.fired and \
                plan.fired[0]["site"] == "serving.host"
            # The victim is out of rotation awaiting reconnect probes.
            assert fleet.router.healthy_count == 1

    def test_no_healthy_host_is_a_transient_rejection(self, workload):
        """With the whole fleet down, a request waits out the no-host
        retry window (reconnect probes might restore someone), then
        fails with the transient vocabulary."""
        with _Fleet(
            workload, n_hosts=1, no_host_retry_s=0.2
        ) as fleet:
            fleet.hosts[0].kill()
            with pytest.raises((RejectedError, RuntimeError)) as exc:
                fleet.router.score(workload.request(0), timeout=10)
            assert "UNAVAILABLE" in str(exc.value)
            assert fleet.router.healthy_count == 0

    def test_whole_fleet_blip_delays_instead_of_failing(self, workload):
        """EVERY host momentarily unreachable: requests in the window
        wait for reconnect probes and still complete (the host-kill
        zero-failures contract extends to total blips shorter than
        ``no_host_retry_s``)."""
        with _Fleet(workload, n_hosts=1) as fleet:
            fleet.hosts[0].kill()
            # Trip the transport failure so the host is marked down.
            fut = fleet.router.submit(workload.request(0))
            restorer = threading.Timer(0.3, fleet.hosts[0].restart)
            restorer.start()
            try:
                assert np.isfinite(fut.result(timeout=30)["score"])
            finally:
                restorer.join()

    def test_drain_removes_host_without_dropping_requests(
        self, workload
    ):
        with _Fleet(workload) as fleet:
            assert fleet.router.drain(0, timeout_s=10.0)
            before = fleet.router.healthz()
            drained = next(
                h for h in before["hosts"] if h["hid"] == 0
            )
            assert drained["state"] == "removed"
            for i in range(6):
                r = fleet.router.score(workload.request(i))
                assert np.isfinite(r["score"])
            after = fleet.router.healthz()
            assert (
                next(h for h in after["hosts"] if h["hid"] == 0)[
                    "requests"
                ] == drained["requests"]
            )
            with pytest.raises(ValueError, match="unknown host id"):
                fleet.router.drain(99)

    def test_join_adds_a_live_host_without_restart(self, workload):
        """Satellite: ``join()`` is ``drain()``'s symmetric
        counterpart — a joined host enters as down-until-ready and
        takes traffic only after its first ready probe, and re-joining
        a drained URL revives the SAME host id."""
        with _Fleet(workload, n_hosts=1) as fleet:
            extra = LocalHost("hx", _service(workload)).start()
            try:
                hid = fleet.router.join(extra.base_url)
                joined = next(
                    h for h in fleet.router.healthz()["hosts"]
                    if h["hid"] == hid
                )
                # Enters down (awaiting its first ready probe); the
                # probe loop may already have admitted it.
                assert joined["state"] in ("down", "healthy")
                assert _wait_until(
                    lambda: fleet.router.healthy_count == 2
                ), fleet.router.healthz()
                # Joining an in-rotation URL is idempotent.
                assert fleet.router.join(extra.base_url) == hid
                # Drain it out, re-join: the same id revives.
                assert fleet.router.drain(hid, timeout_s=10.0)
                assert fleet.router.join(extra.base_url) == hid
                assert _wait_until(
                    lambda: fleet.router.healthy_count == 2
                ), fleet.router.healthz()
                for i in range(6):
                    assert np.isfinite(
                        fleet.router.score(workload.request(i))["score"]
                    )
            finally:
                extra.stop()

    def test_reconnect_backoff_resets_after_sustained_health(
        self, workload
    ):
        """Satellite: repeated HOST-level failure bursts.  The backoff
        walk escalates while a host stays dead, resets to base once
        probes see sustained health, and the NEXT burst escalates from
        base again instead of inheriting the previous burst's delay."""
        with _Fleet(workload) as fleet:
            host = fleet.router.hosts[0]

            def burst():
                fleet.hosts[0].kill()
                # A request trips the transport failure -> mark down.
                assert np.isfinite(
                    fleet.router.score(workload.request(0))["score"]
                )
                assert _wait_until(lambda: host.state == "down")
                # Reconnect probes keep failing: the walk escalates.
                assert _wait_until(
                    lambda: host.reconnect_attempt >= 3, timeout=20.0
                ), fleet.router.healthz()
                first_delay = host.last_delay
                assert first_delay is not None and first_delay > 0
                fleet.hosts[0].restart()
                assert _wait_until(lambda: host.state == "healthy")
                # Sustained health resets the walk (healthy probes run
                # every probe_interval_s).
                assert _wait_until(
                    lambda: host.reconnect_attempt == 0
                    and host.last_delay is None
                ), fleet.router.healthz()

            burst()  # burst 1: escalate, recover, reset
            burst()  # burst 2: must re-escalate from a reset walk


class TestQuotaCoordinator:
    def _clock(self, start=100.0):
        state = {"t": start}

        def clock():
            return state["t"]

        return state, clock

    def test_outstanding_never_exceeds_budget(self):
        state, clock = self._clock()
        coord = QuotaCoordinator(
            [FleetBudget("t", 100.0)], lease_ttl_s=1.0, clock=clock
        )
        for rnd in range(6):
            for host, demand in (("a", 10.0), ("b", 90.0), ("c", 40.0)):
                coord.renew(host, {"t": demand})
                outstanding = coord.stats()["tenants"]["t"][
                    "outstanding_rps"
                ]
                assert outstanding <= 100.0 + 1e-6, coord.stats()
            state["t"] += 0.4  # inside the TTL: nothing expires

    def test_rebalance_converges_to_demand_in_one_round(self):
        state, clock = self._clock()
        coord = QuotaCoordinator(
            [FleetBudget("t", 100.0, min_share=0.1)],
            lease_ttl_s=5.0, clock=clock,
        )
        # First renewer is the only live host: it holds the whole
        # budget until a peer shows up.
        assert coord.renew("a", {"t": 10.0})["t"].rate_rps == \
            pytest.approx(100.0)
        # b's target is demand-proportional but the budget is spoken
        # for — it gets the leftovers (zero), never over-commits.
        assert coord.renew("b", {"t": 30.0})["t"].rate_rps == \
            pytest.approx(0.0)
        # One more renewal each converges to floor + proportional:
        # floor 5 each, variable 90 split 10:30 -> 27.5 / 72.5.
        assert coord.renew("a", {"t": 10.0})["t"].rate_rps == \
            pytest.approx(27.5)
        assert coord.renew("b", {"t": 30.0})["t"].rate_rps == \
            pytest.approx(72.5)
        assert coord.rebalances >= 2

    def test_equal_split_at_zero_demand(self):
        _, clock = self._clock()
        coord = QuotaCoordinator(
            [FleetBudget("t", 60.0)], lease_ttl_s=5.0, clock=clock
        )
        coord.renew("a", {})
        coord.renew("b", {})
        assert coord.renew("a", {})["t"].rate_rps == pytest.approx(30.0)
        assert coord.renew("b", {})["t"].rate_rps == pytest.approx(30.0)

    def test_dead_host_share_reclaimed_after_ttl(self):
        state, clock = self._clock()
        coord = QuotaCoordinator(
            [FleetBudget("t", 100.0)], lease_ttl_s=1.0, clock=clock
        )
        assert coord.renew("a", {"t": 50.0})["t"].rate_rps == \
            pytest.approx(100.0)
        # a dies (stops renewing); its lease expires...
        state["t"] += 1.5
        # ...and b's next renewal reclaims the whole budget.
        assert coord.renew("b", {"t": 50.0})["t"].rate_rps == \
            pytest.approx(100.0)
        assert coord.reclaims == 1
        assert coord.stats()["tenants"]["t"]["outstanding_rps"] == \
            pytest.approx(100.0)


class _FakeService:
    """The two methods LeaseClient needs, with an applied-quota log."""

    def __init__(self):
        self.demand = {}
        self.applied = []

    def demand_snapshot(self):
        return dict(self.demand)

    def set_tenant_quota(self, tenant, rate_rps, burst=None):
        self.applied.append((tenant, rate_rps, burst))


class TestLeaseClient:
    def test_poll_applies_granted_lease(self):
        coord = QuotaCoordinator([FleetBudget("t", 50.0)])
        svc = _FakeService()
        lc = LeaseClient("h0", coord, svc)
        assert lc.poll_once()
        assert lc.leases["t"].rate_rps == pytest.approx(50.0)
        assert svc.applied == [("t", pytest.approx(50.0),
                                pytest.approx(50.0))]
        assert not lc.stale

    def test_partition_degrades_to_last_lease(self):
        """The partition contract: on renewal failure the LAST lease
        keeps enforcing — never unlimited, never zero."""
        coord = QuotaCoordinator([FleetBudget("t", 50.0)])
        svc = _FakeService()
        lc = LeaseClient("h0", coord, svc)
        assert lc.poll_once()
        applied_before = list(svc.applied)
        lease_before = lc.leases["t"]

        lc.partitioned = True
        assert not lc.poll_once()
        assert lc.stale
        assert lc.renew_failures == 1
        # Buckets untouched: no new set_tenant_quota, no zeroing, and
        # the remembered lease still carries a bounded nonzero rate.
        assert svc.applied == applied_before
        assert lc.leases["t"] is lease_before
        assert 0 < lc.leases["t"].rate_rps <= 50.0

        lc.partitioned = False
        assert lc.poll_once()
        assert not lc.stale
        assert len(svc.applied) > len(applied_before)

    def test_chaos_lease_site_degrades_then_recovers(self):
        coord = QuotaCoordinator([FleetBudget("t", 50.0)])
        svc = _FakeService()
        lc = LeaseClient("h0", coord, svc)
        assert lc.poll_once()
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="quota.lease", at=0, count=1),
        ])
        with plan:
            assert not lc.poll_once()  # scripted partition fires
            assert lc.stale
            assert lc.poll_once()  # next renewal heals
        assert plan.fired and plan.fired[0]["site"] == "quota.lease"
        assert not lc.stale
        assert lc.renew_failures == 1

    def test_demand_rates_difference_counters(self):
        times = iter([0.0, 2.0])
        coord = QuotaCoordinator([FleetBudget("t", 50.0)])
        svc = _FakeService()
        lc = LeaseClient(
            "h0", coord, svc, clock=lambda: next(times)
        )
        svc.demand = {"t": 10}
        lc.poll_once()  # first poll: no interval yet -> zero rate
        svc.demand = {"t": 50}
        lc.poll_once()
        # 40 offered requests over 2s -> 20 rps observed demand.
        grant = coord.stats()["tenants"]["t"]["hosts"]["h0"]
        assert grant["demand_rps"] == pytest.approx(20.0)


class TestTokenBucketReset:
    def test_reset_clamps_tokens_down_never_refills_up(self):
        times = iter([0.0, 0.0, 0.0, 0.0, 0.0])
        bucket = TokenBucket(
            100.0, burst=100.0, clock=lambda: next(times)
        )
        assert bucket.try_acquire(60.0)  # 40 tokens left
        bucket.reset_rate(10.0, burst=5.0)
        # Shrinking the burst clamps stored tokens down with it...
        assert bucket.tokens <= 5.0
        bucket.reset_rate(200.0, burst=100.0)
        # ...but raising the rate never mints tokens retroactively.
        assert bucket.tokens <= 5.0

    def test_reset_to_none_is_unlimited(self):
        bucket = TokenBucket(1.0, burst=1.0)
        bucket.reset_rate(None)
        assert all(bucket.try_acquire() for _ in range(100))

    def test_reset_rejects_bad_values(self):
        bucket = TokenBucket(10.0, burst=10.0)
        with pytest.raises(ValueError):
            bucket.reset_rate(-1.0)
        with pytest.raises(ValueError):
            bucket.reset_rate(10.0, burst=0.0)


class TestServiceQuotaSurface:
    def test_set_tenant_quota_requires_tenancy(self, workload):
        service = _service(workload)
        with service:
            with pytest.raises(ValueError):
                service.set_tenant_quota("acme", 10.0)

    def test_demand_counts_offered_not_admitted(self, workload):
        service = _service(workload)
        with service:
            req = dict(workload.request(0))
            req["tenant"] = "acme"
            for _ in range(5):
                service.submit(req).result(timeout=30)
            assert service.demand_snapshot().get("acme") == 5
