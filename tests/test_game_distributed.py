"""Distributed GAME tests on the 8-virtual-device mesh: sharded coordinates
must match their single-device counterparts (the reference's
distributed-vs-single-node parity pattern, SURVEY.md §4)."""

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from photon_ml_tpu.game.coordinates import (
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.data import FixedEffectDataset, build_random_effect_dataset
from photon_ml_tpu.game.descent import CoordinateDescent
from photon_ml_tpu.game.distributed import (
    DistributedFixedEffectCoordinate,
    EntityShardedRandomEffectCoordinate,
)
from photon_ml_tpu.data.dataset import make_glm_data
from photon_ml_tpu.optim.problem import GlmOptimizationConfig, OptimizerConfig
from photon_ml_tpu.optim.regularization import RegularizationContext
from photon_ml_tpu.parallel.distributed import data_mesh


@pytest.fixture
def problem(rng):
    n, d = 331, 9  # deliberately not divisible by 8
    n_users = 13
    X = rng.normal(size=(n, d)).astype(np.float32)
    users = np.array([f"u{rng.integers(n_users)}" for _ in range(n)])
    ue = {f"u{k}": rng.normal(scale=1.5) for k in range(n_users)}
    margins = X @ rng.normal(size=d) + np.array([ue[u] for u in users])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margins))).astype(np.float32)
    bias = sp.csr_matrix(np.ones((n, 1), np.float32))
    opt = GlmOptimizationConfig(
        optimizer=OptimizerConfig(max_iters=50),
        regularization=RegularizationContext.l2(),
    )
    return X, bias, users, y, opt


class TestDistributedGame:
    def test_fixed_effect_parity(self, problem, eight_devices):
        X, _, _, y, opt = problem
        mesh = data_mesh(eight_devices)
        n = X.shape[0]
        offsets = jnp.asarray(np.linspace(-1, 1, n), jnp.float32)

        dist = DistributedFixedEffectCoordinate(
            "fixed", X, y, mesh, "logistic", opt, reg_weight=0.7
        )
        w_dist = dist.train(offsets)
        s_dist = np.asarray(dist.score(w_dist))

        single = FixedEffectCoordinate(
            "fixed",
            FixedEffectDataset(make_glm_data(X, y), n),
            "logistic", opt, reg_weight=0.7,
        )
        w_single = single.train(offsets)
        s_single = np.asarray(single.score(w_single))

        np.testing.assert_allclose(
            np.asarray(w_dist), np.asarray(w_single), rtol=1e-3, atol=1e-4
        )
        np.testing.assert_allclose(s_dist, s_single, rtol=1e-3, atol=1e-4)

    def test_fixed_effect_reg_weight_mutation(self, problem, eight_devices):
        # Hyperparameter tuning mutates coord.reg_weight between runs;
        # reg_weight is a traced argument, so the mutation must take effect
        # without retracing (regression test: it used to be baked into jit).
        X, _, _, y, opt = problem
        mesh = data_mesh(eight_devices)
        offsets = jnp.zeros(X.shape[0], jnp.float32)
        dist = DistributedFixedEffectCoordinate(
            "fixed", X, y, mesh, "logistic", opt, reg_weight=0.1
        )
        w_low = np.asarray(dist.train(offsets))
        dist.reg_weight = 100.0
        w_high = np.asarray(dist.train(offsets))
        assert np.linalg.norm(w_high) < 0.5 * np.linalg.norm(w_low)

    def test_entity_sharded_random_effect_parity(self, problem, eight_devices):
        _, bias, users, y, opt = problem
        mesh = data_mesh(eight_devices)
        n = len(y)
        offsets = jnp.zeros(n, jnp.float32)
        ds = build_random_effect_dataset(
            users, bias, y, np.ones(n, np.float32)
        )
        sharded = EntityShardedRandomEffectCoordinate(
            "re", ds, mesh, "logistic", opt, reg_weight=0.5, entity_key="userId"
        )
        plain = RandomEffectCoordinate(
            "re",
            build_random_effect_dataset(users, bias, y, np.ones(n, np.float32)),
            "logistic", opt, reg_weight=0.5, entity_key="userId",
        )
        s_sharded = np.asarray(sharded.score(sharded.train(offsets)))
        s_plain = np.asarray(plain.score(plain.train(offsets)))
        np.testing.assert_allclose(s_sharded, s_plain, rtol=1e-4, atol=1e-5)

        # finalize drops padding lanes: entity count is exact.
        model = sharded.finalize(sharded.train(offsets))
        assert model.n_entities == 13

    def test_full_distributed_cd_loop(self, problem, eight_devices):
        X, bias, users, y, opt = problem
        mesh = data_mesh(eight_devices)
        n = X.shape[0]
        fixed = DistributedFixedEffectCoordinate(
            "fixed", X, y, mesh, "logistic", opt, reg_weight=0.7
        )
        re = EntityShardedRandomEffectCoordinate(
            "re",
            build_random_effect_dataset(users, bias, y, np.ones(n, np.float32)),
            mesh, "logistic", opt, reg_weight=0.5, entity_key="userId",
        )
        result = CoordinateDescent([fixed, re]).run(
            jnp.zeros(n, jnp.float32), n_iterations=2
        )
        total = np.asarray(result.scores["fixed"]) + np.asarray(
            result.scores["re"]
        )
        from photon_ml_tpu.evaluation.evaluators import AreaUnderROCCurveEvaluator
        auc = AreaUnderROCCurveEvaluator().evaluate(total, y)
        assert auc > 0.8


class TestEstimatorMeshPath:
    def test_estimator_mesh_parity_and_driver_flag(self, tmp_path):
        """GameEstimator(mesh=...) trains the same model as single-device,
        and the driver's --data-parallel auto flag engages it."""
        import json

        import scipy.sparse as sp

        from photon_ml_tpu.game.estimator import (
            FixedEffectCoordinateConfig,
            GameEstimator,
            GameTransformer,
            RandomEffectCoordinateConfig,
        )
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext
        from photon_ml_tpu.parallel.distributed import data_mesh

        rng = np.random.default_rng(17)
        n, n_users = 400, 12
        ue = rng.normal(scale=2.0, size=n_users)
        Xg = rng.normal(size=(n, 4)).astype(np.float32)
        users = rng.integers(n_users, size=n)
        margin = 1.2 * Xg[:, 0] - 0.8 * Xg[:, 1] + ue[users]
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(
            np.float32
        )
        shards = {
            "global": sp.csr_matrix(Xg),
            "userFeatures": sp.csr_matrix(np.ones((n, 1), np.float32)),
        }
        ids = {"userId": np.array([f"u{u}" for u in users])}
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=30),
            regularization=RegularizationContext.l2(),
        )
        configs = {
            "fixed": FixedEffectCoordinateConfig("global", opt, 0.5),
            "per_user": RandomEffectCoordinateConfig(
                "userFeatures", "userId", opt, 0.5
            ),
        }

        single = GameEstimator("logistic", configs, n_iterations=2)
        m1, _ = single.fit(shards, ids, y)
        dist = GameEstimator(
            "logistic", configs, n_iterations=2, mesh=data_mesh()
        )
        m2, _ = dist.fit(shards, ids, y)

        s1 = GameTransformer(m1).transform(shards, ids)
        s2 = GameTransformer(m2).transform(shards, ids)
        np.testing.assert_allclose(s1, s2, atol=2e-3)

        # Driver flag smoke: --data-parallel auto on the 8-device CPU mesh.
        from photon_ml_tpu.data.game_reader import write_game_avro
        from photon_ml_tpu.drivers import game_training_driver

        rows = []
        for i in range(n):
            rows.append({
                "uid": f"r{i}", "response": float(y[i]), "weight": None,
                "offset": None, "ids": {"userId": ids["userId"][i]},
                "features": {
                    "global": [
                        {"name": f"g{j}", "term": "", "value": float(Xg[i, j])}
                        for j in range(4)
                    ],
                    "userFeatures": [{"name": "b", "term": "", "value": 1.0}],
                },
            })
        train = str(tmp_path / "t.avro")
        write_game_avro(train, rows)
        cfg = {
            "task": "logistic", "iterations": 1,
            "coordinates": [
                {"name": "fixed", "type": "fixed", "feature_shard": "global",
                 "optimizer": "lbfgs", "max_iters": 25, "reg_type": "l2",
                 "reg_weight": 0.5},
                {"name": "per_user", "type": "random",
                 "feature_shard": "userFeatures", "entity_key": "userId",
                 "optimizer": "lbfgs", "max_iters": 20, "reg_type": "l2",
                 "reg_weight": 0.5},
            ],
        }
        cfgp = str(tmp_path / "c.json")
        with open(cfgp, "w") as f:
            json.dump(cfg, f)
        result = game_training_driver.run([
            "--train-data", train, "--config", cfgp,
            "--output-dir", str(tmp_path / "out"),
            "--data-parallel", "auto",
        ])
        assert result["train_metric"] > 0.7


class TestMeshWarmStartAndVariances:
    def test_initial_model_on_mesh_path(self, rng):
        """Incremental training works with --data-parallel: a model trained
        single-device warm-starts a mesh fit (previously crashed on the
        distributed coordinates' layout)."""
        import scipy.sparse as sp

        from photon_ml_tpu.game.estimator import (
            FixedEffectCoordinateConfig,
            GameEstimator,
            RandomEffectCoordinateConfig,
        )
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext
        from photon_ml_tpu.parallel.distributed import data_mesh

        n, n_users = 250, 9
        ue = rng.normal(scale=1.5, size=n_users)
        Xg = rng.normal(size=(n, 3)).astype(np.float32)
        users = rng.integers(n_users, size=n)
        y = (rng.uniform(size=n) <
             1 / (1 + np.exp(-(Xg[:, 0] + ue[users])))).astype(np.float32)
        shards = {
            "global": sp.csr_matrix(Xg),
            "userFeatures": sp.csr_matrix(np.ones((n, 1), np.float32)),
        }
        ids = {"userId": np.array([f"u{u}" for u in users])}
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=25),
            regularization=RegularizationContext.l2(),
        )
        configs = {
            "fixed": FixedEffectCoordinateConfig("global", opt, 0.5),
            "per_user": RandomEffectCoordinateConfig(
                "userFeatures", "userId", opt, 0.5
            ),
        }
        single = GameEstimator("logistic", configs, n_iterations=1)
        prior, _ = single.fit(shards, ids, y)
        dist = GameEstimator(
            "logistic", configs, n_iterations=1, mesh=data_mesh()
        )
        model, history = dist.fit(shards, ids, y, initial_model=prior)
        cold = GameEstimator(
            "logistic", configs, n_iterations=1, mesh=data_mesh()
        )
        _, hist_cold = cold.fit(shards, ids, y)
        # Warm start includes the prior random effect from update one.
        assert history[0]["train_metric"] > hist_cold[0]["train_metric"]

    def test_distributed_grid_variances_match_single_device(self, rng):
        import scipy.sparse as sp

        from photon_ml_tpu.data.dataset import make_glm_data
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            GlmOptimizationProblem,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext
        from photon_ml_tpu.parallel.distributed import (
            data_mesh,
            run_grid_distributed,
            shard_glm_data,
        )

        n, d = 320, 20
        X = sp.random(n, d, density=0.4, random_state=4, format="csr")
        y = (np.asarray(X @ rng.normal(size=d)).ravel() > 0).astype(
            np.float32
        )
        problem = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(max_iters=50),
                regularization=RegularizationContext.l2(),
                compute_variances=True,
            ),
        )
        single = problem.run_grid(make_glm_data(X, y), [1.0])
        mesh = data_mesh()
        multi = run_grid_distributed(
            problem, shard_glm_data(X, y, mesh), mesh, [1.0]
        )
        v1 = np.asarray(single[0][1].coefficients.variances)
        v2 = np.asarray(multi[0][1].coefficients.variances)
        assert v2 is not None
        np.testing.assert_allclose(v2, v1, rtol=1e-3)
