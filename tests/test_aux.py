"""Auxiliary subsystem tests: down-sampling, hyperparameter search, tracker."""

import os

import numpy as np
import pytest

from photon_ml_tpu.data.sampling import (
    BinaryClassificationDownSampler,
    DefaultDownSampler,
)
from photon_ml_tpu.hyperparameter.search import (
    GaussianProcessModel,
    GaussianProcessSearch,
    RandomSearch,
    expected_improvement,
)


class TestDownSampling:
    def test_default_unbiased_weight_sum(self, rng):
        n = 20000
        labels = (rng.uniform(size=n) < 0.5).astype(float)
        weights = np.ones(n)
        idx, w = DefaultDownSampler(0.25, seed=1).downsample(labels, weights)
        # Survivor weight sum ≈ original weight sum (unbiased).
        assert abs(w.sum() - n) / n < 0.05
        assert len(idx) == pytest.approx(n * 0.25, rel=0.1)

    def test_binary_keeps_all_positives(self, rng):
        n = 10000
        labels = (rng.uniform(size=n) < 0.05).astype(float)  # 5% positive
        weights = np.ones(n)
        idx, w = BinaryClassificationDownSampler(0.1, seed=2).downsample(
            labels, weights
        )
        kept = labels[idx]
        assert kept.sum() == labels.sum()  # every positive kept, weight 1
        np.testing.assert_allclose(w[kept > 0], 1.0)
        # Kept negatives re-weighted to preserve total negative mass.
        neg_mass = w[kept == 0].sum()
        assert abs(neg_mass - (n - labels.sum())) / n < 0.05

    def test_invalid_rate_rejected(self):
        with pytest.raises(ValueError):
            DefaultDownSampler(0.0)
        with pytest.raises(ValueError):
            BinaryClassificationDownSampler(1.5)


class TestHyperparameterSearch:
    def test_random_search_finds_decent_point(self):
        def f(x):
            return float((x[0] - 3.0) ** 2 + (x[1] + 1.0) ** 2)

        res = RandomSearch([(0, 5), (-3, 3)], seed=4).find(f, 60)
        assert res.best_value < 0.5
        assert len(res.history) == 60

    def test_gp_posterior_interpolates(self):
        X = np.array([[0.0], [0.5], [1.0]])
        y = np.array([1.0, 0.0, 1.0])
        gp = GaussianProcessModel().fit(X, y)
        mean, std = gp.predict(X)
        np.testing.assert_allclose(mean, y, atol=1e-2)
        assert np.all(std < 0.1)
        # Uncertainty grows away from data.
        _, std_far = gp.predict(np.array([[0.25]]))
        assert std_far[0] > std[0]

    def test_ei_prefers_low_mean_and_high_std(self):
        ei = expected_improvement(
            np.array([0.0, 1.0]), np.array([0.1, 0.1]), best=0.5
        )
        assert ei[0] > ei[1]
        ei2 = expected_improvement(
            np.array([1.0, 1.0]), np.array([1.0, 0.01]), best=0.5
        )
        assert ei2[0] > ei2[1]

    def test_gp_search_beats_random_on_smooth_objective(self):
        def f(x):
            return float(np.sin(3 * x[0]) + 0.3 * (x[0] - 4.0) ** 2)

        budget = 18
        gp = GaussianProcessSearch([(0.0, 8.0)], seed=5).find(f, budget)
        assert gp.best_value < 0.1  # true min ≈ -0.04 near x≈4.5
        assert len(gp.history) == budget

    def test_gp_search_log_scale_and_priors(self):
        # Optimum at lambda = 1e-2 on a log-scaled axis.
        def f(x):
            return float((np.log10(x[0]) + 2.0) ** 2)

        priors = [(np.array([1.0]), f(np.array([1.0])))]
        res = GaussianProcessSearch(
            [(1e-4, 1e2)], log_scale=True, seed=6
        ).find(f, 15, priors=priors)
        assert res.best_value < 0.1
        # History includes the prior.
        assert len(res.history) == 16

    def test_maximize_mode(self):
        def f(x):
            return float(-((x[0] - 2.0) ** 2))  # max at x=2

        res = GaussianProcessSearch([(0.0, 5.0)], seed=7).find(
            f, 15, maximize=True
        )
        assert abs(res.best_params[0] - 2.0) < 0.3


class TestCompileCache:
    """Persistent XLA compilation cache plumbing (utils/compile_cache.py)."""

    @pytest.fixture(autouse=True)
    def _restore_jax_cache_config(self):
        """These tests mutate process-global JAX config; restore it so
        later tests don't persist every trivial compile (min secs 0.0) or
        write into this class's tmp dirs."""
        import jax

        prev_dir = jax.config.jax_compilation_cache_dir
        prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
        yield
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_min
        )
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            pass

    def test_enable_returns_and_creates_dir(self, tmp_path):
        import jax

        from photon_ml_tpu.utils.compile_cache import enable_compile_cache

        target = str(tmp_path / "cache")
        got = enable_compile_cache(target, min_compile_secs=0.0)
        assert got == target
        assert os.path.isdir(target)
        assert jax.config.jax_compilation_cache_dir == target
        # A jitted computation should land an entry in the cache dir.  The
        # baked-in constant makes the HLO unique so an in-memory executable
        # from an earlier test can't satisfy it without a fresh compile.
        const = float(np.random.default_rng().uniform(1.0, 2.0))
        jax.jit(lambda x: x * 2.0 + const)(
            jax.numpy.ones((8, 8))
        ).block_until_ready()
        assert len(os.listdir(target)) >= 1

    def test_off_and_failure_are_non_fatal(self, tmp_path):
        import jax

        from photon_ml_tpu.utils import compile_cache

        # 'off' must actively disable a previously enabled cache (bench
        # relies on this for honest cold-run driver timing).
        compile_cache.enable_compile_cache(str(tmp_path / "on"))
        assert compile_cache.enable_compile_cache("off") is None
        assert jax.config.jax_compilation_cache_dir is None
        # unwritable parent: degrade to None, never raise
        blocked = tmp_path / "ro"
        blocked.mkdir()
        blocked.chmod(0o500)
        try:
            got = compile_cache.enable_compile_cache(str(blocked / "sub"))
            assert got is None or os.path.isdir(got)  # root can still write
        finally:
            blocked.chmod(0o700)

    def test_default_dir_env_override(self, monkeypatch, tmp_path):
        from photon_ml_tpu.utils import compile_cache

        monkeypatch.setenv("PHOTON_COMPILE_CACHE", str(tmp_path / "envcache"))
        assert compile_cache.default_cache_dir() == str(tmp_path / "envcache")


class TestMarginalLikelihoodFit:
    """length_scale='fit': type-II ML over a log grid (VERDICT r2 weak #6)."""

    def test_recovers_scale_ordering(self):
        """Smooth data must select a longer length scale than jagged data."""
        rng = np.random.default_rng(0)
        X = rng.uniform(size=(25, 1))
        y_smooth = np.sin(2.0 * np.pi * X[:, 0] * 0.5)
        y_jagged = np.sin(2.0 * np.pi * X[:, 0] * 6.0)
        ls_smooth = GaussianProcessModel("fit").fit(
            X, y_smooth
        ).fitted_length_scale
        ls_jagged = GaussianProcessModel("fit").fit(
            X, y_jagged
        ).fitted_length_scale
        assert ls_smooth > ls_jagged

    def test_fit_improves_interpolation_vs_bad_fixed_scale(self):
        rng = np.random.default_rng(1)
        X = rng.uniform(size=(30, 2))
        y = np.sin(3 * X[:, 0]) + np.cos(5 * X[:, 1])
        Xq = rng.uniform(size=(50, 2))
        yq = np.sin(3 * Xq[:, 0]) + np.cos(5 * Xq[:, 1])
        mean_fit, _ = GaussianProcessModel("fit").fit(X, y).predict(Xq)
        mean_bad, _ = GaussianProcessModel(5.0).fit(X, y).predict(Xq)
        assert np.mean((mean_fit - yq) ** 2) < np.mean((mean_bad - yq) ** 2)

    def test_invalid_length_scale_rejected(self):
        with pytest.raises(ValueError):
            GaussianProcessModel("auto")

    def test_gp_fit_beats_random_in_fewer_evals(self):
        """The VERDICT acceptance bar: the fitted-GP search reaches a
        better optimum on a known 2-D response surface than random search
        gets with MORE evaluations."""

        def branin_like(x):
            # Smooth 2-D bowl with a unique optimum at (0.65, 0.35).
            return (
                (x[0] - 0.65) ** 2 + (x[1] - 0.35) ** 2
                + 0.3 * np.sin(4 * x[0]) * np.sin(4 * x[1])
            )

        bounds = [(0.0, 1.0), (0.0, 1.0)]
        gp = GaussianProcessSearch(
            bounds, seed=7, n_seed_points=4, length_scale="fit"
        ).find(branin_like, n_iterations=15)
        rnd = RandomSearch(bounds, seed=7).find(branin_like, n_iterations=30)
        assert gp.best_value < rnd.best_value
        assert len(gp.history) == 15 and len(rnd.history) == 30
