"""Tuning subsystem tests: proposers, ASHA, executor, journal resume.

Also the first dedicated coverage of hyperparameter/search.py's GP
internals (previously only exercised incidentally via test_aux.py):
Cholesky jitter escalation and duplicate-point handling.
"""

import json
import os
import threading

import numpy as np
import pytest

from photon_ml_tpu.hyperparameter.search import (
    GaussianProcessModel,
    GaussianProcessSearch,
    _chol_with_jitter,
)
from photon_ml_tpu.tuning.executor import (
    TrialReport,
    TuningConfig,
    TuningOrchestrator,
)
from photon_ml_tpu.tuning.scheduler import (
    AshaConfig,
    AshaScheduler,
    GPProposer,
    GridProposer,
    RandomProposer,
    SearchSpace,
    make_proposer,
)
from photon_ml_tpu.tuning.state import (
    STATE_RECORD_TYPES,
    ResumeMismatch,
    SearchAborted,
    TrialStore,
    TuningJournal,
    replay_journal,
)
from photon_ml_tpu.utils.watchdog import RetryPolicy


def _cfg(**kw):
    kw.setdefault("max_trials", 8)
    kw.setdefault("workers", 2)
    kw.setdefault("retry", RetryPolicy())
    kw.setdefault("sleep", lambda s: None)
    return TuningConfig(**kw)


def _decisions(journal):
    """State-bearing journal records minus run-local noise."""
    out = []
    for rec in journal.read():
        if rec.get("type") in STATE_RECORD_TYPES:
            rec = {
                k: v for k, v in rec.items()
                if k not in ("wall", "wall_epoch")
            }
            out.append(rec)
    return out


class TestSearchSpace:
    def test_validation(self):
        with pytest.raises(ValueError):
            SearchSpace.create([(1.0, 1.0)])
        with pytest.raises(ValueError):
            SearchSpace.create([(0.0, 1.0)], log_scale=True)
        with pytest.raises(ValueError):
            SearchSpace.create([(0.0, 1.0)], names=["a", "b"])

    def test_fingerprint_tracks_geometry(self):
        a = SearchSpace.create([(1e-3, 1e3)], log_scale=True, names=["lam"])
        b = SearchSpace.create([(1e-3, 1e3)], log_scale=True, names=["lam"])
        c = SearchSpace.create([(1e-3, 1e2)], log_scale=True, names=["lam"])
        d = SearchSpace.create([(1e-3, 1e3)], log_scale=False, names=["lam"])
        assert a.fingerprint() == b.fingerprint()
        assert a.fingerprint() != c.fingerprint()
        assert a.fingerprint() != d.fingerprint()
        assert SearchSpace.from_config(a.to_config()) == a

    def test_sample_and_normalize(self):
        sp = SearchSpace.create(
            [(1e-2, 1e2), (-1.0, 3.0)], log_scale=[True, False]
        )
        X = sp.sample(np.random.default_rng(0), 200)
        assert X.shape == (200, 2)
        assert np.all(X[:, 0] >= 1e-2) and np.all(X[:, 0] <= 1e2)
        assert np.all(X[:, 1] >= -1.0) and np.all(X[:, 1] <= 3.0)
        Z = sp.normalize(X)
        assert np.all(Z >= 0.0) and np.all(Z <= 1.0)
        # Log dimension: the geometric midpoint maps to 0.5.
        z = sp.normalize(np.array([[1.0, 1.0]]))
        assert z[0, 0] == pytest.approx(0.5)
        assert z[0, 1] == pytest.approx(0.5)


class TestProposers:
    def test_random_deterministic_and_rng_roundtrip(self):
        sp = SearchSpace.create([(0.0, 1.0)] * 2)
        a, b = RandomProposer(sp, seed=3), RandomProposer(sp, seed=3)
        np.testing.assert_array_equal(a.ask(), b.ask())
        state = a.rng_state
        x1 = a.ask()
        a.set_rng_state(state)
        np.testing.assert_array_equal(a.ask(), x1)

    def test_pending_bookkeeping(self):
        sp = SearchSpace.create([(0.0, 1.0)])
        p = RandomProposer(sp, seed=0)
        x1, x2 = p.ask(), p.ask()
        assert len(p.pending) == 2
        p.tell(x1, 0.5)
        assert len(p.pending) == 1 and len(p.observations) == 1
        p.resolve(x2)
        assert not p.pending and len(p.observations) == 1

    def test_grid_order_exhaustion_restore(self):
        sp = SearchSpace.create([(0.0, 10.0)])
        g = GridProposer(sp, [[1.0], [2.0], [3.0]])
        assert g.ask()[0] == 1.0 and g.ask()[0] == 2.0
        assert not g.exhausted()
        assert g.ask()[0] == 3.0
        assert g.exhausted()
        g2 = GridProposer(sp, [[1.0], [2.0], [3.0]])
        g2.restore_ask(np.array([1.0]))
        assert g2.ask()[0] == 2.0

    def test_gp_constant_liar_batch_is_diverse(self):
        """With pending asks imputed at the incumbent, a batch of asks
        must not collapse onto one EI argmax."""
        sp = SearchSpace.create([(0.0, 1.0)])
        p = GPProposer(sp, seed=2, n_seed_points=2, n_candidates=128)
        # Two observations bracketing a clear minimum at 0.4.
        p.tell(np.array([0.2]), 0.04)
        p.tell(np.array([0.8]), 0.16)
        batch = [p.ask() for _ in range(4)]
        assert len(p.pending) == 4
        flat = [float(x[0]) for x in batch]
        assert len({round(v, 6) for v in flat}) == 4, flat
        assert all(0.0 <= v <= 1.0 for v in flat)

    def test_gp_cold_start_is_random_then_model_based(self):
        sp = SearchSpace.create([(0.0, 1.0)])
        p = GPProposer(sp, seed=5, n_seed_points=3)
        xs = [p.ask() for _ in range(3)]  # all cold-start samples
        for x, y in zip(xs, [0.5, 0.2, 0.9]):
            p.tell(x, y)
        x_gp = p.ask()  # surrogate path
        assert 0.0 <= float(x_gp[0]) <= 1.0

    def test_make_proposer_rejects_unknown(self):
        sp = SearchSpace.create([(0.0, 1.0)])
        with pytest.raises(ValueError):
            make_proposer("annealing", sp)


class TestGPRobustness:
    """Satellite: escalating Cholesky jitter + duplicate de-duplication
    in hyperparameter/search.py."""

    def test_duplicate_observations_do_not_crash_fit(self):
        X = np.array([[0.3], [0.3], [0.7], [0.7], [0.7]])
        y = np.array([1.0, 3.0, 2.0, 2.0, 2.0])
        gp = GaussianProcessModel().fit(X, y)
        mean, std = gp.predict(np.array([[0.3], [0.7]]))
        # Duplicates average: posterior interpolates the merged targets.
        assert mean[0] == pytest.approx(2.0, abs=0.1)
        assert mean[1] == pytest.approx(2.0, abs=0.1)
        assert np.all(np.isfinite(std))

    def test_near_duplicates_merge(self):
        X = np.array([[0.5], [0.5 + 1e-12], [0.9]])
        gp = GaussianProcessModel().fit(X, np.array([1.0, 2.0, 0.0]))
        assert gp._X.shape[0] == 2

    def test_jitter_ladder_recovers_psd(self):
        # Rank-1 PSD matrix: exact Cholesky fails, jitter succeeds.
        K = np.ones((6, 6))
        L = _chol_with_jitter(K)
        assert np.all(np.isfinite(L))

    def test_jitter_ladder_gives_up_loudly(self):
        with pytest.raises(np.linalg.LinAlgError, match="jitter"):
            _chol_with_jitter(-np.eye(3))

    def test_search_survives_duplicate_priors(self):
        def f(x):
            return float((x[0] - 2.0) ** 2)

        prior = (np.array([1.0]), f(np.array([1.0])))
        res = GaussianProcessSearch([(0.0, 5.0)], seed=1).find(
            f, 8, priors=[prior, prior, prior]
        )
        assert np.isfinite(res.best_value)


class TestAsha:
    def test_resource_geometry(self):
        cfg = AshaConfig(min_resource=2, reduction_factor=3, num_rungs=3)
        assert [cfg.resource(r) for r in range(3)] == [2, 6, 18]
        assert cfg.top_rung == 2

    def test_promote_kill_sequence(self):
        s = AshaScheduler(AshaConfig(1, 2, 3))
        # First report at a rung is trivially top — promoted.
        assert s.report(0, 0, 0.5) == "promote"
        # Worse than the incumbent with keep=max(1, 2//2)=1 — killed.
        assert s.report(1, 0, 0.9) == "stop"
        # n=3, keep=1: only the best of {0.5, 0.9, 0.1} promotes.
        assert s.report(2, 0, 0.1) == "promote"
        assert s.decide(0, 0) == "stop"
        # Top rung always completes.
        assert s.report(2, 2, 0.1) == "complete"

    def test_ties_break_by_trial_id(self):
        s = AshaScheduler(AshaConfig(1, 2, 2))
        s.record(0, 0, 0.5)
        s.record(1, 0, 0.5)
        assert s.decide(0, 0) == "promote"
        assert s.decide(1, 0) == "stop"

    def test_record_then_decide_matches_report(self):
        a = AshaScheduler(AshaConfig(1, 3, 2))
        b = AshaScheduler(AshaConfig(1, 3, 2))
        rng = np.random.default_rng(0)
        for i in range(9):
            y = float(rng.uniform())
            da = a.report(i, 0, y)
            b.record(i, 0, y)
            assert da == b.decide(i, 0)

    def test_config_validation(self):
        with pytest.raises(ValueError):
            AshaConfig(min_resource=0)
        with pytest.raises(ValueError):
            AshaConfig(reduction_factor=1)


class TestExecutor:
    def test_simple_search_finds_minimum(self, tmp_path):
        sp = SearchSpace.create([(0.0, 1.0)])
        journal = TuningJournal(str(tmp_path))
        res = TuningOrchestrator(
            sp, lambda p, r, w: float((p[0] - 0.37) ** 2),
            RandomProposer(sp, seed=4),
            _cfg(max_trials=12, workers=3), journal,
        ).run()
        journal.close()
        assert res.completed == 12 and res.failed == 0
        assert abs(res.best_params[0] - 0.37) < 0.2
        assert len(res.trials) == 12
        assert {t["status"] for t in res.trials} == {"completed"}

    def test_warm_start_chains_from_nearest_completed(self, tmp_path):
        sp = SearchSpace.create([(0.0, 10.0)])
        seen = {}
        lock = threading.Lock()

        def fn(p, r, w):
            x = float(p[0])
            with lock:
                seen[x] = None if w is None else float(np.asarray(w)[0])
            return TrialReport(
                metric=x, coefficients=np.array([x], np.float32)
            )

        journal = TuningJournal(str(tmp_path))
        TuningOrchestrator(
            sp, fn, GridProposer(sp, [[1.0], [2.0], [9.0]]),
            _cfg(max_trials=3, workers=1), journal,
        ).run()
        journal.close()
        assert seen[1.0] is None  # nothing completed yet
        assert seen[2.0] == 1.0  # nearest completed is 1.0
        assert seen[9.0] == 2.0  # 2.0 is nearer than 1.0

    def test_warm_start_disabled(self, tmp_path):
        sp = SearchSpace.create([(0.0, 10.0)])
        warm = []

        def fn(p, r, w):
            warm.append(w)
            return TrialReport(0.0, coefficients=np.zeros(1))

        journal = TuningJournal(str(tmp_path))
        TuningOrchestrator(
            sp, fn, GridProposer(sp, [[1.0], [2.0]]),
            _cfg(max_trials=2, workers=1, warm_start=False), journal,
        ).run()
        journal.close()
        assert warm == [None, None]

    def test_fatal_failure_marks_trial_and_continues(self, tmp_path):
        sp = SearchSpace.create([(0.0, 1.0)])

        def fn(p, r, w):
            if p[0] > 0.55 and p[0] < 0.65:
                raise ValueError("bad hyperparameters")
            return float(p[0])

        journal = TuningJournal(str(tmp_path))
        res = TuningOrchestrator(
            sp, fn, GridProposer(sp, [[0.1], [0.6], [0.9]]),
            _cfg(max_trials=3), journal,
        ).run()
        journal.close()
        assert res.failed == 1 and res.completed == 2
        failed = [t for t in res.trials if t["status"] == "failed"]
        assert len(failed) == 1
        assert "bad hyperparameters" in failed[0]["error"]
        assert res.best_metric == 0.1  # minimize; search continued

    def test_transient_failure_retries_in_place(self, tmp_path):
        sp = SearchSpace.create([(0.0, 1.0)])
        attempts = []
        sleeps = []

        def fn(p, r, w):
            attempts.append(float(p[0]))
            if len(attempts) == 1:
                raise RuntimeError("UNAVAILABLE: Socket closed")
            return 0.0

        journal = TuningJournal(str(tmp_path))
        res = TuningOrchestrator(
            sp, fn, GridProposer(sp, [[0.5]]),
            _cfg(
                max_trials=1, workers=1,
                retry=RetryPolicy(max_retries=2, backoff_seconds=7.0),
                sleep=sleeps.append,
            ),
            journal,
        ).run()
        journal.close()
        assert len(attempts) == 2 and res.completed == 1
        assert sleeps == [7.0]
        assert res.trials[0]["retries"] == 1
        kinds = [r["type"] for r in journal.read()]
        assert "retry" in kinds and "fail" not in kinds

    def test_transient_budget_exhausted_fails(self, tmp_path):
        sp = SearchSpace.create([(0.0, 1.0)])

        def fn(p, r, w):
            raise RuntimeError("UNAVAILABLE: device lost")

        journal = TuningJournal(str(tmp_path))
        res = TuningOrchestrator(
            sp, fn, GridProposer(sp, [[0.5]]),
            _cfg(max_trials=1, retry=RetryPolicy(max_retries=1)),
            journal,
        ).run()
        journal.close()
        assert res.failed == 1
        fail = [r for r in journal.read() if r["type"] == "fail"][0]
        assert fail["transient"] is True and fail["retries"] == 1

    def test_asha_prunes_and_promotes(self, tmp_path):
        sp = SearchSpace.create([(0.0, 1.0)])
        resources = {}
        lock = threading.Lock()

        def fn(p, r, w):
            with lock:
                resources.setdefault(float(p[0]), []).append(r)
            return float((p[0] - 0.3) ** 2)

        journal = TuningJournal(str(tmp_path))
        res = TuningOrchestrator(
            sp, fn,
            GridProposer(sp, [[0.3], [0.9], [0.35], [0.8]]),
            _cfg(
                max_trials=4, workers=2,
                asha=AshaConfig(
                    min_resource=5, reduction_factor=2, num_rungs=2
                ),
            ),
            journal,
        ).run()
        journal.close()
        assert res.pruned >= 1 and res.completed >= 1
        assert res.best_params == [0.3]
        # Rung resources follow the geometry: 5 then 10.
        assert resources[0.3] == [5, 10]
        assert all(rs[0] == 5 for rs in resources.values())

    def test_parallel_matches_sequential_on_pure_function(self, tmp_path):
        sp = SearchSpace.create([(1e-2, 1e2)], log_scale=True)
        fn = lambda p, r, w: float(np.log10(p[0]) ** 2)  # noqa: E731

        def sweep(workers, sub):
            journal = TuningJournal(str(tmp_path / sub))
            res = TuningOrchestrator(
                sp, fn, GPProposer(sp, seed=9),
                _cfg(
                    max_trials=8, workers=workers,
                    asha=AshaConfig(1, 2, 2),
                ),
                journal,
            ).run()
            journal.close()
            return res

        seq = sweep(1, "seq")
        par = sweep(4, "par")
        # Wave structure differs with worker count, so the histories may
        # differ — but both must land a valid search; the deterministic
        # contract within one worker count is exercised by resume tests.
        assert seq.n_trials == par.n_trials == 8
        assert seq.best_metric is not None and par.best_metric is not None


class TestGlmSweepParity:
    """The bench acceptance bar: parallel-4 vs sequential best-metric
    parity (±1e-6) on a real GLM λ sweep with warm starts ON."""

    def test_parity(self, tmp_path, rng):
        from photon_ml_tpu.drivers.glm_driver import make_fit_once
        from photon_ml_tpu.tuning.scheduler import GridProposer

        n, d = 600, 16
        X = rng.normal(size=(n, d)).astype(np.float32)
        w = rng.normal(size=d).astype(np.float32)
        y = (
            rng.uniform(size=n) < 1 / (1 + np.exp(-(X @ w)))
        ).astype(np.float32)
        fit_once = make_fit_once(
            X[:400], y[:400], X[400:], y[400:],
            task="logistic", reg_type="l2", max_iters=50, tolerance=1e-9,
        )
        sp = SearchSpace.create([(1e-4, 1e2)], log_scale=True)
        lambdas = [[lam] for lam in np.geomspace(1e-3, 10.0, 6)]

        def sweep(workers, sub):
            journal = TuningJournal(str(tmp_path / sub))
            res = TuningOrchestrator(
                sp, fit_once, GridProposer(sp, lambdas),
                _cfg(
                    max_trials=6, workers=workers,
                    maximize=fit_once.larger_is_better,
                ),
                journal,
            ).run()
            journal.close()
            return res

        seq = sweep(1, "seq")
        par = sweep(4, "par")
        assert seq.best_params == par.best_params
        assert abs(seq.best_metric - par.best_metric) <= 1e-6


class TestJournal:
    def test_fsync_append_and_read(self, tmp_path):
        j = TuningJournal(str(tmp_path))
        j.append({"type": "header", "x": 1})
        j.append({"type": "ask", "trial": 0})
        j.close()
        assert [r["type"] for r in j.read()] == ["header", "ask"]

    def test_torn_tail_dropped(self, tmp_path):
        j = TuningJournal(str(tmp_path))
        j.append({"type": "header"})
        j.append({"type": "ask", "trial": 0, "params": [1.0]})
        j.close()
        with open(j.path, "a") as f:
            f.write('{"type": "report", "trial": 0, "met')  # torn write
        assert [r["type"] for r in j.read()] == ["header", "ask"]

    def test_mid_file_corruption_raises(self, tmp_path):
        j = TuningJournal(str(tmp_path))
        j.append({"type": "header"})
        j.close()
        with open(j.path, "a") as f:
            f.write("garbage\n")
            f.write('{"type": "ask", "trial": 0}\n')
        with pytest.raises(ValueError, match="corrupt journal"):
            j.read()

    def test_abort_hook_fires_at_boundary(self, tmp_path):
        j = TuningJournal(str(tmp_path), abort_after=2)
        j.append({"type": "header"})
        j.append({"type": "ask"})
        with pytest.raises(SearchAborted):
            j.append({"type": "ask"})
        j.close()
        assert len(j.read()) == 2

    def test_replay_requires_header(self):
        with pytest.raises(ValueError, match="header"):
            replay_journal([{"type": "ask", "trial": 0}])

    def test_trial_store_roundtrip_and_clear(self, tmp_path):
        store = TrialStore(str(tmp_path))
        store.save(3, np.array([0.5]), np.arange(4, dtype=np.float32))
        params, coefs = store.load(3)
        assert params[0] == 0.5
        np.testing.assert_array_equal(coefs, np.arange(4, dtype=np.float32))
        assert store.load(7) is None
        store.clear()
        assert store.load(3) is None


class TestResume:
    """Kill the search at journal record boundaries, resume, and demand
    the identical trial history + decision sequence — the crash-safe
    reproducibility contract."""

    @staticmethod
    def _search(directory, abort=None, resume=False, seed=5):
        sp = SearchSpace.create([(1e-2, 1e2)], log_scale=True)
        journal = TuningJournal(directory, abort_after=abort)
        orch = TuningOrchestrator(
            sp,
            lambda p, r, w: float(np.log10(p[0]) ** 2 + 0.01 * r),
            GPProposer(sp, seed=seed),
            _cfg(
                max_trials=6, workers=3,
                asha=AshaConfig(1, 2, 2),
                maximize=False,
            ),
            journal,
        )
        try:
            return orch.run(resume=resume), journal
        finally:
            journal.close()

    def test_kill_resume_bit_parity(self, tmp_path):
        ref, ref_journal = self._search(str(tmp_path / "ref"))
        n = len(ref_journal.read())
        assert n > 10
        for abort_at in range(2, n, 5):
            d = str(tmp_path / f"killed_{abort_at}")
            with pytest.raises(SearchAborted):
                self._search(d, abort=abort_at)
            resumed, journal = self._search(d, resume=True)
            assert resumed.trials == ref.trials, f"abort@{abort_at}"
            assert _decisions(journal) == _decisions(ref_journal), (
                f"abort@{abort_at}"
            )
            assert resumed.best_metric == ref.best_metric

    def test_resume_refuses_changed_space(self, tmp_path):
        d = str(tmp_path)
        with pytest.raises(SearchAborted):
            self._search(d, abort=4)
        sp = SearchSpace.create([(1e-3, 1e2)], log_scale=True)  # changed
        journal = TuningJournal(d)
        orch = TuningOrchestrator(
            sp, lambda p, r, w: 0.0, GPProposer(sp, seed=5),
            _cfg(max_trials=6, workers=3, asha=AshaConfig(1, 2, 2)),
            journal,
        )
        with pytest.raises(ResumeMismatch, match="search space"):
            orch.run(resume=True)
        journal.close()

    def test_resume_refuses_changed_config(self, tmp_path):
        d = str(tmp_path)
        with pytest.raises(SearchAborted):
            self._search(d, abort=4)
        sp = SearchSpace.create([(1e-2, 1e2)], log_scale=True)
        journal = TuningJournal(d)
        orch = TuningOrchestrator(
            sp, lambda p, r, w: 0.0, GPProposer(sp, seed=5),
            _cfg(max_trials=6, workers=4, asha=AshaConfig(1, 2, 2)),
            journal,  # workers 3 -> 4
        )
        with pytest.raises(ResumeMismatch, match="workers"):
            orch.run(resume=True)
        journal.close()

    def test_resume_without_journal_fails(self, tmp_path):
        sp = SearchSpace.create([(0.0, 1.0)])
        journal = TuningJournal(str(tmp_path))
        orch = TuningOrchestrator(
            sp, lambda p, r, w: 0.0, RandomProposer(sp),
            _cfg(max_trials=2), journal,
        )
        with pytest.raises(ResumeMismatch, match="no journal"):
            orch.run(resume=True)
        journal.close()

    def test_resume_after_torn_tail(self, tmp_path):
        d = str(tmp_path)
        with pytest.raises(SearchAborted):
            self._search(d, abort=6)
        path = os.path.join(d, TuningJournal.FILENAME)
        with open(path, "a") as f:
            f.write('{"type": "report", "tri')  # torn mid-write record
        resumed, journal = self._search(d, resume=True)
        journal.close()
        ref, ref_journal = self._search(str(tmp_path / "ref"))
        ref_journal.close()
        assert resumed.trials == ref.trials


class TestFitOnceEntries:
    def test_glm_fit_once(self, rng):
        from photon_ml_tpu.drivers.glm_driver import make_fit_once

        n, d = 300, 8
        X = rng.normal(size=(n, d)).astype(np.float32)
        w = rng.normal(size=d).astype(np.float32)
        y = (
            rng.uniform(size=n) < 1 / (1 + np.exp(-(X @ w)))
        ).astype(np.float32)
        fit_once = make_fit_once(
            X[:200], y[:200], X[200:], y[200:],
            task="logistic", reg_type="l2",
        )
        assert fit_once.larger_is_better  # AUC
        metric, metrics, coefs = fit_once(np.array([0.1]), 0, None)
        assert 0.0 <= metric <= 1.0
        assert fit_once.suite.primary in metrics
        assert coefs.shape == (d,)
        # resource caps iterations: 1 iteration from zero is a worse fit.
        weak, _, weak_coefs = fit_once(np.array([0.1]), 1, None)
        assert not np.allclose(weak_coefs, coefs)
        # warm start at the converged solution reproduces it.
        again, _, coefs2 = fit_once(np.array([0.1]), 0, coefs)
        assert again == pytest.approx(metric, abs=1e-6)

    def test_game_fit_once(self):
        from photon_ml_tpu.tuning.__main__ import synthetic_game_fit_once

        fit_once = synthetic_game_fit_once(seed=1)
        m1, metrics, coefs = fit_once(np.array([1.0, 1.0]), 1, None)
        assert 0.0 <= m1 <= 1.0 and coefs is None
        assert fit_once.suite.primary in metrics
        # A wildly different regularization changes the fit.
        m2, _, _ = fit_once(np.array([100.0, 100.0]), 1, None)
        assert m1 != m2
        # Deterministic: same params, same metric, any call order.
        m1b, _, _ = fit_once(np.array([1.0, 1.0]), 1, None)
        assert m1b == m1

    def test_suite_evaluate_primary(self):
        from photon_ml_tpu.evaluation.suite import EvaluationSuite

        suite = EvaluationSuite.from_specs(["auc", "logistic_loss"])
        scores = np.array([-2.0, -1.0, 1.0, 2.0])
        labels = np.array([0.0, 0.0, 1.0, 1.0])
        primary, values = suite.evaluate_primary(scores, labels)
        assert primary == values["auc"] == 1.0
        assert "logistic_loss" in values


class TestSelfcheck:
    def test_selfcheck_passes(self, tmp_path):
        from photon_ml_tpu.tuning.__main__ import run_selfcheck

        failures = run_selfcheck(str(tmp_path))
        assert failures == []
        # The journal + telemetry artifacts exist where documented.
        assert os.path.exists(
            tmp_path / "search_a" / TuningJournal.FILENAME
        )
        assert os.path.exists(tmp_path / "metrics.json")
        with open(tmp_path / "metrics.json") as f:
            snap = json.load(f)
        assert snap["counters"]["tuning_trials_pruned"] >= 1
        assert snap["counters"]["tuning_trials_failed"] == 1
