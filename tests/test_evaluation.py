"""Evaluator tests vs sklearn oracles."""

import numpy as np
import pytest
import sklearn.metrics as skm

from photon_ml_tpu.evaluation.evaluators import (
    AreaUnderROCCurveEvaluator,
    LogisticLossEvaluator,
    PoissonLossEvaluator,
    PrecisionAtKEvaluator,
    RMSEEvaluator,
    get_evaluator,
)


class TestAUC:
    def test_matches_sklearn(self, rng):
        y = (rng.uniform(size=500) < 0.3).astype(float)
        s = rng.normal(size=500) + y
        ours = AreaUnderROCCurveEvaluator().evaluate(s, y)
        np.testing.assert_allclose(ours, skm.roc_auc_score(y, s), atol=1e-12)

    def test_ties_match_sklearn(self, rng):
        y = (rng.uniform(size=300) < 0.4).astype(float)
        s = np.round(rng.normal(size=300), 1)  # heavy ties
        ours = AreaUnderROCCurveEvaluator().evaluate(s, y)
        np.testing.assert_allclose(ours, skm.roc_auc_score(y, s), atol=1e-12)

    def test_weighted_matches_sklearn(self, rng):
        y = (rng.uniform(size=400) < 0.5).astype(float)
        s = rng.normal(size=400) + 0.5 * y
        w = rng.uniform(0.1, 3.0, size=400)
        ours = AreaUnderROCCurveEvaluator().evaluate(s, y, w)
        np.testing.assert_allclose(
            ours, skm.roc_auc_score(y, s, sample_weight=w), atol=1e-10
        )

    def test_zero_weight_rows_excluded(self, rng):
        y = np.array([1.0, 0.0, 1.0, 0.0])
        s = np.array([2.0, 1.0, -5.0, -6.0])
        w = np.array([1.0, 1.0, 0.0, 0.0])  # padding rows
        ours = AreaUnderROCCurveEvaluator().evaluate(s, y, w)
        assert ours == 1.0

    def test_grouped_auc(self, rng):
        y = (rng.uniform(size=200) < 0.5).astype(float)
        s = rng.normal(size=200) + y
        g = rng.integers(0, 5, size=200)
        ours = AreaUnderROCCurveEvaluator().evaluate(s, y, group_ids=g)
        per_group = [
            skm.roc_auc_score(y[g == k], s[g == k])
            for k in range(5)
            if len(np.unique(y[g == k])) == 2
        ]
        np.testing.assert_allclose(ours, np.mean(per_group), atol=1e-12)


class TestOtherMetrics:
    def test_rmse(self, rng):
        y = rng.normal(size=100)
        s = y + rng.normal(size=100)
        ours = RMSEEvaluator().evaluate(s, y)
        np.testing.assert_allclose(
            ours, np.sqrt(skm.mean_squared_error(y, s)), atol=1e-12
        )

    def test_logloss_matches_sklearn(self, rng):
        y = (rng.uniform(size=200) < 0.5).astype(float)
        margins = rng.normal(size=200)
        p = 1 / (1 + np.exp(-margins))
        ours = LogisticLossEvaluator().evaluate(margins, y)
        np.testing.assert_allclose(ours, skm.log_loss(y, p), atol=1e-10)

    def test_poisson_loss_decreases_with_fit(self, rng):
        y = rng.poisson(3.0, size=200).astype(float)
        good = np.log(np.maximum(y, 0.5))
        bad = np.zeros(200)
        ev = PoissonLossEvaluator()
        assert ev.evaluate(good, y) < ev.evaluate(bad, y)
        assert not ev.larger_is_better

    def test_precision_at_k(self):
        # Two groups; top-2 hits are (1,0) and (1,1) → mean precision 0.75.
        s = np.array([3.0, 2.0, 1.0, 9.0, 8.0, 7.0])
        y = np.array([1.0, 0.0, 1.0, 1.0, 1.0, 0.0])
        g = np.array([0, 0, 0, 1, 1, 1])
        ours = PrecisionAtKEvaluator(k=2).evaluate(s, y, group_ids=g)
        assert ours == pytest.approx(0.75)

    def test_get_evaluator_specs(self):
        assert isinstance(get_evaluator("AUC"), AreaUnderROCCurveEvaluator)
        assert get_evaluator("precision@5").k == 5
        with pytest.raises(KeyError):
            get_evaluator("nope")


class TestVectorizedGroupedMetrics:
    """The grouped AUC / precision@k segment-math paths must match a naive
    per-group loop exactly (the loop is what the reference computes)."""

    def _naive_grouped_auc(self, scores, labels, weights, group_ids):
        from photon_ml_tpu.evaluation.evaluators import _auc

        aucs = []
        for gid in np.unique(group_ids):
            m = group_ids == gid
            a = _auc(scores[m], labels[m], weights[m])
            if not np.isnan(a):
                aucs.append(a)
        return float(np.mean(aucs)) if aucs else float("nan")

    def _naive_prec(self, scores, labels, group_ids, k):
        precs = []
        for gid in np.unique(group_ids):
            m = group_ids == gid
            s, y = scores[m], labels[m]
            kk = min(k, len(s))
            top = np.argsort(-s, kind="stable")[:kk]
            precs.append(np.mean(y[top] > 0))
        return float(np.mean(precs))

    def test_grouped_auc_matches_loop(self, rng):
        from photon_ml_tpu.evaluation.evaluators import _grouped_auc_mean

        for trial in range(5):
            n = int(rng.integers(50, 400))
            g = rng.integers(0, int(rng.integers(3, 40)), size=n)
            gids = np.array([f"q{i}" for i in g])
            # Quantized scores force plenty of ties.
            s = np.round(rng.normal(size=n), 1)
            y = (rng.uniform(size=n) < 0.4).astype(np.float64)
            w = rng.uniform(0.5, 2.0, size=n)
            got = _grouped_auc_mean(s, y, w, gids)
            want = self._naive_grouped_auc(s, y, w, gids)
            if np.isnan(want):
                assert np.isnan(got)
            else:
                assert got == pytest.approx(want, abs=1e-12), trial

    def test_grouped_auc_skips_single_class_groups(self):
        from photon_ml_tpu.evaluation.evaluators import _grouped_auc_mean

        s = np.array([0.1, 0.9, 0.3, 0.7])
        y = np.array([1.0, 1.0, 0.0, 1.0])     # group a: all positive
        w = np.ones(4)
        g = np.array(["a", "a", "b", "b"])
        got = _grouped_auc_mean(s, y, w, g)
        assert got == pytest.approx(1.0)        # only group b counts

    def test_grouped_auc_all_invalid_is_nan(self):
        from photon_ml_tpu.evaluation.evaluators import _grouped_auc_mean

        s = np.array([0.1, 0.9])
        y = np.array([1.0, 1.0])
        assert np.isnan(_grouped_auc_mean(s, y, np.ones(2),
                                          np.array(["a", "a"])))

    def test_precision_at_k_matches_loop(self, rng):
        from photon_ml_tpu.evaluation.evaluators import PrecisionAtKEvaluator

        for k in (1, 3, 10):
            ev = PrecisionAtKEvaluator(k=k)
            n = 300
            g = rng.integers(0, 25, size=n)
            gids = np.array([f"q{i}" for i in g])
            s = np.round(rng.normal(size=n), 1)
            y = (rng.uniform(size=n) < 0.3).astype(np.float64)
            got = ev._compute(s, y, np.ones(n), gids)
            want = self._naive_prec(s, y, gids, k)
            assert got == pytest.approx(want, abs=1e-12), k

    def test_scales_to_many_groups(self, rng):
        """10^5 groups complete in well under a second (the loop took
        minutes at this scale)."""
        import time

        from photon_ml_tpu.evaluation.evaluators import _grouped_auc_mean

        n, n_groups = 400_000, 100_000
        g = rng.integers(0, n_groups, size=n)
        s = rng.normal(size=n)
        y = (rng.uniform(size=n) < 0.5).astype(np.float64)
        w = np.ones(n)
        t0 = time.perf_counter()
        val = _grouped_auc_mean(s, y, w, g)
        assert time.perf_counter() - t0 < 5.0
        assert 0.3 < val < 0.7

    def test_empty_input_returns_nan_not_crash(self):
        """All-zero weights mask every row; both grouped metrics must
        return NaN like the old loops, not IndexError."""
        from photon_ml_tpu.evaluation.evaluators import (
            AreaUnderROCCurveEvaluator,
            PrecisionAtKEvaluator,
            _grouped_auc_mean,
        )

        empty_f = np.empty(0, np.float64)
        empty_s = np.empty(0, dtype="<U2")
        assert np.isnan(_grouped_auc_mean(empty_f, empty_f, empty_f, empty_s))
        got = PrecisionAtKEvaluator(k=3)._compute(
            empty_f, empty_f, empty_f, empty_s
        )
        assert np.isnan(got)
        # Through the public evaluate() with zero weights.
        ev = AreaUnderROCCurveEvaluator()
        s = np.array([0.5, 0.1]); y = np.array([1.0, 0.0])
        out = ev.evaluate(s, y, weights=np.zeros(2),
                          group_ids=np.array(["a", "a"]))
        assert np.isnan(out)
