"""Evaluator tests vs sklearn oracles."""

import numpy as np
import pytest
import sklearn.metrics as skm

from photon_ml_tpu.evaluation.evaluators import (
    AreaUnderROCCurveEvaluator,
    LogisticLossEvaluator,
    PoissonLossEvaluator,
    PrecisionAtKEvaluator,
    RMSEEvaluator,
    get_evaluator,
)


class TestAUC:
    def test_matches_sklearn(self, rng):
        y = (rng.uniform(size=500) < 0.3).astype(float)
        s = rng.normal(size=500) + y
        ours = AreaUnderROCCurveEvaluator().evaluate(s, y)
        np.testing.assert_allclose(ours, skm.roc_auc_score(y, s), atol=1e-12)

    def test_ties_match_sklearn(self, rng):
        y = (rng.uniform(size=300) < 0.4).astype(float)
        s = np.round(rng.normal(size=300), 1)  # heavy ties
        ours = AreaUnderROCCurveEvaluator().evaluate(s, y)
        np.testing.assert_allclose(ours, skm.roc_auc_score(y, s), atol=1e-12)

    def test_weighted_matches_sklearn(self, rng):
        y = (rng.uniform(size=400) < 0.5).astype(float)
        s = rng.normal(size=400) + 0.5 * y
        w = rng.uniform(0.1, 3.0, size=400)
        ours = AreaUnderROCCurveEvaluator().evaluate(s, y, w)
        np.testing.assert_allclose(
            ours, skm.roc_auc_score(y, s, sample_weight=w), atol=1e-10
        )

    def test_zero_weight_rows_excluded(self, rng):
        y = np.array([1.0, 0.0, 1.0, 0.0])
        s = np.array([2.0, 1.0, -5.0, -6.0])
        w = np.array([1.0, 1.0, 0.0, 0.0])  # padding rows
        ours = AreaUnderROCCurveEvaluator().evaluate(s, y, w)
        assert ours == 1.0

    def test_grouped_auc(self, rng):
        y = (rng.uniform(size=200) < 0.5).astype(float)
        s = rng.normal(size=200) + y
        g = rng.integers(0, 5, size=200)
        ours = AreaUnderROCCurveEvaluator().evaluate(s, y, group_ids=g)
        per_group = [
            skm.roc_auc_score(y[g == k], s[g == k])
            for k in range(5)
            if len(np.unique(y[g == k])) == 2
        ]
        np.testing.assert_allclose(ours, np.mean(per_group), atol=1e-12)


class TestOtherMetrics:
    def test_rmse(self, rng):
        y = rng.normal(size=100)
        s = y + rng.normal(size=100)
        ours = RMSEEvaluator().evaluate(s, y)
        np.testing.assert_allclose(
            ours, np.sqrt(skm.mean_squared_error(y, s)), atol=1e-12
        )

    def test_logloss_matches_sklearn(self, rng):
        y = (rng.uniform(size=200) < 0.5).astype(float)
        margins = rng.normal(size=200)
        p = 1 / (1 + np.exp(-margins))
        ours = LogisticLossEvaluator().evaluate(margins, y)
        np.testing.assert_allclose(ours, skm.log_loss(y, p), atol=1e-10)

    def test_poisson_loss_decreases_with_fit(self, rng):
        y = rng.poisson(3.0, size=200).astype(float)
        good = np.log(np.maximum(y, 0.5))
        bad = np.zeros(200)
        ev = PoissonLossEvaluator()
        assert ev.evaluate(good, y) < ev.evaluate(bad, y)
        assert not ev.larger_is_better

    def test_precision_at_k(self):
        # Two groups; top-2 hits are (1,0) and (1,1) → mean precision 0.75.
        s = np.array([3.0, 2.0, 1.0, 9.0, 8.0, 7.0])
        y = np.array([1.0, 0.0, 1.0, 1.0, 1.0, 0.0])
        g = np.array([0, 0, 0, 1, 1, 1])
        ours = PrecisionAtKEvaluator(k=2).evaluate(s, y, group_ids=g)
        assert ours == pytest.approx(0.75)

    def test_get_evaluator_specs(self):
        assert isinstance(get_evaluator("AUC"), AreaUnderROCCurveEvaluator)
        assert get_evaluator("precision@5").k == 5
        with pytest.raises(KeyError):
            get_evaluator("nope")
