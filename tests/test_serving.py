"""Online serving subsystem tests (ISSUE 3).

The load-bearing contract: every score the micro-batched path produces is
BIT-IDENTICAL to single-request scoring, across the whole bucket ladder
and regardless of an entity's hot/cold state.  Plus the operational
behaviors: coalescing, admission control (queue-full rejection), deadline
timeouts classified through the watchdog vocabulary, and LRU hot-set
eviction/refill.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from photon_ml_tpu.serving.batcher import (
    BatcherConfig,
    DeadlineExceededError,
    MicroBatcher,
    RejectedError,
)
from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
from photon_ml_tpu.serving.service import ScoringService, start_http_server
from photon_ml_tpu.serving.synthetic import SyntheticWorkload


@pytest.fixture(scope="module")
def workload():
    return SyntheticWorkload(n_entities=32, seed=7, unknown_rate=0.1)


def _runtime(workload, **kwargs):
    cfg = RuntimeConfig(**{"max_batch_size": 8, "hot_entities": 8, **kwargs})
    return ScoringRuntime(workload.model, workload.index_maps, cfg)


def _rows(runtime, workload, n, start=0):
    return [
        runtime.parse_request(workload.request(i))
        for i in range(start, start + n)
    ]


class TestRuntimeParity:
    def test_batched_bit_identical_to_single_all_buckets(self, workload):
        runtime = _runtime(workload)
        rows = _rows(runtime, workload, runtime.buckets[-1])
        # Reference: every row alone (bucket 1), BEFORE any batch has
        # warmed the hot set.
        reference = np.asarray(
            [runtime.score_rows([r])[0][0] for r in rows], np.float32
        )
        for n in range(1, len(rows) + 1):
            margins, means = runtime.score_rows(rows[:n])
            assert margins.tobytes() == reference[:n].tobytes(), (
                f"bucket for n={n} broke bit-parity"
            )
            # means are the margins through the task's inverse link,
            # elementwise — same parity requirement.
            assert means.shape == (n,)

    def test_parity_unchanged_by_hot_cold_state(self, workload):
        """The same row scores identically whether its entity comes from
        the device hot table or the host cold gather."""
        runtime = _runtime(workload, hot_entities=4)
        row = runtime.parse_request(workload.request(1))
        cold_score = runtime.score_rows([row])[0][0]  # cold: promotes
        hot_score = runtime.score_rows([row])[0][0]  # now hot
        assert np.float32(cold_score).tobytes() == \
            np.float32(hot_score).tobytes()

    def test_offset_and_unknown_entity(self, workload):
        runtime = _runtime(workload)
        req = workload.request(2)
        req["ids"] = {"userId": "never-trained"}
        base = runtime.score_rows([runtime.parse_request(req)])[0][0]
        req2 = dict(req, offset=(req.get("offset") or 0.0) + 1.0)
        shifted = runtime.score_rows([runtime.parse_request(req2)])[0][0]
        assert shifted == pytest.approx(base + 1.0, abs=1e-6)
        assert runtime.stats()["hot_sets"]["per_entity"][
            "unknown_entities"] >= 1

    def test_matches_batch_transformer(self, workload):
        """Online margins agree with the batch GameTransformer (shared
        kernels; float32 tolerance — dense jit reduce vs scipy matvec)."""
        import scipy.sparse as sp

        from photon_ml_tpu.game.estimator import GameTransformer

        runtime = _runtime(workload)
        rows = _rows(runtime, workload, 8)
        margins, _ = runtime.score_rows(rows)
        shards = {
            workload.fixed_shard: sp.csr_matrix(
                np.stack([r.features[workload.fixed_shard] for r in rows])
            ),
            workload.re_shard: sp.csr_matrix(
                np.stack([r.features[workload.re_shard] for r in rows])
            ),
        }
        ids = {
            workload.entity_key: np.asarray(
                [r.ids.get(workload.entity_key) for r in rows], object
            )
        }
        offsets = np.asarray([r.offset for r in rows], np.float32)
        batch = GameTransformer(workload.model).transform(
            shards, ids, offsets
        )
        np.testing.assert_allclose(margins, batch, rtol=1e-5, atol=1e-6)

    def test_named_features_resolve_through_index_map(self, workload):
        runtime = _runtime(workload)
        dense_req = workload.request(3)
        named_req = {
            "features": {
                workload.fixed_shard: [
                    {"name": f"g{j}", "term": "", "value": v}
                    for j, v in enumerate(
                        dense_req["dense"][workload.fixed_shard]
                    )
                ] + [{"name": "UNSEEN", "term": "", "value": 99.0}],
                workload.re_shard: [
                    [f"r{j}", "", v]  # triple form
                    for j, v in enumerate(
                        dense_req["dense"][workload.re_shard]
                    )
                ],
            },
            "ids": dense_req["ids"],
            "offset": dense_req["offset"],
        }
        a = runtime.score_rows([runtime.parse_request(dense_req)])[0][0]
        b = runtime.score_rows([runtime.parse_request(named_req)])[0][0]
        assert np.float32(a).tobytes() == np.float32(b).tobytes()

    def test_parse_rejects_bad_input(self, workload):
        runtime = _runtime(workload)
        with pytest.raises(ValueError, match="unknown feature shard"):
            runtime.parse_request({"dense": {"nope": [1.0]}})
        with pytest.raises(ValueError, match="expects"):
            runtime.parse_request(
                {"dense": {workload.fixed_shard: [1.0, 2.0]}}
            )
        with pytest.raises(ValueError, match="exceeds max_batch_size"):
            runtime.score_rows(
                _rows(runtime, workload, runtime.buckets[-1] + 1)
            )

    def test_warmup_compiles_every_bucket(self, workload):
        runtime = ScoringRuntime(
            workload.model, workload.index_maps,
            RuntimeConfig(max_batch_size=8, hot_entities=4, warmup=False),
        )
        assert runtime.warmup_compiles == 0
        n = runtime.warm_up()
        assert n == len(runtime.buckets) == 4  # [1, 2, 4, 8]
        # Warm again: everything already compiled.
        assert runtime.warm_up() == 0


class TestHotSetLRU:
    def test_eviction_and_refill(self, workload):
        runtime = _runtime(workload, hot_entities=2)
        hot = runtime.random[0].hot

        def score_entity(i, ent):
            req = workload.request(i)
            req["ids"] = {workload.entity_key: ent}
            return runtime.score_rows([runtime.parse_request(req)])[0][0]

        s1 = score_entity(0, "u1")  # cold -> promote
        score_entity(1, "u2")  # cold -> promote (table full)
        assert hot.hot_keys() == ["u1", "u2"]
        score_entity(2, "u1")  # hot hit, u1 becomes MRU
        assert hot.hits == 1 and hot.hot_keys() == ["u2", "u1"]
        score_entity(3, "u3")  # cold -> evicts LRU u2
        assert hot.evictions == 1 and hot.hot_keys() == ["u1", "u3"]
        # Refill: the evicted entity scores through the cold path again,
        # bit-identically, and re-promotes.
        s1_again = score_entity(0, "u1")
        assert np.float32(s1).tobytes() == np.float32(s1_again).tobytes()
        score_entity(4, "u2")
        assert "u2" in hot.hot_keys() and hot.misses == 4

    def test_zero_capacity_serves_cold_only(self, workload):
        runtime = _runtime(workload, hot_entities=0)
        ref = _runtime(workload, hot_entities=8)
        rows = _rows(runtime, workload, 8)
        a, _ = runtime.score_rows(rows)
        b, _ = ref.score_rows(rows)
        assert a.tobytes() == b.tobytes()
        assert runtime.random[0].hot.size == 0


class TestMicroBatcher:
    def test_coalesces_concurrent_submissions(self, workload):
        runtime = _runtime(workload)
        batcher = MicroBatcher(runtime, BatcherConfig(
            max_batch_size=8, max_wait_us=50_000, max_queue=64,
        ))
        rows = _rows(runtime, workload, 8)
        reference = np.asarray(
            [runtime.score_rows([r])[0][0] for r in rows], np.float32
        )
        # Enqueue everything BEFORE starting the dispatcher: the first
        # pop must coalesce the rest into one batch deterministically.
        futures = [batcher.submit(r) for r in rows]
        batcher.start()
        got = np.asarray(
            [f.result(timeout=30)["score"] for f in futures], np.float32
        )
        batcher.stop()
        assert got.tobytes() == reference.tobytes()
        stats = batcher.stats()
        assert stats["batches"] == 1 and stats["max_batch_rows"] == 8

    def test_queue_full_rejection(self, workload):
        runtime = _runtime(workload)
        batcher = MicroBatcher(runtime, BatcherConfig(max_queue=3))
        rows = _rows(runtime, workload, 4)
        for r in rows[:3]:
            batcher.submit(r)
        with pytest.raises(RejectedError, match="UNAVAILABLE"):
            batcher.submit(rows[3])
        stats = batcher.stats()
        assert stats["rejected"] == 1
        # UNAVAILABLE is transient in the watchdog vocabulary: clients
        # may retry with backoff.
        assert stats["failed_transient"] == 1
        batcher.start()
        batcher.stop()  # drains the 3 queued rows before exiting

    def test_deadline_timeout_classified_transient(self, workload):
        runtime = _runtime(workload)
        batcher = MicroBatcher(runtime, BatcherConfig())
        row = _rows(runtime, workload, 1)[0]
        fut = batcher.submit(row, timeout_ms=1.0)
        time.sleep(0.02)  # deadline passes while the dispatcher is down
        batcher.start()
        with pytest.raises(DeadlineExceededError, match="DEADLINE_EXCEEDED"):
            fut.result(timeout=30)
        batcher.stop()
        stats = batcher.stats()
        assert stats["expired"] == 1 and stats["failed_transient"] == 1
        from photon_ml_tpu.utils.watchdog import RetryPolicy

        verdict = RetryPolicy().classify(fut.exception())
        assert verdict.transient and verdict.matched == "DEADLINE_EXCEEDED"

    def test_default_timeout_from_config(self, workload):
        runtime = _runtime(workload)
        batcher = MicroBatcher(
            runtime, BatcherConfig(default_timeout_ms=1.0)
        )
        fut = batcher.submit(_rows(runtime, workload, 1)[0])
        time.sleep(0.02)
        batcher.start()
        with pytest.raises(DeadlineExceededError):
            fut.result(timeout=30)
        batcher.stop()


class TestScoringService:
    def test_concurrent_clients_stress(self, workload):
        runtime = _runtime(workload, max_batch_size=16)
        n_clients, per_client = 8, 25
        requests = [
            workload.request(i) for i in range(n_clients * per_client)
        ]
        reference = np.asarray([
            runtime.score_rows([runtime.parse_request(r)])[0][0]
            for r in requests
        ], np.float32)
        service = ScoringService(runtime, BatcherConfig(
            max_batch_size=16, max_wait_us=500, max_queue=512,
        ))
        results = np.zeros(len(requests), np.float32)
        errors: list = []

        def client(c):
            for k in range(per_client):
                i = c * per_client + k
                try:
                    results[i] = np.float32(
                        service.score(requests[i], timeout=60)["score"]
                    )
                except Exception as exc:  # noqa: BLE001
                    errors.append((i, exc))

        with service:
            threads = [
                threading.Thread(target=client, args=(c,))
                for c in range(n_clients)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert not errors
        assert results.tobytes() == reference.tobytes()
        stats = service.stats()
        assert stats["batcher"]["completed"] == len(requests)

    def test_score_many_reports_per_row_errors(self, workload):
        runtime = _runtime(workload)
        service = ScoringService(runtime)
        good = workload.request(0)
        bad = {"dense": {"nope": [1.0]}}
        with service:
            results = service.score_many([good, bad, good])
        assert "score" in results[0] and "score" in results[2]
        assert results[1]["kind"] == "bad_request"

    def test_http_endpoint(self, workload):
        runtime = _runtime(workload)
        reference = [
            float(runtime.score_rows(
                [runtime.parse_request(workload.request(i))]
            )[0][0])
            for i in range(3)
        ]
        service = ScoringService(runtime)
        with service:
            server, _ = start_http_server(service, port=0)
            port = server.server_address[1]
            try:
                body = json.dumps(
                    {"rows": [workload.request(i) for i in range(3)]}
                ).encode()
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/score", data=body,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=30) as resp:
                    assert resp.status == 200
                    results = json.loads(resp.read())["results"]
                got = [np.float32(r["score"]) for r in results]
                assert got == [np.float32(r) for r in reference]
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10
                ) as resp:
                    health = json.loads(resp.read())
                    assert health["status"] == "ok"
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/stats", timeout=10
                ) as resp:
                    stats = json.loads(resp.read())
                    assert stats["batcher"]["completed"] >= 3
                # Bad request -> 400 with a JSON error body.
                bad = urllib.request.Request(
                    f"http://127.0.0.1:{port}/score",
                    data=json.dumps(
                        {"rows": [{"dense": {"nope": [1]}}]}
                    ).encode(),
                )
                try:
                    urllib.request.urlopen(bad, timeout=10)
                    raise AssertionError("expected HTTP 400")
                except urllib.error.HTTPError as e:
                    assert e.code == 400
                    assert json.loads(e.read())["results"][0]["kind"] == \
                        "bad_request"
            finally:
                server.shutdown()
                server.server_close()

    def test_glm_model_serves(self):
        from photon_ml_tpu.models.glm import (
            Coefficients,
            GeneralizedLinearModel,
        )

        rng = np.random.default_rng(5)
        w = rng.normal(size=6).astype(np.float32)
        glm = GeneralizedLinearModel(Coefficients(means=w), "logistic")
        runtime = ScoringRuntime.from_glm_model(
            glm, shard="features",
            config=RuntimeConfig(max_batch_size=4, hot_entities=0),
        )
        x = rng.normal(size=6).astype(np.float32)
        margins, means = runtime.score_rows([runtime.parse_request(
            {"dense": {"features": x.tolist()}}
        )])
        assert margins[0] == pytest.approx(float(np.sum(x * w)), rel=1e-5)
        assert 0.0 < means[0] < 1.0  # sigmoid of the margin


class TestSelfcheckAndLoadGen:
    def test_selfcheck_passes(self, tmp_path):
        from photon_ml_tpu.serving.__main__ import run_selfcheck

        failures = run_selfcheck(str(tmp_path))
        assert failures == []
        with open(tmp_path / "metrics.json") as f:
            snap = json.load(f)
        assert snap["histograms"]["serving_request_latency_seconds"][
            "count"] >= 24
        assert snap["gauges"]["serving_batch_occupancy"] > 0

    @pytest.mark.slow
    def test_closed_loop_loadgen(self, workload):
        from photon_ml_tpu.serving import loadgen

        runtime = _runtime(workload, max_batch_size=16)
        service = ScoringService(runtime, BatcherConfig(
            max_batch_size=16, max_wait_us=200, max_queue=256,
        ))
        with service:
            report = loadgen.closed_loop(
                service.submit, workload.request,
                clients=4, duration_s=1.0,
            )
        snap = report.snapshot()
        assert report.completed > 0 and report.errors == 0
        assert snap["latency_p99_ms"] >= snap["latency_p50_ms"] > 0

    @pytest.mark.slow
    def test_open_loop_loadgen(self, workload):
        from photon_ml_tpu.serving import loadgen

        runtime = _runtime(workload, max_batch_size=16)
        service = ScoringService(runtime, BatcherConfig(
            max_batch_size=16, max_wait_us=200, max_queue=256,
        ))
        with service:
            report = loadgen.open_loop(
                service.submit, workload.request,
                rate_rps=100.0, duration_s=1.0,
            )
        assert report.completed > 0 and report.errors == 0


class TestTenantReport:
    """--tenant-report: the per-tenant accounting summary built from a
    metrics_ts.jsonl time series (docs/serving.md "Tenancy")."""

    def _write(self, path, records):
        with open(path, "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")

    def test_summarizes_rates_and_percentiles(self, tmp_path):
        from photon_ml_tpu.serving.__main__ import tenant_report

        path = tmp_path / "metrics_ts.jsonl"
        first = {
            "seq": 0, "t_wall": 0.0, "t_mono": 10.0,
            "counters": {
                "serving_tenant_acme_requests_total": 100,
                "serving_tenant_acme_shed_total": 5,
                "serving_tenant_acme_rejected_total": 1,
            },
            "gauges": {}, "histograms": {},
        }
        last = {
            "seq": 1, "t_wall": 4.0, "t_mono": 14.0,
            "counters": {
                "serving_tenant_acme_requests_total": 300,
                "serving_tenant_acme_shed_total": 25,
                "serving_tenant_acme_rejected_total": 3,
                # Appears mid-series: deltas fall back to 0 baseline.
                "serving_tenant_free_tier_requests_total": 40,
            },
            "gauges": {},
            "histograms": {
                "serving_tenant_acme_request_latency_seconds": {
                    "count": 295, "p50": 0.004, "p99": 0.020,
                },
            },
        }
        self._write(path, [first, last])

        report = tenant_report(str(path))
        assert report["records"] == 2
        assert report["span_seconds"] == 4.0
        assert sorted(report["tenants"]) == ["acme", "free_tier"]
        acme = report["tenants"]["acme"]
        assert acme["requests"] == 200 and acme["rps"] == 50.0
        assert acme["shed"] == 20 and acme["shed_rps"] == 5.0
        assert acme["rejected"] == 2
        assert acme["completed"] == 295
        assert acme["latency_p50_ms"] == 4.0
        assert acme["latency_p99_ms"] == 20.0
        free = report["tenants"]["free_tier"]
        assert free["requests"] == 40 and free["rps"] == 10.0
        assert free["latency_p99_ms"] is None

    def test_empty_series_raises(self, tmp_path):
        from photon_ml_tpu.serving.__main__ import tenant_report

        path = tmp_path / "metrics_ts.jsonl"
        path.write_text("")
        with pytest.raises(ValueError, match="no time-series records"):
            tenant_report(str(path))
