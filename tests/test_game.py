"""GAME layer tests.

Mirrors the reference's GAME integration-test strategy (SURVEY.md §4): mini
GAME datasets with known per-entity structure; assertions that coordinate
descent recovers it and that mixed-effects beat fixed-effects alone."""

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from photon_ml_tpu.evaluation.evaluators import AreaUnderROCCurveEvaluator
from photon_ml_tpu.game.data import build_random_effect_dataset
from photon_ml_tpu.game.estimator import (
    FactoredRandomEffectCoordinateConfig,
    FixedEffectCoordinateConfig,
    GameEstimator,
    GameTransformer,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.optim.problem import GlmOptimizationConfig, OptimizerConfig
from photon_ml_tpu.optim.regularization import RegularizationContext


def _mixed_effects_problem(rng, n_users=30, rows_per_user=(5, 60), d_global=8,
                           d_user=4):
    """y ~ sigmoid(x_g·w_g + x_u·w_user[u]): global + per-user effects."""
    rows, user_ids = [], []
    for u in range(n_users):
        k = rng.integers(*rows_per_user)
        rows.append(k)
        user_ids.extend([f"user_{u}"] * k)
    n = sum(rows)
    Xg = rng.normal(size=(n, d_global)).astype(np.float32)
    Xu = rng.normal(size=(n, d_user)).astype(np.float32)
    wg = rng.normal(size=d_global)
    w_users = {f"user_{u}": 2.0 * rng.normal(size=d_user) for u in range(n_users)}
    margins = Xg @ wg + np.array(
        [Xu[i] @ w_users[user_ids[i]] for i in range(n)]
    )
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margins))).astype(np.float32)
    return {
        "shards": {"global": sp.csr_matrix(Xg), "per_user": sp.csr_matrix(Xu)},
        "ids": {"userId": np.array(user_ids)},
        "response": y,
        "margins": margins,
    }


class TestRandomEffectDataset:
    def test_grouping_projection_bucketing(self, rng):
        keys = np.array(["b", "a", "b", "c", "a", "b"])
        X = sp.csr_matrix(np.array([
            [1.0, 0.0, 0.0, 2.0],
            [0.0, 3.0, 0.0, 0.0],
            [4.0, 0.0, 0.0, 0.0],
            [0.0, 0.0, 5.0, 0.0],
            [0.0, 6.0, 0.0, 0.0],
            [0.0, 0.0, 0.0, 7.0],
        ], np.float32))
        y = np.arange(6, dtype=np.float32)
        ds = build_random_effect_dataset(keys, X, y, np.ones(6, np.float32))
        assert ds.n_entities == 3
        assert set(ds.entity_to_slot) == {"a", "b", "c"}
        # Every row index appears exactly once across blocks (minus sentinels).
        seen = []
        for block in ds.blocks:
            ri = np.asarray(block.row_index).ravel()
            seen.extend(ri[ri < 6].tolist())
        assert sorted(seen) == list(range(6))
        # Projection: entity "b" touches global cols {0, 3} only.
        b_block, b_lane = ds.entity_to_slot["b"]
        cmap = np.asarray(ds.blocks[b_block].col_map)[b_lane]
        assert set(cmap[cmap >= 0].tolist()) == {0, 3}
        # Block reconstruction matches the original rows.
        blk = ds.blocks[b_block]
        Xb = np.asarray(blk.X)[b_lane]
        rix = np.asarray(blk.row_index)[b_lane]
        for r, gr in enumerate(rix):
            if gr >= 6:
                continue
            dense_row = X[int(gr)].toarray().ravel()
            for k, g in enumerate(cmap):
                if g >= 0:
                    assert Xb[r, k] == dense_row[g]

    def test_max_rows_cap_creates_passive_blocks(self, rng):
        keys = np.array(["u"] * 100)
        X = sp.csr_matrix(rng.normal(size=(100, 3)).astype(np.float32))
        ds = build_random_effect_dataset(
            keys, X, np.zeros(100, np.float32), np.ones(100, np.float32),
            max_rows_per_entity=16,
        )
        assert ds.blocks[0].rows_per_entity == 16
        # The 84 capped-out rows land in a score-only passive block; every
        # global row appears exactly once across active+passive.
        pb = ds.passive_blocks[0]
        assert pb is not None
        seen = []
        for block in (ds.blocks[0], pb):
            ri = np.asarray(block.row_index).ravel()
            seen.extend(ri[ri < 100].tolist())
        assert sorted(seen) == list(range(100))

    def test_capped_coordinate_scores_all_rows(self, rng):
        # Same data trained with and without a cap: the capped coordinate
        # must still produce nonzero scores for EVERY row of a capped entity.
        n = 80
        keys = np.array(["big"] * n)
        X = sp.csr_matrix(
            (rng.normal(size=(n, 3)) + 1.0).astype(np.float32)
        )
        y = (rng.uniform(size=n) < 0.7).astype(np.float32)
        from photon_ml_tpu.game.coordinates import RandomEffectCoordinate
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig, OptimizerConfig)
        from photon_ml_tpu.optim.regularization import RegularizationContext

        ds = build_random_effect_dataset(
            keys, X, y, np.ones(n, np.float32), max_rows_per_entity=16
        )
        coord = RandomEffectCoordinate(
            "re", ds, "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(max_iters=30),
                regularization=RegularizationContext.l2(),
            ),
            reg_weight=1.0,
        )
        state = coord.train(jnp.zeros(n, jnp.float32))
        scores = np.asarray(coord.score(state))
        assert np.all(scores != 0.0), "passive rows must be scored too"


class TestGameTraining:
    def test_mixed_effects_beat_fixed_only(self, rng):
        prob = _mixed_effects_problem(rng)
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=60),
            regularization=RegularizationContext.l2(),
        )
        auc = AreaUnderROCCurveEvaluator()

        fixed_only = GameEstimator(
            "logistic",
            {"fixed": FixedEffectCoordinateConfig("global", opt, reg_weight=1.0)},
            n_iterations=1,
        )
        model_f, hist_f = fixed_only.fit(
            prob["shards"], prob["ids"], prob["response"]
        )
        scores_f = GameTransformer(model_f).transform(prob["shards"], prob["ids"])
        auc_f = auc.evaluate(scores_f, prob["response"])

        game = GameEstimator(
            "logistic",
            {
                "fixed": FixedEffectCoordinateConfig("global", opt, reg_weight=1.0),
                "per_user": RandomEffectCoordinateConfig(
                    "per_user", "userId", opt, reg_weight=1.0
                ),
            },
            n_iterations=3,
        )
        model_g, hist_g = game.fit(prob["shards"], prob["ids"], prob["response"])
        scores_g = GameTransformer(model_g).transform(prob["shards"], prob["ids"])
        auc_g = auc.evaluate(scores_g, prob["response"])

        assert auc_g > auc_f + 0.05, (auc_g, auc_f)
        assert auc_g > 0.85
        # History records training metric per coordinate update.
        assert len(hist_g) == 3 * 2
        assert hist_g[-1]["train_metric"] == pytest.approx(
            auc.evaluate(
                prob["margins"] * 0 + np.asarray(scores_g), prob["response"]
            ),
            abs=0.02,
        )

    def test_coordinate_descent_improves_monotonically(self, rng):
        prob = _mixed_effects_problem(rng, n_users=15)
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=40),
            regularization=RegularizationContext.l2(),
        )
        game = GameEstimator(
            "logistic",
            {
                "fixed": FixedEffectCoordinateConfig("global", opt, reg_weight=1.0),
                "per_user": RandomEffectCoordinateConfig(
                    "per_user", "userId", opt, reg_weight=1.0
                ),
            },
            n_iterations=3,
        )
        _, hist = game.fit(prob["shards"], prob["ids"], prob["response"])
        metrics = [h["train_metric"] for h in hist]
        # AUC after the final update should be >= after the first update.
        assert metrics[-1] >= metrics[0] - 1e-6

    def test_unseen_entities_score_zero_random_effect(self, rng):
        prob = _mixed_effects_problem(rng, n_users=10)
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=30),
            regularization=RegularizationContext.l2(),
        )
        game = GameEstimator(
            "logistic",
            {
                "fixed": FixedEffectCoordinateConfig("global", opt, reg_weight=1.0),
                "per_user": RandomEffectCoordinateConfig(
                    "per_user", "userId", opt, reg_weight=1.0
                ),
            },
            n_iterations=2,
        )
        model, _ = game.fit(prob["shards"], prob["ids"], prob["response"])

        # Score 5 rows with a brand-new user: RE contributes 0, so the total
        # must equal the fixed-effect score alone.
        n_new = 5
        shards_new = {
            "global": prob["shards"]["global"][:n_new],
            "per_user": prob["shards"]["per_user"][:n_new],
        }
        ids_new = {"userId": np.array(["never_seen"] * n_new)}
        total = GameTransformer(model).transform(shards_new, ids_new)
        from photon_ml_tpu.data.dataset import make_glm_data

        fixed_scores = np.asarray(
            model["fixed"].model.compute_score(
                make_glm_data(shards_new["global"], np.zeros(n_new))
            )
        )
        np.testing.assert_allclose(total, fixed_scores, rtol=1e-5, atol=1e-6)

    def test_multi_random_effect_user_item_context(self, rng):
        """BASELINE config 5's shape: fixed + user + item + context effects."""
        n = 900
        n_users, n_items, n_ctx = 20, 15, 4
        users = np.array([f"u{rng.integers(n_users)}" for _ in range(n)])
        items = np.array([f"i{rng.integers(n_items)}" for _ in range(n)])
        ctxs = np.array([f"c{rng.integers(n_ctx)}" for _ in range(n)])
        ue = {f"u{k}": rng.normal(scale=1.5) for k in range(n_users)}
        ie = {f"i{k}": rng.normal(scale=1.5) for k in range(n_items)}
        ce = {f"c{k}": rng.normal(scale=1.0) for k in range(n_ctx)}
        Xg = rng.normal(size=(n, 5)).astype(np.float32)
        wg = rng.normal(size=5)
        margins = (
            Xg @ wg
            + np.array([ue[u] for u in users])
            + np.array([ie[i] for i in items])
            + np.array([ce[c] for c in ctxs])
        )
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margins))).astype(np.float32)
        bias = sp.csr_matrix(np.ones((n, 1), np.float32))
        shards = {"global": sp.csr_matrix(Xg), "bias": bias}
        ids = {"userId": users, "itemId": items, "contextId": ctxs}

        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=40),
            regularization=RegularizationContext.l2(),
        )
        est = GameEstimator(
            "logistic",
            {
                "fixed": FixedEffectCoordinateConfig("global", opt, reg_weight=0.5),
                "per_user": RandomEffectCoordinateConfig(
                    "bias", "userId", opt, reg_weight=0.5),
                "per_item": RandomEffectCoordinateConfig(
                    "bias", "itemId", opt, reg_weight=0.5),
                "per_context": RandomEffectCoordinateConfig(
                    "bias", "contextId", opt, reg_weight=0.5),
            },
            n_iterations=3,
        )
        model, hist = est.fit(shards, ids, y)
        scores = GameTransformer(model).transform(shards, ids)
        auc = AreaUnderROCCurveEvaluator().evaluate(scores, y)
        assert auc > 0.85
        assert model["per_user"].n_entities == n_users
        assert model["per_item"].n_entities == n_items
        assert model["per_context"].n_entities == n_ctx
        # Each coordinate update improved (or held) the training metric.
        metrics = [h["train_metric"] for h in hist]
        assert metrics[-1] > metrics[0]

    def test_int_entity_ids_survive_save_load(self, rng, tmp_path):
        # Regression: int-keyed ids must score identically after the
        # string-keyed Avro round trip.
        from photon_ml_tpu.io.game_store import load_game_model, save_game_model
        from photon_ml_tpu.data.index_map import IndexMap

        prob = _mixed_effects_problem(rng, n_users=6)
        int_ids = {"userId": np.array(
            [int(u.split("_")[1]) for u in prob["ids"]["userId"]]
        )}
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=30),
            regularization=RegularizationContext.l2(),
        )
        est = GameEstimator(
            "logistic",
            {"per_user": RandomEffectCoordinateConfig(
                "per_user", "userId", opt, reg_weight=1.0)},
            n_iterations=1,
        )
        model, _ = est.fit(prob["shards"], int_ids, prob["response"])
        s_before = GameTransformer(model).transform(prob["shards"], int_ids)
        assert np.any(s_before != 0)

        imaps = {"per_user": IndexMap.build(
            [f"f{j}" for j in range(prob["shards"]["per_user"].shape[1])]
        )}
        save_game_model(model, imaps, str(tmp_path / "m"))
        model2, _ = load_game_model(str(tmp_path / "m"))
        s_after = GameTransformer(model2).transform(prob["shards"], int_ids)
        np.testing.assert_allclose(s_after, s_before, rtol=1e-5, atol=1e-6)

    def test_missing_entity_ids_rejected(self, rng):
        keys = np.array(["a", None, "b"], dtype=object)
        X = sp.csr_matrix(np.ones((3, 2), np.float32))
        with pytest.raises(ValueError, match="no entity id"):
            build_random_effect_dataset(
                keys, X, np.zeros(3, np.float32), np.ones(3, np.float32)
            )

    def test_fixed_effect_down_sampling(self, rng):
        prob = _mixed_effects_problem(rng, n_users=10)
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=40),
            regularization=RegularizationContext.l2(),
        )
        est = GameEstimator(
            "logistic",
            {"fixed": FixedEffectCoordinateConfig(
                "global", opt, reg_weight=1.0, down_sampling_rate=0.5)},
            n_iterations=1,
        )
        model, _ = est.fit(prob["shards"], prob["ids"], prob["response"])
        scores = GameTransformer(model).transform(prob["shards"], prob["ids"])
        # Down-sampled training still yields a usable model.
        auc = AreaUnderROCCurveEvaluator().evaluate(scores, prob["response"])
        assert auc > 0.6

    def test_warm_start_states_reused(self, rng):
        # Two CD iterations with max_iters=0 on the second coordinate pass
        # would keep state; here we just check states have block shapes.
        prob = _mixed_effects_problem(rng, n_users=8)
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=20),
            regularization=RegularizationContext.l2(),
        )
        est = GameEstimator(
            "logistic",
            {"per_user": RandomEffectCoordinateConfig(
                "per_user", "userId", opt, reg_weight=1.0)},
            n_iterations=2,
        )
        model, hist = est.fit(prob["shards"], prob["ids"], prob["response"])
        re = model["per_user"]
        assert re.n_entities == 8
        # Every trained user has some nonzero coefficients.
        nonzero = sum(1 for c, v in re.coefficients.values() if len(v))
        assert nonzero == 8


class TestBucketConsolidation:
    def test_growth_reduces_buckets_same_model(self, rng):
        """bucket_growth=4 consolidates the long tail into fewer blocks and
        trains per-entity models identical to the pow2 grid (padding rows
        carry weight 0, so bucket shape never changes the math)."""
        import scipy.sparse as sp

        from photon_ml_tpu.game.coordinates import RandomEffectCoordinate
        from photon_ml_tpu.game.data import build_random_effect_dataset
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        sizes = np.minimum(rng.zipf(1.7, 300), 64)
        n = int(sizes.sum())
        users = np.repeat(
            np.array([f"u{i}" for i in range(300)], dtype=object), sizes
        )
        X = sp.csr_matrix(rng.normal(size=(n, 5)).astype(np.float32))
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        w = np.ones(n, np.float32)

        ds2 = build_random_effect_dataset(users, X, y, w)
        ds4 = build_random_effect_dataset(users, X, y, w, bucket_growth=4.0)
        assert len(ds4.blocks) < len(ds2.blocks)

        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=25),
            regularization=RegularizationContext.l2(),
        )
        import jax.numpy as jnp

        offs = jnp.zeros(n, jnp.float32)
        models = []
        for ds in (ds2, ds4):
            coord = RandomEffectCoordinate(
                "re", ds, "logistic", opt, reg_weight=0.5,
                entity_key="userId",
            )
            models.append(coord.finalize(coord.train(offs)))
        t2, t4 = models[0].coefficients, models[1].coefficients
        assert set(t2) == set(t4)
        for k in t2:
            np.testing.assert_array_equal(t2[k][0], t4[k][0])
            # Padded shapes change f32 reduction order inside the iterative
            # solver; solutions agree to optimization tolerance, not ulps.
            np.testing.assert_allclose(t2[k][1], t4[k][1], atol=2e-3)


class TestRank1FastPath:
    @pytest.mark.parametrize("task", ["logistic", "squared", "poisson"])
    def test_single_row_bucket_matches_generic_solver(self, rng, task):
        """R == 1 buckets take the rank-1 Newton path; it must agree with
        the generic vmapped L-BFGS solve to optimization tolerance."""
        import jax
        import jax.numpy as jnp
        import scipy.sparse as sp

        from photon_ml_tpu.game.coordinates import _make_block_solver
        from photon_ml_tpu.game.data import build_random_effect_dataset
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        n_entities = 80
        users = np.array([f"u{i}" for i in range(n_entities)], dtype=object)
        X = sp.csr_matrix(rng.normal(size=(n_entities, 4)).astype(np.float32))
        if task == "poisson":
            y = rng.poisson(1.5, size=n_entities).astype(np.float32)
        else:
            y = (rng.uniform(size=n_entities) < 0.5).astype(np.float32)
        ds = build_random_effect_dataset(
            users, X, y, np.ones(n_entities, np.float32)
        )
        assert len(ds.blocks) == 1 and ds.blocks[0].rows_per_entity == 1

        cfg = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=60, tolerance=1e-9),
            regularization=RegularizationContext.l2(),
        )
        solver = _make_block_solver(task, cfg)
        block = ds.blocks[0]
        off = jnp.asarray(
            rng.normal(size=(block.n_entities, 1)).astype(np.float32) * 0.3
        )
        w0 = jnp.zeros((block.n_entities, block.block_dim), jnp.float32)
        l1 = jnp.asarray(0.0)
        l2 = jnp.asarray(0.7)
        fast = np.asarray(solver(block, off, w0, l1, l2))

        # Force the generic path by faking R=2 (duplicate the row with the
        # second copy zero-weighted — mathematically identical problem).
        from photon_ml_tpu.game.data import EntityBlock

        block2 = EntityBlock(
            X=jnp.concatenate([block.X, jnp.zeros_like(block.X)], axis=1),
            labels=jnp.concatenate(
                [block.labels, jnp.zeros_like(block.labels)], axis=1
            ),
            weights=jnp.concatenate(
                [block.weights, jnp.zeros_like(block.weights)], axis=1
            ),
            col_map=block.col_map,
            row_index=jnp.concatenate(
                [block.row_index, jnp.full_like(block.row_index, n_entities)],
                axis=1,
            ),
            n_entities=block.n_entities,
            rows_per_entity=2,
            block_dim=block.block_dim,
        )
        off2 = jnp.concatenate([off, jnp.zeros_like(off)], axis=1)
        generic = np.asarray(solver(block2, off2, w0, l1, l2))
        np.testing.assert_allclose(fast, generic, atol=5e-4)

    def test_rank1_large_norm_poisson_no_nan(self, rng):
        """Regression: a large-norm feature row with a huge Poisson count
        must not blow the Newton step into inf/NaN (margin-change clamp)."""
        import jax.numpy as jnp
        import scipy.sparse as sp

        from photon_ml_tpu.game.coordinates import _make_block_solver
        from photon_ml_tpu.game.data import build_random_effect_dataset
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        users = np.array(["a", "b", "c"], dtype=object)
        X = sp.csr_matrix(np.array([
            [20.0, 0.0],      # ||x|| = 20 (s = 400)
            [1e-2, 0.0],      # tiny norm
            [1.0, 1.0],
        ], np.float32))
        y = np.array([1000.0, 100.0, 2.0], np.float32)
        ds = build_random_effect_dataset(users, X, y, np.ones(3, np.float32))
        cfg = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=40),
            regularization=RegularizationContext.l2(),
        )
        solver = _make_block_solver("poisson", cfg)
        for block in ds.blocks:
            w0 = jnp.zeros((block.n_entities, block.block_dim), jnp.float32)
            out = np.asarray(solver(
                block,
                jnp.zeros(
                    (block.n_entities, block.rows_per_entity), jnp.float32
                ),
                w0, jnp.asarray(0.0, jnp.float32),
                jnp.asarray(1e-3, jnp.float32),
            ))
            assert np.all(np.isfinite(out)), out
        # The s=400/y=1000 entity must actually converge: optimal margin is
        # close to log(1000) ≈ 6.9 (weak L2), so exp(m) ≈ y.
        blk, lane = ds.entity_to_slot["a"]
        block = ds.blocks[blk]
        w0 = jnp.zeros((block.n_entities, block.block_dim), jnp.float32)
        w = np.asarray(solver(
            block,
            jnp.zeros(
                (block.n_entities, block.rows_per_entity), jnp.float32
            ),
            w0, jnp.asarray(0.0, jnp.float32),
            jnp.asarray(1e-3, jnp.float32),
        ))
        m = float((np.asarray(block.X)[lane, 0] * w[lane]).sum())
        assert abs(np.exp(m) - 1000.0) / 1000.0 < 0.05, m


class TestTightBucketPadding:
    def test_blocks_pad_to_member_maxima_not_grid(self, rng):
        """Round 4: the geometric grid only GROUPS; block dims are the
        members' actual maxima (the zipf row cap used to pad to the next
        grid point — 2x pure waste on the largest block)."""
        import scipy.sparse as sp

        from photon_ml_tpu.game.data import build_random_effect_dataset

        # One entity with 100 rows: growth=2 grid point is 128, tight is
        # 100.  A second entity with 3 rows lands in a different bucket.
        users = np.array(["a"] * 100 + ["b"] * 3, dtype=object)
        n = len(users)
        X = sp.csr_matrix(rng.normal(size=(n, 5)).astype(np.float32))
        ds = build_random_effect_dataset(
            users, X, np.zeros(n, np.float32), np.ones(n, np.float32),
            bucket_growth=2.0,
        )
        dims = sorted(
            (b.rows_per_entity, b.block_dim) for b in ds.blocks
        )
        assert dims == [(3, 5), (100, 5)], dims  # tight, not (4,8)/(128,8)


class TestDim1Newton:
    def test_bias_random_effect_matches_scalar_oracle(self, rng):
        """D == 1 blocks (per-entity bias — the MovieLens shape) take the
        scalar-Newton path; each entity's solution must match an
        independent 1-D scipy solve of its own regularized objective."""
        import scipy.optimize
        import scipy.sparse as sp

        from photon_ml_tpu.game.coordinates import RandomEffectCoordinate
        from photon_ml_tpu.game.data import build_random_effect_dataset
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        n_users, rows_each = 12, 7  # R > 1 so rank1 does NOT shadow dim1
        n = n_users * rows_each
        users = np.repeat(
            np.array([f"u{i}" for i in range(n_users)], dtype=object),
            rows_each,
        )
        x = rng.normal(size=n).astype(np.float32)  # single feature
        offs = rng.normal(size=n).astype(np.float32) * 0.5
        margins = 1.3 * x + offs
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margins))).astype(
            np.float32
        )
        X = sp.csr_matrix(x[:, None])
        ds = build_random_effect_dataset(
            users, X, y, np.ones(n, np.float32)
        )
        assert all(b.block_dim == 1 for b in ds.blocks)
        assert all(b.rows_per_entity > 1 for b in ds.blocks)
        coord = RandomEffectCoordinate(
            "per_user", ds, "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(max_iters=50, tolerance=1e-9),
                regularization=RegularizationContext.l2(),
            ),
            reg_weight=0.7, entity_key="userId",
        )
        state = coord.train(jnp.asarray(offs))

        def entity_obj(w, rows):
            m = w * x[rows] + offs[rows]
            return float(
                np.sum(np.log1p(np.exp(-m)) * y[rows]
                       + np.log1p(np.exp(m)) * (1 - y[rows]))
                + 0.35 * w * w  # 0.5 * l2, l2 = 0.7
            )

        for bi, (block_ids, coefs) in enumerate(
            zip(ds.entity_ids, state)
        ):
            for lane, key in enumerate(block_ids):
                rows = np.flatnonzero(users == key)
                res = scipy.optimize.minimize_scalar(
                    lambda w: entity_obj(w, rows), bounds=(-20, 20),
                    method="bounded",
                    options={"xatol": 1e-10},
                )
                np.testing.assert_allclose(
                    float(np.asarray(coefs)[lane, 0]), res.x, atol=2e-4,
                    err_msg=f"entity {key}",
                )


class TestDeferredNormFlush:
    """The CD loop defers score_norm readbacks to ONE end-of-run sync when
    nothing needs per-iteration values (game/descent.py flush) — history
    must come out identical to the logger-driven per-iteration path."""

    def _cd(self, rng):
        from photon_ml_tpu.data.dataset import make_glm_data
        from photon_ml_tpu.game.coordinates import (
            FixedEffectCoordinate,
            RandomEffectCoordinate,
        )
        from photon_ml_tpu.game.data import FixedEffectDataset
        from photon_ml_tpu.game.descent import CoordinateDescent

        prob = _mixed_effects_problem(rng, n_users=12)
        n = len(prob["response"])
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=15),
            regularization=RegularizationContext.l2(),
        )
        fixed = FixedEffectCoordinate(
            "fixed",
            FixedEffectDataset(
                data=make_glm_data(
                    prob["shards"]["global"], prob["response"]
                ),
                n_global_rows=n,
            ),
            "logistic", opt, reg_weight=1.0,
        )
        re = RandomEffectCoordinate(
            "per_user",
            build_random_effect_dataset(
                prob["ids"]["userId"], prob["shards"]["per_user"],
                prob["response"], np.ones(n, np.float32),
            ),
            "logistic", opt, reg_weight=1.0, entity_key="userId",
        )
        return CoordinateDescent([fixed, re]), n

    def test_history_matches_logger_path(self, rng, tmp_path):
        from photon_ml_tpu.utils.logging import PhotonLogger

        cd, n = self._cd(rng)
        base = jnp.zeros(n, jnp.float32)
        quiet = cd.run(base, n_iterations=3)
        logged = cd.run(
            base, n_iterations=3, logger=PhotonLogger(str(tmp_path))
        )
        assert len(quiet.history) == len(logged.history) == 6
        for a, b in zip(quiet.history, logged.history):
            assert (a["iteration"], a["coordinate"]) == (
                b["iteration"], b["coordinate"],
            )
            assert a["score_norm"] == pytest.approx(
                b["score_norm"], rel=1e-6
            )
            assert np.isfinite(a["score_norm"])
        # The logger path logged one line per coordinate update.
        log_text = (tmp_path / "photon.log").read_text()
        assert log_text.count("score_norm") == 6

    def test_history_ordered_per_update(self, rng):
        cd, n = self._cd(rng)
        result = cd.run(jnp.zeros(n, jnp.float32), n_iterations=2)
        assert [
            (h["iteration"], h["coordinate"]) for h in result.history
        ] == [
            (0, "fixed"), (0, "per_user"), (1, "fixed"), (1, "per_user"),
        ]

    def test_empty_coordinate_list(self):
        from photon_ml_tpu.game.descent import CoordinateDescent

        result = CoordinateDescent([]).run(
            jnp.zeros(7, jnp.float32), n_iterations=2
        )
        assert result.history == [] and result.scores == {}


class TestBuilderDegenerateInputs:
    def test_all_zero_kept_rows_with_passive_features(self):
        """Capped entity whose KEPT (linspace) rows are all-zero while its
        passive rows carry features: the active-pair table is empty, every
        passive feature drops (projection onto an empty active subspace),
        and the build must not crash."""
        import scipy.sparse as sp

        X = np.zeros((5, 3), np.float32)
        X[1:4] = 1.0  # rows 0 and 4 (the linspace keeps for cap=2) empty
        ds = build_random_effect_dataset(
            np.array(["e"] * 5, dtype=object), sp.csr_matrix(X),
            np.zeros(5, np.float32), np.ones(5, np.float32),
            max_rows_per_entity=2, device=False,
        )
        assert len(ds.blocks) == 1
        assert np.all(np.asarray(ds.blocks[0].col_map) == -1)
        pb = ds.passive_blocks[0]
        assert pb is not None
        # Passive rows are present (scored) but their features dropped.
        assert np.all(np.asarray(pb.X) == 0)
        assert sorted(np.asarray(pb.row_index).ravel()[:3].tolist()) == [1, 2, 3]

    def test_task_alias_shares_solver_cache(self):
        from photon_ml_tpu.game.coordinates import _make_block_solver

        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=5),
            regularization=RegularizationContext.l2(),
        )
        assert _make_block_solver("logistic_regression", opt) is (
            _make_block_solver("logistic", opt)
        )


def _reference_group_build(entity_keys, rows_csr, labels, weights,
                           max_rows_per_entity=None):
    """Obviously-correct per-entity reference of the flat-array builder:
    the pre-vectorization algorithm (scipy slice per entity), kept as the
    differential oracle for the grouping/projection/bucket-fill pipeline."""
    from photon_ml_tpu.game.data import _round_up_geometric

    rows_csr = sp.csr_matrix(rows_csr)
    rows_csr.sum_duplicates()
    n_rows = rows_csr.shape[0]
    keys = np.asarray(entity_keys).astype(str)
    order = np.argsort(keys, kind="stable")
    sk = keys[order]
    starts = np.flatnonzero(np.concatenate([[True], sk[1:] != sk[:-1]]))
    groups = []
    for gi, start in enumerate(starts):
        end = starts[gi + 1] if gi + 1 < len(starts) else len(order)
        ridx = order[start:end]
        passive = np.empty(0, ridx.dtype)
        if max_rows_per_entity is not None and len(ridx) > max_rows_per_entity:
            keep = np.linspace(0, len(ridx) - 1, max_rows_per_entity).astype(int)
            mask = np.zeros(len(ridx), bool)
            mask[keep] = True
            passive = ridx[~mask]
            ridx = ridx[mask]
        sub = rows_csr[ridx]
        groups.append((sk[start], ridx, passive, np.unique(sub.indices), sub))
    buckets = {}
    for i, (_, ridx, _p, active, _s) in enumerate(groups):
        key = (_round_up_geometric(len(ridx), 2.0),
               _round_up_geometric(len(active), 2.0))
        buckets.setdefault(key, []).append(i)
    out = []
    for _key, members in sorted(buckets.items()):
        E = len(members)
        R = max(len(groups[gi][1]) for gi in members)
        D = max(1, max(len(groups[gi][3]) for gi in members))
        X = np.zeros((E, R, D), np.float32)
        lab = np.zeros((E, R), np.float32)
        wts = np.zeros((E, R), np.float32)
        cmap = np.full((E, D), -1, np.int32)
        rindex = np.full((E, R), n_rows, np.int32)
        ids = []
        maxp = max(len(groups[gi][2]) for gi in members)
        Xp = np.zeros((E, maxp, D), np.float32) if maxp else None
        rindexp = np.full((E, maxp), n_rows, np.int32) if maxp else None
        for lane, gi in enumerate(members):
            key, ridx, passive, active, sub = groups[gi]
            ids.append(key)
            cmap[lane, : len(active)] = active
            X[lane, : len(ridx), : len(active)] = sub[:, active].toarray()
            lab[lane, : len(ridx)] = labels[ridx]
            wts[lane, : len(ridx)] = weights[ridx]
            rindex[lane, : len(ridx)] = ridx
            if maxp and len(passive):
                Xp[lane, : len(passive), : len(active)] = (
                    rows_csr[passive][:, active].toarray()
                )
                rindexp[lane, : len(passive)] = passive
        out.append((ids, X, lab, wts, cmap, rindex, Xp, rindexp))
    return out


class TestBuilderDifferential:
    """Randomized differential test of the flat-array dataset builder
    against the per-entity reference algorithm it replaced."""

    @pytest.mark.parametrize("trial", range(6))
    def test_matches_per_entity_reference(self, trial):
        rng = np.random.default_rng(100 + trial)
        n = int(rng.integers(30, 400))
        d = int(rng.integers(1, 12))
        n_ent = int(rng.integers(1, 40))
        density = float(rng.uniform(0.05, 0.9))
        X = sp.random(n, d, density, "csr", dtype=np.float32,
                      random_state=int(rng.integers(1 << 30)))
        keys = np.array(
            [f"e{rng.integers(n_ent)}" for _ in range(n)], dtype=object
        )
        labels = rng.normal(size=n).astype(np.float32)
        weights = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
        cap = (
            None if trial % 2 == 0
            else int(rng.integers(1, max(2, n // max(1, n_ent))))
        )
        ds = build_random_effect_dataset(
            keys, X, labels, weights, max_rows_per_entity=cap, device=False,
        )
        ref = _reference_group_build(
            keys, X, labels, weights, max_rows_per_entity=cap
        )
        assert len(ds.blocks) == len(ref)
        for b, pb, ids, (rids, rX, rlab, rwts, rcmap, rrindex, rXp,
                         rrindexp) in zip(
            ds.blocks, ds.passive_blocks, ds.entity_ids, ref
        ):
            assert list(ids) == list(rids)
            np.testing.assert_array_equal(np.asarray(b.col_map), rcmap)
            np.testing.assert_array_equal(np.asarray(b.row_index), rrindex)
            np.testing.assert_array_equal(np.asarray(b.X), rX)
            np.testing.assert_array_equal(np.asarray(b.labels), rlab)
            np.testing.assert_array_equal(np.asarray(b.weights), rwts)
            if rXp is None:
                assert pb is None
            else:
                np.testing.assert_array_equal(np.asarray(pb.X), rXp)
                np.testing.assert_array_equal(
                    np.asarray(pb.row_index), rrindexp
                )


class TestPartialRetraining:
    """Locked coordinates (the reference's partial retraining): held at
    the prior model, contributing scores but never retrained."""

    def _fit(self, prob, **kw):
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=40),
            regularization=RegularizationContext.l2(),
        )
        est = GameEstimator(
            "logistic",
            {
                "fixed": FixedEffectCoordinateConfig(
                    "global", opt, reg_weight=1.0
                ),
                "per_user": RandomEffectCoordinateConfig(
                    "per_user", "userId", opt, reg_weight=1.0
                ),
            },
            n_iterations=2,
        )
        model, hist = est.fit(
            prob["shards"], prob["ids"], prob["response"], **kw
        )
        return est, model, hist

    def test_locked_submodel_passes_through_verbatim(self, rng):
        prob = _mixed_effects_problem(rng, n_users=15)
        est, base_model, _ = self._fit(prob)
        _, model2, hist2 = self._fit(
            prob, initial_model=base_model,
            locked_coordinates=("per_user",),
        )
        # Identical per-entity tables, the SAME object carried through.
        assert model2.models["per_user"] is base_model.models["per_user"]
        # Only the fixed coordinate produced history entries.
        assert {h["coordinate"] for h in hist2} == {"fixed"}
        assert len(hist2) == 2

    def test_locked_matches_manual_offsets(self, rng):
        """Training fixed against a locked per_user must equal training
        fixed alone with per_user's scores as base offsets."""
        prob = _mixed_effects_problem(rng, n_users=15)
        est, base_model, _ = self._fit(prob)
        _, model_locked, _ = self._fit(
            prob, initial_model=base_model,
            locked_coordinates=("per_user",),
        )
        from photon_ml_tpu.game.model import GameModel

        user_scores = np.asarray(
            GameTransformer(
                GameModel(
                    models={"per_user": base_model.models["per_user"]},
                    task="logistic",
                )
            ).transform(prob["shards"], prob["ids"])
        )
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=40),
            regularization=RegularizationContext.l2(),
        )
        fixed_only = GameEstimator(
            "logistic",
            {"fixed": FixedEffectCoordinateConfig("global", opt, reg_weight=1.0)},
            n_iterations=2,
        )
        model_manual, _ = fixed_only.fit(
            prob["shards"], prob["ids"], prob["response"],
            offset=user_scores,
        )
        w_locked = np.asarray(
            model_locked.models["fixed"].model.coefficients.means
        )
        w_manual = np.asarray(
            model_manual.models["fixed"].model.coefficients.means
        )
        np.testing.assert_allclose(w_locked, w_manual, rtol=2e-4, atol=2e-5)

    def test_locked_requires_initial_model(self, rng):
        prob = _mixed_effects_problem(rng, n_users=15)
        with pytest.raises(ValueError, match="initial_model"):
            self._fit(prob, locked_coordinates=("per_user",))

    def test_locked_unknown_coordinate_rejected(self, rng):
        prob = _mixed_effects_problem(rng, n_users=15)
        _, base_model, _ = self._fit(prob)
        with pytest.raises(ValueError, match="not in the initial model"):
            self._fit(
                prob, initial_model=base_model,
                locked_coordinates=("nope",),
            )

    def test_resume_with_changed_locked_set_rejected(self, rng, tmp_path):
        from photon_ml_tpu.io.checkpoint import CoordinateDescentCheckpointer

        prob = _mixed_effects_problem(rng, n_users=15)
        est, base_model, _ = self._fit(prob)
        ckpt = CoordinateDescentCheckpointer(str(tmp_path / "cd"))
        # Checkpoint a run that trained everything...
        self._fit(prob, checkpointer=ckpt)
        # ...then resuming with a locked coordinate must refuse.
        with pytest.raises(ValueError, match="locked coordinates"):
            self._fit(
                prob, initial_model=base_model,
                locked_coordinates=("per_user",), checkpointer=ckpt,
            )

    def test_all_locked_rejected(self, rng):
        prob = _mixed_effects_problem(rng, n_users=15)
        _, base_model, _ = self._fit(prob)
        with pytest.raises(ValueError, match="nothing to train"):
            self._fit(
                prob, initial_model=base_model,
                locked_coordinates=("fixed", "per_user"),
            )

    def test_locked_factored_rejected_up_front(self, rng):
        """A factored coordinate's saved sub-model can't be locked (its
        (u, V) state is not reconstructible) — the estimator must say so
        accurately instead of descent's generic message."""
        prob = _mixed_effects_problem(rng, n_users=15)
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=20),
            regularization=RegularizationContext.l2(),
        )
        est = GameEstimator(
            "logistic",
            {
                "fixed": FixedEffectCoordinateConfig(
                    "global", opt, reg_weight=1.0
                ),
                "per_user": FactoredRandomEffectCoordinateConfig(
                    "per_user", "userId", rank=2, optimization=opt,
                    reg_weight=1.0,
                ),
            },
            n_iterations=1,
        )
        model, _ = est.fit(prob["shards"], prob["ids"], prob["response"])
        with pytest.raises(ValueError, match="not reconstructible"):
            est.fit(
                prob["shards"], prob["ids"], prob["response"],
                initial_model=model, locked_coordinates=("per_user",),
            )
