"""Failure/elastic recovery (SURVEY.md §5.3): the transient-failure
watchdog and its driver integration.

The reference's elastic recovery is Spark's cluster manager re-running
failed tasks; the TPU analogue is checkpoint + automatic resume.  The
driver tests here kill training MID-GRID with a transport-shaped error
and assert the retry completes from the checkpoint without repeating
finished λs."""

import os

import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.utils.watchdog import (
    RetryPolicy,
    RetryStats,
    run_with_retries,
)


class _FakeLogger:
    def __init__(self):
        self.warnings = []

    def warning(self, msg, *args):
        self.warnings.append(msg % args if args else msg)

    def info(self, *a, **k):
        pass


class TestRetryPolicy:
    def test_transient_classification(self):
        p = RetryPolicy(max_retries=3)
        assert p.is_transient(RuntimeError("UNAVAILABLE: Socket closed"))
        assert p.is_transient(RuntimeError("DEADLINE_EXCEEDED: timed out"))
        assert p.is_transient(OSError("connection reset by peer"))
        assert not p.is_transient(ValueError("bad shape"))
        assert not p.is_transient(
            RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        )

    def test_type_name_classification(self):
        XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
        assert RetryPolicy().is_transient(XlaRuntimeError("whatever"))

    def test_extra_patterns(self):
        p = RetryPolicy(extra_patterns=("my-cluster-oops",))
        assert p.is_transient(RuntimeError("MY-CLUSTER-OOPS happened"))

    def test_backoff_exponential_capped(self):
        p = RetryPolicy(backoff_seconds=2.0, backoff_multiplier=3.0,
                        max_backoff_seconds=10.0)
        assert p.backoff(0) == 2.0
        assert p.backoff(1) == 6.0
        assert p.backoff(2) == 10.0  # capped

    def test_interrupts_never_retryable(self):
        """KeyboardInterrupt/SystemExit are refused as transient even
        when their message screams the transient vocabulary — a user
        interrupt must never put the process back to work."""
        p = RetryPolicy(extra_patterns=("UNAVAILABLE",))
        c = p.classify(KeyboardInterrupt("UNAVAILABLE: device lost"))
        assert not c.transient and c.source == "interrupt"
        c = p.classify(SystemExit("UNAVAILABLE: bye"))
        assert not c.transient and c.source == "interrupt"

    def test_jitter_validation(self):
        with pytest.raises(ValueError, match="jitter"):
            RetryPolicy(jitter="full")

    def test_decorrelated_jitter_bounds_and_determinism(self):
        import random

        p = RetryPolicy(
            backoff_seconds=1.0, max_backoff_seconds=30.0,
            jitter="decorrelated",
        )
        # Seeded RNG -> the whole jittered schedule is reproducible.
        seq = []
        rng = random.Random(7)
        prev = None
        for attempt in range(6):
            d = p.backoff(attempt, rng=rng, previous=prev)
            lo, hi = 1.0, max(1.0, 3.0 * (prev if prev is not None else 1.0))
            assert lo <= d <= min(30.0, hi)
            seq.append(d)
            prev = d
        rng2 = random.Random(7)
        prev = None
        for attempt, want in enumerate(seq):
            got = p.backoff(attempt, rng=rng2, previous=prev)
            assert got == want
            prev = got

    def test_jitter_none_ignores_rng(self):
        import random

        p = RetryPolicy(backoff_seconds=2.0)
        assert p.backoff(1, rng=random.Random(0)) == 4.0


class TestRunWithRetries:
    def test_retries_then_succeeds(self):
        calls = []
        slept = []

        def fn(attempt):
            calls.append(attempt)
            if attempt < 2:
                raise RuntimeError("UNAVAILABLE: transport lost")
            return "ok"

        log = _FakeLogger()
        out = run_with_retries(
            fn, RetryPolicy(max_retries=3, backoff_seconds=0.01),
            log, sleep=slept.append,
        )
        assert out == "ok"
        assert calls == [0, 1, 2]
        assert len(slept) == 2
        assert len(log.warnings) == 2

    def test_budget_exhausted_raises(self):
        def fn(attempt):
            raise RuntimeError("UNAVAILABLE: still down")

        with pytest.raises(RuntimeError, match="still down"):
            run_with_retries(
                fn, RetryPolicy(max_retries=2, backoff_seconds=0),
                sleep=lambda s: None,
            )

    def test_non_transient_propagates_immediately(self):
        calls = []

        def fn(attempt):
            calls.append(attempt)
            raise ValueError("programming error")

        with pytest.raises(ValueError):
            run_with_retries(
                fn, RetryPolicy(max_retries=5), sleep=lambda s: None
            )
        assert calls == [0]

    def test_disabled_by_default(self):
        def fn(attempt):
            raise RuntimeError("UNAVAILABLE")

        with pytest.raises(RuntimeError):
            run_with_retries(fn, RetryPolicy(), sleep=lambda s: None)

    def test_decorrelated_jitter_schedule_is_seeded(self):
        """Two runs with the same seeded RNG sleep the identical jittered
        schedule; the recorded delays stay inside the decorrelated
        envelope ([base, 3·previous], capped)."""
        import random

        def fn(attempt):
            if attempt < 3:
                raise RuntimeError("UNAVAILABLE: flaky")
            return attempt

        policy = RetryPolicy(
            max_retries=5, backoff_seconds=1.0, max_backoff_seconds=5.0,
            jitter="decorrelated",
        )

        def delays(seed):
            slept = []
            run_with_retries(
                fn, policy, sleep=slept.append, rng=random.Random(seed)
            )
            return slept

        a, b = delays(42), delays(42)
        assert a == b and len(a) == 3
        assert a != delays(43)  # a different seed decorrelates
        prev = 1.0
        for d in a:
            assert 1.0 <= d <= min(5.0, max(1.0, 3.0 * prev))
            prev = d


class TestClassification:
    def test_classify_reports_matched_pattern(self):
        p = RetryPolicy()
        c = p.classify(RuntimeError("UNAVAILABLE: Socket closed"))
        assert c.transient and c.matched == "UNAVAILABLE"
        assert c.source == "transient_pattern"
        c = p.classify(RuntimeError("RESOURCE_EXHAUSTED: oom"))
        assert not c.transient and c.matched == "RESOURCE_EXHAUSTED"
        assert c.source == "non_transient_pattern"
        XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
        c = p.classify(XlaRuntimeError("mystery"))
        assert c.transient and c.matched == "XlaRuntimeError"
        assert c.source == "type_name"
        c = p.classify(ValueError("bad shape"))
        assert not c.transient and c.matched is None and c.source == "none"


class TestRetryStats:
    def test_stats_record_each_attempt_without_sleeping(self):
        """The retry-behavior assertion surface: verdicts, matched
        patterns, and backoffs observable on RetryStats — no timing."""
        slept = []

        def fn(attempt):
            if attempt < 2:
                raise RuntimeError("UNAVAILABLE: transport lost")
            return "ok"

        stats = RetryStats()
        out = run_with_retries(
            fn, RetryPolicy(max_retries=3, backoff_seconds=2.0),
            sleep=slept.append, stats=stats,
        )
        assert out == "ok"
        assert stats.succeeded and not stats.gave_up
        assert stats.attempts == 3 and stats.retries == 2
        assert stats.sleep_seconds == pytest.approx(2.0 + 4.0)
        assert [f["attempt"] for f in stats.failures] == [0, 1]
        assert all(f["matched"] == "UNAVAILABLE" for f in stats.failures)
        assert [f["backoff_seconds"] for f in stats.failures] == [2.0, 4.0]
        # snapshot() is JSON-able driver-result material.
        import json

        json.dumps(stats.snapshot())

    def test_stats_mark_gave_up_on_budget_exhaustion(self):
        stats = RetryStats()

        def fn(attempt):
            raise RuntimeError("UNAVAILABLE: still down")

        with pytest.raises(RuntimeError):
            run_with_retries(
                fn, RetryPolicy(max_retries=1, backoff_seconds=0),
                sleep=lambda s: None, stats=stats,
            )
        assert stats.gave_up and not stats.succeeded
        assert stats.attempts == 2 and stats.retries == 1
        assert stats.failures[-1]["backoff_seconds"] is None

    def test_stats_non_transient_single_failure(self):
        stats = RetryStats()

        def fn(attempt):
            raise ValueError("broken")

        with pytest.raises(ValueError):
            run_with_retries(
                fn, RetryPolicy(max_retries=5), sleep=lambda s: None,
                stats=stats,
            )
        assert not stats.gave_up  # non-transient, not a budget give-up
        assert stats.attempts == 1 and stats.retries == 0
        assert stats.failures[0]["transient"] is False

    def test_telemetry_events_per_attempt(self, tmp_path):
        """Every classify/backoff decision is emitted as a
        watchdog.attempt event; retries increment the counter."""
        import json

        from photon_ml_tpu import telemetry

        def fn(attempt):
            if attempt == 0:
                raise RuntimeError("DEADLINE_EXCEEDED: slow transport")
            return 42

        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            out = run_with_retries(
                fn, RetryPolicy(max_retries=2, backoff_seconds=0.5),
                sleep=lambda s: None,
            )
            snap = tel.snapshot()
        assert out == 42
        assert snap["counters"]["watchdog_retries"] == 1
        with open(tmp_path / "events.jsonl") as f:
            records = [json.loads(line) for line in f]
        attempts = [
            r for r in records
            if r.get("type") == "event" and r["name"] == "watchdog.attempt"
        ]
        assert len(attempts) == 1
        a = attempts[0]["attrs"]
        assert a["outcome"] == "retry"
        assert a["matched"] == "DEADLINE_EXCEEDED"
        assert a["backoff_seconds"] == 0.5
        assert any(
            r.get("type") == "event" and r["name"] == "watchdog.recovered"
            for r in records
        )


class TestGlmDriverRecovery:
    def test_mid_grid_crash_resumes_from_checkpoint(
        self, tmp_path, monkeypatch, rng
    ):
        """Kill the run after the FIRST λ checkpoints; --max-retries must
        finish the grid with the first λ restored, matching an
        uninterrupted run's models."""
        from photon_ml_tpu.data import libsvm
        from photon_ml_tpu.drivers import glm_driver
        from photon_ml_tpu.optim.problem import GlmOptimizationProblem

        n, d = 400, 60
        X = sp.random(n, d, density=0.1, random_state=1, format="csr")
        X.data[:] = 1.0
        w_true = rng.normal(size=d) * (rng.uniform(size=d) < 0.4)
        y = np.where(
            rng.uniform(size=n) < 1 / (1 + np.exp(-(X @ w_true))), 1.0, -1.0
        )
        train = str(tmp_path / "t.libsvm")
        libsvm.write_libsvm(train, X, y)
        common = [
            "--train-data", train,
            "--task", "logistic",
            "--reg-type", "l2",
            "--reg-weights", "0.5,5.0",
            "--n-features", str(d),
        ]

        out_ok = str(tmp_path / "ok")
        res_ok = glm_driver.run(common + ["--output-dir", out_ok])

        orig = GlmOptimizationProblem.run_grid
        state = {"attempts": 0, "solves_before_crash": []}

        def flaky_run_grid(self, data, reg_weights, **kw):
            state["attempts"] += 1
            if state["attempts"] == 1:
                inner = kw.get("on_solved")

                def dying_on_solved(lam, w):
                    inner(lam, w)  # persist the checkpoint FIRST
                    state["solves_before_crash"].append(lam)
                    raise RuntimeError(
                        "UNAVAILABLE: TPU transport lost (induced)"
                    )

                kw["on_solved"] = dying_on_solved
            return orig(self, data, reg_weights, **kw)

        monkeypatch.setattr(
            GlmOptimizationProblem, "run_grid", flaky_run_grid
        )
        out = str(tmp_path / "recovered")
        res = glm_driver.run(common + [
            "--output-dir", out, "--max-retries", "2",
            "--retry-backoff", "0.01",
        ])
        # Crashed once after λ=5.0 (grid solves big-to-small), retried,
        # and did NOT re-solve the checkpointed λ.
        assert state["attempts"] == 2
        assert state["solves_before_crash"] == [5.0]
        assert res["best_lambda"] == res_ok["best_lambda"]
        for lam in ("0.5", "5.0"):
            assert res["metrics"][lam] == pytest.approx(
                res_ok["metrics"][lam], abs=1e-6
            )

    def test_non_transient_failure_still_fatal(
        self, tmp_path, monkeypatch, rng
    ):
        from photon_ml_tpu.data import libsvm
        from photon_ml_tpu.drivers import glm_driver
        from photon_ml_tpu.optim.problem import GlmOptimizationProblem

        n, d = 100, 10
        X = sp.random(n, d, density=0.3, random_state=2, format="csr")
        y = np.where(rng.uniform(size=n) < 0.5, 1.0, -1.0)
        train = str(tmp_path / "t.libsvm")
        libsvm.write_libsvm(train, X, y)

        def broken(self, *a, **k):
            raise ValueError("genuinely broken config")

        monkeypatch.setattr(GlmOptimizationProblem, "run_grid", broken)
        with pytest.raises(ValueError, match="genuinely broken"):
            glm_driver.run([
                "--train-data", train,
                "--output-dir", str(tmp_path / "out"),
                "--task", "logistic",
                "--n-features", str(d),
                "--max-retries", "5",
                "--retry-backoff", "0.01",
            ])


class TestGameDriverRecovery:
    def test_cd_crash_resumes_per_iteration(self, tmp_path, monkeypatch):
        """Crash the GAME fit after iteration 0 checkpoints; the retry must
        resume at iteration 1 (not restart) and produce a model."""
        import json

        from photon_ml_tpu.drivers import game_training_driver
        from photon_ml_tpu.game import descent as descent_mod
        from photon_ml_tpu.data.game_reader import write_game_avro

        rng = np.random.default_rng(5)
        n = 300
        records = [
            {
                "uid": f"row{i}",
                "response": float(rng.integers(2)),
                "weight": None,
                "offset": None,
                "ids": {"userId": f"u{rng.integers(20)}"},
                "features": {
                    "global": [
                        {"name": f"g{j}", "term": "",
                         "value": float(rng.normal())}
                        for j in range(3)
                    ],
                    "userFeatures": [
                        {"name": "bias", "term": "", "value": 1.0}
                    ],
                },
            }
            for i in range(n)
        ]
        train = str(tmp_path / "game.avro")
        write_game_avro(train, records)
        config = {
            "task": "logistic",
            "iterations": 2,
            "coordinates": [
                {"name": "fixed", "type": "fixed",
                 "feature_shard": "global", "reg_type": "l2",
                 "reg_weight": 1.0, "max_iters": 5},
                {"name": "per_user", "type": "random",
                 "feature_shard": "userFeatures", "entity_key": "userId",
                 "reg_type": "l2", "reg_weight": 1.0, "max_iters": 5},
            ],
        }
        cfg_path = str(tmp_path / "cfg.json")
        with open(cfg_path, "w") as f:
            json.dump(config, f)

        orig_run = descent_mod.CoordinateDescent.run
        state = {"calls": 0, "resumed_from": None}

        def flaky_run(self, base_offsets, n_iterations=1, checkpointer=None,
                      **kw):
            state["calls"] += 1
            if state["calls"] == 1:
                # First attempt: run ONE iteration (checkpointing), then
                # die as the transport would.
                orig_run(
                    self, base_offsets, n_iterations=1,
                    checkpointer=checkpointer, **kw
                )
                raise RuntimeError("UNAVAILABLE: device lost (induced)")
            saved = checkpointer.load() if checkpointer else None
            state["resumed_from"] = (
                saved["iteration"] if saved is not None else None
            )
            return orig_run(
                self, base_offsets, n_iterations=n_iterations,
                checkpointer=checkpointer, **kw
            )

        monkeypatch.setattr(
            descent_mod.CoordinateDescent, "run", flaky_run
        )
        out = str(tmp_path / "out")
        result = game_training_driver.run([
            "--train-data", train,
            "--config", cfg_path,
            "--output-dir", out,
            "--max-retries", "1",
            "--retry-backoff", "0.01",
        ])
        assert state["calls"] == 2
        assert state["resumed_from"] == 0  # resumed AFTER iteration 0
        assert os.path.isdir(os.path.join(out, "models"))
        assert result["history"]


class TestTypeNameVeto:
    def test_xla_error_with_oom_status_not_retried(self):
        """RESOURCE_EXHAUSTED inside an XlaRuntimeError must veto the
        type-name fallback — a retry re-runs the same allocation."""
        XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
        p = RetryPolicy(max_retries=3)
        assert not p.is_transient(
            XlaRuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating")
        )
        assert not p.is_transient(
            XlaRuntimeError("INVALID_ARGUMENT: shape mismatch")
        )
        # ...but a genuinely transient status still retries.
        assert p.is_transient(XlaRuntimeError("UNAVAILABLE: Socket closed"))
        assert p.is_transient(XlaRuntimeError("unrecognized plugin error"))


class TestGameGridRecovery:
    def test_grid_crash_resumes_at_point_boundary(self, tmp_path, monkeypatch):
        """Kill the GAME fit between grid points; the retry must SKIP the
        completed point (loading its checkpointed model) and fit only the
        rest (VERDICT r3 weak #6 / next-round #8)."""
        import json

        from photon_ml_tpu.data.game_reader import write_game_avro
        from photon_ml_tpu.drivers import game_training_driver
        from photon_ml_tpu.game import estimator as est_mod

        rng = np.random.default_rng(7)
        n = 300
        records = [
            {
                "uid": f"row{i}",
                "response": float(rng.integers(2)),
                "weight": None,
                "offset": None,
                "ids": {"userId": f"u{rng.integers(15)}"},
                "features": {
                    "global": [
                        {"name": f"g{j}", "term": "",
                         "value": float(rng.normal())}
                        for j in range(3)
                    ],
                    "userFeatures": [
                        {"name": "bias", "term": "", "value": 1.0}
                    ],
                },
            }
            for i in range(n)
        ]
        train = str(tmp_path / "game.avro")
        val = str(tmp_path / "val.avro")
        write_game_avro(train, records[: n - 60])
        write_game_avro(val, records[n - 60:])
        config = {
            "task": "logistic",
            "iterations": 1,
            "evaluator": "auc",
            "coordinates": [
                {"name": "fixed", "type": "fixed",
                 "feature_shard": "global", "reg_type": "l2",
                 "reg_weights": [0.1, 1.0, 10.0], "max_iters": 5},
                {"name": "per_user", "type": "random",
                 "feature_shard": "userFeatures", "entity_key": "userId",
                 "reg_type": "l2", "reg_weight": 1.0, "max_iters": 5},
            ],
        }
        cfg_path = str(tmp_path / "cfg.json")
        with open(cfg_path, "w") as f:
            json.dump(config, f)

        orig_fit = est_mod.GameEstimator.fit_coordinates
        state = {"fits": []}

        def flaky_fit(self, *a, **kw):
            # fit_coordinates runs once per NON-RESUMED grid point; die
            # right after the second point's fit returns (its checkpoint
            # has NOT been written yet -> it must re-fit on retry).
            out = orig_fit(self, *a, **kw)
            state["fits"].append(len(state["fits"]))
            if len(state["fits"]) == 2:
                raise RuntimeError("UNAVAILABLE: device lost (induced)")
            return out

        monkeypatch.setattr(
            est_mod.GameEstimator, "fit_coordinates", flaky_fit
        )
        out = str(tmp_path / "out")
        result = game_training_driver.run([
            "--train-data", train,
            "--validate-data", val,
            "--config", cfg_path,
            "--output-dir", out,
            "--max-retries", "1",
            "--retry-backoff", "0.01",
        ])
        # Attempt 1: fits point 0 (checkpointed) + point 1 (killed before
        # checkpoint).  Attempt 2: skips point 0, re-fits points 1 and 2.
        # Total real fits = 4, not 6 — the completed point never re-ran.
        assert len(state["fits"]) == 4
        assert len(result["grid"]) == 3
        assert sum(1 for g in result["grid"] if g["best"]) == 1
        assert os.path.isdir(os.path.join(out, "models"))
        # The checkpointed point 0 still contributed a real metric.
        assert all(g["metric"] is not None for g in result["grid"])
