"""REAL multi-process multi-host proof (VERDICT r2 missing #4).

Everything else in the suite exercises multi-device semantics inside ONE
process.  Here 2 separate processes (2 virtual CPU devices each) rendezvous
through ``jax.distributed`` via ``parallel.multihost.initialize``, carve a
global row space with ``host_local_rows``, build globally-sharded arrays
with ``assemble_global`` (each process feeds ONLY its own block), and run a
data-parallel L-BFGS fit under ``shard_map`` over the 4-device global mesh
— the pod topology of SURVEY.md §5.8 at localhost scale.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import json, os, sys
port, pid, nproc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")

from photon_ml_tpu.parallel import multihost

multi = multihost.initialize(f"localhost:{port}", nproc, pid)
assert multi, "initialize() did not report multi-host"
assert jax.process_count() == nproc, jax.process_count()
assert jax.device_count() == 2 * nproc, jax.device_count()

import numpy as np
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from photon_ml_tpu.data.dataset import GlmData
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.sparse import DenseMatrix
from photon_ml_tpu.optim.lbfgs import LBFGSConfig, lbfgs_solve
from photon_ml_tpu.optim.objective import GlmObjective
from photon_ml_tpu.parallel.distributed import DATA_AXIS

mesh = multihost.global_data_mesh()
n, d = 64, 5
rng = np.random.default_rng(0)  # identical data derivation on every process
X = rng.normal(size=(n, d)).astype(np.float32)
w_true = rng.normal(size=d).astype(np.float32)
y = (rng.random(n) < 1.0 / (1.0 + np.exp(-(X @ w_true)))).astype(np.float32)

lo, hi = multihost.host_local_rows(n)
# Each process feeds ONLY its own host block.
Xg = multihost.assemble_global(X[lo:hi], n, mesh)
yg = multihost.assemble_global(y[lo:hi], n, mesh)

obj = GlmObjective(losses.logistic)


def spmd(Xl, yl):
    data = GlmData(
        DenseMatrix(Xl), yl, jnp.ones_like(yl), jnp.zeros_like(yl)
    )
    return lbfgs_solve(
        lambda w: obj.value_and_grad(
            w, data, l2_weight=1.0, axis_name=DATA_AXIS
        ),
        jnp.zeros(d, jnp.float32),
        LBFGSConfig(max_iters=50, tolerance=1e-9),
    )


res = jax.jit(jax.shard_map(
    spmd, mesh=mesh,
    in_specs=(P(DATA_AXIS), P(DATA_AXIS)), out_specs=P(),
    check_vma=False,
))(Xg, yg)
w = np.asarray(jax.device_get(res.w))
print("RESULT " + json.dumps({
    "pid": pid, "lo": lo, "hi": hi,
    "w": w.tolist(), "value": float(res.value),
}), flush=True)
jax.distributed.shutdown()
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_dp_fit_matches_single_process(tmp_path):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = _free_port()
    nproc = 2
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    env["PYTHONPATH"] = repo_root + ":" + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(port), str(i), str(nproc)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("jax.distributed localhost rendezvous timed out here")
    results = []
    for rc, out, err in outs:
        if rc != 0 and "DISTRIBUTED" in err.upper() and not results:
            pytest.skip(f"jax.distributed unsupported here: {err[-300:]}")
        assert rc == 0, err[-2000:]
        line = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert line, out
        results.append(json.loads(line[0][len("RESULT "):]))

    # The two processes partitioned the row space without gap or overlap.
    bounds = sorted((r["lo"], r["hi"]) for r in results)
    assert bounds[0][0] == 0 and bounds[-1][1] == 64
    assert bounds[0][1] == bounds[1][0]
    # Replicated out_specs: every process holds the SAME solution.
    w0, w1 = (np.asarray(r["w"]) for r in results)
    np.testing.assert_array_equal(w0, w1)

    # Single-process oracle: the IDENTICAL shard_map program on a 4-device
    # mesh inside this process (conftest gives 8 virtual devices).  Same
    # per-device blocks, same psum structure → same numerics.
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from photon_ml_tpu.data.dataset import GlmData
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.sparse import DenseMatrix
    from photon_ml_tpu.optim.lbfgs import LBFGSConfig, lbfgs_solve
    from photon_ml_tpu.optim.objective import GlmObjective
    from photon_ml_tpu.parallel.distributed import DATA_AXIS

    n, d = 64, 5
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-(X @ w_true)))).astype(
        np.float32
    )
    mesh = Mesh(np.array(jax.devices()[:4]), (DATA_AXIS,))
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    Xg = jax.device_put(X, NamedSharding(mesh, P(DATA_AXIS, None)))
    yg = jax.device_put(y, sharding)
    obj = GlmObjective(losses.logistic)

    def spmd(Xl, yl):
        data = GlmData(
            DenseMatrix(Xl), yl, jnp.ones_like(yl), jnp.zeros_like(yl)
        )
        return lbfgs_solve(
            lambda w: obj.value_and_grad(
                w, data, l2_weight=1.0, axis_name=DATA_AXIS
            ),
            jnp.zeros(d, jnp.float32),
            LBFGSConfig(max_iters=50, tolerance=1e-9),
        )

    res = jax.jit(jax.shard_map(
        spmd, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS)), out_specs=P(),
        check_vma=False,
    ))(Xg, yg)
    w_oracle = np.asarray(res.w)
    # Same partitioning and collectives; bit-parity expected, tiny slack
    # tolerated in case the multi-process compile fuses differently.
    np.testing.assert_allclose(w0, w_oracle, atol=1e-6)


_WORKER_STREAM = r"""
import json, os, sys
port, pid, nproc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")

from photon_ml_tpu.parallel import multihost

multi = multihost.initialize(f"localhost:{port}", nproc, pid)
assert multi, "initialize() did not report multi-host"

import numpy as np
import jax.numpy as jnp
import scipy.sparse as sp

from photon_ml_tpu.data.streaming import make_streaming_glm_data
from photon_ml_tpu.optim.lbfgs import LBFGSConfig
from photon_ml_tpu.optim.streaming import (
    StreamingObjective,
    streaming_lbfgs_solve,
)

mesh = multihost.global_data_mesh()
# n=130 is deliberately UNEVEN: proc0 owns 66 rows (3 chunks of 32),
# proc1 owns 64 (2 chunks) — the pod alignment must equalize chunk
# counts with zero-weight blanks or the psum loop deadlocks.  Sparse
# features exercise the common coo_budget requirement.
n, d = 130, 6
rng = np.random.default_rng(0)  # identical derivation on every process
X = sp.random(n, d, density=0.6, random_state=1, format="csr",
              dtype=np.float32)
w_true = rng.normal(size=d).astype(np.float32)
logits = np.asarray(X @ w_true).ravel()
y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)

# Each process builds a chunk store over ITS host-local rows ONLY, with
# one shard per local device; chunks assemble into globally-sharded
# arrays per streamed pass (no host ever holds a global chunk).
lo, hi = multihost.host_local_rows(n)
stream = make_streaming_glm_data(
    X[lo:hi], y[lo:hi],
    chunk_rows=32, use_pallas=False,
    n_shards=jax.local_device_count(),
    coo_budget=int(X.nnz),  # identical pod-wide pad budget
)
sobj = StreamingObjective("logistic", stream, mesh=mesh)
res = streaming_lbfgs_solve(
    lambda w: sobj.value_and_grad(w, 1.0),
    jnp.zeros(d, jnp.float32),
    LBFGSConfig(max_iters=60, tolerance=1e-9),
)
w = np.asarray(jax.device_get(res.w))
print("RESULT " + json.dumps({
    "pid": pid, "lo": lo, "hi": hi,
    "w": w.tolist(), "value": float(res.value),
}), flush=True)
jax.distributed.shutdown()
"""


def test_two_process_streamed_dp_fit_matches_single_process(tmp_path):
    """Multi-host OUT-OF-CORE data parallelism: 2 processes each stream
    a host-local chunk store through the 4-device global mesh; the fit
    must land on the single-process resident solution."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "worker_stream.py"
    worker.write_text(_WORKER_STREAM)
    port = _free_port()
    nproc = 2
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo_root + ":" + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(port), str(i), str(nproc)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("jax.distributed localhost rendezvous timed out here")
    results = []
    for rc, out, err in outs:
        if rc != 0 and "DISTRIBUTED" in err.upper() and not results:
            pytest.skip(f"jax.distributed unsupported here: {err[-300:]}")
        assert rc == 0, err[-2000:]
        line = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert line, out
        results.append(json.loads(line[0][len("RESULT "):]))

    w0, w1 = (np.asarray(r["w"]) for r in results)
    np.testing.assert_array_equal(w0, w1)  # replicated solution

    # Single-process oracle: resident fit on the full data.
    import jax
    import jax.numpy as jnp
    import scipy.sparse as sp

    from photon_ml_tpu.data.dataset import make_glm_data
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.optim.lbfgs import LBFGSConfig, lbfgs_solve
    from photon_ml_tpu.optim.objective import GlmObjective

    n, d = 130, 6
    rng = np.random.default_rng(0)
    X = sp.random(n, d, density=0.6, random_state=1, format="csr",
                  dtype=np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    logits = np.asarray(X @ w_true).ravel()
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)
    data = make_glm_data(X, y)
    obj = GlmObjective(losses.logistic)
    oracle = lbfgs_solve(
        lambda w: obj.value_and_grad(w, data, l2_weight=1.0),
        jnp.zeros(d, jnp.float32),
        LBFGSConfig(max_iters=60, tolerance=1e-9),
    )
    # Streamed + psum reduction order differs from the resident oracle;
    # same tolerance class as the in-process streamed-vs-resident tests.
    np.testing.assert_allclose(
        w0, np.asarray(oracle.w), atol=2e-3
    )


def test_two_process_mismatched_stores_fail_loudly(tmp_path):
    """Per-process stores with DIFFERENT coo budgets must die with the
    explanatory ValueError, not an opaque collective shape error — the
    structure signature is hashed to a scalar before the allgather
    precisely so ragged structures still rendezvous."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "worker_bad.py"
    # Same worker, except each process pads to its OWN nnz budget.
    worker.write_text(_WORKER_STREAM.replace(
        "coo_budget=int(X.nnz),  # identical pod-wide pad budget",
        "coo_budget=int(X.nnz) + 64 * pid,  # DELIBERATE mismatch",
    ))
    port = _free_port()
    nproc = 2
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo_root + ":" + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(port), str(i), str(nproc)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("jax.distributed localhost rendezvous timed out here")
    # Detection-success and unsupported-env BOTH exit nonzero here, so the
    # skip must also require that the detection message never appeared.
    if all(
        "DISTRIBUTED" in err.upper() and rc != 0
        and "mismatched leaf shapes" not in err
        for rc, _, err in outs
    ):
        pytest.skip("jax.distributed unsupported here")
    assert all(rc != 0 for rc, _, _ in outs), "mismatch was not detected"
    assert any(
        "mismatched leaf shapes" in err for _, _, err in outs
    ), outs[0][2][-2000:]
