"""REAL multi-process multi-host proof (VERDICT r2 missing #4).

Everything else in the suite exercises multi-device semantics inside ONE
process.  Here 2 separate processes (2 virtual CPU devices each) rendezvous
through ``jax.distributed`` via ``parallel.multihost.initialize``, carve a
global row space with ``host_local_rows``, build globally-sharded arrays
with ``assemble_global`` (each process feeds ONLY its own block), and run a
data-parallel L-BFGS fit under ``shard_map`` over the 4-device global mesh
— the pod topology of SURVEY.md §5.8 at localhost scale.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np

from photon_ml_tpu.parallel.compat import shard_map
import pytest

_WORKER = r"""
import json, os, sys
port, pid, nproc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")

from photon_ml_tpu.parallel import multihost

multi = multihost.initialize(f"localhost:{port}", nproc, pid)
assert multi, "initialize() did not report multi-host"
assert jax.process_count() == nproc, jax.process_count()
assert jax.device_count() == 2 * nproc, jax.device_count()

import numpy as np
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from photon_ml_tpu.data.dataset import GlmData
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.sparse import DenseMatrix
from photon_ml_tpu.optim.lbfgs import LBFGSConfig, lbfgs_solve
from photon_ml_tpu.optim.objective import GlmObjective
from photon_ml_tpu.parallel.distributed import DATA_AXIS

mesh = multihost.global_data_mesh()
n, d = 64, 5
rng = np.random.default_rng(0)  # identical data derivation on every process
X = rng.normal(size=(n, d)).astype(np.float32)
w_true = rng.normal(size=d).astype(np.float32)
y = (rng.random(n) < 1.0 / (1.0 + np.exp(-(X @ w_true)))).astype(np.float32)

lo, hi = multihost.host_local_rows(n)
# Each process feeds ONLY its own host block.
Xg = multihost.assemble_global(X[lo:hi], n, mesh)
yg = multihost.assemble_global(y[lo:hi], n, mesh)

obj = GlmObjective(losses.logistic)


def spmd(Xl, yl):
    data = GlmData(
        DenseMatrix(Xl), yl, jnp.ones_like(yl), jnp.zeros_like(yl)
    )
    return lbfgs_solve(
        lambda w: obj.value_and_grad(
            w, data, l2_weight=1.0, axis_name=DATA_AXIS
        ),
        jnp.zeros(d, jnp.float32),
        LBFGSConfig(max_iters=50, tolerance=1e-9),
    )


res = jax.jit(shard_map(
    spmd, mesh=mesh,
    in_specs=(P(DATA_AXIS), P(DATA_AXIS)), out_specs=P(),
    check_vma=False,
))(Xg, yg)
w = np.asarray(jax.device_get(res.w))
print("RESULT " + json.dumps({
    "pid": pid, "lo": lo, "hi": hi,
    "w": w.tolist(), "value": float(res.value),
}), flush=True)
jax.distributed.shutdown()
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_dp_fit_matches_single_process(tmp_path):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = _free_port()
    nproc = 2
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    env["PYTHONPATH"] = repo_root + ":" + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(port), str(i), str(nproc)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("jax.distributed localhost rendezvous timed out here")
    results = []
    for rc, out, err in outs:
        if rc != 0 and "DISTRIBUTED" in err.upper() and not results:
            pytest.skip(f"jax.distributed unsupported here: {err[-300:]}")
        assert rc == 0, err[-2000:]
        line = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert line, out
        results.append(json.loads(line[0][len("RESULT "):]))

    # The two processes partitioned the row space without gap or overlap.
    bounds = sorted((r["lo"], r["hi"]) for r in results)
    assert bounds[0][0] == 0 and bounds[-1][1] == 64
    assert bounds[0][1] == bounds[1][0]
    # Replicated out_specs: every process holds the SAME solution.
    w0, w1 = (np.asarray(r["w"]) for r in results)
    np.testing.assert_array_equal(w0, w1)

    # Single-process oracle: the IDENTICAL shard_map program on a 4-device
    # mesh inside this process (conftest gives 8 virtual devices).  Same
    # per-device blocks, same psum structure → same numerics.
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from photon_ml_tpu.data.dataset import GlmData
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.sparse import DenseMatrix
    from photon_ml_tpu.optim.lbfgs import LBFGSConfig, lbfgs_solve
    from photon_ml_tpu.optim.objective import GlmObjective
    from photon_ml_tpu.parallel.distributed import DATA_AXIS

    n, d = 64, 5
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-(X @ w_true)))).astype(
        np.float32
    )
    mesh = Mesh(np.array(jax.devices()[:4]), (DATA_AXIS,))
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    Xg = jax.device_put(X, NamedSharding(mesh, P(DATA_AXIS, None)))
    yg = jax.device_put(y, sharding)
    obj = GlmObjective(losses.logistic)

    def spmd(Xl, yl):
        data = GlmData(
            DenseMatrix(Xl), yl, jnp.ones_like(yl), jnp.zeros_like(yl)
        )
        return lbfgs_solve(
            lambda w: obj.value_and_grad(
                w, data, l2_weight=1.0, axis_name=DATA_AXIS
            ),
            jnp.zeros(d, jnp.float32),
            LBFGSConfig(max_iters=50, tolerance=1e-9),
        )

    res = jax.jit(shard_map(
        spmd, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS)), out_specs=P(),
        check_vma=False,
    ))(Xg, yg)
    w_oracle = np.asarray(res.w)
    # Same partitioning and collectives; bit-parity expected, tiny slack
    # tolerated in case the multi-process compile fuses differently.
    np.testing.assert_allclose(w0, w_oracle, atol=1e-6)


_WORKER_STREAM = r"""
import json, os, sys
port, pid, nproc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")

from photon_ml_tpu.parallel import multihost

multi = multihost.initialize(f"localhost:{port}", nproc, pid)
assert multi, "initialize() did not report multi-host"

import numpy as np
import jax.numpy as jnp
import scipy.sparse as sp

from photon_ml_tpu.data.streaming import make_streaming_glm_data
from photon_ml_tpu.optim.lbfgs import LBFGSConfig
from photon_ml_tpu.optim.streaming import (
    StreamingObjective,
    streaming_lbfgs_solve,
)

mesh = multihost.global_data_mesh()
# n=130 is deliberately UNEVEN: proc0 owns 66 rows (3 chunks of 32),
# proc1 owns 64 (2 chunks) — the pod alignment must equalize chunk
# counts with zero-weight blanks or the psum loop deadlocks.  Sparse
# features exercise the common coo_budget requirement.
n, d = 130, 6
rng = np.random.default_rng(0)  # identical derivation on every process
X = sp.random(n, d, density=0.6, random_state=1, format="csr",
              dtype=np.float32)
w_true = rng.normal(size=d).astype(np.float32)
logits = np.asarray(X @ w_true).ravel()
y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)

# Each process builds a chunk store over ITS host-local rows ONLY, with
# one shard per local device; chunks assemble into globally-sharded
# arrays per streamed pass (no host ever holds a global chunk).
lo, hi = multihost.host_local_rows(n)
stream = make_streaming_glm_data(
    X[lo:hi], y[lo:hi],
    chunk_rows=32, use_pallas=False,
    n_shards=jax.local_device_count(),
    coo_budget=int(X.nnz),  # identical pod-wide pad budget
)
sobj = StreamingObjective("logistic", stream, mesh=mesh)
res = streaming_lbfgs_solve(
    lambda w: sobj.value_and_grad(w, 1.0),
    jnp.zeros(d, jnp.float32),
    LBFGSConfig(max_iters=60, tolerance=1e-9),
)
w = np.asarray(jax.device_get(res.w))
print("RESULT " + json.dumps({
    "pid": pid, "lo": lo, "hi": hi,
    "w": w.tolist(), "value": float(res.value),
}), flush=True)
jax.distributed.shutdown()
"""


def test_two_process_streamed_dp_fit_matches_single_process(tmp_path):
    """Multi-host OUT-OF-CORE data parallelism: 2 processes each stream
    a host-local chunk store through the 4-device global mesh; the fit
    must land on the single-process resident solution."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "worker_stream.py"
    worker.write_text(_WORKER_STREAM)
    port = _free_port()
    nproc = 2
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo_root + ":" + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(port), str(i), str(nproc)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("jax.distributed localhost rendezvous timed out here")
    results = []
    for rc, out, err in outs:
        if rc != 0 and "DISTRIBUTED" in err.upper() and not results:
            pytest.skip(f"jax.distributed unsupported here: {err[-300:]}")
        assert rc == 0, err[-2000:]
        line = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert line, out
        results.append(json.loads(line[0][len("RESULT "):]))

    w0, w1 = (np.asarray(r["w"]) for r in results)
    np.testing.assert_array_equal(w0, w1)  # replicated solution

    # Single-process oracle: resident fit on the full data.
    import jax
    import jax.numpy as jnp
    import scipy.sparse as sp

    from photon_ml_tpu.data.dataset import make_glm_data
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.optim.lbfgs import LBFGSConfig, lbfgs_solve
    from photon_ml_tpu.optim.objective import GlmObjective

    n, d = 130, 6
    rng = np.random.default_rng(0)
    X = sp.random(n, d, density=0.6, random_state=1, format="csr",
                  dtype=np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    logits = np.asarray(X @ w_true).ravel()
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)
    data = make_glm_data(X, y)
    obj = GlmObjective(losses.logistic)
    oracle = lbfgs_solve(
        lambda w: obj.value_and_grad(w, data, l2_weight=1.0),
        jnp.zeros(d, jnp.float32),
        LBFGSConfig(max_iters=60, tolerance=1e-9),
    )
    # Streamed + psum reduction order differs from the resident oracle;
    # same tolerance class as the in-process streamed-vs-resident tests.
    np.testing.assert_allclose(
        w0, np.asarray(oracle.w), atol=2e-3
    )


_WORKER_GAME = r"""
import json, os, sys
port, pid, nproc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")

from photon_ml_tpu.parallel import multihost

multi = multihost.initialize(f"localhost:{port}", nproc, pid)
assert multi, "initialize() did not report multi-host"

import numpy as np
import jax.numpy as jnp
import scipy.sparse as sp

from photon_ml_tpu.data.streaming import make_streaming_glm_data
from photon_ml_tpu.evaluation.device import device_pointwise_partial
from photon_ml_tpu.game.coordinates import RandomEffectCoordinate
from photon_ml_tpu.game.data import build_random_effect_dataset
from photon_ml_tpu.game.descent import CoordinateDescent
from photon_ml_tpu.game.streaming import StreamingFixedEffectCoordinate
from photon_ml_tpu.optim.problem import (
    GlmOptimizationConfig, OptimizerConfig,
)
from photon_ml_tpu.optim.regularization import RegularizationContext

mesh = multihost.global_data_mesh()
# Identical global data derivation on every process; rows grouped by
# entity and entities PARTITIONED to processes (the reference's
# hash-partitioner invariant: an entity's rows live on one executor).
rng = np.random.default_rng(0)
n, d, n_users = 128, 5, 10
X = rng.normal(size=(n, d)).astype(np.float32)
user_of_row = rng.integers(0, n_users, size=n)
w_true = rng.normal(size=d).astype(np.float32)
bias_true = rng.normal(scale=1.5, size=n_users).astype(np.float32)
logits = X @ w_true + bias_true[user_of_row]
y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)
order = np.argsort(user_of_row, kind="stable")  # entity-contiguous rows
X, y, user_of_row = X[order], y[order], user_of_row[order]

# Process p owns users [p*5, (p+1)*5) and exactly their rows.
mine = (user_of_row // (n_users // nproc)) == pid
lo_rows = np.flatnonzero(mine)
Xl, yl, ul = X[lo_rows], y[lo_rows], user_of_row[lo_rows]
n_local = len(yl)

opt = GlmOptimizationConfig(
    optimizer=OptimizerConfig(max_iters=40, tolerance=1e-8),
    regularization=RegularizationContext.l2(),
)
stream = make_streaming_glm_data(
    sp.csr_matrix(Xl), yl, chunk_rows=32, use_pallas=False,
    n_shards=jax.local_device_count(),
    coo_budget=int(sp.csr_matrix(X).nnz),  # identical pod-wide budget
)
fixed = StreamingFixedEffectCoordinate(
    "fixed", stream, "logistic", opt, reg_weight=1.0, mesh=mesh,
)
# The random effect is OUT-OF-CORE per process (mesh=None: under the
# pod's process-local contract each process trains ITS entities on ITS
# devices; only the fixed effect's passes psum pod-wide) — out-of-core
# random effects compose with pods through locality, not pod-sharding.
from photon_ml_tpu.game.ooc_random import OutOfCoreRandomEffectCoordinate

re = OutOfCoreRandomEffectCoordinate(
    "pu",
    build_random_effect_dataset(
        [f"u{u}" for u in ul], sp.csr_matrix(np.ones((n_local, 1), np.float32)),
        yl, np.ones(n_local, np.float32), device=False,
    ),
    "logistic", opt, reg_weight=1.0, entity_key="userId",
    device_budget_bytes=1600,
)
assert len(re.pass_plan) >= 2, "budget too big to exercise multi-group"
result = CoordinateDescent([fixed, re]).run(
    jnp.zeros(n_local, jnp.float32), n_iterations=2
)
total_local = result.scores["fixed"] + result.scores["pu"]
# GLOBAL metric from process-local scores: one scalar pair per process.
num, den = device_pointwise_partial(
    total_local, jnp.asarray(yl), None, kind="logistic_loss"
)
table = {}
for lane_key, (cols, vals) in re.finalize(result.states["pu"]).coefficients.items():
    table[lane_key] = [float(v) for v in vals]
print("RESULT " + json.dumps({
    "pid": pid,
    "w_fixed": np.asarray(result.states["fixed"]).tolist(),
    "num": float(num), "den": float(den),
    "re_table": table,
    "scored_rows": int(total_local.shape[0]),
}), flush=True)
jax.distributed.shutdown()
"""


def test_two_process_streamed_game_cd_matches_single_process(tmp_path):
    """VERDICT r4 missing #3 closed: a streamed-GAME CD step runs on a
    2-process pod — per-row CD state process-local, fixed-effect solve
    psum'd globally, entities partitioned with their rows — and both the
    model and a global metric match the single-process run."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "worker_game.py"
    worker.write_text(_WORKER_GAME)
    port = _free_port()
    nproc = 2
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo_root + ":" + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(port), str(i), str(nproc)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("jax.distributed localhost rendezvous timed out here")
    results = []
    for rc, out, err in outs:
        if rc != 0 and "DISTRIBUTED" in err.upper() and not results:
            pytest.skip(f"jax.distributed unsupported here: {err[-300:]}")
        assert rc == 0, err[-2000:]
        line = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert line, out
        results.append(json.loads(line[0][len("RESULT "):]))

    # The psum'd fixed-effect solve is replicated: identical on both.
    w0, w1 = (np.asarray(r["w_fixed"]) for r in results)
    np.testing.assert_array_equal(w0, w1)
    # Per-row coverage: the two local score vectors partition the rows.
    assert sum(r["scored_rows"] for r in results) == 128
    # Disjoint entity partitions whose union is all 10 users.
    keys0 = set(results[0]["re_table"])
    keys1 = set(results[1]["re_table"])
    assert keys0.isdisjoint(keys1)
    assert len(keys0 | keys1) == 10

    # Single-process oracle: the same CD on the full data.
    import jax
    import jax.numpy as jnp
    import scipy.sparse as sp

    from photon_ml_tpu.evaluation.device import (
        device_pointwise_partial, finish_pointwise_partial,
    )
    from photon_ml_tpu.game.coordinates import (
        FixedEffectCoordinate, RandomEffectCoordinate,
    )
    from photon_ml_tpu.game.data import (
        FixedEffectDataset, build_random_effect_dataset,
    )
    from photon_ml_tpu.game.descent import CoordinateDescent
    from photon_ml_tpu.data.dataset import make_glm_data
    from photon_ml_tpu.optim.problem import (
        GlmOptimizationConfig, OptimizerConfig,
    )
    from photon_ml_tpu.optim.regularization import RegularizationContext

    rng = np.random.default_rng(0)
    n, d, n_users = 128, 5, 10
    X = rng.normal(size=(n, d)).astype(np.float32)
    user_of_row = rng.integers(0, n_users, size=n)
    w_true = rng.normal(size=d).astype(np.float32)
    bias_true = rng.normal(scale=1.5, size=n_users).astype(np.float32)
    logits = X @ w_true + bias_true[user_of_row]
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)
    order = np.argsort(user_of_row, kind="stable")
    X, y, user_of_row = X[order], y[order], user_of_row[order]

    opt = GlmOptimizationConfig(
        optimizer=OptimizerConfig(max_iters=40, tolerance=1e-8),
        regularization=RegularizationContext.l2(),
    )
    fixed = FixedEffectCoordinate(
        "fixed",
        FixedEffectDataset(make_glm_data(sp.csr_matrix(X), y), n),
        "logistic", opt, reg_weight=1.0,
    )
    re = RandomEffectCoordinate(
        "pu",
        build_random_effect_dataset(
            [f"u{u}" for u in user_of_row],
            sp.csr_matrix(np.ones((n, 1), np.float32)),
            y, np.ones(n, np.float32),
        ),
        "logistic", opt, reg_weight=1.0, entity_key="userId",
    )
    oracle = CoordinateDescent([fixed, re]).run(
        jnp.zeros(n, jnp.float32), n_iterations=2
    )
    # Pod fixed coefficients land on the single-process solution.
    np.testing.assert_allclose(
        w0, np.asarray(oracle.states["fixed"]), atol=5e-3
    )
    # Per-entity models: the union of the two partitions matches.
    oracle_table = {
        k: [float(v) for v in vals]
        for k, (cols, vals) in re.finalize(
            oracle.states["pu"]
        ).coefficients.items()
    }
    pod_table = {**results[0]["re_table"], **results[1]["re_table"]}
    assert set(pod_table) == set(oracle_table)
    for k, vals in oracle_table.items():
        np.testing.assert_allclose(pod_table[k], vals, atol=5e-3)
    # The GLOBAL metric assembled from per-process scalar pairs matches.
    o_total = oracle.scores["fixed"] + oracle.scores["pu"]
    o_num, o_den = device_pointwise_partial(
        o_total, jnp.asarray(y), None, kind="logistic_loss"
    )
    pod_metric = finish_pointwise_partial(
        sum(r["num"] for r in results), sum(r["den"] for r in results),
        "logistic_loss",
    )
    oracle_metric = finish_pointwise_partial(
        float(o_num), float(o_den), "logistic_loss"
    )
    assert pod_metric == pytest.approx(oracle_metric, abs=1e-4)


def test_two_process_mismatched_stores_fail_loudly(tmp_path):
    """Per-process stores with DIFFERENT coo budgets must die with the
    explanatory ValueError, not an opaque collective shape error — the
    structure signature is hashed to a scalar before the allgather
    precisely so ragged structures still rendezvous."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "worker_bad.py"
    # Same worker, except each process pads to its OWN nnz budget.
    worker.write_text(_WORKER_STREAM.replace(
        "coo_budget=int(X.nnz),  # identical pod-wide pad budget",
        "coo_budget=int(X.nnz) + 64 * pid,  # DELIBERATE mismatch",
    ))
    port = _free_port()
    nproc = 2
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo_root + ":" + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(port), str(i), str(nproc)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("jax.distributed localhost rendezvous timed out here")
    # Detection-success and unsupported-env BOTH exit nonzero here, so the
    # skip must also require that the detection message never appeared.
    if all(
        "DISTRIBUTED" in err.upper() and rc != 0
        and "mismatched leaf shapes" not in err
        for rc, _, err in outs
    ):
        pytest.skip("jax.distributed unsupported here")
    assert all(rc != 0 for rc, _, _ in outs), "mismatch was not detected"
    assert any(
        "mismatched leaf shapes" in err for _, _, err in outs
    ), outs[0][2][-2000:]
