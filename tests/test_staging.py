"""Coalesced pinned-staging ingest pipeline.

Three contracts pinned here (ISSUE 1 acceptance):

1. **Staging parity** — packing a chunk's leaves into dtype-segregated
   buffers and unpacking (host views AND the compiled device unpack) is
   bit-exact, for every layout (dense / COO / tiled-Pallas) and for
   sharded (leading shard axis) and unsharded chunks.
2. **Streamed ≡ resident through the coalesced path** — the streamed
   objective's value/grad still matches the resident objective now that
   chunks cross as staging buffers with an in-program unpack.
3. **Pipeline bounds & observability** — prefetch-depth edge cases
   (1, > n_chunks), the ≤depth liveness bound, error propagation, and
   the transfer-stat counters bench_streaming reports.
"""

import os
import threading

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

os.environ.setdefault("PHOTON_PALLAS_INTERPRET", "1")

from photon_ml_tpu.data.dataset import make_glm_data
from photon_ml_tpu.data.prefetch import TransferStats, run_prefetched
from photon_ml_tpu.data.staging import pack_chunk, plan_staging
from photon_ml_tpu.data.streaming import make_streaming_glm_data
from photon_ml_tpu.optim.objective import GlmObjective
from photon_ml_tpu.optim.streaming import StreamingObjective
from photon_ml_tpu.ops import losses

LAYOUTS = ["dense", "coo", "pallas"]


def _problem(rng, n, d, layout, seed=11):
    if layout == "dense":
        X = rng.normal(size=(n, d)).astype(np.float32)
        logits = X @ (rng.normal(size=d) * 0.3)
    else:
        X = sp.random(
            n, d, density=0.15, random_state=seed, format="csr",
            dtype=np.float32,
        )
        X = sp.hstack(
            [sp.csr_matrix(np.ones((n, 1), np.float32)), X[:, 1:]]
        ).tocsr()
        logits = np.asarray(X @ (rng.normal(size=d) * 0.3)).ravel()
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(np.float32)
    return X, y


def _stream(rng, layout, n_shards=1, n=640, d=24, chunk_rows=256):
    X, y = _problem(rng, n, d, layout)
    return X, y, make_streaming_glm_data(
        X, y, chunk_rows=chunk_rows, use_pallas=(layout == "pallas"),
        n_shards=n_shards, depth_cap=16,
    )


class TestStagingRoundtrip:
    @pytest.mark.parametrize("layout", LAYOUTS)
    @pytest.mark.parametrize("n_shards", [1, 4])
    def test_pack_view_unpack_bit_exact(self, rng, layout, n_shards):
        _, _, stream = _stream(rng, layout, n_shards=n_shards)
        assert stream.staged is not None and stream.staging is not None
        staging = stream.staging
        # Dtype segregation keeps the per-chunk transfer count O(1).
        assert 1 <= staging.n_buffers <= 4
        assert len(stream.staged) == stream.n_chunks
        for bufs, chunk in zip(stream.staged, stream.chunks):
            leaves = jax.tree_util.tree_leaves(chunk)
            # Host views are ZERO-COPY into the staging buffers (no
            # second host copy of the dataset)...
            for leaf in leaves:
                assert any(
                    np.shares_memory(leaf, np.asarray(b)) for b in bufs
                ) or leaf.size == 0
            # ...and re-packing the views reproduces the buffers
            # bit-for-bit (pack/view are exact inverses).
            repacked = pack_chunk(staging, chunk)
            for a, b in zip(repacked, bufs):
                np.testing.assert_array_equal(a, np.asarray(b))
            # Total staged bytes account for every leaf byte.
            assert staging.nbytes == sum(
                np.asarray(b).nbytes for b in bufs
            )

    @pytest.mark.parametrize("layout", LAYOUTS)
    @pytest.mark.parametrize("n_shards", [1, 4])
    def test_device_unpack_matches_host(self, rng, layout, n_shards):
        """The compiled slice+reshape unpack restores every leaf exactly
        (no kernels involved — pure XLA, so this covers the Pallas
        layout's staging on CPU too)."""
        _, _, stream = _stream(rng, layout, n_shards=n_shards)
        staging = stream.staging
        unpack = jax.jit(lambda bufs: staging.unpack_device(bufs))
        for bufs, chunk in zip(stream.staged, stream.chunks):
            restored = unpack(jax.device_put(bufs))
            host = jax.tree_util.tree_leaves(chunk)
            dev = jax.tree_util.tree_leaves(restored)
            assert len(host) == len(dev)
            for h, d_ in zip(host, dev):
                assert h.shape == d_.shape and h.dtype == d_.dtype
                np.testing.assert_array_equal(np.asarray(d_), h)

    def test_plan_rejects_mismatched_chunk(self, rng):
        _, _, stream = _stream(rng, "coo")
        other = jax.tree_util.tree_map(
            lambda x: np.zeros((3,) + x.shape[1:], x.dtype),
            stream.chunks[0],
        )
        with pytest.raises(ValueError, match="staging plan"):
            pack_chunk(stream.staging, other)

    def test_ensure_staged_retrofits_hand_built_store(self, rng):
        """A directly-constructed store (no builder) stages on first
        consumer contact and keeps its values."""
        from photon_ml_tpu.data.streaming import StreamingGlmData

        X, y = _problem(rng, 300, 12, "dense")
        n = X.shape[0]
        chunks = [
            make_glm_data(X[i: i + 100], y[i: i + 100])
            for i in range(0, n, 100)
        ]
        host_chunks = [
            jax.tree_util.tree_map(np.asarray, c) for c in chunks
        ]
        store = StreamingGlmData(
            chunks=host_chunks, n_rows=n, n_features=12, chunk_rows=100
        )
        before = [
            [np.array(l) for l in jax.tree_util.tree_leaves(c)]
            for c in store.chunks
        ]
        assert store.ensure_staged()
        assert store.staged is not None
        for c, orig in zip(store.chunks, before):
            for leaf, o in zip(jax.tree_util.tree_leaves(c), orig):
                np.testing.assert_array_equal(np.asarray(leaf), o)


class TestCoalescedEquivalence:
    @pytest.mark.parametrize("layout", LAYOUTS)
    def test_value_grad_matches_resident(self, rng, layout):
        X, y, stream = _stream(rng, layout)
        sobj = StreamingObjective("logistic", stream)
        assert stream.staged is not None  # the coalesced path is live
        data = make_glm_data(X, y, use_pallas=False)
        obj = GlmObjective(losses.logistic)
        w = jnp.asarray(rng.normal(size=stream.n_features), jnp.float32)
        v_s, g_s = sobj.value_and_grad(w, l2_weight=0.5)
        v_r, g_r = obj.value_and_grad(w, data, l2_weight=0.5)
        assert float(jnp.abs(v_s - v_r)) < 1e-3 * max(1.0, abs(float(v_r)))
        assert float(jnp.abs(g_s - g_r).max()) < 1e-3

    @pytest.mark.parametrize("layout", ["dense", "coo"])
    def test_sharded_value_grad_matches_resident(self, rng, layout):
        """Streamed DP through the coalesced path: buffers placed
        sharded over the mesh, shard_map unpack, fused psum — same
        numbers as the resident single-device objective."""
        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
        n_dev = mesh.devices.size
        X, y, stream = _stream(rng, layout, n_shards=n_dev, n=960)
        sobj = StreamingObjective("logistic", stream, mesh=mesh)
        data = make_glm_data(X, y, use_pallas=False)
        obj = GlmObjective(losses.logistic)
        w = jnp.asarray(rng.normal(size=stream.n_features), jnp.float32)
        v_s, g_s = sobj.value_and_grad(w, l2_weight=0.5)
        v_r, g_r = obj.value_and_grad(w, data, l2_weight=0.5)
        assert float(jnp.abs(v_s - v_r)) < 1e-3 * max(1.0, abs(float(v_r)))
        assert float(jnp.abs(g_s - g_r).max()) < 1e-3

    def test_sharded_pallas_matches_resident(self, rng):
        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
        n_dev = mesh.devices.size
        X, y, stream = _stream(rng, "pallas", n_shards=n_dev, n=960)
        sobj = StreamingObjective("logistic", stream, mesh=mesh)
        data = make_glm_data(X, y, use_pallas=False)
        obj = GlmObjective(losses.logistic)
        w = jnp.asarray(rng.normal(size=stream.n_features), jnp.float32)
        v_s, g_s = sobj.value_and_grad(w, l2_weight=0.5)
        v_r, g_r = obj.value_and_grad(w, data, l2_weight=0.5)
        assert float(jnp.abs(v_s - v_r)) < 1e-3 * max(1.0, abs(float(v_r)))
        assert float(jnp.abs(g_s - g_r).max()) < 1e-3

    def test_scores_match_through_staging(self, rng):
        X, y, stream = _stream(rng, "coo")
        sobj = StreamingObjective("logistic", stream)
        w = jnp.asarray(rng.normal(size=stream.n_features), jnp.float32)
        np.testing.assert_allclose(
            sobj.scores(w),
            np.asarray(X @ np.asarray(w)).ravel(),
            atol=1e-4,
        )


class TestPrefetchDepth:
    @pytest.mark.parametrize("depth", [1, 3, 99])
    def test_any_depth_matches_double_buffer(self, rng, depth):
        """depth 1 (serial transfer/compute) and depth > n_chunks must
        produce bit-identical results to the default double buffer —
        chunks are consumed strictly in order regardless of depth."""
        X, y, stream = _stream(rng, "coo")
        assert depth != 2
        w = jnp.asarray(rng.normal(size=stream.n_features), jnp.float32)
        ref = StreamingObjective("logistic", stream, prefetch_depth=2)
        v2, g2 = ref.value_and_grad(w, 0.5)
        sobj = StreamingObjective("logistic", stream, prefetch_depth=depth)
        v, g = sobj.value_and_grad(w, 0.5)
        np.testing.assert_array_equal(np.asarray(v), np.asarray(v2))
        np.testing.assert_array_equal(np.asarray(g), np.asarray(g2))
        assert sobj.transfer_stats.max_live <= depth

    def test_depth_exceeding_chunks(self, rng):
        X, y, stream = _stream(rng, "dense")
        sobj = StreamingObjective("logistic", stream, prefetch_depth=99)
        w = jnp.zeros(stream.n_features, jnp.float32)
        v, _ = sobj.value_and_grad(w)
        assert np.isfinite(float(v))
        assert sobj.transfer_stats.max_live <= stream.n_chunks

    def test_invalid_depth_rejected(self, rng):
        _, _, stream = _stream(rng, "dense")
        with pytest.raises(ValueError, match="prefetch_depth"):
            StreamingObjective("logistic", stream, prefetch_depth=0)


class TestTransferStats:
    def test_counters_after_one_pass(self, rng):
        X, y, stream = _stream(rng, "coo")
        sobj = StreamingObjective("logistic", stream)
        w = jnp.zeros(stream.n_features, jnp.float32)
        sobj.value_and_grad(w)
        st = sobj.transfer_stats
        assert st.passes == 1
        assert st.chunks == stream.n_chunks
        assert st.bytes == stream.n_chunks * stream.staging.nbytes
        assert st.h2d_seconds >= 0.0
        assert 1 <= st.max_live <= 2
        snap = st.snapshot()
        assert set(snap) >= {
            "chunks", "bytes", "h2d_seconds", "gbps", "chunk_seconds",
            "producer_stalls", "consumer_stalls", "max_live", "passes",
        }

    def test_accumulates_and_resets(self, rng):
        X, y, stream = _stream(rng, "dense")
        sobj = StreamingObjective("logistic", stream)
        w = jnp.zeros(stream.n_features, jnp.float32)
        sobj.value_and_grad(w)
        sobj.value_and_grad(w)
        st = sobj.transfer_stats
        assert st.passes == 2
        assert st.chunks == 2 * stream.n_chunks
        st.reset()
        assert st.passes == 0 and st.chunks == 0 and st.bytes == 0

    def test_scores_pass_counts_too(self, rng):
        X, y, stream = _stream(rng, "coo")
        sobj = StreamingObjective("logistic", stream)
        sobj.scores(jnp.zeros(stream.n_features, jnp.float32))
        assert sobj.transfer_stats.chunks == stream.n_chunks


class TestRunPrefetched:
    """The pipeline primitive itself, against plain numpy items."""

    def test_order_and_results(self):
        items = [np.full((4,), k, np.float32) for k in range(7)]
        seen = []
        run_prefetched(
            len(items),
            lambda k: items[k],
            lambda h: h * 2,
            lambda k, dev: seen.append((k, float(dev[0]))),
            depth=2,
        )
        assert seen == [(k, 2.0 * k) for k in range(7)]

    def test_liveness_bound_holds_at_put(self):
        counts = {"put": 0, "consumed": 0}
        violations = []
        depth = 3

        def put(h):
            counts["put"] += 1
            if counts["put"] - counts["consumed"] > depth:
                violations.append(dict(counts))
            return h

        run_prefetched(
            20,
            lambda k: np.zeros(1),
            put,
            lambda k, dev: counts.__setitem__(
                "consumed", counts["consumed"] + 1
            ),
            depth=depth,
        )
        assert not violations

    def test_producer_error_propagates(self):
        def get_item(k):
            if k == 2:
                raise RuntimeError("ingest exploded")
            return np.zeros(1)

        consumed = []
        with pytest.raises(RuntimeError, match="ingest exploded"):
            run_prefetched(
                5, get_item, lambda h: h,
                lambda k, dev: consumed.append(k), depth=2,
            )
        assert consumed == [0, 1]

    def test_consumer_error_stops_producer(self):
        stats = TransferStats()

        def consume(k, dev):
            if k == 1:
                raise ValueError("consumer bailed")

        with pytest.raises(ValueError, match="consumer bailed"):
            run_prefetched(
                50, lambda k: np.zeros(1), lambda h: h, consume,
                depth=2, stats=stats,
            )
        # The producer must wind down promptly (no leaked live thread
        # still transferring the remaining ~48 items).
        deadline = 50
        for _ in range(deadline):
            if not any(
                t.name == "h2d-prefetch" and t.is_alive()
                for t in threading.enumerate()
            ):
                break
            import time

            time.sleep(0.1)
        else:
            pytest.fail("producer thread still alive after consumer error")

    def test_empty_and_invalid(self):
        stats = TransferStats()
        assert run_prefetched(
            0, lambda k: None, lambda h: h, lambda k, d: None,
            depth=2, stats=stats,
        ) == 0
        assert stats.passes == 1
        with pytest.raises(ValueError, match="depth"):
            run_prefetched(
                1, lambda k: None, lambda h: h, lambda k, d: None, depth=0
            )


class TestStageAttribution:
    """The three-stage split (pack thread / transfer thread / consumer)
    must attribute wall time per stage, and the attribution must add up:
    dispatch ⊆ h2d, stage_seconds = pack + h2d + consume."""

    def test_stage_seconds_recorded_and_consistent(self):
        import time

        stats = TransferStats()

        def slow_get(k):
            time.sleep(0.002)
            return np.zeros(64, np.float32)

        def slow_put(h):
            time.sleep(0.002)
            return h

        def slow_consume(k, dev):
            time.sleep(0.002)

        run_prefetched(
            6, slow_get, slow_put, slow_consume, depth=2, stats=stats
        )
        assert stats.pack_seconds > 0.0
        assert stats.dispatch_seconds > 0.0
        assert stats.h2d_seconds >= stats.dispatch_seconds
        assert stats.consume_seconds > 0.0
        expect = (
            stats.pack_seconds + stats.h2d_seconds + stats.consume_seconds
        )
        assert abs(stats.stage_seconds - expect) < 1e-12
        snap = stats.snapshot()
        assert set(snap) >= {
            "pack_seconds", "dispatch_seconds", "consume_seconds",
            "stage_seconds",
        }

    def test_pack_runs_on_its_own_thread(self):
        """get_item must execute off BOTH the caller thread and the
        transfer thread — the split that lets packing overlap the link."""
        import threading

        names = set()

        def get_item(k):
            names.add(threading.current_thread().name)
            return np.zeros(8, np.float32)

        put_names = set()

        def put(h):
            put_names.add(threading.current_thread().name)
            return h

        run_prefetched(4, get_item, put, lambda k, d: None, depth=2)
        assert names == {"h2d-pack"}
        assert put_names == {"h2d-prefetch"}

    def test_pack_failure_propagates_in_order(self):
        """A pack-stage exception must surface at the failed item's
        position AFTER items 0..k-1 were consumed (the two-thread relay
        preserves stream order)."""
        consumed = []

        def get_item(k):
            if k == 3:
                raise RuntimeError("pack exploded")
            return np.zeros(4, np.float32)

        with pytest.raises(RuntimeError, match="pack exploded"):
            run_prefetched(
                8, get_item, lambda h: h,
                lambda k, d: consumed.append(k), depth=2,
            )
        assert consumed == [0, 1, 2]


# ---------------------------------------------------------------------------
# Compressed chunk formats: wire encodings + on-device decode
# ---------------------------------------------------------------------------

from photon_ml_tpu.data.staging import (  # noqa: E402
    COMPRESSION_MODES,
    plan_compression,
)


def _codec_roundtrip(stream, mode):
    """Encode every chunk and decode on device; returns (codec, list of
    (decoded leaves, reference leaves)) where the reference is the RAW
    staged path's device decode — the exact arrays the uncompressed
    stream would compute on."""
    staging = stream.staging
    codec = plan_compression(staging, stream.staged, mode)
    dec = jax.jit(codec.unpack_device)
    raw = jax.jit(staging.unpack_device)
    pairs = []
    for bufs in stream.staged:
        wire = codec.encode(bufs)
        got = jax.tree_util.tree_leaves(dec(jax.device_put(wire)))
        ref = jax.tree_util.tree_leaves(raw(jax.device_put(bufs)))
        pairs.append((got, ref))
    return codec, pairs


class TestChunkCodec:
    @pytest.mark.parametrize("layout", LAYOUTS)
    @pytest.mark.parametrize("n_shards", [1, 4])
    def test_lossless_bitwise_and_smaller(self, rng, layout, n_shards):
        """'lossless' mode: every decoded device leaf is BITWISE the
        raw staged path's leaf, for every layout and sharding — the
        contract that lets compressed solves promise bit-identity —
        and the wire is actually smaller on these stores."""
        _, _, stream = _stream(rng, layout, n_shards=n_shards)
        codec, pairs = _codec_roundtrip(stream, "lossless")
        assert codec.is_lossless
        assert codec.ratio > 1.0
        assert codec.wire_nbytes < codec.logical_nbytes
        for got, ref in pairs:
            for g, r in zip(got, ref):
                assert g.dtype == r.dtype and g.shape == r.shape
                assert np.asarray(g).tobytes() == np.asarray(r).tobytes()

    @pytest.mark.parametrize("layout", ["dense", "coo"])
    def test_fp16_error_bounds(self, rng, layout):
        """fp16 mode: float32 value slots round-trip within half-
        precision error; integer and {0,1} slots stay bitwise exact
        (they keep their lossless encodings)."""
        _, _, stream = _stream(rng, layout)
        codec, pairs = _codec_roundtrip(stream, "fp16")
        assert not codec.is_lossless and "fp16" in codec.kinds
        for got, ref in pairs:
            for g, r in zip(got, ref):
                r_np = np.asarray(r)
                if r_np.dtype.kind != "f" or set(
                    np.unique(r_np)
                ) <= {0.0, 1.0}:
                    assert np.asarray(g).tobytes() == r_np.tobytes()
                else:
                    np.testing.assert_allclose(
                        np.asarray(g), r_np, rtol=1e-3, atol=1e-4
                    )

    @pytest.mark.parametrize("layout", ["dense", "coo"])
    def test_int8_error_bounds(self, rng, layout):
        """int8 mode: per-(shard-row, slot) symmetric quantization —
        absolute error ≤ maxabs/127 per slot (half a quantization step
        rounds to the nearest level, so one full step is a safe
        bound)."""
        _, _, stream = _stream(rng, layout)
        codec, pairs = _codec_roundtrip(stream, "int8")
        assert "int8" in codec.kinds
        for got, ref in pairs:
            for g, r in zip(got, ref):
                r_np = np.asarray(r)
                if r_np.dtype.kind != "f" or set(
                    np.unique(r_np)
                ) <= {0.0, 1.0}:
                    assert np.asarray(g).tobytes() == r_np.tobytes()
                else:
                    bound = np.abs(r_np).max() / 127 + 1e-7
                    assert np.abs(np.asarray(g) - r_np).max() <= bound

    def test_delta_beats_downcast_on_sorted_large_values(self):
        """A sorted int64 slot whose VALUES need 32 bits but whose
        per-row deltas (and first element — it rides the delta wire
        raw) fit 8 forces the delta encoding (cumsum decode), and the
        decode is bitwise exact."""
        base = np.arange(256, dtype=np.int64) * 100  # max 25500 > int8,
        # deltas all 100 -> delta wires int8, downcast needs int16
        chunk = {"idx": base.copy(), "v": np.ones(4, np.float32)}
        staging = plan_staging(chunk, 1)
        staged = [pack_chunk(staging, chunk)]
        codec = plan_compression(staging, staged, "lossless")
        kinds = {
            s.size: e.kind
            for s, e in zip(staging.slots, codec.encodings)
        }
        assert kinds[256] == "delta"
        got = jax.tree_util.tree_leaves(
            jax.jit(codec.unpack_device)(
                jax.device_put(codec.encode(staged[0]))
            )
        )
        ref = jax.tree_util.tree_leaves(
            jax.jit(staging.unpack_device)(jax.device_put(staged[0]))
        )
        for g, r in zip(got, ref):
            assert np.asarray(g).tobytes() == np.asarray(r).tobytes()

    def test_bitmap_rejects_negative_zero(self):
        """-0.0 is NOT bitwise +0.0: a slot containing it must refuse
        the bitmap encoding (whose decode emits +0.0) to keep the
        lossless guarantee strict."""
        ok = {"b": np.array([0.0, 1.0, 1.0, 0.0], np.float32)}
        st = plan_staging(ok, 1)
        codec = plan_compression(st, [pack_chunk(st, ok)], "lossless")
        assert codec.encodings[0].kind == "bitmap"
        bad = {"b": np.array([-0.0, 1.0, 1.0, 0.0], np.float32)}
        st2 = plan_staging(bad, 1)
        codec2 = plan_compression(st2, [pack_chunk(st2, bad)], "lossless")
        assert codec2.encodings[0].kind == "raw"

    def test_fp16_overflow_falls_back_to_raw(self):
        """A float slot exceeding fp16 range must stay raw rather than
        quantize to inf."""
        chunk = {"v": np.array([1e5, -2.0, 3.0, 4.0], np.float32)}
        st = plan_staging(chunk, 1)
        codec = plan_compression(st, [pack_chunk(st, chunk)], "fp16")
        assert codec.encodings[0].kind == "raw"

    def test_mode_off_and_unknown(self, rng):
        _, _, stream = _stream(rng, "coo")
        assert plan_compression(
            stream.staging, stream.staged, "off"
        ) is None
        with pytest.raises(ValueError, match="compress must be one of"):
            plan_compression(stream.staging, stream.staged, "zstd")
        assert set(COMPRESSION_MODES) == {"off", "lossless", "fp16", "int8"}


class TestChunkCodecWideFloats:
    """f64 and bf16 lossless planning: bitmaps for bitwise-{0,1} blocks,
    an f32 wire for f64 blocks whose every value round-trips bitwise,
    raw for everything else — the lossless guarantee stays strict."""

    def _roundtrip(self, chunk, mode="lossless"):
        from photon_ml_tpu.data.staging import plan_compression

        st = plan_staging(chunk, 1)
        staged = [pack_chunk(st, chunk)]
        codec = plan_compression(st, staged, mode)
        got = jax.tree_util.tree_leaves(
            jax.jit(codec.unpack_device)(
                jax.device_put(codec.encode(staged[0]))
            )
        )
        ref = jax.tree_util.tree_leaves(
            jax.jit(st.unpack_device)(jax.device_put(staged[0]))
        )
        return codec, got, ref

    def test_f64_binary_slot_bitmaps_bitwise(self):
        chunk = {
            "mask": np.array([0.0, 1.0, 1.0, 0.0, 1.0], np.float64),
            "v": np.linspace(-1, 1, 8, dtype=np.float32),
        }
        codec, got, ref = self._roundtrip(chunk)
        kinds = {
            s.size: e.kind
            for s, e in zip(codec.staging.slots, codec.encodings)
        }
        assert kinds[5] == "bitmap"
        assert codec.is_lossless
        assert codec.wire_nbytes < codec.logical_nbytes
        for g, r in zip(got, ref):
            assert g.dtype == r.dtype and g.shape == r.shape
            assert np.asarray(g).tobytes() == np.asarray(r).tobytes()

    def test_f64_bitmap_rejects_negative_zero(self):
        from photon_ml_tpu.data.staging import plan_compression

        # -0.0 must refuse the BITMAP (its decode emits +0.0, a bit
        # flip) — but it survives an f32 wire bitwise, so the planner
        # may still take the downcast; the sign bit rides along.
        bad = {"mask": np.array([-0.0, 1.0, 0.0], np.float64)}
        st = plan_staging(bad, 1)
        codec = plan_compression(st, [pack_chunk(st, bad)], "lossless")
        assert codec.encodings[0].kind == "downcast"
        wire = codec.encode(pack_chunk(st, bad))[0]
        assert np.signbit(wire.astype(np.float64)[0, 0])

    def test_f64_downcasts_to_f32_wire_when_bitwise_exact(self):
        # Every value exactly representable in f32: the codec must take
        # the half-width wire, and the WIRE itself must reconstruct the
        # f64 bit patterns (host check — device canonicalization may
        # narrow f64 anyway when x64 is off).
        vals = np.array([1.0, -0.5, 2.75, 1024.0, -3.125], np.float64)
        chunk = {"offs": vals.copy()}
        codec, got, ref = self._roundtrip(chunk)
        assert codec.encodings[0].kind == "downcast"
        assert codec.wire_dtypes[codec.encodings[0].wire_buffer] == (
            np.dtype(np.float32)
        )
        assert codec.is_lossless
        wire = codec.encode([pack_chunk(
            codec.staging, chunk
        )[0]])[codec.encodings[0].wire_buffer]
        back = wire.astype(np.float64)
        assert back.tobytes() == np.ascontiguousarray(
            vals.reshape(1, -1)
        ).tobytes()
        for g, r in zip(got, ref):
            assert np.asarray(g).tobytes() == np.asarray(r).tobytes()

    def test_f64_needing_full_mantissa_stays_raw(self):
        from photon_ml_tpu.data.staging import plan_compression

        # 0.1 and 1 + 2**-40 do NOT survive an f32 round-trip bitwise.
        chunk = {"offs": np.array([0.1, 1.0 + 2.0 ** -40], np.float64)}
        st = plan_staging(chunk, 1)
        codec = plan_compression(st, [pack_chunk(st, chunk)], "lossless")
        assert codec.encodings[0].kind == "raw"
        assert codec.is_lossless  # raw is still bitwise

    def test_bf16_binary_slot_bitmaps_bitwise(self):
        import ml_dtypes

        bf16 = ml_dtypes.bfloat16
        chunk = {
            "mask": np.array([0.0, 1.0, 0.0, 1.0, 1.0, 0.0], bf16),
            "v": np.ones(4, np.float32),
        }
        codec, got, ref = self._roundtrip(chunk)
        kinds = {
            s.size: e.kind
            for s, e in zip(codec.staging.slots, codec.encodings)
        }
        assert kinds[6] == "bitmap"
        assert codec.is_lossless
        for g, r in zip(got, ref):
            assert g.dtype == r.dtype and g.shape == r.shape
            assert np.asarray(g).tobytes() == np.asarray(r).tobytes()

    def test_bf16_general_values_stay_raw(self):
        import ml_dtypes

        from photon_ml_tpu.data.staging import plan_compression

        bf16 = ml_dtypes.bfloat16
        chunk = {"v": np.array([0.25, 3.0, -1.5], bf16)}
        st = plan_staging(chunk, 1)
        codec = plan_compression(st, [pack_chunk(st, chunk)], "lossless")
        assert codec.encodings[0].kind == "raw"
        neg = {"v": np.array([-0.0, 1.0], bf16)}
        st2 = plan_staging(neg, 1)
        codec2 = plan_compression(st2, [pack_chunk(st2, neg)], "lossless")
        assert codec2.encodings[0].kind == "raw"
