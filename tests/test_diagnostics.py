"""Training diagnostics report (SURVEY.md §5.1's removed-upstream
diagnostics package, rebuilt): Hosmer-Lemeshow, bootstrap CIs, feature
importance, and the driver's report artifacts."""

import json
import os

import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.diagnostics import (
    TrainingReport,
    bootstrap_metric_ci,
    feature_importance,
    hosmer_lemeshow,
)


class TestHosmerLemeshow:
    def test_calibrated_model_passes(self, rng):
        n = 20000
        p = rng.uniform(0.05, 0.95, size=n)
        y = (rng.uniform(size=n) < p).astype(np.float64)
        hl = hosmer_lemeshow(p, y, scores_are_margins=False)
        assert hl["p_value"] > 0.01  # well calibrated -> not rejected
        assert len(hl["table"]) == 10

    def test_miscalibrated_model_fails(self, rng):
        n = 20000
        p = rng.uniform(0.05, 0.95, size=n)
        # True rates systematically squashed toward 0.5 vs predictions.
        true_p = 0.5 + 0.3 * (p - 0.5)
        y = (rng.uniform(size=n) < true_p).astype(np.float64)
        hl = hosmer_lemeshow(p, y, scores_are_margins=False)
        assert hl["p_value"] < 1e-4
        assert hl["statistic"] > hosmer_lemeshow(
            p, (rng.uniform(size=n) < p).astype(np.float64),
            scores_are_margins=False,
        )["statistic"]

    def test_margins_squashed_by_default(self, rng):
        n = 20000
        m = rng.normal(size=n) * 2.0  # raw margins (the driver's input)
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-m))).astype(np.float64)
        hl = hosmer_lemeshow(m, y)
        assert 0.0 <= hl["table"][0]["mean_predicted"] <= 1.0
        assert hl["p_value"] > 0.01  # calibrated by construction

    def test_margins_in_unit_interval_still_squashed(self, rng):
        """A regularized model's margins can all fall inside [0,1]; the
        explicit flag (not range detection) must still apply the link."""
        n = 20000
        m = rng.uniform(0.0, 1.0, size=n)  # margins that LOOK like probs
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-m))).astype(np.float64)
        hl = hosmer_lemeshow(m, y)  # default: margins
        assert hl["p_value"] > 0.01  # correctly squashed -> calibrated
        # treated as probabilities instead, calibration is rejected
        wrong = hosmer_lemeshow(m, y, scores_are_margins=False)
        assert wrong["p_value"] < 1e-6

    def test_probability_range_validated(self):
        with pytest.raises(ValueError, match="outside"):
            hosmer_lemeshow(
                np.array([-0.5, 0.5, 2.0]), np.array([0.0, 1.0, 1.0]),
                scores_are_margins=False,
            )


class TestBootstrapCI:
    def test_ci_covers_point_and_tightens_with_n(self, rng):
        from sklearn.metrics import roc_auc_score

        def auc(s, l):
            return roc_auc_score(l, s)

        def make(n):
            m = rng.normal(size=n) + 1.0
            y = (rng.uniform(size=n) < 1 / (1 + np.exp(-m))).astype(float)
            return m, y

        s_small, y_small = make(300)
        s_big, y_big = make(10000)
        ci_small = bootstrap_metric_ci(auc, s_small, y_small, n_boot=100)
        ci_big = bootstrap_metric_ci(auc, s_big, y_big, n_boot=100)
        for ci in (ci_small, ci_big):
            assert ci["lo"] <= ci["point"] <= ci["hi"]
            assert ci["n_boot"] > 50
        assert (ci_big["hi"] - ci_big["lo"]) < (
            ci_small["hi"] - ci_small["lo"]
        )

    def test_degenerate_resamples_skipped(self):
        # 2 rows, one per class: many resamples are single-class and the
        # metric raises; the CI must still come back.
        from sklearn.metrics import roc_auc_score

        ci = bootstrap_metric_ci(
            lambda s, l: roc_auc_score(l, s),
            np.array([0.1, 0.9]), np.array([0.0, 1.0]), n_boot=50,
        )
        assert ci["point"] == 1.0


class TestFeatureImportance:
    def test_ranking_uses_std(self):
        w = np.array([1.0, 1.0, 0.0])
        std = np.array([0.1, 10.0, 5.0])
        top = feature_importance(w, std, names=["a", "b", "c"])
        assert top[0]["feature"] == "b"
        assert [t["feature"] for t in top] == ["b", "a"]  # zero-coef dropped

    def test_top_k(self, rng):
        w = rng.normal(size=100)
        top = feature_importance(w, top_k=7)
        assert len(top) == 7
        imps = [t["importance"] for t in top]
        assert imps == sorted(imps, reverse=True)


class TestReportArtifacts:
    def test_report_roundtrip(self, tmp_path, rng):
        r = TrainingReport(task="logistic")
        r.add_convergence(1.0, [10.0, 5.0, 4.0, np.nan], [3.0, 1.0, 0.1])
        r.add_metric("AUC", 1.0, {"point": 0.8, "lo": 0.75, "hi": 0.85,
                                  "n_boot": 100})
        r.add_calibration(1.0, hosmer_lemeshow(
            rng.uniform(size=500), (rng.uniform(size=500) < 0.5).astype(float)
        ))
        r.add_importance(1.0, [{"feature": "f<0>", "coefficient": 1.0,
                                "importance": 2.0}])
        jpath, hpath = r.save(str(tmp_path))
        data = json.load(open(jpath))
        assert data["task"] == "logistic"
        assert [s["kind"] for s in data["sections"]] == [
            "convergence", "metric", "calibration", "feature_importance",
        ]
        html = open(hpath).read()
        assert "Hosmer" in html and "AUC" in html
        assert "f&lt;0&gt;" in html  # names are escaped
        assert "<svg" in html  # convergence sparkline

    def test_driver_writes_report(self, tmp_path, rng):
        from photon_ml_tpu.data import libsvm
        from photon_ml_tpu.drivers import glm_driver

        n, d = 500, 40
        X = sp.random(n, d, density=0.15, random_state=3, format="csr")
        X.data[:] = 1.0
        w_true = rng.normal(size=d) * (rng.uniform(size=d) < 0.4)
        y = np.where(
            rng.uniform(size=n) < 1 / (1 + np.exp(-(X @ w_true))), 1.0, -1.0
        )
        train = str(tmp_path / "t.libsvm")
        libsvm.write_libsvm(train, X, y)
        out = str(tmp_path / "out")
        result = glm_driver.run([
            "--train-data", train,
            "--output-dir", out,
            "--task", "logistic",
            "--reg-type", "l2",
            "--reg-weights", "0.5,5.0",
            "--n-features", str(d),
            "--training-report",
        ])
        assert os.path.exists(os.path.join(out, "report.json"))
        assert os.path.exists(os.path.join(out, "report.html"))
        rep = json.load(open(os.path.join(out, "report.json")))
        kinds = [s["kind"] for s in rep["sections"]]
        # Per lambda: convergence + metric + calibration + importance.
        assert kinds.count("convergence") == 2
        assert kinds.count("calibration") == 2
        metric = next(s for s in rep["sections"] if s["kind"] == "metric")
        assert metric["lo"] <= metric["point"] <= metric["hi"]
        assert "report" in result
