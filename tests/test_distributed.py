"""Distributed (multi-device) tests on the 8-virtual-CPU-device mesh.

The analogue of the reference's `local[*]`-Spark integration tests
(SURVEY.md §4): real psum/sharding semantics, fake devices.  The key parity
property mirrors the reference's distributed-vs-single-node objective test:
the sharded objective and solver must agree with the single-device ones.
"""

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.parallel.compat import shard_map
import pytest
import scipy.sparse as sp

from photon_ml_tpu.data.dataset import make_glm_data
from photon_ml_tpu.ops import losses
from photon_ml_tpu.optim.lbfgs import LBFGSConfig, lbfgs_solve
from photon_ml_tpu.optim.objective import GlmObjective
from photon_ml_tpu.parallel.distributed import (
    DATA_AXIS,
    data_mesh,
    distributed_solve,
    shard_glm_data,
)


def _problem(rng, n=173, d=12, sparse=False):
    X = rng.normal(size=(n, d)).astype(np.float32)
    if sparse:
        X = X * (rng.uniform(size=(n, d)) < 0.4)
    w_true = rng.normal(size=d)
    p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    weights = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    return (sp.csr_matrix(X) if sparse else X), y, weights


class TestShardedObjectiveParity:
    def test_dense_value_and_grad_matches_single_device(self, rng, eight_devices):
        X, y, w_row = _problem(rng)
        mesh = data_mesh(eight_devices)
        dist = shard_glm_data(X, y, mesh, weights=w_row)
        local_data = make_glm_data(X, y, weights=w_row)
        obj = GlmObjective(losses.logistic)
        w = jnp.asarray(rng.normal(size=X.shape[1]), jnp.float32)

        val_1, grad_1 = obj.value_and_grad(w, local_data, l2_weight=0.3)

        def spmd(dd, w):
            return obj.value_and_grad(
                w, dd.local(), l2_weight=0.3, axis_name=DATA_AXIS
            )

        val_8, grad_8 = jax.jit(
            shard_map(
                spmd,
                mesh=mesh,
                in_specs=(jax.sharding.PartitionSpec(DATA_AXIS),
                          jax.sharding.PartitionSpec()),
                out_specs=jax.sharding.PartitionSpec(),
                check_vma=False,
            )
        )(dist, w)
        np.testing.assert_allclose(float(val_8), float(val_1), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(grad_8), np.asarray(grad_1), rtol=1e-4, atol=1e-5
        )

    def test_sparse_shards_match_dense(self, rng, eight_devices):
        Xs, y, w_row = _problem(rng, n=90, d=7, sparse=True)
        mesh = data_mesh(eight_devices)
        dist_sparse = shard_glm_data(Xs, y, mesh, weights=w_row)
        dist_dense = shard_glm_data(Xs.toarray(), y, mesh, weights=w_row)
        obj = GlmObjective(losses.logistic)
        w = jnp.asarray(rng.normal(size=7), jnp.float32)

        def run(dd):
            def spmd(dd, w):
                return obj.value_and_grad(w, dd.local(), axis_name=DATA_AXIS)

            return jax.jit(
                shard_map(
                    spmd,
                    mesh=mesh,
                    in_specs=(jax.sharding.PartitionSpec(DATA_AXIS),
                              jax.sharding.PartitionSpec()),
                    out_specs=jax.sharding.PartitionSpec(),
                    check_vma=False,
                )
            )(dd, w)

        v_s, g_s = run(dist_sparse)
        v_d, g_d = run(dist_dense)
        np.testing.assert_allclose(float(v_s), float(v_d), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g_s), np.asarray(g_d), rtol=1e-4,
                                   atol=1e-5)


class TestDistributedSolve:
    def test_lbfgs_inside_shard_map_matches_single_device(self, rng, eight_devices):
        X, y, w_row = _problem(rng, n=240, d=10)
        mesh = data_mesh(eight_devices)
        dist = shard_glm_data(X, y, mesh, weights=w_row)
        obj = GlmObjective(losses.logistic)
        l2 = 0.5
        cfg = LBFGSConfig(max_iters=100, tolerance=1e-7)

        def solve_fn(local_data, w0):
            return lbfgs_solve(
                lambda w: obj.value_and_grad(
                    w, local_data, l2_weight=l2, axis_name=DATA_AXIS
                ),
                w0,
                cfg,
            )

        res = distributed_solve(solve_fn, dist, jnp.zeros(10, jnp.float32), mesh)

        local_data = make_glm_data(X, y, weights=w_row)
        res_1 = lbfgs_solve(
            lambda w: obj.value_and_grad(w, local_data, l2_weight=l2),
            jnp.zeros(10, jnp.float32),
            cfg,
        )
        assert bool(res.converged)
        np.testing.assert_allclose(float(res.value), float(res_1.value), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(res.w), np.asarray(res_1.w), rtol=1e-3, atol=1e-4
        )


class TestDistributedGrid:
    def test_run_grid_distributed_matches_single_device(self, rng):
        """The sharded λ-grid warm-start chain reproduces the single-device
        grid (same λs, same coefficients to solver tolerance)."""
        import scipy.sparse as sp

        from photon_ml_tpu.data.dataset import make_glm_data
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            GlmOptimizationProblem,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext
        from photon_ml_tpu.parallel.distributed import (
            data_mesh,
            run_grid_distributed,
            shard_glm_data,
        )

        n, d = 400, 30
        X = sp.random(n, d, density=0.3, random_state=2, format="csr")
        w_true = rng.normal(size=d)
        y = (np.asarray(X @ w_true).ravel() > 0).astype(np.float32)
        problem = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(max_iters=60),
                regularization=RegularizationContext.l2(),
            ),
        )
        lams = [5.0, 0.5]
        single = problem.run_grid(make_glm_data(X, y), lams)
        mesh = data_mesh()
        dist = shard_glm_data(X, y, mesh)
        multi = run_grid_distributed(problem, dist, mesh, lams)
        for (l1_, m1, _), (l2_, m2, _) in zip(single, multi):
            assert l1_ == l2_
            np.testing.assert_allclose(
                np.asarray(m1.coefficients.means),
                np.asarray(m2.coefficients.means),
                atol=2e-3,
            )

    def test_glm_driver_data_parallel_flag(self, rng, tmp_path):
        import scipy.sparse as sp

        from photon_ml_tpu.data import libsvm
        from photon_ml_tpu.drivers import glm_driver

        n, d = 300, 25
        X = sp.random(n, d, density=0.25, random_state=3, format="csr")
        w_true = rng.normal(size=d)
        y = np.where(np.asarray(X @ w_true).ravel() > 0, 1.0, -1.0)
        train = str(tmp_path / "t.libsvm")
        libsvm.write_libsvm(train, X, y)
        args = [
            "--train-data", train, "--task", "logistic", "--reg-type", "l2",
            "--reg-weights", "0.5,5.0", "--n-features", str(d),
            "--max-iters", "40", "--output-dir",
        ]
        r_dp = glm_driver.run(
            args + [str(tmp_path / "dp"), "--data-parallel", "auto"]
        )
        r_sd = glm_driver.run(args + [str(tmp_path / "sd")])
        assert r_dp["best_lambda"] == r_sd["best_lambda"]
        for k in r_sd["metrics"]:
            assert r_dp["metrics"][k] == pytest.approx(
                r_sd["metrics"][k], abs=1e-3
            )


class TestDriverStreamedDataParallel:
    def test_glm_driver_streaming_composes_with_data_parallel(
        self, rng, tmp_path
    ):
        """--stream-chunk-rows + --data-parallel auto: out-of-core chunks
        sharded over the 8-device mesh, same selection and metrics as the
        plain single-device run (the streamed treeAggregate shape)."""
        import scipy.sparse as sp

        from photon_ml_tpu.data import libsvm
        from photon_ml_tpu.drivers import glm_driver

        n, d = 320, 20
        X = sp.random(n, d, density=0.25, random_state=5, format="csr")
        w_true = rng.normal(size=d)
        y = np.where(np.asarray(X @ w_true).ravel() > 0, 1.0, -1.0)
        train = str(tmp_path / "t.libsvm")
        libsvm.write_libsvm(train, X, y)
        args = [
            "--train-data", train, "--task", "logistic", "--reg-type", "l2",
            "--reg-weights", "0.5,5.0", "--n-features", str(d),
            "--max-iters", "40", "--output-dir",
        ]
        r_sdp = glm_driver.run(args + [
            str(tmp_path / "sdp"),
            "--stream-chunk-rows", "80", "--data-parallel", "auto",
        ])
        r_ref = glm_driver.run(args + [str(tmp_path / "ref")])
        assert r_sdp["best_lambda"] == r_ref["best_lambda"]
        for k in r_ref["metrics"]:
            assert r_sdp["metrics"][k] == pytest.approx(
                r_ref["metrics"][k], abs=2e-3
            )

    def test_game_driver_streaming_composes_with_data_parallel(
        self, rng, tmp_path
    ):
        """GAME JSON config 'streaming_chunk_rows' + --data-parallel auto:
        mesh-sharded streamed fixed effect + entity-sharded random effect
        through the CLI, matching the plain run's validation metric."""
        import json

        from photon_ml_tpu.data.game_reader import write_game_avro
        from photon_ml_tpu.drivers import game_training_driver

        n, n_users = 400, 15
        user_eff = {f"u{u}": rng.normal() for u in range(n_users)}
        rows = []
        for i in range(n):
            u = f"u{rng.integers(n_users)}"
            xg = rng.normal(size=3)
            m = 1.2 * xg[0] - 0.9 * xg[1] + user_eff[u]
            rows.append({
                "uid": f"r{i}",
                "response": float(rng.uniform() < 1 / (1 + np.exp(-m))),
                "weight": None, "offset": None, "ids": {"userId": u},
                "features": {
                    "global": [
                        {"name": f"g{j}", "term": "", "value": float(xg[j])}
                        for j in range(3)
                    ],
                    "userFeatures": [
                        {"name": "bias", "term": "", "value": 1.0}
                    ],
                },
            })
        train = str(tmp_path / "g.avro")
        val = str(tmp_path / "v.avro")
        write_game_avro(train, rows[:320])
        write_game_avro(val, rows[320:])
        cfg = {
            "task": "logistic", "iterations": 2, "evaluator": "auc",
            "coordinates": [
                {"name": "fixed", "type": "fixed", "feature_shard": "global",
                 "reg_type": "l2", "reg_weight": 0.5, "max_iters": 40,
                 "streaming_chunk_rows": 100},
                {"name": "per_user", "type": "random",
                 "feature_shard": "userFeatures", "entity_key": "userId",
                 "reg_type": "l2", "reg_weight": 1.0, "max_iters": 30},
            ],
        }
        cfg_path = str(tmp_path / "cfg.json")
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)
        base = [
            "--train-data", train, "--validate-data", val,
            "--config", cfg_path, "--output-dir",
        ]
        r_dp = game_training_driver.run(base + [
            str(tmp_path / "dp"), "--data-parallel", "auto",
        ])
        r_sd = game_training_driver.run(base + [str(tmp_path / "sd")])
        assert r_dp["validation_metric"] == pytest.approx(
            r_sd["validation_metric"], abs=2e-3
        )
