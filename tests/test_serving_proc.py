"""Process-level serving workers (ISSUE 11).

The load-bearing contracts:

- N worker processes attach ONE shared-memory model publication and
  score bit-identically to an in-process runtime;
- a real SIGKILL of a worker mid-stream costs ZERO failed requests
  (socket EOF -> transient failure -> supervisor resubmission) and the
  worker respawns;
- the ``serving.worker`` chaos site kills the routed worker for real,
  so the scripted crash exercises the actual death path;
- a cross-process hot swap is bit-identical on both sides, and a
  rollback converges even when a worker restarted after the commit has
  no retained previous runtime (one extra restart, never a wrong
  version left serving);
- shared-memory attach is verify-or-die: a flipped segment byte or a
  torn/tampered manifest raises ``ModelMapError`` and counts
  ``model_map_unverified_total`` — never a silent partial map;
- shutdown leaks neither processes (strict ``ProcessLeakSentinel``)
  nor shared segments (``live_segments() == []``).
"""

import os
import socket
import threading
import time
import types

import numpy as np
import pytest

from photon_ml_tpu import chaos
from photon_ml_tpu import telemetry
from photon_ml_tpu.io.game_store import save_game_model
from photon_ml_tpu.serving import loadgen, shm_model
from photon_ml_tpu.serving.batcher import BatcherConfig
from photon_ml_tpu.serving.protocol import (
    FrameConn,
    MAX_FRAME_BYTES,
    ProtocolError,
)
from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
from photon_ml_tpu.serving.procpool import WorkerPool
from photon_ml_tpu.serving.service import ScoringService
from photon_ml_tpu.serving.supervisor import ReplicaSupervisor
from photon_ml_tpu.serving.synthetic import SyntheticWorkload


@pytest.fixture(scope="module")
def workload():
    return SyntheticWorkload(n_entities=32, seed=7)


@pytest.fixture(scope="module")
def workload_v2():
    # Same shard shapes, different coefficients: one request stream
    # valid on both versions, scoring differently.
    return SyntheticWorkload(n_entities=32, seed=8)


RT_CFG = dict(max_batch_size=8, hot_entities=8)


def _reference(workload, requests):
    runtime = ScoringRuntime(
        workload.model, workload.index_maps, RuntimeConfig(**RT_CFG)
    )
    return np.asarray(
        [
            runtime.score_rows([runtime.parse_request(r)])[0][0]
            for r in requests
        ],
        np.float32,
    )


def _wait_until(predicate, timeout_s=60.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


@pytest.fixture(scope="module")
def hub():
    """Metrics-only telemetry hub for the whole module (the pool folds
    worker heartbeat metrics into the CURRENT hub)."""
    prev = telemetry.current()
    tel = telemetry.Telemetry(enabled=True, sinks=[])
    telemetry.set_current(tel)
    yield tel
    telemetry.set_current(prev)


@pytest.fixture(scope="module")
def proc(hub, workload):
    """One 2-worker pool + supervisor + service shared by the spawn
    tests below; every test restores the state it perturbs (kills wait
    for respawn, the swap test rolls back), so ordering is free."""
    pool = WorkerPool(
        workload.model, workload.index_maps,
        runtime_config=RuntimeConfig(**RT_CFG), version=1,
    )
    # Generous probe budget: on a 1-CPU container a neighboring test's
    # worker spawns can stall THIS pool's probe round-trips past the
    # default timeout and restart a healthy worker mid-test.  A real
    # kill is still detected instantly (submit to a dead worker raises,
    # in-flight rows fail on the pipe EOF), so respawns stay fast.
    supervisor = ReplicaSupervisor(
        pool=pool, n_replicas=2, probe_interval_s=0.05,
        probe_timeout_s=60.0, probe_failure_threshold=5,
    )
    service = ScoringService(supervisor, BatcherConfig(
        max_batch_size=8, max_wait_us=2_000, max_queue=256,
    ))
    with service:
        yield types.SimpleNamespace(
            pool=pool, supervisor=supervisor, service=service
        )


# ---------------------------------------------------------------------------
# Worker pool: parity, SIGKILL, chaos, accounting
# ---------------------------------------------------------------------------

class TestWorkerPool:
    def test_scores_bit_identical_to_in_process(self, proc, workload):
        requests = [workload.request(i) for i in range(24)]
        expected = _reference(workload, requests)
        futures = [proc.service.submit(r) for r in requests]
        got = np.asarray(
            [np.float32(f.result(timeout=60)["score"]) for f in futures],
            np.float32,
        )
        assert got.tobytes() == expected.tobytes()

    def test_sigkill_mid_stream_zero_failed_requests(
        self, proc, workload
    ):
        sup = proc.supervisor
        assert _wait_until(lambda: sup.healthy_count == 2), sup.stats()
        restarts_before = sum(
            r["restarts"] for r in sup.stats()["replicas"]
        )
        requests = [workload.request(i) for i in range(48)]
        futures = [proc.service.submit(r) for r in requests[:24]]
        sup.kill_replica(0)  # SIGKILL: a real process dies mid-batch
        futures += [proc.service.submit(r) for r in requests[24:]]
        results = [f.result(timeout=60) for f in futures]
        assert all(np.isfinite(r["score"]) for r in results)
        assert _wait_until(lambda: sup.healthy_count == 2), sup.stats()
        assert sum(
            r["restarts"] for r in sup.stats()["replicas"]
        ) == restarts_before + 1

    def test_sigkill_under_open_loop_load_zero_errors(
        self, proc, workload
    ):
        sup = proc.supervisor
        assert _wait_until(lambda: sup.healthy_count == 2), sup.stats()
        killer = threading.Timer(0.3, lambda: sup.kill_replica(1))
        killer.start()
        report = loadgen.open_loop(
            proc.service.submit, workload.request,
            rate_rps=120.0, duration_s=1.5,
        )
        killer.join()
        assert report.errors == 0, report.snapshot()
        assert report.rejected == 0, report.snapshot()
        assert report.completed > 50
        assert _wait_until(lambda: sup.healthy_count == 2), sup.stats()

    def test_chaos_worker_site_kills_for_real_and_reroutes(
        self, proc, workload
    ):
        sup = proc.supervisor
        assert _wait_until(lambda: sup.healthy_count == 2), sup.stats()
        def pids():
            return {
                getattr(r.batcher.runtime, "pid", None)
                for r in sup.replicas
            }

        pids_before = pids()
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="serving.worker", at=0),
        ])
        with plan:
            result = proc.service.submit(
                workload.request(0)
            ).result(timeout=60)
        assert np.isfinite(result["score"])
        assert plan.fired and plan.fired[0]["site"] == "serving.worker"
        assert _wait_until(lambda: sup.healthy_count == 2), sup.stats()
        pids_after = pids()
        # The scripted fault SIGKILLed a real process: one pid changed.
        assert pids_after != pids_before

    def test_shared_segments_mapped_once_not_per_worker(
        self, proc, hub
    ):
        published = sum(
            seg["nbytes"]
            for gen in proc.pool._generations
            for seg in gen.manifest["segments"].values()
        )
        assert published > 0
        gauge = hub.snapshot()["gauges"].get(
            "serving_shared_segment_bytes"
        )
        assert gauge == published  # one publication, not x workers

    def test_worker_metrics_fold_into_parent_registry(
        self, proc, workload, hub
    ):
        before = hub.snapshot()["counters"].get(
            "serving_requests_total", 0
        )
        futures = [
            proc.service.submit(workload.request(i)) for i in range(8)
        ]
        for f in futures:
            f.result(timeout=60)
        # Heartbeats carry worker-side counter deltas at
        # heartbeat_interval_s; give two intervals.
        assert _wait_until(
            lambda: hub.snapshot()["counters"].get(
                "serving_requests_total", 0
            ) >= before + 8,
            timeout_s=10.0,
        ), hub.snapshot()["counters"]


# ---------------------------------------------------------------------------
# Cross-process hot swap + rollback
# ---------------------------------------------------------------------------

class TestProcessSwap:
    def test_swap_and_rollback_bit_identical_with_convergence(
        self, proc, workload, workload_v2, tmp_path
    ):
        v2_dir = str(tmp_path / "v2")
        save_game_model(workload_v2.model, workload_v2.index_maps, v2_dir)
        requests = [workload.request(i) for i in range(16)]
        ref_v1 = _reference(workload, requests)
        ref_v2 = _reference(workload_v2, requests)
        sup, service = proc.supervisor, proc.service
        assert _wait_until(lambda: sup.healthy_count == 2), sup.stats()
        version_before = service.swapper.version

        def scores():
            futures = [service.submit(r) for r in requests]
            return np.asarray(
                [
                    np.float32(f.result(timeout=60)["score"])
                    for f in futures
                ],
                np.float32,
            )

        result = service.reload(v2_dir)
        assert result.status == "swapped", result
        assert service.swapper.version == version_before + 1
        assert scores().tobytes() == ref_v2.tobytes()

        # A worker killed AFTER the commit respawns attached to v2 and
        # retains no previous runtime; the rollback below must still
        # converge (that worker is respawned on the restored
        # generation — one extra restart, never a wrong version).
        sup.kill_replica(1, "post-swap kill")
        assert _wait_until(lambda: sup.healthy_count == 2), sup.stats()

        rolled = service.reload(rollback=True)
        assert rolled.status == "rolled_back", rolled
        assert _wait_until(lambda: sup.healthy_count == 2), sup.stats()
        assert scores().tobytes() == ref_v1.tobytes()


class TestProcessDelta:
    def test_delta_apply_and_rollback_bit_identical(
        self, proc, workload, tmp_path
    ):
        """ISSUE 12: a delta publication hot-applies across process
        workers — parent patches its host copy, publishes ONE new shm
        generation, workers clone with carried hot sets — bit-identical
        to in-process scoring of the patched model, and the rollback
        restores v1 bitwise."""
        from photon_ml_tpu.freshness.delta import diff_game_models
        from photon_ml_tpu.freshness.publisher import DeltaPublisher

        target_w = SyntheticWorkload(n_entities=32, seed=7)
        re = target_w.model.models["per_entity"]
        for k in [f"u{i}" for i in range(5)]:
            cols, vals = re.coefficients[k]
            re.coefficients[k] = (
                cols, (vals + np.float32(0.25)).astype(np.float32)
            )
        requests = [workload.request(i) for i in range(16)]
        ref_v1 = _reference(workload, requests)
        ref_target = _reference(target_w, requests)
        assert ref_v1.tobytes() != ref_target.tobytes()
        sup, service = proc.supervisor, proc.service
        assert _wait_until(lambda: sup.healthy_count == 2), sup.stats()
        version_before = service.swapper.version

        with DeltaPublisher(str(tmp_path / "pubs")) as pub:
            p = pub.publish(diff_game_models(
                workload.model, target_w.model, event_wall_epoch=1.0
            ))

        def scores():
            futures = [service.submit(r) for r in requests]
            return np.asarray(
                [
                    np.float32(f.result(timeout=60)["score"])
                    for f in futures
                ],
                np.float32,
            )

        result = service.reload(p.path, mode="delta")
        assert result.status == "swapped", result
        # The registry is MONOTONE: after an earlier swap+rollback the
        # next version skips past every version ever committed.
        assert service.swapper.version > version_before
        assert service.swapper.version == result.version_after
        assert scores().tobytes() == ref_target.tobytes()

        rolled = service.reload(rollback=True)
        assert rolled.status == "rolled_back", rolled
        assert _wait_until(lambda: sup.healthy_count == 2), sup.stats()
        assert scores().tobytes() == ref_v1.tobytes()


# ---------------------------------------------------------------------------
# Clean shutdown: no leaked processes, no leaked segments
# ---------------------------------------------------------------------------

class TestCleanShutdown:
    def test_stop_leaks_nothing(self, hub, workload):
        from photon_ml_tpu.analysis.sanitizers import ProcessLeakSentinel

        # The module-scoped pool keeps ITS segments live; this pool's
        # must all be gone after stop.
        before = set(shm_model.live_segments())
        with ProcessLeakSentinel(grace_s=15.0, strict=True):
            pool = WorkerPool(
                workload.model, workload.index_maps,
                runtime_config=RuntimeConfig(**RT_CFG), version=1,
            )
            # Generous probe budget for the same reason as the module
            # fixture: this test's own 2-worker spawn stalls the box,
            # and a probe timeout here would down/restart a healthy
            # worker racing the stop() below.
            supervisor = ReplicaSupervisor(
                pool=pool, n_replicas=2, probe_interval_s=0.05,
                probe_timeout_s=60.0, probe_failure_threshold=5,
            )
            with supervisor:
                result = supervisor.submit(
                    supervisor.parse_request(workload.request(0))
                ).result(timeout=60)
                assert np.isfinite(result["score"])
            assert set(shm_model.live_segments()) == before
        # Sentinel exit (strict): any surviving worker process raises.


# ---------------------------------------------------------------------------
# Shared-memory publication: verify-or-die attach (no processes)
# ---------------------------------------------------------------------------

class TestShmModel:
    def _published(self, workload, **kwargs):
        manifest = shm_model.publish_model(workload.model, **kwargs)
        return manifest

    def test_attach_reconstructs_bit_identical_scores(self, workload):
        manifest = self._published(workload, version=1)
        try:
            model, attachment = shm_model.attach_model(manifest)
            with attachment:
                runtime = ScoringRuntime(
                    model, workload.index_maps, RuntimeConfig(**RT_CFG)
                )
                requests = [workload.request(i) for i in range(8)]
                expected = _reference(workload, requests)
                got = np.asarray(
                    [
                        runtime.score_rows(
                            [runtime.parse_request(r)]
                        )[0][0]
                        for r in requests
                    ],
                    np.float32,
                )
                assert got.tobytes() == expected.tobytes()
        finally:
            shm_model.unpublish_model(manifest)

    def test_flipped_segment_byte_fails_checksum(self, workload, hub):
        manifest = self._published(workload, version=1)
        try:
            before = hub.snapshot()["counters"].get(
                "model_map_unverified_total", 0
            )
            name = next(iter(manifest["segments"]))
            from multiprocessing import shared_memory

            shm = shared_memory.SharedMemory(name=name)
            try:
                shm.buf[0] = shm.buf[0] ^ 0xFF
                with pytest.raises(
                    shm_model.ModelMapError, match="checksum"
                ):
                    shm_model.attach_model(manifest)
            finally:
                shm.buf[0] = shm.buf[0] ^ 0xFF  # restore for unlink
                shm.close()
            after = hub.snapshot()["counters"].get(
                "model_map_unverified_total", 0
            )
            assert after == before + 1
        finally:
            shm_model.unpublish_model(manifest)

    def test_torn_manifest_fails_self_digest(self, workload, hub):
        manifest = self._published(workload, version=1)
        try:
            torn = dict(manifest, version=manifest["version"] + 1)
            with pytest.raises(shm_model.ModelMapError):
                shm_model.attach_model(torn)
            # Tampering a recorded segment digest is also torn — the
            # self-digest covers it, so the lie is caught before any
            # byte comparison could be fooled.
            name = next(iter(manifest["segments"]))
            lied = {
                **manifest,
                "segments": {
                    **manifest["segments"],
                    name: {
                        **manifest["segments"][name],
                        "sha256": "0" * 64,
                    },
                },
            }
            with pytest.raises(shm_model.ModelMapError):
                shm_model.attach_model(lied)
        finally:
            shm_model.unpublish_model(manifest)

    def test_stale_manifest_after_unpublish_raises(self, workload):
        manifest = self._published(workload, version=1)
        shm_model.unpublish_model(manifest)
        with pytest.raises(shm_model.ModelMapError):
            shm_model.attach_model(manifest)

    def test_gauge_tracks_publish_and_unpublish(self, workload, hub):
        base = hub.snapshot()["gauges"].get(
            "serving_shared_segment_bytes", 0
        )
        manifest = self._published(workload, version=1)
        published = sum(
            seg["nbytes"] for seg in manifest["segments"].values()
        )
        assert hub.snapshot()["gauges"][
            "serving_shared_segment_bytes"
        ] == base + published
        shm_model.unpublish_model(manifest)
        assert hub.snapshot()["gauges"][
            "serving_shared_segment_bytes"
        ] == base


# ---------------------------------------------------------------------------
# Wire protocol
# ---------------------------------------------------------------------------

class TestProtocol:
    def _pair(self):
        a, b = socket.socketpair()
        return FrameConn(a), FrameConn(b)

    def test_roundtrip(self):
        left, right = self._pair()
        try:
            payload = {"kind": "score", "id": 7, "row": [1.0, 2.0]}
            left.send(payload)
            assert right.recv() == payload
        finally:
            left.close()
            right.close()

    def test_clean_eof_returns_none(self):
        left, right = self._pair()
        left.close()
        try:
            assert right.recv() is None
        finally:
            right.close()

    def test_truncated_frame_raises(self):
        a, b = socket.socketpair()
        right = FrameConn(b)
        try:
            # A length prefix promising more bytes than ever arrive.
            a.sendall((1024).to_bytes(4, "big") + b"\x00\x01")
            a.close()
            with pytest.raises(ProtocolError):
                right.recv()
        finally:
            right.close()

    def test_oversized_length_refused_at_recv(self):
        a, b = socket.socketpair()
        right = FrameConn(b)
        try:
            # A forged header promising a frame beyond the cap: refuse
            # before allocating, the stream is desynced.
            a.sendall((MAX_FRAME_BYTES + 1).to_bytes(4, "big"))
            with pytest.raises(ProtocolError, match="cap"):
                right.recv()
        finally:
            a.close()
            right.close()


# ---------------------------------------------------------------------------
# Metrics transport (heartbeat payloads)
# ---------------------------------------------------------------------------

class TestMetricsTransport:
    def test_absorb_delta_folds_counters_gauges_histograms(self):
        src = telemetry.Telemetry(enabled=True, sinks=[]).metrics
        dst = telemetry.Telemetry(enabled=True, sinks=[]).metrics
        src.counter("serving_requests_total").inc(3)
        src.gauge("serving_queue_depth").set(5)
        src.histogram("serving_request_latency_seconds").observe(0.01)
        first = src.transport_snapshot()
        dst.absorb_delta(first)
        src.counter("serving_requests_total").inc(2)
        src.histogram("serving_request_latency_seconds").observe(0.02)
        second = src.transport_snapshot()
        dst.absorb_delta(second, first)
        snap = dst.snapshot()
        assert snap["counters"]["serving_requests_total"] == 5
        assert snap["gauges"]["serving_queue_depth"] == 5
        assert snap["histograms"][
            "serving_request_latency_seconds"
        ]["count"] == 2

    def test_absorb_is_delta_not_double_count(self):
        src = telemetry.Telemetry(enabled=True, sinks=[]).metrics
        dst = telemetry.Telemetry(enabled=True, sinks=[]).metrics
        src.counter("serving_rows_scored_total").inc(10)
        snap1 = src.transport_snapshot()
        dst.absorb_delta(snap1)
        # The same cumulative snapshot absorbed again WITH prev is a
        # no-op — senders keep cumulative state, receivers fold deltas.
        dst.absorb_delta(snap1, snap1)
        assert dst.snapshot()["counters"][
            "serving_rows_scored_total"
        ] == 10


# ---------------------------------------------------------------------------
# Loadgen catalog + p999
# ---------------------------------------------------------------------------

class TestLoadgenAdditions:
    def test_worker_kill_scenario_registered(self):
        assert "worker_kill" in loadgen.SCENARIOS
        scenario = loadgen.SCENARIOS["worker_kill"]
        assert any(
            phase.action == "kill_worker" for phase in scenario.phases
        )

    def test_report_snapshot_carries_p999(self):
        report = loadgen.LoadReport(
            mode="test", wall_seconds=1.0, completed=3, rejected=0,
            errors=0, latencies_ms=np.asarray([1.0, 2.0, 100.0]),
        )
        snap = report.snapshot()
        assert "latency_p999_ms" in snap
        assert snap["latency_p999_ms"] >= snap["latency_p99_ms"] >= \
            snap["latency_p50_ms"]


# ---------------------------------------------------------------------------
# process-lifecycle static rule
# ---------------------------------------------------------------------------

GOOD_LIFECYCLE = """
import multiprocessing
class Owner:
    def start(self):
        self._proc = multiprocessing.get_context("spawn").Process(
            target=print)
        self._proc.start()
    def stop(self):
        try:
            self._proc.join(timeout=5)
        finally:
            self._proc.terminate()
            self._proc.join(timeout=2)
"""

NEVER_REAPED = """
import multiprocessing
def go():
    p = multiprocessing.Process(target=print)
    p.start()
"""

HAPPY_PATH_ONLY = """
import multiprocessing
def go():
    p = multiprocessing.Process(target=print)
    p.start()
    work()
    p.join()
    p.terminate()
"""

NO_ESCALATION = """
import subprocess
def go():
    p = subprocess.Popen(["true"])
    p.wait()
"""

EXEMPT_RUN = """
import subprocess
def go():
    subprocess.run(["true"], check=True)
"""


class TestProcessLifecycleRule:
    def _findings(self, tmp_path, source):
        from photon_ml_tpu.analysis import RULES_BY_ID
        from photon_ml_tpu.analysis.engine import SourceTree, run_rules

        (tmp_path / "case.py").write_text(source)
        tree = SourceTree(roots=[str(tmp_path)], repo_root=str(tmp_path))
        return run_rules(tree, [RULES_BY_ID["process-lifecycle"]])

    def test_good_lifecycle_split_is_clean(self, tmp_path):
        assert self._findings(tmp_path, GOOD_LIFECYCLE) == []

    def test_never_reaped_flagged(self, tmp_path):
        findings = self._findings(tmp_path, NEVER_REAPED)
        assert findings and "never joined" in findings[0].message

    def test_happy_path_only_reap_flagged(self, tmp_path):
        findings = self._findings(tmp_path, HAPPY_PATH_ONLY)
        assert findings and "happy path" in findings[0].message

    def test_popen_without_escalation_flagged(self, tmp_path):
        findings = self._findings(tmp_path, NO_ESCALATION)
        assert findings and "terminate" in findings[0].message

    def test_subprocess_run_exempt(self, tmp_path):
        assert self._findings(tmp_path, EXEMPT_RUN) == []

    def test_procpool_itself_is_clean(self):
        from photon_ml_tpu.analysis import RULES_BY_ID
        from photon_ml_tpu.analysis.engine import SourceTree, run_rules

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        tree = SourceTree(
            roots=[os.path.join(repo, "photon_ml_tpu", "serving")],
            repo_root=repo,
        )
        assert run_rules(tree, [RULES_BY_ID["process-lifecycle"]]) == []


# ---------------------------------------------------------------------------
# Chaos-site registration
# ---------------------------------------------------------------------------

def test_serving_worker_site_registered():
    assert "serving.worker" in chaos.KNOWN_SITES
    # Construction-time validation still refuses typos.
    with pytest.raises(ValueError):
        chaos.FaultSpec(site="serving.wroker")
