"""End-to-end GAME driver tests: train → save → load → score round trip."""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.data.game_reader import (
    read_game_avro,
    write_game_avro,
)
from photon_ml_tpu.drivers import (
    feature_indexing_driver,
    game_scoring_driver,
    game_training_driver,
)
from photon_ml_tpu.io.game_store import load_game_model, save_game_model


def _make_game_rows(rng, user_effect, n_rows, uid_start=0):
    """Synthetic MovieLens-shaped data: global features + per-user effects."""
    rows = []
    n_users = len(user_effect)
    for i in range(uid_start, uid_start + n_rows):
        u = f"u{rng.integers(n_users)}"
        xg = rng.normal(size=3)
        margin = 1.5 * xg[0] - 1.0 * xg[1] + user_effect[u]
        y = float(rng.uniform() < 1 / (1 + np.exp(-margin)))
        rows.append({
            "uid": f"row{i}",
            "response": y,
            "weight": None,
            "offset": None,
            "ids": {"userId": u},
            "features": {
                "global": [
                    {"name": f"g{j}", "term": "", "value": float(xg[j])}
                    for j in range(3)
                ],
                "userFeatures": [
                    {"name": "bias", "term": "", "value": 1.0}
                ],
            },
        })
    return rows


@pytest.fixture(scope="module")
def game_files(tmp_path_factory):
    root = tmp_path_factory.mktemp("game")
    rng = np.random.default_rng(11)
    train = str(root / "train.avro")
    val = str(root / "val.avro")
    # Train and validation share ONE population of per-user effects, so the
    # learned random effects transfer (users recur across both files).
    user_effect = {f"u{u}": rng.normal(scale=2.0) for u in range(20)}
    write_game_avro(train, _make_game_rows(rng, user_effect, 600))
    write_game_avro(val, _make_game_rows(rng, user_effect, 200, uid_start=600))
    config = {
        "task": "logistic",
        "iterations": 2,
        "evaluator": "auc",
        "coordinates": [
            {"name": "fixed", "type": "fixed", "feature_shard": "global",
             "optimizer": "lbfgs", "max_iters": 50, "reg_type": "l2",
             "reg_weight": 0.5},
            {"name": "per_user", "type": "random",
             "feature_shard": "userFeatures", "entity_key": "userId",
             "optimizer": "lbfgs", "max_iters": 30, "reg_type": "l2",
             "reg_weight": 0.5},
        ],
    }
    config_path = str(root / "config.json")
    with open(config_path, "w") as f:
        json.dump(config, f)
    return train, val, config_path


class TestGameReader:
    def test_roundtrip_shapes(self, game_files):
        train, _, _ = game_files
        shards, ids, response, weight, offset, uids, imaps = read_game_avro(train)
        assert shards["global"].shape == (600, 3)
        assert shards["userFeatures"].shape == (600, 1)
        assert set(ids) == {"userId"}
        assert len(imaps["global"]) == 3
        assert uids[0] == "row0"

    def test_scoring_path_drops_unseen_features(self, game_files, tmp_path):
        train, _, _ = game_files
        _, _, _, _, _, _, imaps = read_game_avro(train)
        extra = str(tmp_path / "extra.avro")
        rows = [{
            "uid": None, "response": 1.0, "weight": None, "offset": None,
            "ids": {"userId": "u0"},
            "features": {"global": [
                {"name": "g0", "term": "", "value": 2.0},
                {"name": "BRAND_NEW", "term": "", "value": 9.9},
            ]},
        }]
        write_game_avro(extra, rows)
        shards, _, _, _, _, _, _ = read_game_avro(extra, index_maps=imaps)
        assert shards["global"].shape == (1, 3)
        assert shards["global"].nnz == 1  # the unseen feature was dropped


class TestGameDrivers:
    def test_train_then_score_roundtrip(self, game_files, tmp_path):
        train, val, config = game_files
        out = str(tmp_path / "train_out")
        result = game_training_driver.run([
            "--train-data", train,
            "--validate-data", val,
            "--config", config,
            "--output-dir", out,
        ])
        assert result["train_metric"] > 0.70
        assert result["validation_metric"] > 0.65
        # Random effect must help: metric after final update beats the first.
        assert os.path.isdir(os.path.join(out, "models", "random-effect"))

        # Score the validation file with the saved model.
        score_out = str(tmp_path / "score_out")
        sresult = game_scoring_driver.run([
            "--data", val,
            "--model-dir", out,
            "--output-dir", score_out,
            "--evaluator", "auc",
        ])
        assert sresult["n_rows"] == 200
        # Scoring-path AUC equals the training driver's validation AUC.
        assert sresult["metric"] == pytest.approx(
            result["validation_metric"], abs=1e-6
        )
        from photon_ml_tpu.io import avro
        _, scores = avro.read_container(
            os.path.join(score_out, "scores.avro")
        )
        assert len(scores) == 200
        assert scores[0]["ids"]["userId"].startswith("u")

    def test_streamed_scoring_matches_resident(self, game_files, tmp_path):
        """Out-of-core scoring (VERDICT r3 #5): block-bounded read → score
        → write matches the materialized path bit-for-bit, including the
        AUC computed from streamed scores."""
        from photon_ml_tpu.data.game_reader import GAME_EXAMPLE_SCHEMA
        from photon_ml_tpu.io import avro as avro_io

        train, val, config = game_files
        out = str(tmp_path / "train_out")
        game_training_driver.run([
            "--train-data", train, "--config", config, "--output-dir", out,
        ])
        # Re-cut the validation file into small container blocks so the
        # streamed read actually yields several blocks (the iterator
        # flushes at container-block boundaries).
        _, recs = avro_io.read_container(val)
        val_mb = str(tmp_path / "val_mb.avro")
        avro_io.write_container(
            val_mb, GAME_EXAMPLE_SCHEMA, recs, records_per_block=32
        )

        r_out = str(tmp_path / "score_resident")
        s_out = str(tmp_path / "score_streamed")
        resident = game_scoring_driver.run([
            "--data", val_mb, "--model-dir", out, "--output-dir", r_out,
            "--evaluator", "auc",
        ])
        streamed = game_scoring_driver.run([
            "--data", val_mb, "--model-dir", out, "--output-dir", s_out,
            "--evaluator", "auc", "--stream-block-rows", "64",
        ])
        assert streamed["n_rows"] == resident["n_rows"] == 200
        assert streamed["metric"] == resident["metric"]  # same scores → same AUC
        _, r_recs = avro_io.read_container(os.path.join(r_out, "scores.avro"))
        _, s_recs = avro_io.read_container(os.path.join(s_out, "scores.avro"))
        assert len(s_recs) == len(r_recs) == 200
        for rr, sr in zip(r_recs, s_recs):
            assert sr["uid"] == rr["uid"]
            assert sr["ids"] == rr["ids"]
            assert sr["predictionScore"] == rr["predictionScore"]  # bit-for-bit

    def test_device_metrics_scoring_and_training(self, game_files, tmp_path):
        """--device-metrics end to end: the streamed pointwise metric
        accumulates as two scalars per block (NO column retention) and
        matches the host evaluator; resident device AUC matches the host
        AUC; the training driver's per-iteration validation metrics match
        the host path."""
        from photon_ml_tpu.data.game_reader import GAME_EXAMPLE_SCHEMA
        from photon_ml_tpu.io import avro as avro_io

        train, val, config = game_files
        out = str(tmp_path / "train_out")
        host_run = game_training_driver.run([
            "--train-data", train, "--validate-data", val,
            "--config", config, "--output-dir", out,
        ])
        dev_out = str(tmp_path / "train_out_dev")
        dev_run = game_training_driver.run([
            "--train-data", train, "--validate-data", val,
            "--config", config, "--output-dir", dev_out,
            "--device-metrics",
        ])
        assert dev_run["validation_metric"] == pytest.approx(
            host_run["validation_metric"], abs=1e-5
        )

        _, recs = avro_io.read_container(val)
        val_mb = str(tmp_path / "val_mb.avro")
        avro_io.write_container(
            val_mb, GAME_EXAMPLE_SCHEMA, recs, records_per_block=32
        )
        host = game_scoring_driver.run([
            "--data", val_mb, "--model-dir", out, "--output-dir",
            str(tmp_path / "s_host"), "--evaluator", "logistic_loss",
            "--stream-block-rows", "64",
        ])
        dev = game_scoring_driver.run([
            "--data", val_mb, "--model-dir", out, "--output-dir",
            str(tmp_path / "s_dev"), "--evaluator", "logistic_loss",
            "--stream-block-rows", "64", "--device-metrics",
        ])
        assert dev["metric"] == pytest.approx(host["metric"], abs=1e-5)
        dev_auc = game_scoring_driver.run([
            "--data", val_mb, "--model-dir", out, "--output-dir",
            str(tmp_path / "s_dev_auc"), "--evaluator", "auc",
            "--device-metrics",
        ])
        host_auc = game_scoring_driver.run([
            "--data", val_mb, "--model-dir", out, "--output-dir",
            str(tmp_path / "s_host_auc"), "--evaluator", "auc",
        ])
        assert dev_auc["metric"] == pytest.approx(
            host_auc["metric"], abs=1e-6
        )

    def test_iter_game_avro_blocks_concatenate_to_full_read(self, game_files):
        from photon_ml_tpu.data.game_reader import iter_game_avro

        train, _, _ = game_files
        full = read_game_avro(train)
        shards_f, ids_f, resp_f, w_f, off_f, uids_f, imaps = full
        blocks = list(iter_game_avro(train, imaps, block_rows=100))
        # 600 rows in one 4096-record container block: game-schema flushes
        # at container boundaries, so everything lands in one yield here —
        # the multi-block case is covered by the driver test's re-cut file.
        assert sum(len(b[2]) for b in blocks) == 600
        resp_cat = np.concatenate([b[2] for b in blocks])
        np.testing.assert_array_equal(resp_cat, resp_f)
        g_cat = np.concatenate(
            [b[0]["global"].toarray() for b in blocks], axis=0
        )
        np.testing.assert_array_equal(g_cat, shards_f["global"].toarray())
        uid_cat = [u for b in blocks for u in b[5]]
        assert uid_cat == uids_f

    def test_streamed_scoring_survives_idless_blocks(
        self, game_files, tmp_path
    ):
        """A block consisting entirely of rows WITHOUT the entity id must
        still score (the model's id columns materialize None-padded per
        block) and match the whole-file path."""
        from photon_ml_tpu.data.game_reader import GAME_EXAMPLE_SCHEMA
        from photon_ml_tpu.io import avro as avro_io

        train, val, config = game_files
        out = str(tmp_path / "train_out")
        game_training_driver.run([
            "--train-data", train, "--config", config, "--output-dir", out,
        ])
        # 64 id-less rows FIRST (one full streamed block with no userId),
        # then the real validation rows, in 32-record container blocks.
        _, recs = avro_io.read_container(val)
        idless = [
            {
                "uid": f"noid{i}", "response": float(i % 2),
                "weight": None, "offset": None, "ids": {},
                "features": {"global": [
                    {"name": "g0", "term": "", "value": 1.0}
                ]},
            }
            for i in range(64)
        ]
        mixed = str(tmp_path / "mixed.avro")
        avro_io.write_container(
            mixed, GAME_EXAMPLE_SCHEMA, idless + recs, records_per_block=32
        )
        r_out = str(tmp_path / "sc_res")
        s_out = str(tmp_path / "sc_str")
        resident = game_scoring_driver.run([
            "--data", mixed, "--model-dir", out, "--output-dir", r_out,
        ])
        streamed = game_scoring_driver.run([
            "--data", mixed, "--model-dir", out, "--output-dir", s_out,
            "--stream-block-rows", "64",
        ])
        assert streamed["n_rows"] == resident["n_rows"] == 264
        _, r_recs = avro_io.read_container(os.path.join(r_out, "scores.avro"))
        _, s_recs = avro_io.read_container(os.path.join(s_out, "scores.avro"))
        for rr, sr in zip(r_recs, s_recs):
            assert sr["predictionScore"] == rr["predictionScore"]
            assert sr["ids"] == rr["ids"]

    def test_iter_game_avro_python_fallback_matches_native(
        self, game_files, monkeypatch
    ):
        """PHOTON_NO_NATIVE=1 routes the block iterator through the pure-
        Python payload decoder; blocks must be identical to the native
        C++ session path."""
        from photon_ml_tpu.data.game_reader import iter_game_avro

        train, _, _ = game_files
        *_, imaps = read_game_avro(train)
        native = list(iter_game_avro(train, imaps, block_rows=100))
        monkeypatch.setenv("PHOTON_NO_NATIVE", "1")
        pyth = list(iter_game_avro(train, imaps, block_rows=100))
        assert len(pyth) == len(native)
        for (bn, bp) in zip(native, pyth):
            np.testing.assert_array_equal(bp[2], bn[2])  # response
            np.testing.assert_array_equal(bp[3], bn[3])  # weight
            np.testing.assert_array_equal(bp[4], bn[4])  # offset
            assert bp[5] == bn[5]                        # uids
            for shard in bn[0]:
                np.testing.assert_array_equal(
                    bp[0][shard].toarray(), bn[0][shard].toarray()
                )
            for k in bn[1]:
                np.testing.assert_array_equal(bp[1][k], bn[1][k])

    def test_iter_game_avro_requires_index_maps(self, game_files):
        from photon_ml_tpu.data.game_reader import iter_game_avro

        train, _, _ = game_files
        with pytest.raises(ValueError, match="index maps"):
            list(iter_game_avro(train, None))

    def test_streamed_tron_fixed_effect_via_config(
        self, game_files, tmp_path
    ):
        """JSON config composition: 'optimizer': 'tron' +
        'streaming_chunk_rows' on the fixed effect trains out-of-core
        through the streamed trust-region solver and matches the
        resident-TRON run's metric."""
        train, val, config = game_files
        with open(config) as f:
            cfg = json.load(f)
        cfg["coordinates"][0].update(optimizer="tron")
        resident_cfg = str(tmp_path / "tron.json")
        with open(resident_cfg, "w") as f:
            json.dump(cfg, f)
        cfg["coordinates"][0]["streaming_chunk_rows"] = 200
        streamed_cfg = str(tmp_path / "tron_streamed.json")
        with open(streamed_cfg, "w") as f:
            json.dump(cfg, f)

        r = game_training_driver.run([
            "--train-data", train, "--validate-data", val,
            "--config", resident_cfg,
            "--output-dir", str(tmp_path / "out_r"),
        ])
        s = game_training_driver.run([
            "--train-data", train, "--validate-data", val,
            "--config", streamed_cfg,
            "--output-dir", str(tmp_path / "out_s"),
        ])
        assert s["validation_metric"] == pytest.approx(
            r["validation_metric"], abs=1e-3
        )
        assert s["validation_metric"] > 0.65

    def test_model_store_roundtrip_preserves_scores(self, game_files, tmp_path):
        train, val, config = game_files
        out = str(tmp_path / "rt_out")
        game_training_driver.run([
            "--train-data", train, "--config", config, "--output-dir", out,
        ])
        model, imaps = load_game_model(os.path.join(out, "models"))
        resaved = str(tmp_path / "resaved")
        save_game_model(model, imaps, resaved)
        model2, _ = load_game_model(resaved)
        from photon_ml_tpu.game.estimator import GameTransformer
        shards, ids, *_ = read_game_avro(val, index_maps=imaps)
        s1 = GameTransformer(model).transform(shards, ids)
        s2 = GameTransformer(model2).transform(shards, ids)
        np.testing.assert_allclose(s1, s2, rtol=1e-6)

    def test_bayesian_tuning_mode(self, game_files, tmp_path):
        train, val, config = game_files
        with open(config) as f:
            cfg = json.load(f)
        cfg["tuning"] = {"mode": "bayesian", "iterations": 5,
                         "range": [1e-2, 1e2]}
        cfg["iterations"] = 1
        tuned_config = str(tmp_path / "tuned_config.json")
        with open(tuned_config, "w") as f:
            json.dump(cfg, f)
        out = str(tmp_path / "tuned_out")
        result = game_training_driver.run([
            "--train-data", train, "--validate-data", val,
            "--config", tuned_config, "--output-dir", out,
        ])
        assert result["tuning"]["n_evaluations"] == 5
        assert set(result["tuning"]["best_reg_weights"]) == {"fixed", "per_user"}
        # Final fit used the tuned weights and achieved the tuned metric.
        assert result["validation_metric"] == pytest.approx(
            result["tuning"]["best_metric"], abs=1e-6
        )

    def test_tuning_without_validation_fails_cleanly(self, game_files, tmp_path):
        train, _, config = game_files
        with open(config) as f:
            cfg = json.load(f)
        cfg["tuning"] = {"iterations": 2}
        bad_config = str(tmp_path / "bad.json")
        with open(bad_config, "w") as f:
            json.dump(cfg, f)
        with pytest.raises(ValueError, match="requires --validate-data"):
            game_training_driver.run([
                "--train-data", train, "--config", bad_config,
                "--output-dir", str(tmp_path / "x"),
            ])

    def test_feature_indexing_driver(self, game_files, tmp_path):
        train, _, _ = game_files
        out = str(tmp_path / "maps")
        result = feature_indexing_driver.run([
            "--data", train, "--output-dir", out, "--binary",
        ])
        assert result["shards"]["global"] == 3
        from photon_ml_tpu.data.index_map import BinaryIndexMap, IndexMap
        imap = IndexMap.load(os.path.join(out, "global"))
        bmap = BinaryIndexMap(os.path.join(out, "global"))
        assert bmap.get_index("g1") == imap["g1"]


class TestFactoredDriver:
    def test_factored_coordinate_end_to_end(self, game_files, tmp_path):
        """'factored_random' JSON spec → trained + saved as a standard
        random-effect model → scoring driver round trip."""
        train, val, config = game_files
        with open(config) as f:
            cfg = json.load(f)
        cfg["coordinates"][1] = {
            "name": "per_user", "type": "factored_random",
            "feature_shard": "userFeatures", "entity_key": "userId",
            "rank": 1, "alternations": 2,
            "optimizer": "lbfgs", "max_iters": 30, "reg_type": "l2",
            "reg_weight": 0.5,
        }
        fcfg = str(tmp_path / "factored.json")
        with open(fcfg, "w") as f:
            json.dump(cfg, f)
        out = str(tmp_path / "train_out")
        result = game_training_driver.run([
            "--train-data", train,
            "--validate-data", val,
            "--config", fcfg,
            "--output-dir", out,
        ])
        # userFeatures is a single bias column, so rank 1 is full rank:
        # quality must match the plain random effect (metric floor as the
        # plain-coordinate test uses).
        assert result["validation_metric"] > 0.65
        assert os.path.isdir(os.path.join(out, "models", "random-effect"))

        score_out = str(tmp_path / "score_out")
        sresult = game_scoring_driver.run([
            "--data", val,
            "--model-dir", out,
            "--output-dir", score_out,
            "--evaluator", "auc",
        ])
        assert sresult["metric"] == pytest.approx(
            result["validation_metric"], abs=1e-6
        )

    def test_factored_resume_reproduces_run(self, game_files, tmp_path):
        """Nested (u_list, V) state survives the checkpoint round trip:
        a resumed run reproduces the uninterrupted result bit-for-bit."""
        train, val, config = game_files
        with open(config) as f:
            cfg = json.load(f)
        cfg["iterations"] = 2
        cfg["coordinates"][1] = {
            "name": "per_user", "type": "factored_random",
            "feature_shard": "userFeatures", "entity_key": "userId",
            "rank": 1, "alternations": 1,
            "optimizer": "lbfgs", "max_iters": 20, "reg_type": "l2",
            "reg_weight": 0.5,
        }
        fcfg = str(tmp_path / "factored.json")
        with open(fcfg, "w") as f:
            json.dump(cfg, f)

        out_full = str(tmp_path / "full")
        r_full = game_training_driver.run([
            "--train-data", train, "--validate-data", val,
            "--config", fcfg, "--output-dir", out_full,
        ])

        # Interrupted: run 1 iteration, then resume to 2.
        cfg1 = dict(cfg, iterations=1)
        fcfg1 = str(tmp_path / "factored1.json")
        with open(fcfg1, "w") as f:
            json.dump(cfg1, f)
        out_resume = str(tmp_path / "resume")
        game_training_driver.run([
            "--train-data", train, "--validate-data", val,
            "--config", fcfg1, "--output-dir", out_resume,
        ])
        r_resumed = game_training_driver.run([
            "--train-data", train, "--validate-data", val,
            "--config", fcfg, "--output-dir", out_resume, "--resume",
        ])
        assert r_resumed["validation_metric"] == pytest.approx(
            r_full["validation_metric"], abs=1e-7
        )

    def test_factored_initial_model_starts_cold_not_crash(
        self, game_files, tmp_path
    ):
        """--initial-model with a factored coordinate: the saved model holds
        only materialized w_e = V u_e (not the factorization), so the
        coordinate starts cold — and must not crash unpacking state."""
        train, val, config = game_files
        with open(config) as f:
            cfg = json.load(f)
        cfg["coordinates"][1] = {
            "name": "per_user", "type": "factored_random",
            "feature_shard": "userFeatures", "entity_key": "userId",
            "rank": 1, "alternations": 1,
            "optimizer": "lbfgs", "max_iters": 20, "reg_type": "l2",
            "reg_weight": 0.5,
        }
        fcfg = str(tmp_path / "factored.json")
        with open(fcfg, "w") as f:
            json.dump(cfg, f)
        out1 = str(tmp_path / "first")
        game_training_driver.run([
            "--train-data", train, "--validate-data", val,
            "--config", fcfg, "--output-dir", out1,
        ])
        out2 = str(tmp_path / "second")
        r2 = game_training_driver.run([
            "--train-data", train, "--validate-data", val,
            "--config", fcfg, "--output-dir", out2,
            "--initial-model", os.path.join(out1, "models"),
        ])
        assert r2["validation_metric"] > 0.6


class TestStreamingGameDriver:
    def test_streamed_fixed_coordinate_matches_resident(
        self, game_files, tmp_path
    ):
        """"streaming_chunk_rows" on a fixed coordinate: the CLI run must
        select a model equivalent to the resident run."""
        import copy

        train, val, config_path = game_files
        with open(config_path) as f:
            config = json.load(f)
        out_r = str(tmp_path / "resident")
        res_r = game_training_driver.run([
            "--train-data", train, "--validate-data", val,
            "--config", config_path, "--output-dir", out_r,
        ])
        streamed_cfg = copy.deepcopy(config)
        streamed_cfg["coordinates"][0]["streaming_chunk_rows"] = 150
        cfg2 = str(tmp_path / "cfg_stream.json")
        with open(cfg2, "w") as f:
            json.dump(streamed_cfg, f)
        out_s = str(tmp_path / "streamed")
        res_s = game_training_driver.run([
            "--train-data", train, "--validate-data", val,
            "--config", cfg2, "--output-dir", out_s,
        ])
        assert res_s["validation_metric"] == pytest.approx(
            res_r["validation_metric"], abs=2e-3
        )
        m_s, _ = load_game_model(os.path.join(out_s, "models"))
        m_r, _ = load_game_model(os.path.join(out_r, "models"))
        np.testing.assert_allclose(
            np.asarray(m_s["fixed"].model.coefficients.means),
            np.asarray(m_r["fixed"].model.coefficients.means),
            atol=5e-3,
        )


class TestPartialRetrainingDriver:
    def test_locked_coordinate_held_at_initial_model(
        self, game_files, tmp_path
    ):
        """--locked-coordinates holds the named coordinate at
        --initial-model: its saved per-entity coefficients come through
        byte-identical while the other coordinate retrains."""
        train, val, config = game_files
        out1 = str(tmp_path / "base")
        game_training_driver.run([
            "--train-data", train,
            "--validate-data", val,
            "--config", config,
            "--output-dir", out1,
        ])
        out2 = str(tmp_path / "partial")
        result = game_training_driver.run([
            "--train-data", train,
            "--validate-data", val,
            "--config", config,
            "--output-dir", out2,
            "--initial-model", os.path.join(out1, "models"),
            "--locked-coordinates", "per_user",
        ])
        from photon_ml_tpu.io.game_store import load_game_model

        m1, _ = load_game_model(os.path.join(out1, "models"))
        m2, _ = load_game_model(os.path.join(out2, "models"))
        re1, re2 = m1.models["per_user"], m2.models["per_user"]
        assert set(re1.coefficients) == set(re2.coefficients)
        for k, (c1, v1) in re1.coefficients.items():
            c2, v2 = re2.coefficients[k]
            np.testing.assert_array_equal(c1, c2)
            np.testing.assert_array_equal(v1, v2)
        assert result["validation_metric"] > 0.65
        # Only the fixed coordinate appears in the history.
        assert {h["coordinate"] for h in result["history"]} == {"fixed"}

    def test_locked_without_initial_model_rejected(
        self, game_files, tmp_path
    ):
        train, val, config = game_files
        with pytest.raises(SystemExit, match="initial-model"):
            game_training_driver.run([
                "--train-data", train,
                "--config", config,
                "--output-dir", str(tmp_path / "x"),
                "--locked-coordinates", "per_user",
            ])
