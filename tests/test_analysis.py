"""Invariant-checker tests: every rule fires on a violating fixture and
stays silent on the conforming twin; suppression and baseline mechanics
behave; the real tree is clean; and the runtime sanitizers detect a
scripted lock-order inversion and a leaked thread (ISSUE 10).

Fixtures are tiny source trees written to tmp_path — the checker runs on
files, never imports them, so the fixtures are free to be wrong on
purpose (which is also why ``tests/`` is excluded from the default scan
roots).
"""

import json
import os
import threading
import time

import pytest

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.analysis import (
    ALL_RULES,
    RULES_BY_ID,
    Baseline,
    SourceTree,
    check,
    run_rules,
)
from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.analysis.__main__ import main as analysis_main
from photon_ml_tpu.analysis.engine import default_roots


def _tree(tmp_path, source: str, name: str = "mod.py") -> SourceTree:
    path = tmp_path / name
    path.write_text(source)
    return SourceTree(roots=[str(path)], repo_root=str(tmp_path))


def _findings(tmp_path, rule_id: str, source: str):
    tree = _tree(tmp_path, source)
    return [
        f for f in run_rules(tree, [RULES_BY_ID[rule_id]])
        if not tree.files[0].is_suppressed(f.rule, f.line)
    ]


# ---------------------------------------------------------------------------
# concurrency rules
# ---------------------------------------------------------------------------

class TestThreadLifecycle:
    def test_flags_unjoined_non_daemon(self, tmp_path):
        found = _findings(tmp_path, "thread-lifecycle", (
            "import threading\n"
            "def go(fn):\n"
            "    t = threading.Thread(target=fn)\n"
            "    t.start()\n"
        ))
        assert len(found) == 1
        assert "never joined" in found[0].message
        assert found[0].line == 3

    def test_flags_happy_path_only_join(self, tmp_path):
        found = _findings(tmp_path, "thread-lifecycle", (
            "import threading\n"
            "def go(fn, risky):\n"
            "    t = threading.Thread(target=fn)\n"
            "    t.start()\n"
            "    risky()\n"
            "    t.join()\n"
        ))
        assert len(found) == 1
        assert "happy path" in found[0].message

    def test_flags_unbound_creation(self, tmp_path):
        found = _findings(tmp_path, "thread-lifecycle", (
            "import threading\n"
            "def go(fns):\n"
            "    for fn in fns:\n"
            "        threading.Thread(target=fn).start()\n"
        ))
        assert len(found) == 1
        assert "without a binding" in found[0].message

    def test_accepts_daemon(self, tmp_path):
        assert _findings(tmp_path, "thread-lifecycle", (
            "import threading\n"
            "def go(fn):\n"
            "    t = threading.Thread(target=fn, daemon=True)\n"
            "    t.start()\n"
        )) == []

    def test_accepts_join_in_finally(self, tmp_path):
        assert _findings(tmp_path, "thread-lifecycle", (
            "import threading\n"
            "def go(fn, risky):\n"
            "    t = threading.Thread(target=fn)\n"
            "    t.start()\n"
            "    try:\n"
            "        risky()\n"
            "    finally:\n"
            "        t.join()\n"
        )) == []

    def test_accepts_lifecycle_stop_pattern(self, tmp_path):
        # start() and join() in different methods: MicroBatcher's shape.
        assert _findings(tmp_path, "thread-lifecycle", (
            "import threading\n"
            "class Owner:\n"
            "    def start(self, fn):\n"
            "        self._t = threading.Thread(target=fn)\n"
            "        self._t.start()\n"
            "    def stop(self):\n"
            "        self._t.join()\n"
        )) == []


class TestLockBlockingCall:
    def test_flags_sleep_under_lock(self, tmp_path):
        found = _findings(tmp_path, "lock-blocking-call", (
            "import threading, time\n"
            "lock = threading.Lock()\n"
            "def slow():\n"
            "    with lock:\n"
            "        time.sleep(1.0)\n"
        ))
        assert len(found) == 1
        assert "time.sleep()" in found[0].message

    def test_flags_join_under_tracked_lock(self, tmp_path):
        # tracked(...) wrappers still count as locks.
        found = _findings(tmp_path, "lock-blocking-call", (
            "import threading\n"
            "from photon_ml_tpu.analysis import sanitizers\n"
            "lock = sanitizers.tracked(threading.Lock(), 'w')\n"
            "def bad(t):\n"
            "    with lock:\n"
            "        t.join()\n"
        ))
        assert len(found) == 1
        assert "thread join while holding lock" in found[0].message

    def test_flags_device_sync_and_fsync(self, tmp_path):
        found = _findings(tmp_path, "lock-blocking-call", (
            "import os, threading\n"
            "lock = threading.Lock()\n"
            "def bad(x, fd):\n"
            "    with lock:\n"
            "        x.block_until_ready()\n"
            "        os.fsync(fd)\n"
        ))
        assert len(found) == 2

    def test_accepts_nonblocking_joins_under_lock(self, tmp_path):
        # os.path.join and "sep".join never block — only thread-like
        # .join() calls convoy the lock.
        assert _findings(tmp_path, "lock-blocking-call", (
            "import os, threading\n"
            "lock = threading.Lock()\n"
            "def ok(parts):\n"
            "    with lock:\n"
            "        p = os.path.join('a', 'b')\n"
            "        s = ', '.join(parts)\n"
            "    return p, s\n"
        )) == []

    def test_accepts_sleep_outside_lock(self, tmp_path):
        assert _findings(tmp_path, "lock-blocking-call", (
            "import threading, time\n"
            "lock = threading.Lock()\n"
            "def ok():\n"
            "    with lock:\n"
            "        x = 1\n"
            "    time.sleep(0.1)\n"
            "    return x\n"
        )) == []


class TestWallClockInterval:
    def test_flags_interval_math(self, tmp_path):
        found = _findings(tmp_path, "wall-clock-interval", (
            "import time\n"
            "def lat(t0):\n"
            "    return time.time() - t0\n"
        ))
        assert len(found) == 1
        assert "monotonic" in found[0].message

    def test_flags_bare_latency_assignment(self, tmp_path):
        assert len(_findings(tmp_path, "wall-clock-interval", (
            "import time\n"
            "def stamp():\n"
            "    t_start = time.time()\n"
            "    return t_start\n"
        ))) == 1

    def test_accepts_wall_anchoring(self, tmp_path):
        assert _findings(tmp_path, "wall-clock-interval", (
            "import time\n"
            "def anchor():\n"
            "    epoch_wall = time.time()\n"
            "    meta = {'wall_epoch': time.time()}\n"
            "    rec(wall_epoch=time.time())\n"
            "    return epoch_wall, meta\n"
        )) == []


# ---------------------------------------------------------------------------
# jax rules
# ---------------------------------------------------------------------------

class TestDonatedBufferReuse:
    def test_flags_read_after_donate(self, tmp_path):
        found = _findings(tmp_path, "donated-buffer-reuse", (
            "import jax\n"
            "prog = jax.jit(lambda a, b: a + b, donate_argnums=(0,))\n"
            "def step(g, x):\n"
            "    out = prog(g, x)\n"
            "    return g + out\n"
        ))
        assert len(found) == 1
        assert "donated" in found[0].message
        assert found[0].line == 5

    def test_accepts_carry_rebinding(self, tmp_path):
        # optim/streaming's `g = prog(g, x)` idiom.
        assert _findings(tmp_path, "donated-buffer-reuse", (
            "import jax\n"
            "prog = jax.jit(lambda a, b: a + b, donate_argnums=(0,))\n"
            "def step(g, x):\n"
            "    g = prog(g, x)\n"
            "    return g\n"
        )) == []

    def test_accepts_rebind_before_use(self, tmp_path):
        assert _findings(tmp_path, "donated-buffer-reuse", (
            "import jax\n"
            "prog = jax.jit(lambda a, b: a + b, donate_argnums=(0,))\n"
            "def step(g, x, fresh):\n"
            "    out = prog(g, x)\n"
            "    g = fresh()\n"
            "    return g + out\n"
        )) == []

    def test_dynamic_donation_is_skipped(self, tmp_path):
        # donate_argnums=self._donate[kind]: positions unknown, no flag.
        assert _findings(tmp_path, "donated-buffer-reuse", (
            "import jax\n"
            "class S:\n"
            "    def build(self, f, kind):\n"
            "        self._p = jax.jit(f, donate_argnums=self._d[kind])\n"
            "    def step(self, g, x):\n"
            "        out = self._p(g, x)\n"
            "        return g + out\n"
        )) == []


class TestJitSideEffect:
    def test_flags_telemetry_in_decorated_body(self, tmp_path):
        found = _findings(tmp_path, "jit-side-effect", (
            "import jax\n"
            "@jax.jit\n"
            "def f(x, tel):\n"
            "    tel.counter('cd_steps_total').inc()\n"
            "    return x * 2\n"
        ))
        assert len(found) == 1
        assert "trace time" in found[0].message

    def test_flags_maybe_fail_in_jitted_def(self, tmp_path):
        found = _findings(tmp_path, "jit-side-effect", (
            "import jax\n"
            "from photon_ml_tpu.chaos import maybe_fail\n"
            "def step(x):\n"
            "    maybe_fail('cd.iteration')\n"
            "    return x + 1\n"
            "prog = jax.jit(step)\n"
        ))
        assert len(found) == 1
        assert "maybe_fail()" in found[0].message

    def test_accepts_effect_at_call_site(self, tmp_path):
        # game/descent.py's shape: effects AROUND the program call.
        assert _findings(tmp_path, "jit-side-effect", (
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * 2\n"
            "def drive(x, tel):\n"
            "    y = f(x)\n"
            "    tel.counter('cd_steps_total').inc()\n"
            "    return y\n"
        )) == []


class TestUnseededRng:
    def test_flags_module_global_numpy(self, tmp_path):
        found = _findings(tmp_path, "unseeded-rng", (
            "import numpy as np\n"
            "def jitter():\n"
            "    return np.random.uniform()\n"
        ))
        assert len(found) == 1
        assert "module-global numpy RNG" in found[0].message

    def test_flags_unseeded_constructors(self, tmp_path):
        found = _findings(tmp_path, "unseeded-rng", (
            "import random\n"
            "import numpy as np\n"
            "a = np.random.default_rng()\n"
            "b = random.Random()\n"
        ))
        assert len(found) == 2

    def test_accepts_seeded(self, tmp_path):
        assert _findings(tmp_path, "unseeded-rng", (
            "import random\n"
            "import numpy as np\n"
            "a = np.random.default_rng(23)\n"
            "b = random.Random(7)\n"
            "c = np.random.SeedSequence(5)\n"
        )) == []


# ---------------------------------------------------------------------------
# registry rules
# ---------------------------------------------------------------------------

class TestChaosSiteSync:
    CORE = (
        "KNOWN_SITES = {\n"
        "    'a.one': 'first seam',\n"
        "    'a.two': 'second seam',\n"
        "}\n"
    )

    def _tree(self, tmp_path, user_src: str) -> SourceTree:
        core = tmp_path / "photon_ml_tpu" / "chaos" / "core.py"
        core.parent.mkdir(parents=True)
        core.write_text(self.CORE)
        user = tmp_path / "photon_ml_tpu" / "user.py"
        user.write_text(user_src)
        return SourceTree(
            roots=[str(tmp_path / "photon_ml_tpu")],
            repo_root=str(tmp_path),
        )

    def test_flags_both_directions(self, tmp_path):
        tree = self._tree(tmp_path, (
            "from photon_ml_tpu import chaos\n"
            "def f(k):\n"
            "    chaos.maybe_fail('a.one', item=k)\n"
            "    chaos.maybe_fail('a.rogue', item=k)\n"
        ))
        found = run_rules(tree, [RULES_BY_ID["chaos-site-sync"]])
        msgs = sorted(f.message for f in found)
        assert len(found) == 2
        assert "'a.two' is registered" in msgs[0]
        assert "'a.rogue' is not in chaos/core.py" in msgs[1]

    def test_silent_when_in_sync(self, tmp_path):
        tree = self._tree(tmp_path, (
            "from photon_ml_tpu import chaos\n"
            "def f(k):\n"
            "    chaos.maybe_fail('a.one', item=k)\n"
            "    chaos.maybe_fail('a.two', item=k)\n"
        ))
        assert run_rules(tree, [RULES_BY_ID["chaos-site-sync"]]) == []


class TestChaosSiteTested:
    CORE = (
        "KNOWN_SITES = {\n"
        "    'a.one': 'first seam',\n"
        "    'a.two': 'second seam',\n"
        "}\n"
    )

    def _tree(self, tmp_path, test_src=None) -> SourceTree:
        core = tmp_path / "photon_ml_tpu" / "chaos" / "core.py"
        core.parent.mkdir(parents=True)
        core.write_text(self.CORE)
        if test_src is not None:
            tests = tmp_path / "tests"
            tests.mkdir()
            (tests / "test_mod.py").write_text(test_src)
        return SourceTree(
            roots=[str(tmp_path / "photon_ml_tpu")],
            repo_root=str(tmp_path),
        )

    def test_flags_site_no_test_references(self, tmp_path):
        tree = self._tree(tmp_path, (
            "from photon_ml_tpu import chaos\n"
            "def test_one():\n"
            "    chaos.FaultSpec(site='a.one', at=0)\n"
        ))
        found = run_rules(tree, [RULES_BY_ID["chaos-site-tested"]])
        assert len(found) == 1
        assert "'a.two'" in found[0].message
        assert "no test file references it" in found[0].message

    def test_silent_when_every_site_referenced(self, tmp_path):
        # Either quote style counts — the reference is textual on
        # purpose (FaultSpec args, plan JSON, parametrize ids all
        # count as exercising the site).
        tree = self._tree(tmp_path, (
            "def test_both():\n"
            "    plan(['a.one'])\n"
            '    assert fired("a.two")\n'
        ))
        assert run_rules(
            tree, [RULES_BY_ID["chaos-site-tested"]]
        ) == []

    def test_silent_without_tests_dir(self, tmp_path):
        # Rule fixtures (and vendored subsets) have no tests/ tree:
        # nothing to cross-reference, nothing to flag.
        tree = self._tree(tmp_path, test_src=None)
        assert run_rules(
            tree, [RULES_BY_ID["chaos-site-tested"]]
        ) == []

    def test_live_registry_fully_tested(self):
        # The real repo must hold the invariant the rule enforces:
        # every KNOWN_SITES entry is exercised by some test.
        assert run_rules(
            SourceTree(), [RULES_BY_ID["chaos-site-tested"]]
        ) == []


class TestMetricNaming:
    def test_flags_bad_names_and_kind_conflict(self, tmp_path):
        found = _findings(tmp_path, "metric-naming", (
            "def f(tel):\n"
            "    tel.counter(\"bogus_thing_total\").inc()\n"
            "    tel.gauge(\"serving_thing_blobs\").set(1)\n"
            "    tel.counter(\"serving_dual_total\").inc()\n"
            "    tel.gauge(\"serving_dual_total\").set(2)\n"
        ))
        msgs = " | ".join(f.message for f in found)
        assert "unknown subsystem prefix" in msgs
        assert "unknown unit suffix" in msgs
        assert "multiple kinds" in msgs

    def test_silent_on_conforming_and_legacy(self, tmp_path):
        assert _findings(tmp_path, "metric-naming", (
            "def f(tel):\n"
            "    tel.gauge(\"hbm_live_bytes\").set(0)\n"
            "    tel.counter(\"chaos_faults_injected\").inc()\n"
        )) == []

    def test_lint_metrics_alias_still_works(self, capsys):
        from photon_ml_tpu.telemetry.__main__ import lint_metrics

        assert lint_metrics() == 0
        assert "metric lint OK" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# suppression + baseline mechanics
# ---------------------------------------------------------------------------

class TestSuppression:
    SRC = (
        "import time\n"
        "def lat(t0):\n"
        "    return time.time() - t0{inline}\n"
    )

    def test_inline_suppression(self, tmp_path):
        src = self.SRC.format(
            inline="  # photon: disable=wall-clock-interval"
        )
        assert _findings(tmp_path, "wall-clock-interval", src) == []

    def test_preceding_comment_line_covers_next(self, tmp_path):
        assert _findings(tmp_path, "wall-clock-interval", (
            "import time\n"
            "def lat(t0):\n"
            "    # photon: disable=wall-clock-interval\n"
            "    return time.time() - t0\n"
        )) == []

    def test_disable_all(self, tmp_path):
        src = self.SRC.format(inline="  # photon: disable=all")
        assert _findings(tmp_path, "wall-clock-interval", src) == []

    def test_other_rule_suppression_does_not_cover(self, tmp_path):
        src = self.SRC.format(inline="  # photon: disable=unseeded-rng")
        assert len(_findings(tmp_path, "wall-clock-interval", src)) == 1


class TestBaseline:
    SRC = (
        "import time\n"
        "def lat(t0):\n"
        "    return time.time() - t0\n"
    )

    def _check(self, tmp_path, baseline_path=None):
        (tmp_path / "mod.py").write_text(self.SRC)
        return check(
            roots=[str(tmp_path / "mod.py")],
            repo_root=str(tmp_path),
            baseline_path=baseline_path,
            rules=[RULES_BY_ID["wall-clock-interval"]],
        )

    def test_unbaselined_finding_fails(self, tmp_path):
        report = self._check(tmp_path)
        assert not report.ok
        assert len(report.findings) == 1

    def test_baselined_finding_passes_and_line_drift_survives(
        self, tmp_path
    ):
        report = self._check(tmp_path)
        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps({"entries": [{
            "rule": f.rule, "path": f.path, "message": f.message,
            "justification": "test grandfather",
        } for f in report.findings]}))
        report2 = self._check(tmp_path, baseline_path=str(bl))
        assert report2.ok and report2.baselined == 1
        # shift the finding down two lines: key has no line number
        (tmp_path / "mod.py").write_text("# pad\n# pad\n" + self.SRC)
        report3 = check(
            roots=[str(tmp_path / "mod.py")], repo_root=str(tmp_path),
            baseline_path=str(bl),
            rules=[RULES_BY_ID["wall-clock-interval"]],
        )
        assert report3.ok and report3.baselined == 1

    def test_justification_required(self, tmp_path):
        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps({"entries": [{
            "rule": "wall-clock-interval", "path": "mod.py",
            "message": "anything",
        }]}))
        with pytest.raises(ValueError, match="justification"):
            Baseline.load(str(bl))

    def test_stale_entries_reported(self, tmp_path):
        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps({"entries": [{
            "rule": "wall-clock-interval", "path": "gone.py",
            "message": "was fixed long ago",
            "justification": "old",
        }]}))
        (tmp_path / "mod.py").write_text("x = 1\n")
        report = check(
            roots=[str(tmp_path / "mod.py")], repo_root=str(tmp_path),
            baseline_path=str(bl),
            rules=[RULES_BY_ID["wall-clock-interval"]],
        )
        assert report.ok  # stale entries warn, not fail
        assert len(report.stale_baseline) == 1

    def test_write_carries_justifications_forward(self, tmp_path):
        report = self._check(tmp_path)
        old = Baseline([{
            "rule": f.rule, "path": f.path, "message": f.message,
            "justification": "kept across rewrites",
        } for f in report.findings])
        out = tmp_path / "new_baseline.json"
        Baseline.write(str(out), report.findings, old)
        data = json.loads(out.read_text())
        assert data["entries"][0]["justification"] == (
            "kept across rewrites"
        )


# ---------------------------------------------------------------------------
# the real tree + CLI
# ---------------------------------------------------------------------------

class TestRealTree:
    def test_package_is_clean(self):
        report = check()
        assert report.parse_errors == []
        assert report.findings == [], "\n".join(
            str(f) for f in report.findings
        )
        assert report.stale_baseline == []
        # the committed baseline stays small and justified
        assert report.baselined <= 10

    def test_default_roots_exclude_tests(self):
        roots = default_roots()
        assert not any(r.endswith("tests") for r in roots)

    def test_cli_check_exit_codes(self, tmp_path, capsys):
        assert analysis_main(["--check"]) == 0
        capsys.readouterr()
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import time\ndef lat(t0):\n    return time.time() - t0\n"
        )
        empty_bl = tmp_path / "bl.json"
        empty_bl.write_text('{"entries": []}')
        assert analysis_main([
            "--check", "--root", str(bad), "--baseline", str(empty_bl),
        ]) == 1
        out = capsys.readouterr().out
        assert "wall-clock-interval" in out and "FAILED" in out

    def test_cli_list_and_explain(self, capsys):
        assert analysis_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in ALL_RULES:
            assert rule.id in out
        assert analysis_main(["--explain", "donated-buffer-reuse"]) == 0
        assert "use-after-free" in capsys.readouterr().out
        assert analysis_main(["--explain", "nope"]) == 1

    def test_cli_update_baseline_roundtrip(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import time\ndef lat(t0):\n    return time.time() - t0\n"
        )
        bl = tmp_path / "bl.json"
        assert analysis_main([
            "--update-baseline", "--root", str(bad),
            "--baseline", str(bl),
        ]) == 0
        capsys.readouterr()
        data = json.loads(bl.read_text())
        assert len(data["entries"]) == 1
        # fresh entries carry the TODO placeholder the loader refuses
        assert "TODO" in data["entries"][0]["justification"]
        with pytest.raises(ValueError):
            Baseline.load(str(bl))
        data["entries"][0]["justification"] = "grandfathered in test"
        bl.write_text(json.dumps(data))
        assert analysis_main([
            "--check", "--root", str(bad), "--baseline", str(bl),
        ]) == 0


# ---------------------------------------------------------------------------
# runtime sanitizers
# ---------------------------------------------------------------------------

class TestLockOrderSanitizer:
    def test_detects_scripted_inversion(self):
        with sanitizers.LockOrderSanitizer() as san:
            a = sanitizers.tracked(threading.Lock(), "order.a")
            b = sanitizers.tracked(threading.Lock(), "order.b")
            with a:
                with b:
                    pass

            def inverted():
                with b:
                    with a:
                        pass

            t = threading.Thread(target=inverted, daemon=True)
            t.start()
            t.join()
        assert len(san.reports) == 1
        rep = san.reports[0]
        assert rep["kind"] == "lock-order-inversion"
        assert rep["cycle"] == ["order.a", "order.b", "order.a"]

    def test_consistent_order_is_silent(self):
        with sanitizers.LockOrderSanitizer() as san:
            a = sanitizers.tracked(threading.Lock(), "same.a")
            b = sanitizers.tracked(threading.Lock(), "same.b")

            def nested():
                with a:
                    with b:
                        pass

            threads = [
                threading.Thread(target=nested, daemon=True)
                for _ in range(4)
            ]
            try:
                for t in threads:
                    t.start()
            finally:
                for t in threads:
                    t.join()
            nested()
        assert san.reports == []

    def test_strict_mode_raises(self):
        with sanitizers.LockOrderSanitizer(strict=True):
            a = sanitizers.tracked(threading.Lock(), "strict.a")
            b = sanitizers.tracked(threading.Lock(), "strict.b")
            with a:
                with b:
                    pass
            with pytest.raises(sanitizers.LockOrderViolation):
                with b:
                    with a:
                        pass

    def test_three_lock_transitive_cycle(self):
        with sanitizers.LockOrderSanitizer() as san:
            a = sanitizers.tracked(threading.Lock(), "tri.a")
            b = sanitizers.tracked(threading.Lock(), "tri.b")
            c = sanitizers.tracked(threading.Lock(), "tri.c")
            with a, b:
                pass
            with b, c:
                pass
            with c, a:  # closes a -> b -> c -> a
                pass
        assert len(san.reports) == 1
        assert san.reports[0]["cycle"][0] == san.reports[0]["cycle"][-1]

    def test_disabled_path_returns_raw_lock(self):
        raw = threading.Lock()
        assert sanitizers.tracked(raw, "raw") is raw

    def test_uninstalled_tracked_lock_is_passthrough(self):
        with sanitizers.LockOrderSanitizer():
            tl = sanitizers.tracked(threading.Lock(), "late")
        # sanitizer gone: TrackedLock still works, records nothing
        with tl:
            assert tl.locked()
        assert not tl.locked()

    def test_try_acquire_failure_unwinds(self):
        with sanitizers.LockOrderSanitizer() as san:
            tl = sanitizers.tracked(threading.Lock(), "try.a")
            other = sanitizers.tracked(threading.Lock(), "try.b")
            assert tl.acquire(blocking=False)
            assert not tl.acquire(blocking=False)  # held: must unwind
            tl.release()
            # had the failed acquire leaked a stack entry, this nesting
            # would record try.a -> try.b and the reverse would report
            with other:
                with tl:
                    pass
            with tl:
                pass
        assert san.reports == []

    def test_report_deduped_per_pair(self):
        with sanitizers.LockOrderSanitizer() as san:
            a = sanitizers.tracked(threading.Lock(), "dup.a")
            b = sanitizers.tracked(threading.Lock(), "dup.b")
            with a, b:
                pass
            for _ in range(5):
                with b, a:
                    pass
        assert len(san.reports) == 1

    def test_inversion_bumps_counter_and_dumps_recorder(self, tmp_path):
        with telemetry_mod.Telemetry(output_dir=str(tmp_path)) as tel:
            with sanitizers.LockOrderSanitizer():
                a = sanitizers.tracked(threading.Lock(), "fr.a")
                b = sanitizers.tracked(threading.Lock(), "fr.b")
                with a, b:
                    pass
                with b, a:
                    pass
            assert (
                tel.counter(
                    "analysis_lock_order_reports_total"
                ).value == 1
            )
        dump = os.path.join(str(tmp_path), "flightrecorder.json")
        assert os.path.exists(dump)
        with open(dump) as f:
            data = json.load(f)
        assert data["reason"].startswith("lockorder:")


class TestThreadLeakSentinel:
    def test_detects_leaked_thread(self):
        stop = threading.Event()
        try:
            with sanitizers.ThreadLeakSentinel(grace_s=0.2) as sentinel:
                threading.Thread(
                    target=stop.wait, name="leaky-worker", daemon=True
                ).start()
            assert sentinel.leaked == ["leaky-worker"]
        finally:
            stop.set()

    def test_joined_threads_are_clean(self):
        with sanitizers.ThreadLeakSentinel(grace_s=1.0) as sentinel:
            t = threading.Thread(target=lambda: None, daemon=True)
            t.start()
            t.join()
        assert sentinel.leaked == []

    def test_allow_prefix(self):
        stop = threading.Event()
        try:
            with sanitizers.ThreadLeakSentinel(
                grace_s=0.2, allow=("exporter-",)
            ) as sentinel:
                threading.Thread(
                    target=stop.wait, name="exporter-http", daemon=True
                ).start()
            assert sentinel.leaked == []
        finally:
            stop.set()

    def test_strict_raises(self):
        stop = threading.Event()
        try:
            with pytest.raises(sanitizers.ThreadLeakError):
                with sanitizers.ThreadLeakSentinel(
                    grace_s=0.2, strict=True
                ):
                    threading.Thread(
                        target=stop.wait, name="strict-leak",
                        daemon=True,
                    ).start()
        finally:
            stop.set()

    def test_grace_covers_slow_finish(self):
        with sanitizers.ThreadLeakSentinel(grace_s=2.0) as sentinel:
            threading.Thread(
                target=lambda: time.sleep(0.1), daemon=True
            ).start()
        assert sentinel.leaked == []


class TestSanitizedSubsystems:
    """The wired production locks run clean under an installed
    sanitizer: a streamed prefetch pass exercises prefetch.live with
    witness tracking on and reports nothing."""

    @pytest.mark.parametrize("depth", [1, 2])
    def test_streamed_pass_clean_under_sanitizer(self, depth):
        import numpy as np

        from photon_ml_tpu.data.prefetch import run_prefetched

        items = [np.full((4,), k, np.float32) for k in range(6)]
        consumed = []
        with sanitizers.LockOrderSanitizer(strict=True) as san:
            run_prefetched(
                len(items),
                get_item=lambda k: items[k],
                put=lambda host: host + 1,
                consume=lambda k, dev: consumed.append((k, dev)),
                depth=depth,
            )
        assert [k for k, _ in consumed] == list(range(6))
        assert san.reports == []
