"""Objective tests: closed-form gradient/HVP vs autodiff oracle, sparse vs
dense agreement, and normalization-context semantics — mirroring the
reference's distributed-vs-single-node numerical parity pattern
(SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.data.dataset import make_glm_data
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.ops import losses, sparse
from photon_ml_tpu.optim.objective import GlmObjective


def _random_problem(rng, n=50, d=8, density=0.4, loss=losses.logistic):
    X = sp.random(n, d, density=density, random_state=np.random.RandomState(0),
                  format="csr", dtype=np.float64)
    if loss.name == "poisson":
        y = rng.poisson(1.5, n).astype(np.float32)
    elif loss.name in ("logistic", "smoothed_hinge"):
        y = rng.integers(0, 2, n).astype(np.float32)
    else:
        y = rng.normal(size=n).astype(np.float32)
    w_rows = rng.uniform(0.5, 2.0, n).astype(np.float32)
    offs = rng.normal(size=n).astype(np.float32) * 0.1
    return X, y, w_rows, offs


@pytest.mark.parametrize("loss", [losses.logistic, losses.squared, losses.poisson],
                         ids=lambda l: l.name)
@pytest.mark.parametrize("dense", [True, False], ids=["dense", "sparse"])
def test_grad_matches_autodiff(rng, loss, dense):
    X, y, w_rows, offs = _random_problem(rng, loss=loss)
    feats = X.toarray() if dense else X
    data = make_glm_data(feats, y, w_rows, offs)
    obj = GlmObjective(loss)
    w = jnp.asarray(rng.normal(size=X.shape[1]) * 0.3, jnp.float32)
    l2 = 0.7

    val, grad = obj.value_and_grad(w, data, l2)
    auto_val, auto_grad = jax.value_and_grad(lambda ww: obj.value(ww, data, l2))(w)
    np.testing.assert_allclose(float(val), float(auto_val), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(auto_grad),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("loss", [losses.logistic, losses.squared, losses.poisson],
                         ids=lambda l: l.name)
def test_hvp_matches_autodiff(rng, loss):
    X, y, w_rows, offs = _random_problem(rng, loss=loss)
    data = make_glm_data(X, y, w_rows, offs)
    obj = GlmObjective(loss)
    w = jnp.asarray(rng.normal(size=X.shape[1]) * 0.3, jnp.float32)
    v = jnp.asarray(rng.normal(size=X.shape[1]), jnp.float32)
    l2 = 0.3

    hvp = obj.hvp(w, v, data, l2)
    # Forward-over-reverse oracle. For GLM losses the Gauss-Newton form IS
    # the true Hessian (margins are linear in w), so these must agree.
    auto = jax.jvp(jax.grad(lambda ww: obj.value(ww, data, l2)), (w,), (v,))[1]
    np.testing.assert_allclose(np.asarray(hvp), np.asarray(auto),
                               rtol=1e-3, atol=1e-3)


def test_sparse_matches_dense(rng):
    X, y, w_rows, offs = _random_problem(rng)
    obj = GlmObjective(losses.logistic)
    d_sparse = make_glm_data(X, y, w_rows, offs)
    d_dense = make_glm_data(X.toarray(), y, w_rows, offs)
    w = jnp.asarray(rng.normal(size=X.shape[1]), jnp.float32)
    v_s, g_s = obj.value_and_grad(w, d_sparse, 0.1)
    v_d, g_d = obj.value_and_grad(w, d_dense, 0.1)
    np.testing.assert_allclose(float(v_s), float(v_d), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g_s), np.asarray(g_d), rtol=1e-4, atol=1e-5)


def test_nnz_padding_is_inert(rng):
    X, y, w_rows, offs = _random_problem(rng)
    obj = GlmObjective(losses.logistic)
    d0 = make_glm_data(X, y, w_rows, offs)
    d_pad = make_glm_data(X, y, w_rows, offs, pad_rows=64, pad_nnz=X.nnz + 37)
    w = jnp.asarray(rng.normal(size=X.shape[1]), jnp.float32)
    v0, g0 = obj.value_and_grad(w, d0, 0.0)
    v1, g1 = obj.value_and_grad(w, d_pad, 0.0)
    np.testing.assert_allclose(float(v0), float(v1), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g0), np.asarray(g1), rtol=1e-4, atol=1e-5)


def test_normalization_context_equals_pre_scaled_data(rng):
    """Training with a NormalizationContext on raw data must equal training on
    explicitly standardized data — the reference's core normalization claim."""
    n, d = 40, 5
    Xd = rng.normal(size=(n, d)).astype(np.float64) * 3.0 + 1.0
    Xd[:, -1] = 1.0  # intercept column
    y = rng.integers(0, 2, n).astype(np.float32)
    mean = Xd.mean(axis=0)
    std = Xd.std(axis=0, ddof=0)
    factors = 1.0 / np.where(std > 0, std, 1.0)
    shifts = mean.copy()
    factors[-1], shifts[-1] = 1.0, 0.0

    Xs = (Xd - shifts) * factors  # explicitly standardized
    norm = NormalizationContext(
        factors=jnp.asarray(factors, jnp.float32),
        shifts=jnp.asarray(shifts, jnp.float32),
        intercept_index=d - 1,
    )
    obj_norm = GlmObjective(losses.logistic, normalization=norm)
    obj_plain = GlmObjective(losses.logistic)
    data_raw = make_glm_data(Xd, y)
    data_scaled = make_glm_data(Xs, y)

    w = jnp.asarray(rng.normal(size=d), jnp.float32)
    v_n, g_n = obj_norm.value_and_grad(w, data_raw, 0.5)
    v_s, g_s = obj_plain.value_and_grad(w, data_scaled, 0.5)
    np.testing.assert_allclose(float(v_n), float(v_s), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(g_n), np.asarray(g_s), rtol=1e-3, atol=1e-3)

    # Round-trip of the coefficient-space transforms.
    w_orig = norm.model_to_original(w)
    w_back = norm.original_to_model(w_orig)
    np.testing.assert_allclose(np.asarray(w_back), np.asarray(w), rtol=1e-4, atol=1e-5)

    # Margins computed in model space on raw data == margins of original-space
    # coefficients on raw data.
    m_model = obj_norm.margins(w, data_raw)
    m_orig = obj_plain.margins(w_orig, data_raw)
    np.testing.assert_allclose(np.asarray(m_model), np.asarray(m_orig),
                               rtol=1e-3, atol=1e-3)


def test_sparse_to_dense_roundtrip(rng):
    X = sp.random(20, 7, density=0.5, random_state=np.random.RandomState(1),
                  format="csr")
    sm = sparse.from_scipy_csr(X, pad_nnz=X.nnz + 11)
    np.testing.assert_allclose(np.asarray(sm.to_dense().data), X.toarray(),
                               rtol=1e-6, atol=1e-6)


class TestAccumulatePrecision:
    """Opt-in f64 value accumulation (VERDICT r2 missing #5): at 1e8 rows
    the f32 weighted sum's rounding competes with 1e-7 convergence
    tolerances; the f64 option must track the numpy f64 oracle tightly."""

    def test_f64_value_tracks_oracle_at_1e8_rows(self):
        import numpy as np
        import jax.numpy as jnp

        from photon_ml_tpu.data.dataset import GlmData
        from photon_ml_tpu.ops import losses
        from photon_ml_tpu.ops.sparse import DenseMatrix
        from photon_ml_tpu.optim.objective import GlmObjective

        n = 100_000_000
        rng = np.random.default_rng(0)
        # Margins ride the offsets so no matvec is needed at this scale;
        # a 1-column zero feature block keeps GlmData's shape contract.
        offsets = rng.normal(size=n).astype(np.float32) * 3.0
        labels = (rng.random(n) < 0.5).astype(np.float32)
        data = GlmData(
            features=DenseMatrix(jnp.zeros((n, 1), jnp.float32)),
            labels=jnp.asarray(labels),
            weights=jnp.ones(n, jnp.float32),
            offsets=jnp.asarray(offsets),
        )
        w = jnp.zeros(1, jnp.float32)
        v32 = float(GlmObjective(losses.logistic).raw_value(w, data))
        obj64 = GlmObjective(losses.logistic, accumulate="f64")
        v64 = float(obj64.raw_value(w, data))
        # f64 oracle: numpy f64 sum over the same f32 per-row losses
        oracle = float(np.sum(
            np.asarray(
                losses.logistic.value(
                    jnp.asarray(offsets), jnp.asarray(labels)
                ),
                np.float64,
            )
        ))
        assert abs(v64 - oracle) <= 1e-9 * abs(oracle)
        # and it is at least as close as the f32 reduction
        assert abs(v64 - oracle) <= abs(v32 - oracle) + 1e-12 * abs(oracle)

    def test_f64_fit_matches_f32_fit(self, rng):
        """The precise path changes the value dtype only — the solver must
        land on the same solution, with w still float32 throughout."""
        import numpy as np
        import scipy.sparse as sp
        import jax.numpy as jnp

        from photon_ml_tpu.data.dataset import make_glm_data
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            GlmOptimizationProblem,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        n, d = 600, 25
        X = sp.random(n, d, density=0.2, random_state=1, format="csr",
                      dtype=np.float32)
        y = (np.random.default_rng(1).random(n) < 0.5).astype(np.float32)
        data = make_glm_data(X, y)
        cfg = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=100, tolerance=1e-8),
            regularization=RegularizationContext.l2(),
        )
        res32 = GlmOptimizationProblem("logistic", cfg).solve_single_device(
            data, 1.0
        )
        res64 = GlmOptimizationProblem(
            "logistic", cfg, accumulate="f64"
        ).solve_single_device(data, 1.0)
        assert res64.w.dtype == jnp.float32
        assert res64.value.dtype == jnp.float64
        np.testing.assert_allclose(
            np.asarray(res64.w), np.asarray(res32.w), atol=2e-4
        )

    def test_f64_requires_x64(self):
        from photon_ml_tpu.ops import losses
        from photon_ml_tpu.optim.objective import GlmObjective
        import jax
        import pytest as _pytest

        old = jax.config.jax_enable_x64
        try:
            jax.config.update("jax_enable_x64", False)
            with _pytest.raises(ValueError, match="x64"):
                GlmObjective(losses.logistic, accumulate="f64")
        finally:
            jax.config.update("jax_enable_x64", old)
