"""Live ops plane tests (ISSUE 7): time-series sampler, Prometheus
exporter, HBM accounting, trace-context propagation, flight recorder,
metric-name lint, and the /stats single-source-of-truth dedupe.

The lifecycle bar: every background piece (sampler thread, exporter
server thread) must JOIN on close — including when a chaos fault tears
a streamed pass down mid-flight — and every fault-injection path must
leave a flight-recorder dump whose last event is the fault site.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu import chaos
from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry.exporter import (
    MetricsExporter,
    parse_prometheus_text,
    prometheus_text,
)
from photon_ml_tpu.telemetry.recorder import FlightRecorder
from photon_ml_tpu.telemetry.timeseries import TimeSeriesSampler, read_series


def _get(port, route):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{route}", timeout=10
    ) as resp:
        return resp.status, resp.read().decode()


def _small_stream(n=160, d=10, chunk_rows=40):
    from photon_ml_tpu.data.streaming import make_streaming_glm_data

    rng = np.random.default_rng(11)
    X = sp.random(n, d, density=0.5, random_state=2, format="csr",
                  dtype=np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    return make_streaming_glm_data(X, y, chunk_rows=chunk_rows,
                                   use_pallas=False)


# ---------------------------------------------------------------------------
# Time-series sampler
# ---------------------------------------------------------------------------

class TestTimeSeriesSampler:
    def test_brackets_run_with_monotone_snapshots(self, tmp_path):
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            tel.counter("solver_iterations").inc(3)
            tel.gauge("hbm_live_bytes").set(4096)
            with TimeSeriesSampler(tel, interval_s=0.02) as sampler:
                time.sleep(0.07)
                tel.counter("solver_iterations").inc(4)
            assert not sampler.alive
        series = read_series(str(tmp_path / "metrics_ts.jsonl"))
        assert len(series) >= 2  # start + interval(s) + stop
        seqs = [r["seq"] for r in series]
        monos = [r["t_mono"] for r in series]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        assert all(b > a for a, b in zip(monos, monos[1:]))
        # Counters are cumulative snapshots; the final record equals the
        # end-of-run state and carries the HBM gauge.
        assert series[-1]["counters"]["solver_iterations"] == 7
        assert series[-1]["gauges"]["hbm_live_bytes"] == 4096

    def test_rotation_bounds_disk(self, tmp_path):
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            for i in range(64):
                tel.gauge(f"hbm_g{i}_bytes").set(i)
            sampler = TimeSeriesSampler(
                tel, interval_s=1e9, max_bytes=2048, keep=2
            )
            sampler.start()
            for _ in range(40):
                sampler.sample()
            sampler.stop()
        path = str(tmp_path / "metrics_ts.jsonl")
        assert os.path.exists(path) and os.path.exists(path + ".1")
        assert not os.path.exists(path + ".3")  # keep=2 bounds the set
        # Every retained generation is bounded by max_bytes + one record.
        for p in (path, path + ".1", path + ".2"):
            if os.path.exists(p):
                assert os.path.getsize(p) < 2048 + 4096
        # The live file still parses after rotation.
        assert read_series(path)

    def test_disabled_hub_is_noop(self, tmp_path):
        tel = telemetry.Telemetry(
            output_dir=str(tmp_path / "off"), enabled=False
        )
        sampler = TimeSeriesSampler(tel, interval_s=0.01)
        sampler.start()
        assert not sampler.enabled and not sampler.alive
        sampler.stop()
        assert not os.path.exists(tmp_path / "off" / "metrics_ts.jsonl")


# ---------------------------------------------------------------------------
# Prometheus exporter
# ---------------------------------------------------------------------------

class TestExporter:
    def test_metrics_exposition_parses_and_matches(self, tmp_path):
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            tel.counter("solver_iterations").inc(12)
            tel.gauge("hbm_live_bytes").set(1 << 20)
            tel.gauge("run_note_count").set("not-numeric")  # skipped
            for v in (0.001, 0.002, 0.004):
                tel.histogram("serving_request_latency_seconds").observe(v)
            exporter = MetricsExporter(tel, port=0).start()
            try:
                status, body = _get(exporter.port, "/metrics")
            finally:
                exporter.close()
        assert status == 200
        parsed = parse_prometheus_text(body)
        assert parsed[("solver_iterations", "")] == 12
        assert parsed[("hbm_live_bytes", "")] == float(1 << 20)
        assert ("run_note_count", "") not in parsed
        assert parsed[
            ("serving_request_latency_seconds_count", "")
        ] == 3
        assert parsed[
            ("serving_request_latency_seconds", '{quantile="0.5"}')
        ] == pytest.approx(0.002, rel=0.3)

    def test_snapshot_and_healthz_endpoints(self, tmp_path):
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            tel.counter("solver_iterations").inc(5)
            exporter = MetricsExporter(tel, port=0).start()
            try:
                _, snap_body = _get(exporter.port, "/snapshot")
                _, hz_body = _get(exporter.port, "/healthz")
                status_404, _ = _get_404(exporter.port)
            finally:
                exporter.close()
        snap = json.loads(snap_body)
        assert snap["counters"]["solver_iterations"] == 5
        assert snap["trace"] == tel.trace_id and snap["pid"] == os.getpid()
        assert json.loads(hz_body)["status"] == "ok"
        assert status_404 == 404

    def test_close_joins_thread_no_leak(self, tmp_path):
        before = {t.name for t in threading.enumerate()}
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            plane = telemetry.mount_ops_plane(
                tel, port=0, interval_s=0.01
            )
            assert plane.exporter.alive and plane.sampler.alive
            plane.close()
            plane.close()  # idempotent
            assert not plane.exporter.alive and not plane.sampler.alive
        leaked = {
            t.name for t in threading.enumerate()
            if t.name.startswith("telemetry-")
        } - before
        assert not leaked

    def test_lifecycle_survives_chaos_mid_pass_teardown(self, tmp_path):
        """The ops plane mounted over a streamed pass that a chaos fault
        kills mid-flight: the fault dumps the flight recorder, the pass
        tears down without leaking prefetch threads, and plane.close()
        still joins both ops threads cleanly."""
        import jax.numpy as jnp

        from photon_ml_tpu.optim.streaming import StreamingObjective

        stream = _small_stream()
        sobj = StreamingObjective("logistic", stream)
        w = jnp.zeros((stream.n_features,), jnp.float32)
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            plane = telemetry.mount_ops_plane(tel, port=0, interval_s=0.02)
            try:
                plan = chaos.FaultPlan([
                    chaos.FaultSpec(site="streaming.carry_sync", at=1),
                ])
                with plan:
                    with pytest.raises(chaos.InjectedFault):
                        sobj.value_and_grad(w, 1.0)
                assert tel.counter("prefetch_thread_leak").value == 0
                # The exporter still answers after the fault.
                status, _ = _get(plane.port, "/metrics")
                assert status == 200
            finally:
                plane.close()
            assert not plane.exporter.alive and not plane.sampler.alive
        dump = json.load(open(tmp_path / "flightrecorder.json"))
        assert dump["events"][-1]["name"] == "chaos.fault"
        assert dump["events"][-1]["attrs"]["site"] == "streaming.carry_sync"


def _get_404(port):
    try:
        return _get(port, "/nope")
    except urllib.error.HTTPError as e:
        return e.code, ""


# ---------------------------------------------------------------------------
# Histogram quantiles (satellite: the one estimator behind loadgen/bench)
# ---------------------------------------------------------------------------

class TestHistogramQuantile:
    def test_quantiles_track_percentiles(self):
        rng = np.random.default_rng(7)
        values = rng.lognormal(mean=0.0, sigma=1.5, size=5000)
        h = telemetry.Histogram(threading.Lock())
        for v in values:
            h.observe(v)
        for q in (0.5, 0.9, 0.99):
            exact = float(np.percentile(values, 100 * q))
            assert h.quantile(q) == pytest.approx(exact, rel=0.15)

    def test_edges(self):
        h = telemetry.Histogram(threading.Lock())
        assert h.quantile(0.5) is None
        h.observe(3.0)
        assert h.quantile(0.0) == 3.0 and h.quantile(1.0) == 3.0
        assert h.quantile(0.5) == pytest.approx(3.0, rel=0.3)
        s = h.summary()
        assert s["p50"] is not None and s["p99"] is not None

    def test_loadgen_report_uses_histogram_quantile(self):
        from photon_ml_tpu.serving.loadgen import LoadReport

        lat = np.sort(np.linspace(1.0, 100.0, 400))
        report = LoadReport(
            mode="test", wall_seconds=1.0, completed=400, rejected=0,
            errors=0, latencies_ms=lat,
        )
        snap = report.snapshot()
        assert snap["latency_p50_ms"] == pytest.approx(50.0, rel=0.15)
        assert snap["latency_p99_ms"] == pytest.approx(99.0, rel=0.15)
        assert snap["latency_max_ms"] == pytest.approx(100.0)
        empty = LoadReport(
            mode="test", wall_seconds=1.0, completed=0, rejected=0,
            errors=0, latencies_ms=np.zeros(0),
        )
        assert empty.percentile_ms(50) is None


# ---------------------------------------------------------------------------
# Metric-name lint
# ---------------------------------------------------------------------------

class TestMetricLint:
    def test_registry_rejects_kind_conflicts(self):
        reg = telemetry.MetricsRegistry()
        reg.counter("serving_requests_total")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("serving_requests_total")
        with pytest.raises(ValueError, match="already registered"):
            reg.histogram("serving_requests_total")
        reg.counter("serving_requests_total").inc()  # same kind: fine

    def test_lint_name_convention(self):
        from photon_ml_tpu.telemetry.lint import lint_name

        assert lint_name("hbm_live_bytes") == []
        assert lint_name("serving_request_latency_seconds") == []
        assert lint_name("chaos_faults_injected") == []  # grandfathered
        assert any("subsystem" in i for i in lint_name("bogus_thing_total"))
        assert any("unit" in i for i in lint_name("serving_thing_blobs"))
        assert lint_name("CamelCase") != []

    def test_source_tree_is_clean(self):
        from photon_ml_tpu.telemetry.lint import lint_source

        n_names, problems = lint_source()
        assert n_names > 40  # the scan actually found the registrations
        assert problems == []

    def test_lint_cli(self, capsys):
        from photon_ml_tpu.telemetry.__main__ import lint_metrics

        assert lint_metrics() == 0
        assert "metric lint OK" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Trace-context propagation
# ---------------------------------------------------------------------------

def _read_events(out_dir):
    with open(os.path.join(out_dir, "events.jsonl")) as f:
        return [json.loads(line) for line in f]


class TestTraceContext:
    def test_attach_parents_cross_thread_spans(self, tmp_path):
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            with tel.span("run") as run_span:
                ctx = tel.current_context()
                # (trace_id, span_id, remote_ctx) — remote is None
                # outside an adopted distributed context.
                assert ctx == (tel.trace_id, run_span.span_id, None)

                def worker():
                    with tel.attach(ctx):
                        with tel.span("worker_stage"):
                            tel.event("worker.event")

                t = threading.Thread(target=worker)
                t.start()
                t.join()
        records = _read_events(str(tmp_path))
        spans = {r["name"]: r for r in records if r["type"] == "span"}
        events = {r["name"]: r for r in records if r["type"] == "event"}
        assert spans["worker_stage"]["parent"] == spans["run"]["id"]
        assert events["worker.event"]["parent"] == spans["worker_stage"]["id"]

    def test_prefetch_stage_spans_nest_under_caller(self, tmp_path):
        import jax.numpy as jnp

        from photon_ml_tpu.optim.streaming import StreamingObjective

        stream = _small_stream()
        sobj = StreamingObjective("logistic", stream)
        w = jnp.zeros((stream.n_features,), jnp.float32)
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            with tel.span("solve"):
                sobj.value_and_grad(w, 1.0)
        records = _read_events(str(tmp_path))
        spans = {r["name"]: r for r in records if r["type"] == "span"}
        solve_id = spans["solve"]["id"]
        assert spans["prefetch.pack_stage"]["parent"] == solve_id
        assert spans["prefetch.transfer_stage"]["parent"] == solve_id
        # Different threads, one tree.
        tids = {
            spans[n]["tid"]
            for n in ("solve", "prefetch.pack_stage",
                      "prefetch.transfer_stage")
        }
        assert len(tids) == 3

    def test_serving_batch_span_parents_to_submitter(self, tmp_path):
        from photon_ml_tpu.serving.batcher import BatcherConfig, MicroBatcher
        from photon_ml_tpu.serving.runtime import (
            RuntimeConfig,
            ScoringRuntime,
        )
        from photon_ml_tpu.serving.synthetic import SyntheticWorkload

        workload = SyntheticWorkload(n_entities=16, seed=3)
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            runtime = ScoringRuntime(
                workload.model, workload.index_maps,
                RuntimeConfig(max_batch_size=4, hot_entities=8),
            )
            batcher = MicroBatcher(runtime, BatcherConfig(
                max_batch_size=4, max_wait_us=0, max_queue=16,
            ))
            batcher.start()
            with tel.span("request"):
                fut = batcher.submit(
                    runtime.parse_request(workload.request(0))
                )
                fut.result(timeout=30)
            batcher.stop()
        records = _read_events(str(tmp_path))
        spans = {r["name"]: r for r in records if r["type"] == "span"}
        assert spans["serving.batch"]["parent"] == spans["request"]["id"]
        assert spans["serving.batch"]["tid"] != spans["request"]["tid"]

    def test_wall_anchored_chrome_trace(self, tmp_path):
        """Multi-process merge: trace.json timestamps are wall-anchored
        (microseconds since the epoch), not run-relative."""
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            wall0 = tel._epoch_wall
            with tel.span("run"):
                pass
        trace = json.load(open(tmp_path / "trace.json"))
        xs = [ev for ev in trace if ev.get("ph") == "X"]
        assert xs and all(ev["ts"] >= wall0 * 1e6 for ev in xs)


# ---------------------------------------------------------------------------
# HBM accounting
# ---------------------------------------------------------------------------

class TestHbmAccounting:
    def test_streamed_pass_publishes_hbm_gauges(self):
        import jax.numpy as jnp

        from photon_ml_tpu.optim.streaming import StreamingObjective

        stream = _small_stream()
        sobj = StreamingObjective("logistic", stream, prefetch_depth=2)
        w = jnp.zeros((stream.n_features,), jnp.float32)
        with telemetry.Telemetry(enabled=True, sinks=[]) as tel:
            sobj.value_and_grad(w, 1.0)
            snap = tel.snapshot()
        g = snap["gauges"]
        # All transfers consumed by end of pass: live bytes back to 0,
        # but the pass's peak pinned > 0 and bounded by depth x chunk.
        assert g["hbm_live_bytes"] == 0
        assert g["hbm_live_peak_bytes"] > 0
        chunk_bytes = g["hbm_stream_chunk_bytes"]
        assert chunk_bytes > 0
        assert g["hbm_live_peak_bytes"] <= 2 * chunk_bytes
        # Window residency peak: live + dispatched-unexecuted, in bytes.
        assert g["hbm_stream_window_peak_bytes"] >= g["hbm_live_peak_bytes"]
        assert g["hbm_stream_window_peak_bytes"] <= 4 * chunk_bytes
        assert 0.0 < g["prefetch_ring_occupancy_ratio"] <= 1.0 or (
            g["prefetch_ring_occupancy_ratio"] == 0.0
        )
        assert sobj.transfer_stats.max_live_bytes > 0

    def test_serving_hot_table_bytes_gauge(self):
        from photon_ml_tpu.serving.runtime import (
            RuntimeConfig,
            ScoringRuntime,
        )
        from photon_ml_tpu.serving.synthetic import SyntheticWorkload

        workload = SyntheticWorkload(n_entities=32, seed=5)
        with telemetry.Telemetry(enabled=True, sinks=[]) as tel:
            runtime = ScoringRuntime(
                workload.model, workload.index_maps,
                RuntimeConfig(max_batch_size=4, hot_entities=16),
            )
            rows = [
                runtime.parse_request(workload.request(i)) for i in range(4)
            ]
            runtime.score_rows(rows)
            snap = tel.snapshot()
        expected = sum(
            (c.hot.capacity + 1) * c.hot.dim * 4 for c in runtime.random
        )
        assert expected > 0
        assert snap["gauges"]["hbm_serving_hot_table_bytes"] == expected
        assert runtime.hot_table_bytes == expected
        assert snap["gauges"]["serving_hot_resident_rows"] >= 1


# ---------------------------------------------------------------------------
# Serving /stats dedupe (satellite: single source of truth)
# ---------------------------------------------------------------------------

class TestServingStatsSource:
    def _service(self, workload):
        from photon_ml_tpu.serving.batcher import BatcherConfig
        from photon_ml_tpu.serving.runtime import (
            RuntimeConfig,
            ScoringRuntime,
        )
        from photon_ml_tpu.serving.service import ScoringService

        runtime = ScoringRuntime(
            workload.model, workload.index_maps,
            RuntimeConfig(max_batch_size=4, hot_entities=8),
        )
        return ScoringService(runtime, BatcherConfig(
            max_batch_size=4, max_wait_us=0, max_queue=16,
        ))

    def test_enabled_hub_stats_derive_from_registry(self):
        from photon_ml_tpu.serving.synthetic import SyntheticWorkload

        workload = SyntheticWorkload(n_entities=16, seed=9)
        with telemetry.Telemetry(enabled=True, sinks=[]) as tel:
            service = self._service(workload)
            with service:
                for i in range(5):
                    service.score(workload.request(i))
                stats = service.stats()["batcher"]
            assert stats["source"] == "telemetry"
            assert stats["submitted"] == 5
            assert stats["completed"] == 5
            assert stats["batches"] >= 1
            # Drift is impossible: the internal mirror was never written.
            assert service.batcher._counts["submitted"] == 0
            # The numbers ARE the registry's.
            snap = tel.snapshot()
            assert stats["submitted"] == snap["counters"][
                "serving_requests_total"
            ]

    def test_disabled_hub_keeps_internal_mirror(self):
        from photon_ml_tpu.serving.synthetic import SyntheticWorkload

        workload = SyntheticWorkload(n_entities=16, seed=9)
        assert not telemetry.current().enabled
        service = self._service(workload)
        with service:
            for i in range(3):
                service.score(workload.request(i))
            stats = service.stats()["batcher"]
        assert stats["source"] == "internal"
        assert stats["submitted"] == 3 and stats["completed"] == 3


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_ring_is_bounded(self):
        rec = FlightRecorder(capacity=8)
        for i in range(50):
            rec.emit({"type": "event", "name": f"e{i}", "ts": float(i)})
        events = rec.snapshot()
        assert len(events) == 8
        assert events[0]["name"] == "e42" and events[-1]["name"] == "e49"
        assert rec.records_seen == 50

    def test_serving_batch_fault_dumps_last_events(self, tmp_path):
        """The satellite's required forensics test: an injected
        serving.batch fault leaves flightrecorder.json whose event
        window ENDS at the fault, while the request itself fails
        cleanly through the batcher's classified-error path."""
        from photon_ml_tpu.serving.batcher import BatcherConfig
        from photon_ml_tpu.serving.runtime import (
            RuntimeConfig,
            ScoringRuntime,
        )
        from photon_ml_tpu.serving.service import ScoringService
        from photon_ml_tpu.serving.synthetic import SyntheticWorkload

        workload = SyntheticWorkload(n_entities=16, seed=7)
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            runtime = ScoringRuntime(
                workload.model, workload.index_maps,
                RuntimeConfig(max_batch_size=4, hot_entities=8),
            )
            service = ScoringService(runtime, BatcherConfig(
                max_batch_size=4, max_wait_us=0, max_queue=16,
            ))
            plan = chaos.FaultPlan([
                chaos.FaultSpec(site="serving.batch", at=1),
            ])
            with service, plan:
                ok = service.score(workload.request(0))  # pre-fault traffic
                assert "score" in ok
                fut = service.submit(workload.request(1))
                with pytest.raises(chaos.InjectedFault):
                    fut.result(timeout=10)
            assert tel.counter("chaos_faults_injected").value == 1
        dump = json.load(open(tmp_path / "flightrecorder.json"))
        assert dump["reason"].startswith("chaos:serving.batch")
        events = dump["events"]
        assert events, "flight recorder dumped no events"
        assert events[-1]["name"] == "chaos.fault"
        assert events[-1]["attrs"]["site"] == "serving.batch"
        assert events[-1]["attrs"]["rows"] == 1
        # The pre-fault traffic is in the window too (it's a RECORDER,
        # not just the fault record): the healthy batch span precedes.
        names = [e["name"] for e in events]
        assert "serving.batch" in names
        assert len(events) <= dump["capacity"]

    def test_driver_crash_dump_via_hub_exit(self, tmp_path):
        with pytest.raises(RuntimeError, match="boom"):
            with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
                with tel.span("run"):
                    tel.event("about.to.die", step=3)
                    raise RuntimeError("boom")
        dump = json.load(open(tmp_path / "flightrecorder.json"))
        assert dump["reason"].startswith("crash: RuntimeError: boom")
        names = [e["name"] for e in dump["events"]]
        assert "about.to.die" in names and "run" in names

    def test_watchdog_fatal_dump(self, tmp_path):
        from photon_ml_tpu.utils.watchdog import (
            RetryPolicy,
            run_with_retries,
        )

        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            with tel.span("run"):
                def fn(attempt):
                    raise ValueError("INVALID_ARGUMENT: bad shape")

                with pytest.raises(ValueError):
                    run_with_retries(
                        fn, RetryPolicy(max_retries=2),
                        sleep=lambda s: None,
                    )
            # Dumped at the fatal verdict, not at hub exit.
            dump = json.load(
                open(tmp_path / "flightrecorder.json")
            )
        assert dump["reason"].startswith("watchdog-fatal: ValueError")
        assert dump["events"][-1]["name"] == "watchdog.attempt"
        assert dump["events"][-1]["attrs"]["outcome"] == "non_transient"

    def test_no_dump_on_clean_exit(self, tmp_path):
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            with tel.span("run"):
                pass
        assert not os.path.exists(tmp_path / "flightrecorder.json")


# ---------------------------------------------------------------------------
# The extended module selfcheck ties it all together
# ---------------------------------------------------------------------------

class TestSelfcheck:
    def test_extended_selfcheck_passes(self, tmp_path):
        from photon_ml_tpu.telemetry.__main__ import selfcheck

        keep = str(tmp_path / "sc")
        assert selfcheck(keep) == 0
        # The acceptance artifacts exist and validate.
        assert os.path.exists(os.path.join(keep, "metrics_ts.jsonl"))
        assert os.path.exists(os.path.join(keep, "flightrecorder.json"))

    def test_prometheus_text_round_trip(self):
        snap = {
            "counters": {"serving_requests_total": 7},
            "gauges": {"hbm_live_bytes": 123, "run_name_count": "x"},
            "histograms": {
                "solver_wall_seconds": {
                    "count": 2, "sum": 3.0, "min": 1.0, "max": 2.0,
                    "mean": 1.5, "last": 2.0, "p50": 1.4, "p90": 1.9,
                    "p99": 2.0,
                },
            },
        }
        parsed = parse_prometheus_text(prometheus_text(snap))
        assert parsed[("serving_requests_total", "")] == 7
        assert parsed[("hbm_live_bytes", "")] == 123
        assert parsed[("solver_wall_seconds_sum", "")] == 3.0
        assert parsed[("solver_wall_seconds", '{quantile="0.99"}')] == 2.0
        with pytest.raises(ValueError, match="unparseable"):
            parse_prometheus_text("this is } not exposition format\n")
