"""Cluster control plane tests (ISSUE 19): replicated quota
coordination, service discovery, and publication-based distribution.

The load-bearing contracts:

- a membership record expires after ``heartbeat_ttl_s`` without a beat,
  and beating an expired id CANNOT resurrect it (the registration
  record is gone — the agent must re-register, which it does on its
  own via the ``cluster.heartbeat`` seam's failure path);
- the coordinator leader lease fails over: a killed leader's lease
  expires, the next renewal elects a new term, and the journal replay
  seeds the new leader with the dead leader's outstanding grants so
  the budget invariant survives the handoff;
- a ``cluster.lease`` fault on one replica moves the client's walk to
  the next replica; every replica faulted is the full partition
  (UNAVAILABLE — the lease client degrades, tested one tier down);
- a fetched publication is checksum-verified end to end: a tampered
  artifact byte is refused (``FetchError``), a transient drop on the
  ``cluster.fetch`` seam retries, and nothing half-fetched is ever
  visible at the final cache path;
- retention is blocked by a registered-but-never-acking subscriber,
  the summary NAMES the guilty id, and unregistering it releases the
  prune (the runbook lever).
"""

import http.client
import json
import os
import threading
import time
from types import SimpleNamespace

import pytest

from photon_ml_tpu import chaos
from photon_ml_tpu.cluster import (
    CoordinatorReplica,
    FetchError,
    HeartbeatAgent,
    MembershipRegistry,
    MembershipWatcher,
    NotLeaderError,
    PublicationClient,
    PublicationServer,
    RegistryClient,
    RemoteApplier,
    ReplicatedQuotaCoordinator,
    cold_start,
)
from photon_ml_tpu.freshness.delta import DeltaError
from photon_ml_tpu.freshness.publisher import (
    DeltaPublisher,
    read_acks,
    remove_ack,
    write_ack,
)


class _Clock:
    """Injectable monotonic clock: liveness tests advance time instead
    of sleeping through it."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------------------
# Membership registry
# ---------------------------------------------------------------------------

class TestMembershipRegistry:
    def test_register_heartbeat_and_expiry(self):
        clock = _Clock()
        registry = MembershipRegistry(heartbeat_ttl_s=1.0, clock=clock)
        member = registry.register("h0", "http://a:1/")
        assert member["state"] == "alive"
        assert member["url"] == "http://a:1"  # trailing slash normalized

        # A beat inside the TTL keeps the member alive across what
        # would otherwise be two expiry windows.
        clock.advance(0.9)
        assert registry.heartbeat("h0") is True
        clock.advance(0.9)
        assert "h0" in registry.members()

        # Silence past the TTL expires it, and beating the expired id
        # returns False — the registration record is gone.
        clock.advance(1.1)
        assert registry.members() == {}
        assert registry.heartbeat("h0") is False

        # Re-registering re-admits (the agent's healing path).
        registry.register("h0", "http://a:1")
        assert registry.members()["h0"]["state"] == "alive"

    def test_drain_keeps_member_visible_and_leave_removes(self):
        clock = _Clock()
        registry = MembershipRegistry(heartbeat_ttl_s=5.0, clock=clock)
        registry.register("h0", "http://a:1")
        assert registry.drain("h0") is True
        # Draining stays visible: the router needs to see it to finish
        # its in-flight work before removal.
        assert registry.members()["h0"]["state"] == "draining"
        assert registry.drain("nope") is False

        assert registry.leave("h0") is True
        assert registry.members() == {}
        assert registry.leave("h0") is False

    def test_heartbeat_cannot_resurrect_a_draining_member_as_alive(self):
        clock = _Clock()
        registry = MembershipRegistry(heartbeat_ttl_s=5.0, clock=clock)
        registry.register("h0", "http://a:1")
        registry.drain("h0")
        assert registry.heartbeat("h0") is True  # still a member...
        assert registry.members()["h0"]["state"] == "draining"  # ...but


class TestRegistryHTTP:
    def test_protocol_roundtrip_over_the_wire(self):
        registry = MembershipRegistry(heartbeat_ttl_s=5.0).serve()
        try:
            client = RegistryClient(registry.base_url)
            member = client.register(
                "h0", "http://a:1", metrics_url="http://a:2"
            )
            assert member["host_id"] == "h0"
            assert member["metrics_url"] == "http://a:2"
            assert set(client.members()) == {"h0"}
            assert client.heartbeat("h0") is True
            # Unknown id rides the 410 Gone contract back as False —
            # the verdict the HeartbeatAgent re-registers on.
            assert client.heartbeat("ghost") is False
            assert client.drain("ghost") is False
            assert client.drain("h0") is True
            assert client.members()["h0"]["state"] == "draining"
            assert client.leave("h0") is True
            assert client.members() == {}
        finally:
            registry.close()


class TestHeartbeatAgent:
    def test_register_then_beat_then_heal_after_expiry(self):
        clock = _Clock()
        registry = MembershipRegistry(heartbeat_ttl_s=1.0, clock=clock)
        agent = HeartbeatAgent(
            registry, "h0", "http://a:1", interval_s=0.5
        )
        assert agent.beat_once() is True  # registers
        assert agent.beat_once() is True  # beats
        assert agent.beats == 1

        # Expire the member (a stall longer than the TTL), then watch
        # the agent heal: one False beat flips it back to registering,
        # the next cycle re-admits the host.
        clock.advance(1.5)
        assert agent.beat_once() is False
        assert agent.reregisters == 1
        assert agent.beat_once() is True
        assert registry.members()["h0"]["state"] == "alive"

    def test_chaos_heartbeat_site_counts_failure_then_recovers(self):
        registry = MembershipRegistry(heartbeat_ttl_s=5.0)
        agent = HeartbeatAgent(
            registry, "h0", "http://a:1", interval_s=0.5
        )
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="cluster.heartbeat", at=0, count=1),
        ])
        with plan:
            assert agent.beat_once() is False
            assert agent.beat_failures == 1
            # A lost beat is not fatal: the next cycle registers.
            assert agent.beat_once() is True
        assert plan.fired and plan.fired[0]["site"] == "cluster.heartbeat"
        assert "h0" in registry.members()


class _FakeRouter:
    """Records join/drain calls; mimics FleetRouter.healthz()'s host
    rows (url / hid / state)."""

    def __init__(self):
        self.hosts = {}  # url -> [hid, state]
        self.joins = []
        self.drains = []
        self._n = 0

    def healthz(self):
        return {"hosts": [
            {"url": url, "hid": hid, "state": state}
            for url, (hid, state) in self.hosts.items()
        ]}

    def join(self, url):
        self.joins.append(url)
        hid = f"host{self._n}"
        self._n += 1
        self.hosts[url] = [hid, "healthy"]
        return hid

    def drain(self, hid, timeout_s=None):
        self.drains.append(hid)
        for entry in self.hosts.values():
            if entry[0] == hid:
                entry[1] = "removed"
        return True


class _FakeAggregator:
    def __init__(self):
        self.synced = []

    def sync_membership(self, hosts):
        self.synced.append(dict(hosts))


class TestMembershipWatcher:
    def test_converges_router_and_aggregator_to_membership(self):
        registry = MembershipRegistry(heartbeat_ttl_s=60.0)
        router, aggregator = _FakeRouter(), _FakeAggregator()
        watcher = MembershipWatcher(registry, router, aggregator)

        registry.register("h0", "http://a:1", metrics_url="http://a:2")
        assert watcher.poll_once() is True
        assert router.joins == ["http://a:1"]
        # The aggregator sees metrics_url, not the serving url.
        assert aggregator.synced[-1] == {"h0": "http://a:2"}

        # Draining in the registry drains the router; the member stays
        # in the aggregator view (departure needs leave/expiry).
        registry.drain("h0")
        watcher.poll_once()
        assert router.drains == ["host0"]
        assert "h0" in aggregator.synced[-1]

        # A removed routed entry re-joins when the host comes back.
        registry.leave("h0")
        watcher.poll_once()
        assert "h0" not in aggregator.synced[-1]
        registry.register("h0", "http://a:1")
        watcher.poll_once()
        assert router.joins == ["http://a:1", "http://a:1"]

    def test_registry_outage_keeps_last_converged_state(self):
        # Nothing listens on this port: the read fails fast, and the
        # watcher must keep the last converged state, not drain anyone.
        router = _FakeRouter()
        router.join("http://a:1")
        watcher = MembershipWatcher(
            RegistryClient("http://127.0.0.1:1", timeout_s=0.2), router
        )
        assert watcher.poll_once() is False
        assert watcher.poll_failures == 1
        assert router.drains == []


# ---------------------------------------------------------------------------
# Replicated quota coordination
# ---------------------------------------------------------------------------

def _replica_pair(tmp_path, clock, lease_ttl_s=10.0, leader_ttl_s=1.0):
    store = str(tmp_path / "coord")
    budgets = {"t": 100.0}
    r0 = CoordinatorReplica(
        "r0", store, budgets, lease_ttl_s=lease_ttl_s,
        leader_ttl_s=leader_ttl_s, clock=clock, fsync=False,
    )
    r1 = CoordinatorReplica(
        "r1", store, budgets, lease_ttl_s=lease_ttl_s,
        leader_ttl_s=leader_ttl_s, clock=clock, fsync=False,
    )
    return r0, r1, ReplicatedQuotaCoordinator([r0, r1])


class TestReplicatedCoordination:
    def test_first_renew_elects_and_followers_refuse_with_hint(
        self, tmp_path
    ):
        clock = _Clock()
        r0, r1, rc = _replica_pair(tmp_path, clock)
        leases = rc.renew("hA", {"t": 50.0})
        assert leases["t"].rate_rps > 0
        assert rc.leader() == "r0"
        assert r0.term == 1 and r0.is_leader()
        with pytest.raises(NotLeaderError) as exc:
            r1.renew("hA", {"t": 50.0})
        assert exc.value.leader_hint == "r0"

    def test_kill_fails_over_and_replay_preserves_budget_bound(
        self, tmp_path
    ):
        clock = _Clock()
        r0, r1, rc = _replica_pair(tmp_path, clock)
        a = rc.renew("hA", {"t": 100.0})["t"]
        b = rc.renew("hB", {"t": 100.0})["t"]
        assert a.rate_rps + b.rate_rps <= 100.0 + 1e-6

        # Kill the leader.  Its lease is deliberately not released, so
        # failover must ride the lease expiry.
        r0.kill()
        clock.advance(1.5)  # > leader_ttl_s, << lease_ttl_s
        a2 = rc.renew("hA", {"t": 100.0})["t"]
        assert rc.leader() == "r1"
        assert rc.failovers == 1
        assert r1.term == 2

        # hB's grant was replayed from the journal: it is still live
        # (its lease has not expired), so the new leader's grant to hA
        # must leave room for it — the invariant survives the handoff.
        b2 = rc.renew("hB", {"t": 100.0})["t"]
        assert a2.rate_rps + b2.rate_rps <= 100.0 + 1e-6
        records = r1._read_journal()
        election = [
            r for r in records
            if r.get("kind") == "election" and r["term"] == 2
        ]
        assert election and election[0]["replayed_grants"] == 2

        # A restarted replica comes back as a follower, never resumes
        # its stale term.
        r0.restart()
        with pytest.raises(NotLeaderError):
            r0.renew("hA", {"t": 100.0})

    def test_torn_journal_tail_is_tolerated_on_replay(self, tmp_path):
        clock = _Clock()
        r0, r1, rc = _replica_pair(tmp_path, clock)
        rc.renew("hA", {"t": 100.0})
        # Simulate the journal writer dying mid-line.
        with open(r0._journal_path, "a") as f:
            f.write('{"kind": "gra')
        r0.kill()
        clock.advance(1.5)
        leases = rc.renew("hA", {"t": 100.0})
        assert leases["t"].rate_rps > 0
        assert r1.term == 2

    def test_chaos_lease_site_moves_the_walk_to_the_next_replica(
        self, tmp_path
    ):
        clock = _Clock()
        r0, r1, rc = _replica_pair(tmp_path, clock)
        rc.renew("hA", {"t": 100.0})
        # Expire the leader lease so the surviving replica CAN take
        # over when the fault knocks out the path to r0.
        clock.advance(1.5)
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="cluster.lease", at=0, count=1),
        ])
        with plan:
            leases = rc.renew("hA", {"t": 100.0})
        assert plan.fired and plan.fired[0]["site"] == "cluster.lease"
        assert leases["t"].rate_rps > 0
        assert rc.leader() == "r1"
        assert rc.failovers == 1

    def test_every_replica_faulted_is_the_full_partition(self, tmp_path):
        clock = _Clock()
        _r0, _r1, rc = _replica_pair(tmp_path, clock)
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="cluster.lease", at=0, count=10),
        ])
        with plan, pytest.raises(RuntimeError, match="UNAVAILABLE"):
            rc.renew("hA", {"t": 100.0})


# ---------------------------------------------------------------------------
# Publication-based model distribution
# ---------------------------------------------------------------------------

def _model_dir(tmp_path) -> str:
    model = tmp_path / "model"
    (model / "sub").mkdir(parents=True)
    (model / "weights.bin").write_bytes(b"\x00\x01\x02" * 100)
    (model / "meta.json").write_text('{"kind": "test-model"}')
    (model / "sub" / "nested.bin").write_bytes(b"nested-bytes")
    return str(model)


def _read_tree(root: str) -> dict:
    out = {}
    for dirpath, _dirs, files in os.walk(root):
        for name in files:
            full = os.path.join(dirpath, name)
            with open(full, "rb") as f:
                out[os.path.relpath(full, root)] = f.read()
    return out


@pytest.fixture()
def pub_root(tmp_path):
    root = str(tmp_path / "pubroot")
    publisher = DeltaPublisher(root, fsync=False)
    publisher.publish_snapshot(_model_dir(tmp_path))
    return root, publisher


@pytest.fixture()
def pub_server(pub_root):
    root, publisher = pub_root
    server = PublicationServer(root).serve()
    yield root, publisher, server
    server.close()


class TestDistribution:
    def test_fetch_is_bitwise_faithful_and_idempotent(
        self, pub_server, tmp_path
    ):
        root, _publisher, server = pub_server
        client = PublicationClient(
            server.base_url, str(tmp_path / "cache")
        )
        pubs = client.publications()
        assert [p.kind for p in pubs] == ["snapshot"]
        local = client.fetch(pubs[0])
        served = _read_tree(local)
        original = {
            k: v for k, v in _read_tree(
                os.path.join(root, f"snapshot-{pubs[0].seq:06d}")
            ).items()
        }
        assert served == original  # manifest rides along, byte-equal
        # Second fetch returns the cached dir without touching the
        # wire: the atomic rename is the completeness marker.
        assert client.fetch(pubs[0]) == local
        assert client.fetches == 1

    def test_chaos_fetch_site_retries_then_exhausts(
        self, pub_server, tmp_path
    ):
        _root, _publisher, server = pub_server
        client = PublicationClient(
            server.base_url, str(tmp_path / "cache-a")
        )
        pub = client.publications()[0]
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="cluster.fetch", at=0, count=1),
        ])
        with plan:
            local = client.fetch(pub)
        assert plan.fired and plan.fired[0]["site"] == "cluster.fetch"
        assert os.path.isdir(local)
        assert client.fetch_retries == 1

        # Exhausted retries refuse the artifact, and nothing
        # half-fetched is visible at the final path.
        client2 = PublicationClient(
            server.base_url, str(tmp_path / "cache-b"), retries=1,
        )
        with chaos.FaultPlan([
            chaos.FaultSpec(site="cluster.fetch", at=0, count=50),
        ]):
            with pytest.raises(FetchError, match="attempts"):
                client2.fetch(pub)
        assert not os.path.isdir(client2._local_dir(pub))

    def test_tampered_artifact_is_refused(self, pub_server, tmp_path):
        root, _publisher, server = pub_server
        client = PublicationClient(
            server.base_url, str(tmp_path / "cache")
        )
        pub = client.publications()[0]
        victim = os.path.join(
            root, f"snapshot-{pub.seq:06d}", "model", "weights.bin"
        )
        with open(victim, "r+b") as f:
            f.write(b"\xff")
        with pytest.raises(FetchError, match="sha256 mismatch"):
            client.fetch(pub)
        assert not os.path.isdir(client._local_dir(pub))

    def test_blob_route_refuses_path_traversal(self, pub_server):
        _root, _publisher, server = pub_server
        host, port = server.base_url[len("http://"):].split(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=5)
        try:
            # Raw http.client request: urllib would normalize the
            # "../" away before it ever reached the server.
            conn.request("GET", "/blob/1/../publish_journal.jsonl")
            resp = conn.getresponse()
            body = json.loads(resp.read())
            assert resp.status == 403
            assert "escapes" in body["error"]
        finally:
            conn.close()

    def test_cold_start_fetches_newest_snapshot_and_acks(
        self, pub_server, tmp_path
    ):
        root, _publisher, server = pub_server
        client = PublicationClient(
            server.base_url, str(tmp_path / "cache")
        )
        model_dir, pub = cold_start(client, subscriber_id="cold1")
        assert pub.kind == "snapshot"
        assert os.path.basename(model_dir) == "model"
        with open(os.path.join(model_dir, "meta.json")) as f:
            assert json.load(f)["kind"] == "test-model"
        # The ack registers the host with retention at the snapshot
        # seq, so every later delta is held until applied.
        assert read_acks(root)["cold1"] == pub.seq

    def test_cold_start_without_snapshot_is_a_pointed_error(
        self, tmp_path
    ):
        root = str(tmp_path / "empty-root")
        DeltaPublisher(root, fsync=False)  # settles an empty root
        server = PublicationServer(root).serve()
        try:
            client = PublicationClient(
                server.base_url, str(tmp_path / "cache")
            )
            with pytest.raises(DeltaError, match="publish_snapshot"):
                cold_start(client)
        finally:
            server.close()

    def test_remote_applier_applies_in_order_and_never_retries(
        self, pub_server, tmp_path
    ):
        root, publisher, server = pub_server
        publisher.publish_snapshot(
            os.path.join(root, "snapshot-000001", "model")
        )
        client = PublicationClient(
            server.base_url, str(tmp_path / "cache")
        )
        service = SimpleNamespace(reloads=[])

        def reload(path, mode=None):
            service.reloads.append((os.path.basename(path), mode))
            return SimpleNamespace(
                status="swapped", stage=None, reason=None
            )

        service.reload = reload
        applier = RemoteApplier(service, client, "subA", start_seq=0)
        results = applier.poll_once()
        assert [r.status for r in results] == ["swapped", "swapped"]
        assert applier.applied_seq == 2
        assert service.reloads == [("model", None), ("model", None)]
        assert read_acks(root)["subA"] == 2

        # A failed apply is recorded once and NEVER retried — the
        # runbook escalates to a fresh cold start instead.
        publisher.publish_snapshot(
            os.path.join(root, "snapshot-000001", "model")
        )
        service.reload = lambda path, mode=None: SimpleNamespace(
            status="rolled_back", stage="validate", reason="boom"
        )
        applier.poll_once()
        assert applier.failed == [3]
        assert applier.poll_once() == []
        assert applier.failed == [3]


# ---------------------------------------------------------------------------
# Retention vs. remote subscribers (satellite: the never-acking host)
# ---------------------------------------------------------------------------

class TestRetentionBlockedBySubscriber:
    def test_blocking_names_the_guilty_id_and_unregister_releases(
        self, tmp_path
    ):
        root = str(tmp_path / "root")
        publisher = DeltaPublisher(root, fsync=False)
        model = _model_dir(tmp_path)
        for _ in range(3):
            publisher.publish_snapshot(model)  # seqs 1, 2, 3

        # A subscriber registered at seq 1 and then went silent: seq 1
        # prunes (it acked it), seq 2 is held — and the summary NAMES
        # the holder, so the operator knows exactly who to chase.
        write_ack(root, "laggard", 1)
        summary = publisher.retain(keep_last=1)
        assert summary["pruned"] == [1]
        assert summary["blocked"] == [2]
        assert summary["blocking"] == {2: ["laggard"]}
        assert os.path.isdir(os.path.join(root, "snapshot-000002"))

        # Unregistering the dead subscriber releases the prune.
        assert remove_ack(root, "laggard") is True
        summary = publisher.retain(keep_last=1)
        assert summary["pruned"] == [2]
        assert summary["blocked"] == []
        assert summary["kept"] == [3]
        assert not os.path.isdir(os.path.join(root, "snapshot-000002"))
