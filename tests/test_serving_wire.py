"""Million-QPS data plane tests (ISSUE 16).

The load-bearing contracts:

- The binary wire codec round-trips requests and responses exactly —
  scores that cross the wire as frames are BITWISE identical to the
  JSON path, because both carry float64 end to end.
- Every malformed frame refuses loudly before anything trusts a length
  field: truncated, bad magic, unknown version, forged lengths beyond
  the 256 MB cap, unknown dtype tags — mirroring the frame-cap
  discipline of serving/protocol.py.
- The fused scoring kernel produces bit-identical margins/means to the
  composed kernels across the whole bucket ladder and hot/cold states.
- The adaptive micro-batcher sizes its wait from the arrival EWMA,
  bounded by the SLO fraction, and BatcherConfig refuses bad knobs
  with errors that name the field.
- Worker IPC (protocol.py), the shm ingress ring, and the fleet
  router's binary mode all ride the same codec and agree with JSON.
"""

import json
import socket
import struct
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from photon_ml_tpu.serving import wire
from photon_ml_tpu.serving.batcher import (
    BatcherConfig,
    DeadlineExceededError,
    MicroBatcher,
    RejectedError,
)
from photon_ml_tpu.serving.protocol import (
    FrameConn,
    ProtocolError,
    _encode_payload,
)
from photon_ml_tpu.serving.runtime import Row, RuntimeConfig, ScoringRuntime
from photon_ml_tpu.serving.service import ScoringService, start_http_server
from photon_ml_tpu.serving.shm_ingress import (
    ShmIngress,
    ShmIngressClient,
    ShmIngressError,
)
from photon_ml_tpu.serving.synthetic import SyntheticWorkload


@pytest.fixture(scope="module")
def workload():
    return SyntheticWorkload(n_entities=32, seed=7, unknown_rate=0.1)


def _runtime(workload, **kwargs):
    cfg = RuntimeConfig(**{"max_batch_size": 4, "hot_entities": 8, **kwargs})
    return ScoringRuntime(workload.model, workload.index_maps, cfg)


def _requests(workload, n, start=0):
    return [workload.request(i) for i in range(start, start + n)]


# ---------------------------------------------------------------------------
# Container codec
# ---------------------------------------------------------------------------

class TestColumnCodec:
    def test_round_trips_every_wire_dtype(self):
        from photon_ml_tpu.data.staging import WIRE_DTYPE_TAGS
        rng = np.random.default_rng(11)
        columns = {}
        for dt in WIRE_DTYPE_TAGS:
            dt = np.dtype(dt)
            if dt == np.bool_:
                columns[f"c_{dt.name}"] = rng.random(7) > 0.5
            elif dt.kind == "f":
                columns[f"c_{dt.name}"] = rng.normal(size=7).astype(dt)
            else:
                columns[f"c_{dt.name}"] = rng.integers(
                    0, 100, size=7
                ).astype(dt)
        columns["mat"] = rng.normal(size=(7, 3)).astype(np.float32)
        buf = wire.encode_columns(columns, wire.KIND_REQUEST, 7)
        kind, n, out = wire.decode_columns(buf)
        assert (kind, n) == (wire.KIND_REQUEST, 7)
        assert list(out) == list(columns)  # insertion order preserved
        for name, arr in columns.items():
            assert out[name].dtype == arr.dtype, name
            assert np.array_equal(out[name], arr), name

    def test_fuzz_random_shapes_round_trip(self):
        rng = np.random.default_rng(23)
        for trial in range(50):
            n = int(rng.integers(1, 40))
            columns = {}
            for c in range(int(rng.integers(1, 6))):
                if rng.random() < 0.5:
                    columns[f"v{c}"] = rng.normal(size=n).astype(
                        rng.choice([np.float32, np.float64])
                    )
                else:
                    columns[f"m{c}"] = rng.normal(
                        size=(n, int(rng.integers(1, 9)))
                    ).astype(np.float32)
            buf = wire.encode_columns(columns, wire.KIND_RESPONSE, n)
            kind, n2, out = wire.decode_columns(buf)
            assert n2 == n
            for name, arr in columns.items():
                assert arr.tobytes() == np.ascontiguousarray(
                    out[name]
                ).tobytes(), f"trial {trial} column {name}"

    def test_decode_is_zero_copy(self):
        arr = np.arange(32, dtype=np.float32)
        buf = wire.encode_columns({"x": arr}, wire.KIND_REQUEST, 32)
        _, _, out = wire.decode_columns(buf)
        assert out["x"].base is not None  # a view, not a copy

    def test_refuses_truncated_header(self):
        with pytest.raises(wire.WireFormatError, match="truncated"):
            wire.decode_columns(b"PHW")

    def test_refuses_bad_magic(self):
        buf = bytearray(
            wire.encode_columns(
                {"x": np.zeros(1, np.float32)}, wire.KIND_REQUEST, 1
            )
        )
        buf[:4] = b"EVIL"
        with pytest.raises(wire.WireFormatError, match="magic"):
            wire.decode_columns(bytes(buf))

    def test_refuses_unknown_version(self):
        buf = bytearray(
            wire.encode_columns(
                {"x": np.zeros(1, np.float32)}, wire.KIND_REQUEST, 1
            )
        )
        struct.pack_into("<H", buf, 4, 99)
        with pytest.raises(wire.WireFormatError, match="version 99"):
            wire.decode_columns(bytes(buf))

    def test_refuses_forged_lengths_beyond_cap(self):
        # A 24-byte header claiming a 300 MB payload: the decoder must
        # refuse on the cap BEFORE attempting any allocation — same
        # discipline as protocol.py's MAX_FRAME_BYTES.
        header = struct.pack(
            "<4sHBBHHIII", b"PHWF", wire.WIRE_VERSION, 1, 0, 0, 0,
            1, 0, 300 << 20,
        )
        with pytest.raises(wire.WireFormatError, match="forged"):
            wire.decode_columns(header)

    def test_refuses_total_length_mismatch(self):
        buf = wire.encode_columns(
            {"x": np.zeros(4, np.float32)}, wire.KIND_REQUEST, 4
        )
        with pytest.raises(wire.WireFormatError, match="length mismatch"):
            wire.decode_columns(buf + b"extra")
        with pytest.raises(wire.WireFormatError, match="length mismatch"):
            wire.decode_columns(buf[:-1])

    def test_refuses_unknown_dtype_tag(self):
        buf = bytearray(
            wire.encode_columns(
                {"x": np.zeros(2, np.float32)}, wire.KIND_REQUEST, 2
            )
        )
        # dtype tag is byte 6 of the directory entry, after the header.
        struct.pack_into("<B", buf, 24 + 6, 250)
        with pytest.raises(wire.WireFormatError, match="dtype tag"):
            wire.decode_columns(bytes(buf))

    def test_refuses_column_payload_overrun(self):
        buf = bytearray(
            wire.encode_columns(
                {"x": np.zeros(2, np.float32)}, wire.KIND_REQUEST, 2
            )
        )
        # Forge the column's row count so its payload range overruns.
        struct.pack_into("<I", buf, 24 + 8, 1 << 20)
        with pytest.raises(wire.WireFormatError, match="payload range"):
            wire.decode_columns(bytes(buf))


# ---------------------------------------------------------------------------
# Request / response layers
# ---------------------------------------------------------------------------

class TestRequestResponseFrames:
    def test_request_round_trip_matches_json_parser(self, workload):
        runtime = _runtime(workload)
        reqs = _requests(workload, 8)
        frame = wire.encode_request(reqs)
        rows = wire.decode_request(frame, runtime._parser)
        for row, req in zip(rows, reqs):
            ref = runtime.parse_request(req)
            assert row.offset == ref.offset
            assert row.timeout_ms == ref.timeout_ms
            assert row.priority == ref.priority
            assert row.ids == ref.ids
            assert set(row.features) == set(ref.features)
            for shard in ref.features:
                assert np.asarray(row.features[shard]).tobytes() == \
                    np.asarray(ref.features[shard]).tobytes()

    def test_refuses_named_sparse_features(self):
        with pytest.raises(ValueError, match="JSON path"):
            wire.encode_request([
                {"features": {"g": [["a", "", 1.0]]}}
            ])

    def test_refuses_unknown_shard_like_json(self, workload):
        runtime = _runtime(workload)
        frame = wire.encode_request([{"dense": {"nope": [1.0, 2.0]}}])
        with pytest.raises(wire.WireFormatError, match="unknown feature"):
            wire.decode_request(frame, runtime._parser)

    def test_refuses_wrong_shard_width_like_json(self, workload):
        runtime = _runtime(workload)
        shard = workload.fixed_shard
        frame = wire.encode_request([{"dense": {shard: [1.0, 2.0, 3.0]}}])
        with pytest.raises(wire.WireFormatError, match="features"):
            wire.decode_request(frame, runtime._parser)

    def test_response_round_trip_is_exact(self):
        results = [
            {"score": 0.1234567890123456789, "mean": 0.5,
             "latency_ms": 1.875},
            {"error": "queue full; shedding", "kind": "rejected"},
            {"error": "past deadline", "kind": "deadline"},
            None,
        ]
        out = wire.decode_response(wire.encode_response(results))
        assert out[0] == results[0]  # bitwise float64 equality
        assert out[1] == results[1]
        assert out[2] == results[2]
        assert out[3]["kind"] == "internal"

    def test_priority_and_tenant_round_trip(self, workload):
        req = dict(workload.request(0))
        req.update(priority="high", tenant="acme", timeout_ms=125.5)
        rows = wire.decode_request(wire.encode_request([req]))
        assert rows[0].priority == "high"
        assert rows[0].tenant == "acme"
        assert rows[0].timeout_ms == 125.5


# ---------------------------------------------------------------------------
# Fused scoring kernel
# ---------------------------------------------------------------------------

class TestFusedKernel:
    def test_fused_bit_identical_to_composed_all_buckets(self, workload):
        fused = _runtime(workload, fused=True)
        composed = _runtime(workload, fused=False)
        rows_f = [
            fused.parse_request(workload.request(i)) for i in range(4)
        ]
        rows_c = [
            composed.parse_request(workload.request(i)) for i in range(4)
        ]
        for n in range(1, 5):  # bucket ladder 1, 2, 4
            mf, ef = fused.score_rows(rows_f[:n])
            mc, ec = composed.score_rows(rows_c[:n])
            assert mf.tobytes() == mc.tobytes(), f"margins differ at n={n}"
            assert ef.tobytes() == ec.tobytes(), f"means differ at n={n}"

    def test_fused_parity_survives_hot_promotion(self, workload):
        # Score the same entities repeatedly so they promote into the
        # hot table, then confirm parity again: the fused gather path
        # must agree with the composed one in BOTH hot and cold states.
        fused = _runtime(workload, fused=True)
        composed = _runtime(workload, fused=False)
        for _ in range(3):
            rows_f = [
                fused.parse_request(workload.request(i)) for i in range(4)
            ]
            rows_c = [
                composed.parse_request(workload.request(i))
                for i in range(4)
            ]
            mf, ef = fused.score_rows(rows_f)
            mc, ec = composed.score_rows(rows_c)
            assert mf.tobytes() == mc.tobytes()
            assert ef.tobytes() == ec.tobytes()


# ---------------------------------------------------------------------------
# Adaptive micro-batching + config validation
# ---------------------------------------------------------------------------

class TestBatcherConfigValidation:
    @pytest.mark.parametrize("kwargs,field", [
        ({"max_batch_size": 0}, "max_batch_size"),
        ({"max_wait_us": -1}, "max_wait_us"),
        ({"max_queue": 0}, "max_queue"),
        ({"shed_watermark": 0.9, "reject_watermark": 0.5},
         "shed_watermark"),
        ({"shed_watermark": 0.0}, "shed_watermark"),
        ({"default_timeout_ms": 0}, "default_timeout_ms"),
        ({"p99_slo_ms": -5}, "p99_slo_ms"),
        ({"admission_interval_s": -0.1}, "admission_interval_s"),
        ({"min_wait_us": -1}, "min_wait_us"),
        ({"wait_ewma_alpha": 0.0}, "wait_ewma_alpha"),
        ({"wait_ewma_alpha": 1.5}, "wait_ewma_alpha"),
        ({"slo_wait_fraction": 0.0}, "slo_wait_fraction"),
        ({"slo_wait_fraction": 2.0}, "slo_wait_fraction"),
    ])
    def test_bad_knob_names_the_field(self, kwargs, field):
        with pytest.raises(ValueError, match=field):
            BatcherConfig(**kwargs)

    def test_valid_config_constructs(self):
        BatcherConfig(
            adaptive_wait=True, min_wait_us=50, wait_ewma_alpha=0.5,
            slo_wait_fraction=0.1,
        )


class TestAdaptiveWait:
    def _batcher(self, workload, **cfg_kwargs):
        runtime = _runtime(workload, max_batch_size=8)
        cfg = BatcherConfig(**{
            "max_batch_size": 8, "max_wait_us": 2000, "max_queue": 64,
            "adaptive_wait": True, **cfg_kwargs,
        })
        return MicroBatcher(runtime, cfg)

    def test_static_mode_returns_ceiling(self, workload):
        runtime = _runtime(workload)
        b = MicroBatcher(runtime, BatcherConfig(max_wait_us=1500))
        assert b._wait_budget_s() == pytest.approx(1.5e-3)

    def test_dense_traffic_waits_to_fill(self, workload):
        b = self._batcher(workload)
        b._arrival_ewma_s = 50e-6  # 20k rps: fill = 350 µs < ceiling
        assert b._wait_budget_s() == pytest.approx(50e-6 * 7)

    def test_sparse_traffic_drops_to_floor(self, workload):
        b = self._batcher(workload, min_wait_us=100)
        b._arrival_ewma_s = 0.050  # 20 rps: fill ≫ ceiling → floor
        assert b._wait_budget_s() == pytest.approx(100e-6)

    def test_slo_fraction_caps_the_wait(self, workload):
        b = self._batcher(
            workload, p99_slo_ms=2.0, slo_wait_fraction=0.25,
            max_wait_us=100_000,
        )
        b._arrival_ewma_s = 10e-3  # fill = 70 ms, ceiling 100 ms
        # cap = 0.25 × 2 ms = 500 µs
        assert b._wait_budget_s() == pytest.approx(500e-6)

    def test_cold_start_uses_ceiling(self, workload):
        b = self._batcher(workload, max_wait_us=800)
        assert b._arrival_ewma_s is None
        assert b._wait_budget_s() == pytest.approx(800e-6)

    def test_submit_updates_ewma_and_stats(self, workload):
        b = self._batcher(workload)
        b.start()
        try:
            for i in range(6):
                b.submit(
                    b.runtime.parse_request(workload.request(i))
                ).result(timeout=30)
            stats = b.stats()
            assert stats["adaptive_wait"] is True
            assert "arrival_ewma_ms" in stats
            assert b._arrival_ewma_s is not None
        finally:
            b.stop()

    def test_adaptive_scores_match_static(self, workload):
        runtime = _runtime(workload)
        static = MicroBatcher(runtime, BatcherConfig(max_batch_size=8))
        static.start()
        try:
            ref = [
                static.submit(
                    runtime.parse_request(workload.request(i))
                ).result(timeout=30)["score"]
                for i in range(6)
            ]
        finally:
            static.stop()
        runtime2 = _runtime(workload)
        adaptive = MicroBatcher(runtime2, BatcherConfig(
            max_batch_size=8, adaptive_wait=True,
        ))
        adaptive.start()
        try:
            got = [
                adaptive.submit(
                    runtime2.parse_request(workload.request(i))
                ).result(timeout=30)["score"]
                for i in range(6)
            ]
        finally:
            adaptive.stop()
        assert got == ref  # batching policy never changes the math


# ---------------------------------------------------------------------------
# HTTP data plane: JSON vs binary
# ---------------------------------------------------------------------------

class _Http:
    def __init__(self, workload, **runtime_kwargs):
        self.runtime = _runtime(workload, **runtime_kwargs)
        self.service = ScoringService(self.runtime)
        self.service.start()
        self.server, _ = start_http_server(self.service, port=0)
        self.base = f"http://127.0.0.1:{self.server.server_address[1]}"

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.server.shutdown()
        self.server.server_close()
        self.service.stop()
        return False

    def post(self, path, body, headers):
        req = urllib.request.Request(
            self.base + path, data=body, headers=headers
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.headers.get("Content-Type"), resp.read()


class TestHttpBinaryPath:
    def test_binary_scores_bitwise_match_json(self, workload):
        with _Http(workload) as http:
            reqs = _requests(workload, 8)
            _, raw = http.post(
                "/score", json.dumps({"rows": reqs}).encode(),
                {"Content-Type": "application/json"},
            )
            via_json = json.loads(raw)["results"]
            ctype, raw = http.post(
                "/score", wire.encode_request(reqs),
                {"Content-Type": wire.CONTENT_TYPE},
            )
            assert ctype == wire.CONTENT_TYPE
            via_bin = wire.decode_response(raw)
            assert len(via_bin) == len(via_json) == 8
            for b, j in zip(via_bin, via_json):
                assert b["score"] == j["score"]
                assert b["mean"] == j["mean"]

    def test_accept_json_falls_back_to_json_response(self, workload):
        with _Http(workload) as http:
            ctype, raw = http.post(
                "/score", wire.encode_request(_requests(workload, 2)),
                {"Content-Type": wire.CONTENT_TYPE,
                 "Accept": "application/json"},
            )
            assert "application/json" in ctype
            assert len(json.loads(raw)["results"]) == 2

    def test_garbage_frame_is_a_400(self, workload):
        with _Http(workload) as http:
            with pytest.raises(urllib.error.HTTPError) as err:
                http.post(
                    "/score", b"not a frame at all",
                    {"Content-Type": wire.CONTENT_TYPE},
                )
            assert err.value.code == 400


# ---------------------------------------------------------------------------
# Worker IPC frames (protocol.py)
# ---------------------------------------------------------------------------

class TestProtocolWireFrames:
    def _pair(self):
        a, b = socket.socketpair()
        return FrameConn(a), FrameConn(b)

    def test_score_message_rides_the_wire_codec(self):
        row = Row(
            features={"g": np.arange(4, dtype=np.float32)},
            ids={"memberId": "m1"}, offset=0.25, timeout_ms=50.0,
            priority="high", tenant="acme",
        )
        msg = {"kind": "score", "id": 7, "row": row, "tenant": "acme",
               "timeout_ms": 50.0, "bypass": True}
        assert _encode_payload(msg)[0] == 1  # wire, not pickle
        ca, cb = self._pair()
        try:
            ca.send(msg)
            got = cb.recv()
            assert got["id"] == 7 and got["bypass"] is True
            assert got["row"].features["g"].tobytes() == \
                row.features["g"].tobytes()
            assert got["row"].tenant == "acme"
        finally:
            ca.close()
            cb.close()

    def test_success_result_rides_the_wire_codec(self):
        msg = {"kind": "result", "id": 3, "ok": True, "value": {
            "score": 1.0000000000000002, "mean": 0.5, "latency_ms": 0.75,
        }}
        assert _encode_payload(msg)[0] == 2
        ca, cb = self._pair()
        try:
            ca.send(msg)
            assert cb.recv() == msg  # bitwise float64 equality
        finally:
            ca.close()
            cb.close()

    @pytest.mark.parametrize("msg", [
        {"kind": "result", "id": 3, "ok": False, "error": "boom",
         "error_kind": "internal"},
        {"kind": "result", "id": 3, "ok": True, "value": {"depth": 4}},
        {"kind": "stats", "id": 1},
        {"kind": "swap_prepare", "id": 2, "model_dir": "/x"},
        ["not", "a", "dict"],
    ])
    def test_everything_else_stays_pickle(self, msg):
        assert _encode_payload(msg)[0] == 0
        ca, cb = self._pair()
        try:
            ca.send(msg)
            assert cb.recv() == msg
        finally:
            ca.close()
            cb.close()

    def test_corrupt_wire_payload_raises_protocol_error(self):
        ca, cb = self._pair()
        try:
            bad = bytes([1]) + b"XXXX" + bytes(40)
            ca._sock.sendall(struct.pack(">I", len(bad)) + bad)
            with pytest.raises(ProtocolError, match="corrupt wire"):
                cb.recv()
        finally:
            ca.close()
            cb.close()

    def test_unknown_kind_byte_raises_protocol_error(self):
        ca, cb = self._pair()
        try:
            ca._sock.sendall(struct.pack(">I", 1) + bytes([9]))
            with pytest.raises(ProtocolError, match="kind byte"):
                cb.recv()
        finally:
            ca.close()
            cb.close()


# ---------------------------------------------------------------------------
# Shared-memory ingress
# ---------------------------------------------------------------------------

class _Ring:
    def __init__(self, workload, **kwargs):
        self.service = ScoringService(_runtime(workload))
        self.service.start()
        self.ingress = ShmIngress(self.service, **{
            "n_slots": 4, "slot_bytes": 64 << 10, **kwargs,
        }).start()
        self.client = ShmIngressClient(self.ingress.name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.client.close()
        self.ingress.stop()
        self.service.stop()
        return False


class TestShmIngress:
    def test_ring_scores_match_in_process(self, workload):
        with _Ring(workload) as ring:
            reqs = _requests(workload, 8)
            via_ring = ring.client.score_many(reqs, timeout_s=60.0)
            via_proc = ring.service.score_many(
                [dict(r) for r in reqs]
            )
            for a, b in zip(via_ring, via_proc):
                if "error" in b:
                    assert a.get("kind") == b.get("kind")
                else:
                    assert a["score"] == b["score"]

    def test_concurrent_clients_share_the_ring(self, workload):
        with _Ring(workload) as ring:
            reqs = _requests(workload, 3)
            errors = []

            def hammer():
                try:
                    for _ in range(5):
                        out = ring.client.score_many(reqs, timeout_s=60.0)
                        assert len(out) == 3
                except Exception as exc:  # noqa: BLE001 — collect
                    errors.append(exc)

            threads = [
                threading.Thread(target=hammer) for _ in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors, errors

    def test_oversized_request_refused_client_side(self, workload):
        with _Ring(workload) as ring:
            big = [{"dense": {"g": [0.0] * 64}}] * 4096
            with pytest.raises(ShmIngressError, match="exceeds"):
                ring.client.score_many(big, timeout_s=5.0)

    def test_garbage_frame_answers_in_band(self, workload):
        with _Ring(workload) as ring:
            out = ring.client._roundtrip(b"JUNK" + bytes(28), 30.0)
            assert out[0]["kind"] == "bad_request"

    def test_missing_segment_refused(self):
        with pytest.raises(ShmIngressError, match="gone"):
            ShmIngressClient("no-such-ingress-ring")

    def test_geometry_validation(self, workload):
        service = ScoringService(_runtime(workload))
        with pytest.raises(ValueError, match="n_slots"):
            ShmIngress(service, n_slots=0)
        with pytest.raises(ValueError, match="slot_bytes"):
            ShmIngress(service, slot_bytes=16)


# ---------------------------------------------------------------------------
# Fleet binary mode
# ---------------------------------------------------------------------------

class TestFleetBinaryMode:
    def test_binary_fleet_matches_json_fleet(self, workload):
        from photon_ml_tpu.serving.fleet import FleetRouter, LocalHost
        host = LocalHost("h0", ScoringService(_runtime(workload))).start()
        try:
            reqs = _requests(workload, 6)
            json_router = FleetRouter(
                [host.base_url], probe_interval_s=0.05,
            ).start()
            try:
                via_json = [json_router.score(r) for r in reqs]
            finally:
                json_router.stop()
            bin_router = FleetRouter(
                [host.base_url], probe_interval_s=0.05,
                wire_format="binary",
            ).start()
            try:
                via_bin = [bin_router.score(r) for r in reqs]
            finally:
                bin_router.stop()
            for a, b in zip(via_bin, via_json):
                assert a["score"] == b["score"]
                assert a["mean"] == b["mean"]
        finally:
            host.stop()

    def test_wire_format_validated(self):
        from photon_ml_tpu.serving.fleet import FleetRouter
        with pytest.raises(ValueError, match="wire_format"):
            FleetRouter(["http://127.0.0.1:1"], wire_format="msgpack")
