"""Tiled Pallas sparse kernels vs the COO oracle (interpreter mode on CPU).

The kernels' numerics must match the plain COO path (same f32 math, only
summation order differs) across shapes that exercise padding, sub-tile
matrices, depth spill, and dense rows/columns.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

os.environ.setdefault("PHOTON_PALLAS_INTERPRET", "1")

from photon_ml_tpu.data.dataset import make_glm_data
from photon_ml_tpu.ops.sparse import from_coo
from photon_ml_tpu.ops.sparse_pallas import (
    PallasSparseMatrix,
    build_pallas_matrix,
)


def _random_problem(rng, n, d, nnz, dense_col=True, dense_row=True):
    rows = rng.integers(0, n, size=nnz).astype(np.int64)
    cols = rng.integers(0, d, size=nnz).astype(np.int64)
    vals = rng.normal(size=nnz).astype(np.float32)
    if dense_col:  # a bias column touched by every row
        rows = np.concatenate([rows, np.arange(n, dtype=np.int64)])
        cols = np.concatenate([cols, np.zeros(n, np.int64)])
        vals = np.concatenate([vals, np.ones(n, np.float32)])
    if dense_row:  # one row touching many features
        k = min(d, 200)
        rows = np.concatenate([rows, np.full(k, n // 2, np.int64)])
        cols = np.concatenate([cols, np.arange(k, dtype=np.int64)])
        vals = np.concatenate([vals, np.full(k, 0.5, np.float32)])
    return rows, cols, vals


def _rel(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return np.abs(a - b).max() / max(1e-6, np.abs(b).max())


class TestPallasKernels:
    @pytest.mark.parametrize(
        "n,d,nnz",
        [
            (5000, 3000, 40000),   # multi-tile both dims
            (2048, 2048, 10000),   # exactly one tile
            (100, 60, 600),        # far below one tile
            (4096, 257, 30000),    # narrow, non-128-multiple cols
            (300, 4100, 20000),    # wide, few rows
        ],
    )
    def test_matches_coo(self, rng, n, d, nnz):
        rows, cols, vals = _random_problem(rng, n, d, nnz)
        P = build_pallas_matrix(rows, cols, vals, n, d, depth_cap=32)
        C = from_coo(rows, cols, vals, n, d)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        u = jnp.asarray(rng.normal(size=n).astype(np.float32))
        assert _rel(P.matvec(w), C.matvec(w)) < 1e-5
        assert _rel(P.rmatvec(u), C.rmatvec(u)) < 1e-5
        assert _rel(P.row_sq_matvec(w), C.row_sq_matvec(w)) < 1e-5
        assert _rel(P.sq_rmatvec(u), C.sq_rmatvec(u)) < 1e-5

    def test_depth_spill_is_exact(self, rng):
        # Force heavy spill with a tiny depth cap: results must still match
        # because spilled entries ride the COO path.
        n, d = 1000, 500
        rows, cols, vals = _random_problem(rng, n, d, 20000)
        P = build_pallas_matrix(rows, cols, vals, n, d, depth_cap=2)
        C = from_coo(rows, cols, vals, n, d)
        assert P.spill.has_spill
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        u = jnp.asarray(rng.normal(size=n).astype(np.float32))
        assert _rel(P.matvec(w), C.matvec(w)) < 1e-5
        assert _rel(P.rmatvec(u), C.rmatvec(u)) < 1e-5

    def test_cold_paths_delegate(self, rng):
        n, d = 700, 300
        rows, cols, vals = _random_problem(rng, n, d, 5000)
        P = build_pallas_matrix(rows, cols, vals, n, d)
        C = from_coo(rows, cols, vals, n, d)
        np.testing.assert_array_equal(
            np.asarray(P.col_nnz()), np.asarray(C.col_nnz()))
        pm, px = P.col_min_max()
        cm, cx = C.col_min_max()
        np.testing.assert_allclose(np.asarray(pm), np.asarray(cm))
        np.testing.assert_allclose(np.asarray(px), np.asarray(cx))
        assert P.shape == (n, d)
        assert P.nnz == C.nnz

    def test_pytree_roundtrip(self, rng):
        import jax

        rows, cols, vals = _random_problem(rng, 500, 300, 3000)
        P = build_pallas_matrix(rows, cols, vals, 500, 300)
        leaves, treedef = jax.tree.flatten(P)
        P2 = jax.tree.unflatten(treedef, leaves)
        assert isinstance(P2, PallasSparseMatrix)
        w = jnp.asarray(rng.normal(size=300).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(P.matvec(w)), np.asarray(P2.matvec(w)))

    def test_make_glm_data_pallas_opt_in(self, rng):
        import scipy.sparse as sp

        X = sp.random(400, 200, density=0.05, random_state=3, format="csr",
                      dtype=np.float32)
        y = rng.uniform(size=400).astype(np.float32)
        data = make_glm_data(X, y, use_pallas=True)
        assert isinstance(data.features, PallasSparseMatrix)
        dense = make_glm_data(X, y, use_pallas=False)
        w = jnp.asarray(rng.normal(size=200).astype(np.float32))
        assert _rel(data.features.matvec(w), dense.features.matvec(w)) < 1e-5

    def test_nonfinite_vector_entries_stay_localized(self, rng):
        """A non-finite w entry must affect ONLY rows whose stored entries
        touch that column — matching COO/dense semantics.  (A one-hot
        matmul table build would leak it tile-wide via 0*inf = NaN.)"""
        n, d = 300, 2048
        rows = np.array([0, 1, 2], np.int64)
        # col 128 sits at OFFSET 0 of its window: empty slots' placeholder
        # lo=0 gathers exactly w[128], the hardest leak case (0*inf=NaN
        # would hit every lane of the window's sublanes).
        cols = np.array([0, 128, 72], np.int64)
        vals = np.ones(3, np.float32)
        P = build_pallas_matrix(rows, cols, vals, n, d)
        w = np.zeros(d, np.float32)
        w[128] = np.inf
        w[72] = 5.0
        w[0] = 1.0
        out = np.asarray(P.matvec(jnp.asarray(w)))
        assert out[0] == 1.0
        assert np.isinf(out[1])
        assert out[2] == 5.0, f"row 2 contaminated: {out[2]}"
        assert np.all(out[3:] == 0.0)
        # also an inf at a window-interior offset
        w2 = np.zeros(d, np.float32)
        w2[72] = np.inf
        out2 = np.asarray(P.matvec(jnp.asarray(w2)))
        assert np.isinf(out2[2]) and out2[0] == 0.0 and np.all(out2[3:] == 0)
        # rmatvec side: a non-finite residual in one row
        u = np.zeros(n, np.float32)
        u[1] = np.nan
        u[2] = 2.0
        ru = np.asarray(P.rmatvec(jnp.asarray(u)))
        assert np.isnan(ru[128])
        assert ru[72] == 2.0
        assert ru[0] == 0.0

    def test_objective_parity(self, rng):
        """Full fused value+grad through GlmObjective matches the COO path."""
        import scipy.sparse as sp

        from photon_ml_tpu.ops import losses
        from photon_ml_tpu.optim.objective import GlmObjective

        n, d = 600, 400
        X = sp.random(n, d, density=0.04, random_state=5, format="csr",
                      dtype=np.float32)
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        obj = GlmObjective(losses.logistic)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))

        dp = make_glm_data(X, y, use_pallas=True)
        dc = make_glm_data(X, y, use_pallas=False)
        vp, gp = obj.value_and_grad(w, dp, l2_weight=0.3)
        vc, gc = obj.value_and_grad(w, dc, l2_weight=0.3)
        assert abs(float(vp) - float(vc)) < 1e-3 * max(1.0, abs(float(vc)))
        assert _rel(gp, gc) < 1e-5
        hp = obj.hvp(w, w, dp, l2_weight=0.3)
        hc = obj.hvp(w, w, dc, l2_weight=0.3)
        assert _rel(hp, hc) < 1e-5


class TestDegenerateInputs:
    def test_all_zero_values(self):
        """All stored values zero → empty live set; must build, not crash."""
        P = build_pallas_matrix(
            np.array([0]), np.array([0]), np.array([0.0], np.float32), 10, 10
        )
        w = jnp.arange(10, dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(P.matvec(w)), 0.0)
        np.testing.assert_array_equal(
            np.asarray(P.rmatvec(jnp.ones(10, jnp.float32))), 0.0
        )

    def test_empty_entry_list(self):
        P = build_pallas_matrix(
            np.empty(0, np.int64), np.empty(0, np.int64),
            np.empty(0, np.float32), 7, 5,
        )
        assert P.shape == (7, 5)
        np.testing.assert_array_equal(
            np.asarray(P.matvec(jnp.ones(5, jnp.float32))), 0.0
        )


class TestColumnPermutation:
    """Clustered hot columns are spread across gather windows when the
    predicted packed-A cost says it wins; numerics stay exact."""

    def _clustered(self, rng, n=3000, d=4096):
        # Hot block: many entries concentrated in the FIRST 128-wide
        # window (popularity-sorted ids) — heavy enough that the predicted
        # slot saving clears the gather-cost guard; sparse background
        # everywhere else.  (The top few columns exceed the dense-stripe
        # threshold and are extracted; the remaining hot tail still
        # overloads the window.)
        hot_c = rng.integers(0, 128, size=60000).astype(np.int64)
        hot_r = rng.integers(0, n, size=60000).astype(np.int64)
        bg_c = rng.integers(128, d, size=9000).astype(np.int64)
        bg_r = rng.integers(0, n, size=9000).astype(np.int64)
        rows = np.concatenate([hot_r, bg_r])
        cols = np.concatenate([hot_c, bg_c])
        vals = rng.normal(size=len(rows)).astype(np.float32)
        return rows, cols, vals

    def test_permutation_engages_and_avoids_spill(self, rng):
        # max_dense=0 isolates the permutation from dense-stripe
        # extraction, which would otherwise absorb this hot cluster.
        rows, cols, vals = self._clustered(rng)
        n, d = 3000, 4096
        P = build_pallas_matrix(rows, cols, vals, n, d, max_dense=0)
        P0 = build_pallas_matrix(rows, cols, vals, n, d, max_dense=0,
                                 col_permutation=False)
        assert P.has_col_perm
        # The win is NOT raw sublane count — the identity build "solves"
        # the hot window by SPILLING it to the XLA scatter path (the
        # latency-floor cost measured ~ms per eval); the permuted build
        # spreads the mass and needs no spill at all.
        assert not P.spill.has_spill
        assert P0.spill.has_spill

    def test_permuted_numerics_match_coo(self, rng):
        rows, cols, vals = self._clustered(rng)
        n, d = 3000, 4096
        P = build_pallas_matrix(rows, cols, vals, n, d, max_dense=0)
        assert P.has_col_perm
        C = from_coo(rows, cols, vals, n, d)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        u = jnp.asarray(rng.normal(size=n).astype(np.float32))
        assert _rel(P.matvec(w), C.matvec(w)) < 1e-5
        assert _rel(P.rmatvec(u), C.rmatvec(u)) < 1e-5
        assert _rel(P.row_sq_matvec(w), C.row_sq_matvec(w)) < 1e-5
        assert _rel(P.sq_rmatvec(u), C.sq_rmatvec(u)) < 1e-5

    def test_uniform_data_keeps_identity(self, rng):
        # Uniform spread: permutation cannot win; identity layout (and its
        # zero-cost pad path) must be kept.
        rows = rng.integers(0, 2000, size=20000).astype(np.int64)
        cols = rng.integers(0, 2048, size=20000).astype(np.int64)
        vals = rng.normal(size=20000).astype(np.float32)
        P = build_pallas_matrix(rows, cols, vals, 2000, 2048)
        assert not P.has_col_perm


class TestStorageClasses:
    """Depth inflation fix: dense stripes + occupancy depth + compact spill."""

    def test_bias_column_becomes_dense_stripe(self, rng):
        """A bias column touched by every row must not inflate the slot
        depth (it previously drove depth_b to the cap, ~12x memory)."""
        n, d, nnz = 70000, 3000, 8 * 70000
        rows = rng.integers(0, n, size=nnz).astype(np.int64)
        cols = rng.integers(1, d, size=nnz).astype(np.int64)
        vals = rng.normal(size=nnz).astype(np.float32)
        # bias column 0 on every row
        rows = np.concatenate([rows, np.arange(n, dtype=np.int64)])
        cols = np.concatenate([cols, np.zeros(n, np.int64)])
        vals = np.concatenate([vals, np.ones(n, np.float32)])
        P = build_pallas_matrix(rows, cols, vals, n, d)
        assert P.has_dense_cols
        assert 0 in np.asarray(P.dense_col_ids)
        # Without extraction the bias column forces depth_b to the 128 cap
        # (its cells hold one entry per window row); the background tail
        # alone needs far less.
        assert P.depth_b <= 32, f"depth_b inflated to {P.depth_b}"
        C = from_coo(rows, cols, vals, n, d)
        w = rng.normal(size=d).astype(np.float32)
        u = rng.normal(size=n).astype(np.float32)
        assert _rel(P.matvec(jnp.asarray(w)), C.matvec(jnp.asarray(w))) < 1e-5
        assert _rel(P.rmatvec(jnp.asarray(u)), C.rmatvec(jnp.asarray(u))) < 1e-5

    def test_compact_spill_scales_with_overflow(self, rng):
        """Spill matrix holds only the overflow, not a full masked copy."""
        n, d = 4096, 4096
        # A hot 64-entry cell (same row-window, same lane pattern) on top of
        # a sparse background, with a tiny depth cap to force spill.
        rows = rng.integers(0, n, size=20000).astype(np.int64)
        cols = rng.integers(0, d, size=20000).astype(np.int64)
        vals = rng.normal(size=20000).astype(np.float32)
        # One row, 64 DISTINCT columns inside one 128-wide window: all 64
        # entries share the (tile, gwin, lane) cell in orientation F, far
        # past depth_cap=8 — spill is forced (the cap binds, regardless of
        # the cost model).
        hot_rows = np.full(64, 7, np.int64)
        hot_cols = np.arange(64, dtype=np.int64)
        hot_vals = np.ones(64, np.float32)
        rows = np.concatenate([rows, hot_rows])
        cols = np.concatenate([cols, hot_cols])
        vals = np.concatenate([vals, hot_vals])
        P = build_pallas_matrix(rows, cols, vals, n, d, depth_cap=8)
        assert P.spill.has_spill
        assert P.spill.spill_coo.nnz < 2048  # compact, not ~20k
        C = from_coo(rows, cols, vals, n, d)
        w = rng.normal(size=d).astype(np.float32)
        assert _rel(P.matvec(jnp.asarray(w)), C.matvec(jnp.asarray(w))) < 1e-5
        u = rng.normal(size=n).astype(np.float32)
        assert _rel(P.sq_rmatvec(jnp.asarray(u)),
                    C.sq_rmatvec(jnp.asarray(u))) < 1e-5


class TestDenseStripeBudget:
    def test_memory_budget_caps_stripe_count(self, rng):
        """The per-side dense budget must bound stripes regardless of how
        many columns clear the count threshold (at 10^8 rows each stripe
        is ~400 MB — the count cap alone would blow HBM)."""
        n, d = 4000, 600
        # 40 columns all above threshold (max(256, n/32) = 256)
        hot = np.repeat(np.arange(40, dtype=np.int64), 300)
        rows = rng.integers(0, n, size=len(hot)).astype(np.int64)
        vals = rng.normal(size=len(hot)).astype(np.float32)
        budget = 10 * n * 4  # room for exactly 10 column stripes
        P = build_pallas_matrix(rows, hot, vals, n, d,
                                dense_budget_bytes=budget)
        assert P.has_dense_cols
        assert P.dense_col_ids.shape[0] <= 10
        C = from_coo(rows, hot, vals, n, d)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        assert _rel(P.matvec(w), C.matvec(w)) < 1e-5
        u = jnp.asarray(rng.normal(size=n).astype(np.float32))
        assert _rel(P.rmatvec(u), C.rmatvec(u)) < 1e-5


class TestNonPowerOfTwoTile:
    def test_tile_384_decode_matches_coo(self):
        """Regression: packed-code decode must mask ohi with (1<<OBITS)-1,
        not (WINS-1) — for TILE=384 (WINS=3, OBITS=2) the old 0b10 mask
        zeroed bit 0, so every slot with output window 1 (or 3) decoded to
        the wrong window (advisor round 2).  TILE_R is frozen at import, so
        the check runs in a subprocess."""
        import subprocess
        import sys

        prog = """
import numpy as np, jax.numpy as jnp
from photon_ml_tpu.ops.sparse import from_coo
from photon_ml_tpu.ops.sparse_pallas import WINS, build_pallas_matrix
assert WINS == 3, WINS  # non-power-of-two windows per tile
rng = np.random.default_rng(0)
n, d, nnz = 1500, 900, 20000
rows = rng.integers(0, n, size=nnz).astype(np.int64)
cols = rng.integers(0, d, size=nnz).astype(np.int64)
vals = rng.normal(size=nnz).astype(np.float32)
P = build_pallas_matrix(rows, cols, vals, n, d, depth_cap=32)
C = from_coo(rows, cols, vals, n, d)
w = jnp.asarray(rng.normal(size=d).astype(np.float32))
u = jnp.asarray(rng.normal(size=n).astype(np.float32))
rel_m = float(np.abs(np.asarray(P.matvec(w) - C.matvec(w))).max())
rel_r = float(np.abs(np.asarray(P.rmatvec(u) - C.rmatvec(u))).max())
scale_m = max(1e-6, float(np.abs(np.asarray(C.matvec(w))).max()))
scale_r = max(1e-6, float(np.abs(np.asarray(C.rmatvec(u))).max()))
assert rel_m / scale_m < 1e-5, rel_m / scale_m
assert rel_r / scale_r < 1e-5, rel_r / scale_r
print("OK")
"""
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PHOTON_PALLAS_TILE"] = "384"
        env["PHOTON_PALLAS_INTERPRET"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo_root + ":" + env.get("PYTHONPATH", "")
        r = subprocess.run([sys.executable, "-c", prog], env=env,
                           capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr
        assert "OK" in r.stdout


class TestUnitValueLayout:
    """Binary matrices drop the f32 val stream (codes only, 3x less DMA);
    validity rides the codes' EMPTY sign bit.  Numerics must stay exact."""

    def _binary_problem(self, rng, n, d, nnz, bias=True):
        # UNIQUE coordinates: duplicate (row, col) pairs canonicalize by
        # summing to 2.0, which correctly disables the unit layout.
        flat = rng.choice(n * (d - 1), size=nnz, replace=False)
        rows = (flat // (d - 1)).astype(np.int64)
        cols = (flat % (d - 1) + 1).astype(np.int64)  # keep col 0 for bias
        if bias:  # dense stripe: kept VALUED even in unit mode
            rows = np.concatenate([rows, np.arange(n, dtype=np.int64)])
            cols = np.concatenate([cols, np.zeros(n, np.int64)])
        vals = np.ones(len(rows), np.float32)
        return rows, cols, vals

    @pytest.mark.parametrize("n,d,nnz", [(5000, 3000, 40000), (300, 4100, 20000)])
    def test_all_four_ops_match_coo(self, rng, n, d, nnz):
        rows, cols, vals = self._binary_problem(rng, n, d, nnz)
        P = build_pallas_matrix(rows, cols, vals, n, d, depth_cap=32)
        assert P.unit_vals
        assert P.f_val.size == 1 and P.b_val.size == 1  # placeholders
        C = from_coo(rows, cols, vals, n, d)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        u = jnp.asarray(rng.normal(size=n).astype(np.float32))
        assert _rel(P.matvec(w), C.matvec(w)) < 1e-5
        assert _rel(P.rmatvec(u), C.rmatvec(u)) < 1e-5
        assert _rel(P.row_sq_matvec(w * w), C.row_sq_matvec(w * w)) < 1e-5
        assert _rel(P.sq_rmatvec(u * u), C.sq_rmatvec(u * u)) < 1e-5

    def test_non_binary_values_keep_valued_layout(self, rng):
        rows, cols, _ = self._binary_problem(rng, 1000, 500, 5000, bias=False)
        vals = rng.normal(size=len(rows)).astype(np.float32)
        P = build_pallas_matrix(rows, cols, vals, 1000, 500)
        assert not P.unit_vals

    def test_unit_values_forced_off(self, rng):
        rows, cols, vals = self._binary_problem(rng, 1000, 500, 5000)
        P = build_pallas_matrix(
            rows, cols, vals, 1000, 500, unit_values=False
        )
        assert not P.unit_vals
        C = from_coo(rows, cols, vals, 1000, 500)
        w = jnp.asarray(rng.normal(size=500).astype(np.float32))
        assert _rel(P.matvec(w), C.matvec(w)) < 1e-5

    def test_forced_on_with_nonunit_values_rejected(self, rng):
        rows, cols, _ = self._binary_problem(rng, 500, 300, 2000, bias=False)
        vals = rng.normal(size=len(rows)).astype(np.float32)
        with pytest.raises(ValueError, match="unit_values"):
            build_pallas_matrix(
                rows, cols, vals, 500, 300, unit_values=True
            )

    def test_nonfinite_vector_stays_localized_unit_mode(self, rng):
        """An inf in w must only reach rows that actually touch that
        column — empty slots (sign-marked) must contribute exact zero even
        though there is no val array to mask with."""
        n, d = 2000, 1500
        rows, cols, vals = self._binary_problem(
            rng, n, d, 8000, bias=False
        )
        P = build_pallas_matrix(rows, cols, vals, n, d, depth_cap=32)
        assert P.unit_vals
        bad_col = 777
        w = np.ones(d, np.float32)
        w[bad_col] = np.inf
        out = np.asarray(P.matvec(jnp.asarray(w)))
        touches = np.zeros(n, bool)
        touches[rows[cols == bad_col]] = True
        assert np.all(np.isinf(out[touches]) | np.isnan(out[touches]))
        assert np.all(np.isfinite(out[~touches]))

    def test_mixed_unit_chunks_uniformize(self, rng):
        """Streaming: a binary chunk next to a weighted chunk falls back to
        the valued layout with materialized 1.0 values — parity holds."""
        from photon_ml_tpu.ops.sparse_pallas import (
            layout_to_host,
            uniformize_pallas_layouts,
        )

        n, d = 1500, 800
        r1, c1, v1 = self._binary_problem(rng, n, d, 6000, bias=False)
        r2 = rng.integers(0, n, size=5000).astype(np.int64)
        c2 = rng.integers(0, d, size=5000).astype(np.int64)
        v2 = rng.normal(size=5000).astype(np.float32)
        m1 = build_pallas_matrix(r1, c1, v1, n, d, col_permutation=False)
        m2 = build_pallas_matrix(r2, c2, v2, n, d, col_permutation=False)
        assert m1.unit_vals and not m2.unit_vals
        uni = uniformize_pallas_layouts(
            [layout_to_host(m1), layout_to_host(m2)]
        )
        assert not uni[0].unit_vals and not uni[1].unit_vals
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        import jax as _jax

        fn = _jax.jit(lambda P, w: P.matvec(w))
        for U, (r, c, v) in zip(uni, [(r1, c1, v1), (r2, c2, v2)]):
            C = from_coo(r, c, v, n, d)
            assert _rel(fn(_jax.device_put(U), w), C.matvec(w)) < 1e-5

    def test_all_unit_chunks_stay_unit(self, rng):
        from photon_ml_tpu.ops.sparse_pallas import (
            layout_to_host,
            uniformize_pallas_layouts,
        )

        n, d = 1200, 600
        mats, oracles = [], []
        for k in range(3):
            r, c, v = self._binary_problem(
                rng, n, d, 3000 + 2000 * k, bias=False
            )
            mats.append(layout_to_host(build_pallas_matrix(
                r, c, v, n, d, col_permutation=False
            )))
            oracles.append(from_coo(r, c, v, n, d))
        uni = uniformize_pallas_layouts(mats)
        assert all(m.unit_vals for m in uni)
        import jax as _jax

        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        fn = _jax.jit(lambda P, w: P.matvec(w))
        for U, C in zip(uni, oracles):
            assert _rel(fn(_jax.device_put(U), w), C.matvec(w)) < 1e-5


class TestNativeLayoutSorter:
    """native/layout_sort.cpp vs the numpy build: BIT-identical layouts
    (stable radix sort with numpy's tie order), including spill."""

    def _build_both(self, rows, cols, vals, n, d, **kw):
        import photon_ml_tpu.native as native_mod

        if native_mod.load_layout_sorter() is None:
            pytest.skip("no native toolchain here")
        P_nat = build_pallas_matrix(rows, cols, vals, n, d, **kw)
        old = os.environ.get("PHOTON_NO_NATIVE")
        os.environ["PHOTON_NO_NATIVE"] = "1"
        try:
            P_py = build_pallas_matrix(rows, cols, vals, n, d, **kw)
        finally:
            if old is None:
                del os.environ["PHOTON_NO_NATIVE"]
            else:
                os.environ["PHOTON_NO_NATIVE"] = old
        return P_nat, P_py

    def test_multithread_team_bit_identical(self, tmp_path):
        """VERDICT r4 weak #4: the sorter's multi-thread stable-partition
        paths had only ever executed at team=1 on this single-CPU
        container.  OMP_NUM_THREADS forces a real 4-thread team (legal on
        any core count) in a fresh subprocess — the run asserts the team
        actually materialized (no vacuous pass) and that the layout is
        bit-identical to the single-threaded numpy build."""
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = tmp_path / "team_check.py"
        script.write_text(r"""
import os, sys
import numpy as np

import photon_ml_tpu.native as native_mod
from photon_ml_tpu.ops.sparse_pallas import build_pallas_matrix

lib = native_mod.load_layout_sorter()
if lib is None:
    print("SKIP no native toolchain")
    sys.exit(0)
team = int(lib.pl_observed_team())
if team < 2:
    print(f"SKIP team={team} (OpenMP did not deliver >1 threads)")
    sys.exit(0)
rng = np.random.default_rng(3)
n, d, nnz = 6000, 4000, 1 << 18
rows = rng.integers(0, n, size=nnz).astype(np.int64)
cols = rng.integers(0, d, size=nnz).astype(np.int64)
rows[:2000] = 7          # hot cell -> spill partition path
cols[:2000] = np.arange(2000) % 40
vals = rng.normal(size=nnz).astype(np.float32)
P_nat = build_pallas_matrix(rows, cols, vals, n, d, depth_cap=4,
                            col_permutation=False)
os.environ["PHOTON_NO_NATIVE"] = "1"
P_py = build_pallas_matrix(rows, cols, vals, n, d, depth_cap=4,
                           col_permutation=False)
for f in ("f_code", "f_val", "b_code", "b_val"):
    np.testing.assert_array_equal(
        np.asarray(getattr(P_nat, f)), np.asarray(getattr(P_py, f)),
        err_msg=f,
    )
for f in ("row_ids", "col_ids", "values"):
    np.testing.assert_array_equal(
        np.asarray(getattr(P_nat.spill.spill_coo, f)),
        np.asarray(getattr(P_py.spill.spill_coo, f)), err_msg=f,
    )
print(f"OK team={team}")
""")
        env = dict(os.environ)
        env.pop("PHOTON_NO_NATIVE", None)
        env["OMP_NUM_THREADS"] = "4"
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo + ":" + env.get("PYTHONPATH", "")
        r = subprocess.run(
            [sys.executable, str(script)], env=env,
            capture_output=True, text=True, timeout=300,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        if "SKIP" in r.stdout:
            pytest.skip(r.stdout.strip())
        assert "OK team=" in r.stdout, r.stdout

    def test_bit_identical_layouts(self, rng):
        # ≥ 2^18 entries so the native path engages.
        n, d, nnz = 6000, 4000, 1 << 18
        rows = rng.integers(0, n, size=nnz).astype(np.int64)
        cols = rng.integers(0, d, size=nnz).astype(np.int64)
        vals = rng.normal(size=nnz).astype(np.float32)
        P_nat, P_py = self._build_both(rows, cols, vals, n, d)
        assert P_nat.a_f == P_py.a_f and P_nat.depth_f == P_py.depth_f
        for f in ("f_code", "f_val", "b_code", "b_val"):
            np.testing.assert_array_equal(
                np.asarray(getattr(P_nat, f)), np.asarray(getattr(P_py, f)),
                err_msg=f,
            )
        np.testing.assert_array_equal(
            np.asarray(P_nat.spill.spill_coo.values),
            np.asarray(P_py.spill.spill_coo.values),
        )

    def test_bit_identical_with_forced_spill(self, rng):
        n, d, nnz = 4000, 3000, 1 << 18
        rows = rng.integers(0, n, size=nnz).astype(np.int64)
        cols = rng.integers(0, d, size=nnz).astype(np.int64)
        # hot cell: many entries in one (tile, window, lane) → spill
        rows[:3000] = 7
        cols[:3000] = np.arange(3000) % 40
        vals = rng.normal(size=nnz).astype(np.float32)
        P_nat, P_py = self._build_both(
            rows, cols, vals, n, d, depth_cap=4, col_permutation=False
        )
        assert P_nat.spill.has_spill and P_py.spill.has_spill
        assert P_nat.spill.spill_coo.nnz == P_py.spill.spill_coo.nnz
        for f in ("f_code", "f_val", "b_code", "b_val"):
            np.testing.assert_array_equal(
                np.asarray(getattr(P_nat, f)), np.asarray(getattr(P_py, f)),
                err_msg=f,
            )
        for f in ("row_ids", "col_ids", "values"):
            np.testing.assert_array_equal(
                np.asarray(getattr(P_nat.spill.spill_coo, f)),
                np.asarray(getattr(P_py.spill.spill_coo, f)),
            )

    def test_unit_layout_through_native(self, rng):
        n, d, nnz = 5000, 3000, 1 << 18
        flat = rng.choice(n * d, size=nnz, replace=False)
        rows = (flat // d).astype(np.int64)
        cols = (flat % d).astype(np.int64)
        vals = np.ones(nnz, np.float32)
        P_nat, P_py = self._build_both(rows, cols, vals, n, d)
        assert P_nat.unit_vals and P_py.unit_vals
        C = from_coo(rows, cols, vals, n, d)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        assert _rel(P_nat.matvec(w), C.matvec(w)) < 1e-5
