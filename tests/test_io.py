"""IO tests: Avro codec, model store, LIBSVM, index maps, stats."""

import json
import os

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from photon_ml_tpu.data import libsvm
from photon_ml_tpu.data.dataset import make_glm_data
from photon_ml_tpu.data.index_map import (
    INTERCEPT_KEY,
    BinaryIndexMap,
    IndexMap,
    feature_key,
)
from photon_ml_tpu.data.stats import summarize
from photon_ml_tpu.io import avro
from photon_ml_tpu.io.model_store import load_glm_model, save_glm_model
from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel


class TestAvroCodec:
    def test_roundtrip_records(self, tmp_path):
        schema = TRAINING_EXAMPLE
        records = [
            {
                "uid": "r1",
                "response": 1.0,
                "weight": 2.0,
                "offset": None,
                "features": [
                    {"name": "age", "term": "", "value": 0.5},
                    {"name": "geo", "term": "us", "value": 1.0},
                ],
            },
            {
                "uid": None,
                "response": 0.0,
                "weight": None,
                "offset": -1.5,
                "features": [],
            },
        ]
        path = str(tmp_path / "data.avro")
        avro.write_container(path, schema, records)
        rschema, rrecords = avro.read_container(path)
        assert rschema == schema
        assert rrecords == records

    def test_null_codec_and_many_blocks(self, tmp_path):
        schema = {"type": "record", "name": "R",
                  "fields": [{"name": "x", "type": "long"}]}
        records = [{"x": i} for i in range(10000)]
        path = str(tmp_path / "many.avro")
        avro.write_container(path, schema, records, codec="null",
                             records_per_block=100)
        _, out = avro.read_container(path)
        assert out == records

    def test_varint_extremes(self, tmp_path):
        schema = {"type": "record", "name": "R",
                  "fields": [{"name": "x", "type": "long"}]}
        vals = [0, -1, 1, 2**62, -(2**62), 127, -128]
        path = str(tmp_path / "ints.avro")
        avro.write_container(path, schema, [{"x": v} for v in vals])
        _, out = avro.read_container(path)
        assert [r["x"] for r in out] == vals

    def test_corrupt_magic_rejected(self, tmp_path):
        path = tmp_path / "bad.avro"
        path.write_bytes(b"nope")
        with pytest.raises(ValueError, match="not an Avro container"):
            avro.read_container(str(path))


class TestModelStore:
    def test_roundtrip_with_variances(self, tmp_path, rng):
        imap = IndexMap.build(["a", feature_key("b", "t1"), "c"],
                              add_intercept=True)
        means = jnp.asarray(np.array([1.5, 0.0, -2.0, 0.25], np.float32))
        variances = jnp.asarray(np.array([0.1, 0.2, 0.3, 0.4], np.float32))
        model = GeneralizedLinearModel(Coefficients(means, variances), "logistic")
        path = str(tmp_path / "model.avro")
        save_glm_model(model, imap, path, sparsify=False)
        loaded, imap2 = load_glm_model(path, imap)
        np.testing.assert_allclose(np.asarray(loaded.coefficients.means),
                                   np.asarray(means))
        np.testing.assert_allclose(np.asarray(loaded.coefficients.variances),
                                   np.asarray(variances))
        assert loaded.task == "logistic"

    def test_sparsified_variance_survives_reconstructed_map(self, tmp_path):
        # A coefficient with mean 0 but nonzero variance is sparsified out of
        # the means entries; reconstructing the index map on load (no map
        # supplied) must still give it a slot so its variance round-trips.
        imap = IndexMap.build(["a", "b", "c"])
        means = jnp.asarray(np.array([1.0, 0.0, 3.0], np.float32))
        variances = jnp.asarray(np.array([0.1, 0.2, 0.3], np.float32))
        model = GeneralizedLinearModel(Coefficients(means, variances), "squared")
        path = str(tmp_path / "model.avro")
        save_glm_model(model, imap, path, sparsify=True)
        loaded, imap2 = load_glm_model(path)  # no index map supplied
        assert len(imap2) == 3
        got = {
            imap2.index_to_name(j): (
                float(loaded.coefficients.means[j]),
                float(loaded.coefficients.variances[j]),
            )
            for j in range(3)
        }
        expected = {"a": (1.0, 0.1), "b": (0.0, 0.2), "c": (3.0, 0.3)}
        assert got.keys() == expected.keys()
        for k in expected:
            np.testing.assert_allclose(got[k], expected[k], rtol=1e-6)

    def test_sparsified_save_drops_zeros(self, tmp_path):
        imap = IndexMap.build(["a", "b", "c"])
        means = jnp.asarray(np.array([1.0, 0.0, 3.0], np.float32))
        model = GeneralizedLinearModel(Coefficients(means), "squared")
        path = str(tmp_path / "model.avro")
        save_glm_model(model, imap, path)
        _, records = avro.read_container(path)
        assert len(records[0]["means"]) == 2
        loaded, _ = load_glm_model(path, imap)
        np.testing.assert_allclose(np.asarray(loaded.coefficients.means),
                                   np.asarray(means))


class TestLibsvm:
    def test_roundtrip(self, tmp_path, rng):
        X = sp.random(50, 20, density=0.3, random_state=7, format="csr")
        y = (rng.uniform(size=50) < 0.5).astype(np.float32) * 2 - 1
        path = str(tmp_path / "data.libsvm")
        libsvm.write_libsvm(path, X, y)
        X2, y2 = libsvm.read_libsvm(path, n_features=20,
                                    binary_labels_to_01=False)
        np.testing.assert_allclose(X2.toarray(), X.toarray(), rtol=1e-6)
        np.testing.assert_allclose(y2, y)

    def test_pm1_to_01_mapping_and_intercept(self, tmp_path):
        path = str(tmp_path / "pm1.libsvm")
        with open(path, "w") as f:
            f.write("+1 1:0.5 3:1\n-1 2:2\n")
        X, y = libsvm.read_libsvm(path, add_intercept=True)
        np.testing.assert_allclose(y, [1.0, 0.0])
        assert X.shape == (2, 4)
        np.testing.assert_allclose(X.toarray()[:, -1], 1.0)


class TestIndexMap:
    def test_build_lookup_reverse(self):
        imap = IndexMap.build(["x", "y", "z"], add_intercept=True)
        assert imap["x"] == 0 and imap[INTERCEPT_KEY] == 3
        assert imap.intercept_index == 3
        assert imap.index_to_name(1) == "y"
        assert imap.get_index("missing") == -1
        assert len(imap) == 4

    def test_save_load(self, tmp_path):
        imap = IndexMap.build([f"f{i}" for i in range(100)])
        imap.save(str(tmp_path))
        loaded = IndexMap.load(str(tmp_path))
        assert dict(loaded) == dict(imap)

    def test_binary_map(self, tmp_path):
        imap = IndexMap.build([f"feat_{i}" for i in range(1000)])
        imap.save_binary(str(tmp_path))
        bmap = BinaryIndexMap(str(tmp_path))
        assert len(bmap) == 1000
        for probe in ["feat_0", "feat_123", "feat_999"]:
            assert bmap.get_index(probe) == imap[probe]
        assert bmap.get_index("nope") == -1


class TestStats:
    def test_matches_numpy_weighted(self, rng):
        X = rng.normal(size=(100, 6))
        X[X < 0.3] = 0.0
        w = rng.uniform(0.5, 2.0, size=100)
        data = make_glm_data(X, np.zeros(100), weights=w)
        s = summarize(data)
        mean = np.average(X, axis=0, weights=w)
        var = np.average((X - mean) ** 2, axis=0, weights=w)
        np.testing.assert_allclose(np.asarray(s.mean), mean, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(s.variance), var, rtol=1e-3)
        np.testing.assert_allclose(np.asarray(s.min), X.min(axis=0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(s.max), X.max(axis=0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(s.nnz), (X != 0).sum(axis=0))

    def test_sparse_matches_dense(self, rng):
        Xd = rng.normal(size=(60, 5)) * (rng.uniform(size=(60, 5)) < 0.4)
        data_d = make_glm_data(Xd, np.zeros(60))
        data_s = make_glm_data(sp.csr_matrix(Xd), np.zeros(60))
        sd, ss = summarize(data_d), summarize(data_s)
        for field in ("mean", "variance", "min", "max"):
            np.testing.assert_allclose(
                np.asarray(getattr(ss, field)),
                np.asarray(getattr(sd, field)),
                rtol=1e-5, atol=1e-6,
            )
        # Padded rows must not change stats.
        data_p = make_glm_data(sp.csr_matrix(Xd), np.zeros(60), pad_rows=64,
                               pad_nnz=200)
        sp_ = summarize(data_p)
        np.testing.assert_allclose(np.asarray(sp_.mean), np.asarray(sd.mean),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(sp_.min), np.asarray(sd.min),
                                   rtol=1e-5)

    def test_padding_does_not_leak_into_min_max(self, rng):
        # Regression: an all-positive dense column must keep its positive min
        # even when zero-weight padding rows are appended.
        X = rng.uniform(1.0, 2.0, size=(20, 3))
        for features in (X, sp.csr_matrix(X)):
            data = make_glm_data(features, np.zeros(20), pad_rows=32,
                                 pad_nnz=100 if sp.issparse(features) else None)
            s = summarize(data)
            np.testing.assert_allclose(np.asarray(s.min), X.min(axis=0),
                                       rtol=1e-6)
            np.testing.assert_allclose(np.asarray(s.max), X.max(axis=0),
                                       rtol=1e-6)
            np.testing.assert_allclose(np.asarray(s.nnz), [20, 20, 20])


class TestSnappyCodec:
    """Pure-Python snappy block format (Avro framing: + crc32 big-endian)."""

    def test_container_roundtrip(self, tmp_path):
        from photon_ml_tpu.io import avro

        schema = {
            "type": "record", "name": "R",
            "fields": [
                {"name": "uid", "type": "string"},
                {"name": "response", "type": "double"},
                {"name": "features", "type": {"type": "array",
                                              "items": "float"}},
            ],
        }
        recs = [
            {"uid": f"user_{i % 7}", "response": float(i),
             "features": [float(i), 0.5, -1.25]}
            for i in range(500)
        ]
        path = str(tmp_path / "s.avro")
        avro.write_container(path, schema, recs, codec="snappy",
                             records_per_block=64)
        rschema, out = avro.read_container(path)
        assert rschema == schema
        assert out == recs

    def test_compressor_actually_compresses(self):
        from photon_ml_tpu.io.avro import (
            _snappy_compress, _snappy_uncompress,
        )

        raw = (b"abcdefgh" * 4000) + bytes(range(256)) * 10
        comp = _snappy_compress(raw)
        assert len(comp) < len(raw) // 2
        assert _snappy_uncompress(comp) == raw

    def test_decoder_handles_all_tags(self):
        """Hand-built streams exercising every element type, including the
        overlapping copy (run-length case) and 1/4-byte offsets — streams a
        conformant snappy ENCODER may emit but ours does not."""
        from photon_ml_tpu.io.avro import _snappy_uncompress

        # literal "a" + overlapping 1-byte-offset copy len 4 -> "aaaaa"
        s = bytes([5, 0b00000000, ord("a"), 0b00000001, 1])
        assert _snappy_uncompress(s) == b"aaaaa"
        # literal "abcd" + 2-byte-offset copy(off=4, len=4) -> "abcdabcd"
        s = bytes([8, 0b00001100]) + b"abcd" + bytes([0b00001110, 4, 0])
        assert _snappy_uncompress(s) == b"abcdabcd"
        # 4-byte-offset copy
        s = bytes([8, 0b00001100]) + b"abcd" + bytes(
            [0b00001111, 4, 0, 0, 0]
        )
        assert _snappy_uncompress(s) == b"abcdabcd"
        # 61-byte literal (length in 1 trailing byte)
        body = bytes(range(61))
        s = bytes([61, 60 << 2, 60]) + body
        assert _snappy_uncompress(s) == body

    def test_random_roundtrips(self, rng):
        from photon_ml_tpu.io.avro import (
            _snappy_compress, _snappy_uncompress,
        )

        for n in (0, 1, 3, 59, 60, 61, 100, 4096, 70000):
            raw = bytes(rng.integers(0, 4, size=n, dtype=np.uint8))
            assert _snappy_uncompress(_snappy_compress(raw)) == raw

    def test_crc_mismatch_rejected(self, tmp_path):
        from photon_ml_tpu.io import avro

        schema = {"type": "record", "name": "R",
                  "fields": [{"name": "x", "type": "long"}]}
        path = str(tmp_path / "s.avro")
        avro.write_container(
            path, schema, [{"x": i} for i in range(100)], codec="snappy"
        )
        blob = bytearray(open(path, "rb").read())
        blob[-20] ^= 0xFF  # flip a byte inside the last block's payload
        open(path, "wb").write(bytes(blob))
        with pytest.raises(ValueError):
            avro.read_container(path)


class TestScoringContainerWriter:
    """Columnar ScoringResultAvro writer (native/score_encoder.cpp): the
    write-side mirror of the native decoder.  Byte parity is the whole
    contract — record path, columnar native path, and columnar Python
    fallback must produce IDENTICAL files."""

    @staticmethod
    def _data(n=3000, seed=0):
        rng = np.random.default_rng(seed)
        uids = [None if i % 17 == 0 else f"row{i}" for i in range(n)]
        scores = rng.normal(size=n).astype(np.float32)
        labels = [None if i % 23 == 0 else float(i % 2) for i in range(n)]
        ids = {
            "songId": [
                None if i % 5 == 0 else f"s{i % 41}" for i in range(n)
            ],
            "userId": [f"u{i % 97}" for i in range(n)],
        }
        return uids, scores, labels, ids

    def test_native_and_fallback_byte_parity(self, tmp_path, monkeypatch):
        import hashlib

        from photon_ml_tpu import native as native_mod
        from photon_ml_tpu.io.schemas import SCORING_RESULT

        if native_mod.load_score_encoder() is None:
            pytest.skip("native score encoder unavailable (no toolchain)")
        uids, scores, labels, ids = self._data()
        records = [
            {
                "uid": uids[i],
                "predictionScore": float(scores[i]),
                "label": labels[i],
                "ids": {
                    k: str(ids[k][i])
                    for k in sorted(ids)
                    if ids[k][i] is not None
                },
            }
            for i in range(len(scores))
        ]
        p_rec = str(tmp_path / "rec.avro")
        avro.write_container(p_rec, SCORING_RESULT, records)
        ids_sorted = {k: ids[k] for k in sorted(ids)}
        # Two columnar blocks with an uneven cut: the writer re-chunks to
        # records_per_block internally, so block boundaries (and bytes)
        # must not depend on the input blocking.
        cut = 1234
        blocks = [
            (uids[:cut], scores[:cut], labels[:cut],
             {k: v[:cut] for k, v in ids_sorted.items()}),
            (uids[cut:], scores[cut:], labels[cut:],
             {k: v[cut:] for k, v in ids_sorted.items()}),
        ]
        p_nat = str(tmp_path / "nat.avro")
        assert avro.write_scoring_container(p_nat, blocks) == len(scores)
        monkeypatch.setenv("PHOTON_NO_NATIVE", "1")
        native_mod._CACHE.pop("encoder", None)
        p_py = str(tmp_path / "py.avro")
        assert avro.write_scoring_container(p_py, blocks) == len(scores)
        native_mod._CACHE.pop("encoder", None)

        def digest(p):
            with open(p, "rb") as f:
                return hashlib.sha256(f.read()).hexdigest()

        assert digest(p_rec) == digest(p_nat) == digest(p_py)
        # And the file round-trips through the reader.
        _, got = avro.read_container(p_nat)
        assert len(got) == len(records)
        assert got[0] == records[0] and got[-1] == records[-1]

    def test_id_columns_may_come_and_go_across_blocks(self, tmp_path):
        """Streamed blocks carry only the id columns their rows had; the
        writer None-pads (None entries are omitted per row — the old
        per-record writer's semantics), in canonical sorted order."""
        uids, scores, labels, _ = self._data(100)
        blocks = [
            (uids[:50], scores[:50], labels[:50],
             {"b": [f"x{i}" for i in range(50)]}),
            (uids[50:], scores[50:], labels[50:],
             {"a": [f"y{i}" for i in range(50)]}),
        ]
        p = str(tmp_path / "x.avro")
        assert avro.write_scoring_container(p, blocks) == 100
        _, recs = avro.read_container(p)
        assert recs[0]["ids"] == {"b": "x0"}
        assert recs[99]["ids"] == {"a": "y49"}

    def test_misaligned_columns_rejected(self, tmp_path):
        uids, scores, labels, ids = self._data(100)
        blocks = [(uids[:99], scores, labels, ids)]
        with pytest.raises(ValueError, match="do not match len"):
            avro.write_scoring_container(str(tmp_path / "y.avro"), blocks)


class TestModelFingerprints:
    """PR-3 satellite: save-time fingerprints verified at load, NaN/inf
    coefficients rejected at save (io/model_store.py, io/game_store.py)."""

    def _save(self, tmp_path, means, task="logistic", variances=None):
        imap = IndexMap.build([f"f{j}" for j in range(len(means))])
        model = GeneralizedLinearModel(
            Coefficients(
                jnp.asarray(np.asarray(means, np.float32)),
                None if variances is None else jnp.asarray(
                    np.asarray(variances, np.float32)
                ),
            ),
            task,
        )
        path = str(tmp_path / "model.avro")
        fp = save_glm_model(model, imap, path)
        return path, imap, fp

    def test_fingerprint_written_and_verified(self, tmp_path):
        path, imap, fp = self._save(tmp_path, [1.0, -2.0, 0.5])
        assert fp["task"] == "logistic" and fp["feature_count"] == 3
        assert os.path.exists(path + ".meta.json")
        loaded, _ = load_glm_model(path, imap)  # verifies silently
        assert loaded.task == "logistic"

    def test_tampered_file_rejected(self, tmp_path):
        from photon_ml_tpu.io.schemas import BAYESIAN_LINEAR_MODEL

        path, imap, _ = self._save(tmp_path, [1.0, -2.0, 0.5])
        _, records = avro.read_container(path)
        records[0]["means"][0]["value"] = 99.0  # silent corruption
        avro.write_container(path, BAYESIAN_LINEAR_MODEL, records)
        with pytest.raises(ValueError, match="checksum mismatch"):
            load_glm_model(path, imap)

    def test_wrong_width_index_map_rejected(self, tmp_path):
        path, _, _ = self._save(tmp_path, [1.0, -2.0, 0.5])
        wider = IndexMap.build([f"f{j}" for j in range(5)])
        with pytest.raises(ValueError, match="saved with 3 features"):
            load_glm_model(path, wider)

    def test_missing_sidecar_loads_unverified(self, tmp_path):
        # Pre-fingerprint files (older saves) keep loading.
        path, imap, _ = self._save(tmp_path, [1.0, -2.0, 0.5])
        os.remove(path + ".meta.json")
        loaded, _ = load_glm_model(path, imap)
        assert loaded.task == "logistic"

    def test_nan_coefficients_rejected_at_save(self, tmp_path):
        with pytest.raises(ValueError, match="non-finite"):
            self._save(tmp_path, [1.0, float("nan"), 0.5])
        with pytest.raises(ValueError, match="non-finite"):
            self._save(tmp_path, [1.0, float("inf"), 0.5])
        with pytest.raises(ValueError, match="variance"):
            self._save(
                tmp_path, [1.0, 2.0], variances=[0.1, float("nan")]
            )

    def _game_model(self, bad_entity=False):
        from photon_ml_tpu.game.model import (
            FixedEffectModel,
            GameModel,
            RandomEffectModel,
        )

        glm = GeneralizedLinearModel(
            Coefficients(jnp.asarray(np.array([1.0, -1.0], np.float32))),
            "logistic",
        )
        vals2 = np.array(
            [np.nan if bad_entity else 0.5, 1.5], np.float32
        )
        table = {
            "e1": (np.array([0, 1], np.int32),
                   np.array([0.5, -0.5], np.float32)),
            "e2": (np.array([0, 1], np.int32), vals2),
        }
        model = GameModel(
            models={
                "fixed": FixedEffectModel(glm, "global"),
                "per_e": RandomEffectModel(
                    coefficients=table, feature_shard="ef",
                    entity_key="eid", task="logistic", n_features=2,
                ),
            },
            task="logistic",
        )
        imaps = {
            "global": IndexMap.build(["g0", "g1"]),
            "ef": IndexMap.build(["r0", "r1"]),
        }
        return model, imaps

    def test_game_fingerprints_roundtrip(self, tmp_path):
        from photon_ml_tpu.io.game_store import (
            load_game_model,
            save_game_model,
        )

        model, imaps = self._game_model()
        d = str(tmp_path / "game")
        save_game_model(model, imaps, d)
        with open(os.path.join(d, "metadata.json")) as f:
            manifest = json.load(f)
        assert set(manifest["fingerprints"]) == {"fixed", "per_e"}
        assert manifest["fingerprints"]["per_e"]["n_entities"] == 2
        loaded, _ = load_game_model(d)  # verifies both coordinates
        assert set(loaded.models) == {"fixed", "per_e"}

    def test_game_tampered_random_effect_rejected(self, tmp_path):
        from photon_ml_tpu.io.game_store import (
            RANDOM_EFFECT_MODEL_SCHEMA,
            load_game_model,
            save_game_model,
        )

        model, imaps = self._game_model()
        d = str(tmp_path / "game")
        save_game_model(model, imaps, d)
        path = os.path.join(
            d, "random-effect", "per_e", "coefficients.avro"
        )
        _, records = avro.read_container(path)
        records[0]["coefficients"][0]["value"] = 42.0
        avro.write_container(path, RANDOM_EFFECT_MODEL_SCHEMA, records)
        with pytest.raises(ValueError, match="checksum mismatch"):
            load_game_model(d)

    def test_game_nan_random_effect_rejected_at_save(self, tmp_path):
        from photon_ml_tpu.io.game_store import save_game_model

        model, imaps = self._game_model(bad_entity=True)
        with pytest.raises(ValueError, match="non-finite"):
            save_game_model(model, imaps, str(tmp_path / "game"))


class TestCompileCacheWarmup:
    """PR-3 satellite: utils/compile_cache.warmup pre-compiles jitted
    fns at given shapes and reports the compile count via telemetry."""

    def test_warmup_compiles_and_counts(self):
        import jax

        from photon_ml_tpu import telemetry as telemetry_mod
        from photon_ml_tpu.utils.compile_cache import warmup

        calls = []

        @jax.jit
        def f(x, y):
            calls.append(1)
            return x * 2.0 + y

        sds = jax.ShapeDtypeStruct
        shapes = [
            (sds((4,), np.float32), (sds((4,), np.float32))),
            (sds((8,), np.float32), (sds((8,), np.float32))),
        ]
        tel = telemetry_mod.Telemetry(enabled=True, sinks=[])
        with tel:
            n = warmup([f, f], shapes)
            assert n == 2  # two distinct shapes -> two compiles
            # Re-warming the same shapes compiles nothing new.
            assert warmup([f, f], shapes) == 0
            snap = tel.snapshot()
        assert snap["counters"]["compile_cache_warmup_compiles"] == 2
        assert snap["gauges"]["compile_cache_warmup_seconds"] >= 0

    def test_warmup_populates_the_jit_cache(self):
        import jax

        traces = []

        @jax.jit
        def g(x):
            traces.append(x.shape)
            return x + 1.0

        from photon_ml_tpu.utils.compile_cache import warmup

        warmup([g], [(jax.ShapeDtypeStruct((3,), np.float32),)])
        n_traces = len(traces)
        g(jnp.zeros(3, jnp.float32))  # request-path call: no retrace
        assert len(traces) == n_traces

    def test_length_mismatch_rejected(self):
        from photon_ml_tpu.utils.compile_cache import warmup

        with pytest.raises(ValueError, match="one shape tree per fn"):
            warmup([lambda: None], [])
