"""Multi-host glue (parallel/multihost.py): initialization fallbacks, the
process-block math, and global-array assembly on the virtual device mesh.
True multi-process runs need a pod; everything testable single-process is
tested here (the compute paths themselves are host-count-agnostic SPMD)."""

import numpy as np

from photon_ml_tpu.parallel.compat import shard_map
import pytest

import jax
import jax.numpy as jnp

from photon_ml_tpu.parallel import multihost


class TestInitialize:
    def test_noop_without_config_on_cpu(self, monkeypatch):
        for var in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
                    "NUM_PROCESSES", "JAX_NUM_PROCESSES",
                    "PROCESS_ID", "JAX_PROCESS_ID", "PHOTON_MULTIHOST"):
            monkeypatch.delenv(var, raising=False)
        # CPU backend + no env: must not touch jax.distributed at all.
        assert multihost.initialize() is False

    def test_env_fallback_reads_both_prefixes(self, monkeypatch):
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "1.2.3.4:1234")
        assert multihost._env_first(multihost._ENV_COORD) == "1.2.3.4:1234"
        monkeypatch.setenv("COORDINATOR_ADDRESS", "5.6.7.8:99")
        assert multihost._env_first(multihost._ENV_COORD) == "5.6.7.8:99"


class TestProcessRowBounds:
    def test_single_process_owns_everything(self):
        assert multihost.host_local_rows(1000) == (0, 1000)

    @pytest.mark.parametrize(
        "n,nproc,ldc",
        [(10, 2, 2), (1000, 4, 8), (7, 4, 1), (8, 4, 2), (3, 4, 2)],
    )
    def test_blocks_tile_the_row_space_device_chunked(
        self, n, nproc, ldc, monkeypatch
    ):
        monkeypatch.setattr(jax, "process_count", lambda: nproc)
        total = nproc * ldc
        chunk = -(-n // total)
        covered = []
        for pid in range(nproc):
            start, stop = multihost._process_row_bounds(n, pid, ldc)
            assert start <= stop <= n
            # Matches the per-DEVICE ceil-chunk layout XLA uses.
            assert start == min(pid * ldc * chunk, n)
            covered.append((start, stop))
        # Contiguous tiling of [0, n).
        assert covered[0][0] == 0
        assert covered[-1][1] == n
        for (a, b), (c, d) in zip(covered, covered[1:]):
            assert b == c

    def test_uneven_case_differs_from_even_split(self, monkeypatch):
        # 10 rows, 2 procs x 2 devices: device chunks are 3,3,3,1 so
        # process 0 owns 6 rows — an even per-process split (5/5) would
        # disagree with the sharding.
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        assert multihost._process_row_bounds(10, 0, 2) == (0, 6)
        assert multihost._process_row_bounds(10, 1, 2) == (6, 10)


class TestAssembleGlobal:
    def test_single_process_roundtrip_sharded(self, rng):
        mesh = multihost.global_data_mesh()
        n = 8 * 13  # not a multiple of anything interesting per device
        x = rng.normal(size=(n, 5)).astype(np.float32)
        arr = multihost.assemble_global(x, n, mesh)
        assert arr.shape == (n, 5)
        np.testing.assert_allclose(np.asarray(arr), x)
        # Actually sharded over the data axis.
        assert len(arr.sharding.device_set) == len(jax.devices())

    def test_wrong_block_size_raises(self, rng):
        mesh = multihost.global_data_mesh()
        with pytest.raises(ValueError, match="owns"):
            multihost.assemble_global(
                np.zeros((5, 3), np.float32), 100, mesh
            )

    def test_assembled_array_feeds_psum_program(self, rng):
        """The assembled array works under shard_map with a psum — the
        treeAggregate-analogue consumption pattern."""
        from jax.sharding import PartitionSpec as P

        mesh = multihost.global_data_mesh()
        n = 16 * len(jax.devices())
        x = rng.normal(size=(n,)).astype(np.float32)
        arr = multihost.assemble_global(x, n, mesh)

        def f(block):
            return jax.lax.psum(jnp.sum(block), multihost.DATA_AXIS)

        total = jax.jit(shard_map(
            f, mesh=mesh, in_specs=P(multihost.DATA_AXIS),
            out_specs=P(),
        ))(arr)
        np.testing.assert_allclose(float(total), x.sum(), rtol=1e-5)


class TestPartialConfig:
    def test_partial_explicit_config_raises(self, monkeypatch):
        for var in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
                    "NUM_PROCESSES", "JAX_NUM_PROCESSES",
                    "PROCESS_ID", "JAX_PROCESS_ID", "PHOTON_MULTIHOST"):
            monkeypatch.delenv(var, raising=False)
        with pytest.raises(ValueError, match="ALL of"):
            multihost.initialize(num_processes=4)
        monkeypatch.setenv("COORDINATOR_ADDRESS", "1.2.3.4:99")
        with pytest.raises(ValueError, match="ALL of"):
            multihost.initialize()

    def test_stray_generic_env_vars_are_ignored(self, monkeypatch):
        """Unrelated tooling commonly exports NUM_PROCESSES / PROCESS_ID;
        without a coordinator address they must not abort a single-host
        run (regression: the all-or-none check fired on them)."""
        for var in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
                    "PHOTON_MULTIHOST"):
            monkeypatch.delenv(var, raising=False)
        monkeypatch.setenv("NUM_PROCESSES", "4")
        monkeypatch.setenv("PROCESS_ID", "17")
        assert multihost.initialize() is False

    def test_partial_jax_prefixed_env_fails_loudly(self, monkeypatch):
        """JAX_-prefixed vars are deliberate config: a partial set (lost
        coordinator) must error, not silently run single-host."""
        for var in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
                    "NUM_PROCESSES", "PROCESS_ID", "PHOTON_MULTIHOST"):
            monkeypatch.delenv(var, raising=False)
        monkeypatch.setenv("JAX_NUM_PROCESSES", "8")
        monkeypatch.setenv("JAX_PROCESS_ID", "3")
        with pytest.raises(ValueError, match="ALL of"):
            multihost.initialize()
