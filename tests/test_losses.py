"""Unit tests for pointwise losses: derivatives vs finite differences and
autodiff — the TPU-native mirror of the reference's loss-function unit tests
(SURVEY.md §4: "loss tests check value/gradient/Hessian against finite
differences")."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops import losses

ALL_LOSSES = [losses.logistic, losses.squared, losses.poisson, losses.smoothed_hinge]


def _labels_for(loss, rng, n):
    if loss.name in ("logistic", "smoothed_hinge"):
        return rng.integers(0, 2, n).astype(np.float32)
    if loss.name == "poisson":
        return rng.poisson(2.0, n).astype(np.float32)
    return rng.normal(size=n).astype(np.float32)


@pytest.mark.parametrize("loss", ALL_LOSSES, ids=lambda l: l.name)
def test_d1_matches_finite_difference(loss, rng):
    n = 64
    # Stay away from the hinge's (measure-zero) kink points z ∈ {0, 1}.
    m = rng.uniform(-3.0, 3.0, n).astype(np.float64)
    y = _labels_for(loss, rng, n).astype(np.float64)
    eps = 1e-5
    num = (np.asarray(loss.value(m + eps, y), np.float64) -
           np.asarray(loss.value(m - eps, y), np.float64)) / (2 * eps)
    ana = np.asarray(loss.d1(m, y), np.float64)
    np.testing.assert_allclose(ana, num, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("loss", ALL_LOSSES, ids=lambda l: l.name)
def test_d2_matches_finite_difference(loss, rng):
    n = 64
    m = rng.uniform(-3.0, 3.0, n).astype(np.float64)
    # Keep margins off the hinge's kink neighborhoods.
    m = np.where(np.abs(m) < 0.05, 0.5, m)
    m = np.where(np.abs(m - 1.0) < 0.05, 0.5, m)
    m = np.where(np.abs(m + 1.0) < 0.05, -0.5, m)
    y = _labels_for(loss, rng, n).astype(np.float64)
    eps = 1e-5
    num = (np.asarray(loss.d1(m + eps, y), np.float64) -
           np.asarray(loss.d1(m - eps, y), np.float64)) / (2 * eps)
    ana = np.asarray(loss.d2(m, y), np.float64)
    np.testing.assert_allclose(ana, num, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("loss", ALL_LOSSES, ids=lambda l: l.name)
def test_d1_matches_autodiff(loss, rng):
    m = jnp.asarray(rng.uniform(-3.0, 3.0, 32), jnp.float32)
    y = jnp.asarray(_labels_for(loss, rng, 32))
    auto = jax.vmap(jax.grad(lambda mm, yy: loss.value(mm, yy)))(m, y)
    np.testing.assert_allclose(np.asarray(loss.d1(m, y)), np.asarray(auto),
                               rtol=1e-3, atol=1e-4)


def test_logistic_value_is_negative_log_likelihood():
    m = jnp.asarray([0.0, 2.0, -2.0])
    y = jnp.asarray([1.0, 1.0, 0.0])
    p = jax.nn.sigmoid(m)
    expected = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
    np.testing.assert_allclose(
        np.asarray(losses.logistic.value(m, y)), np.asarray(expected), rtol=1e-6
    )


def test_convexity_d2_nonnegative(rng):
    m = jnp.asarray(rng.uniform(-5, 5, 100), jnp.float32)
    for loss in ALL_LOSSES:
        y = jnp.asarray(_labels_for(loss, rng, 100))
        assert np.all(np.asarray(loss.d2(m, y)) >= 0.0), loss.name


def test_registry_lookup_and_aliases():
    assert losses.get("LOGISTIC_REGRESSION") is losses.logistic
    assert losses.get("linear_regression") is losses.squared
    assert losses.get("POISSON_REGRESSION") is losses.poisson
    assert losses.get("smoothed_hinge_loss_linear_svm") is losses.smoothed_hinge
    with pytest.raises(KeyError):
        losses.get("hubber")
