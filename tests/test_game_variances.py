"""GAME coefficient variances + per-group evaluation plumbing.

The reference computes optional coefficient variances for fixed AND
per-entity random effects (Bayesian model output) and evaluates per-query
("sharded") metrics via an id column; these tests cover the TPU analogues.
"""

import json

import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.evaluation.suite import EvaluationSuite
from photon_ml_tpu.game.estimator import (
    FixedEffectCoordinateConfig,
    GameEstimator,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.optim.problem import (
    GlmOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.optim.regularization import RegularizationContext


def _data(rng, n=300, n_users=10):
    ue = rng.normal(scale=1.5, size=n_users)
    Xg = rng.normal(size=(n, 3)).astype(np.float32)
    users = rng.integers(n_users, size=n)
    margin = 1.1 * Xg[:, 0] - 0.6 * Xg[:, 1] + ue[users]
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(np.float32)
    shards = {
        "global": sp.csr_matrix(Xg),
        "userFeatures": sp.csr_matrix(np.ones((n, 1), np.float32)),
    }
    ids = {"userId": np.array([f"u{u}" for u in users])}
    return shards, ids, y, users, Xg


def _configs(compute_variances=True):
    opt = GlmOptimizationConfig(
        optimizer=OptimizerConfig(max_iters=40),
        regularization=RegularizationContext.l2(),
        compute_variances=compute_variances,
    )
    return {
        "fixed": FixedEffectCoordinateConfig("global", opt, 0.5),
        "per_user": RandomEffectCoordinateConfig(
            "userFeatures", "userId", opt, 0.5
        ),
    }


class TestGameVariances:
    def test_variances_present_and_match_closed_form(self, rng):
        shards, ids, y, users, Xg = _data(rng)
        est = GameEstimator("logistic", _configs(), n_iterations=2)
        model, _ = est.fit(shards, ids, y)

        fe = model["fixed"].model.coefficients
        assert fe.variances is not None
        assert np.all(np.asarray(fe.variances) > 0)

        re = model["per_user"]
        assert re.variances is not None
        # Closed form for one entity: its feature is the constant 1, so
        # H = sum over its rows of sigmoid'(m) + l2, variance = 1/H, with
        # m the FULL margin (fixed-effect score + its own bias).
        w_fe = np.asarray(fe.means)
        key = "u3"
        rows = np.flatnonzero(ids["userId"] == key)
        bias = re.coefficients[key][1][0]
        m = Xg[rows] @ w_fe + bias
        p = 1 / (1 + np.exp(-m))
        H = np.sum(p * (1 - p)) + 0.5  # l2 = reg_weight
        assert re.variances[key][0] == pytest.approx(1.0 / H, rel=1e-3)

    def test_variances_off_by_default(self, rng):
        shards, ids, y, *_ = _data(rng)
        est = GameEstimator(
            "logistic", _configs(compute_variances=False), n_iterations=1
        )
        model, _ = est.fit(shards, ids, y)
        assert model["fixed"].model.coefficients.variances is None
        assert model["per_user"].variances is None

    def test_store_round_trip_preserves_variances(self, rng, tmp_path):
        from photon_ml_tpu.data.index_map import IndexMap
        from photon_ml_tpu.io.game_store import (
            load_game_model,
            save_game_model,
        )

        shards, ids, y, *_ = _data(rng)
        est = GameEstimator("logistic", _configs(), n_iterations=1)
        model, _ = est.fit(shards, ids, y)
        imaps = {
            "global": IndexMap.build([f"g{j}" for j in range(3)]),
            "userFeatures": IndexMap.build(["bias"]),
        }
        out = str(tmp_path / "m")
        save_game_model(model, imaps, out)
        loaded, _ = load_game_model(out)
        orig = model["per_user"].variances
        got = loaded["per_user"].variances
        assert got is not None and set(got) == set(orig)
        for k in orig:
            np.testing.assert_allclose(got[k], orig[k], rtol=1e-6)


class TestGroupedEvaluation:
    def test_per_query_metric_in_history_and_driver(self, rng, tmp_path):
        from photon_ml_tpu.data.game_reader import write_game_avro
        from photon_ml_tpu.drivers import game_training_driver

        shards, ids, y, users, Xg = _data(rng, n=400)
        # Query column: few rows per query.
        queries = np.array([f"q{i % 40}" for i in range(400)])
        ids = dict(ids, queryId=queries)

        suite = EvaluationSuite.from_specs(
            ["auc", "precision@2"], group_column="queryId"
        )
        est = GameEstimator("logistic", _configs(False), n_iterations=1)
        model, history = est.fit(
            shards, ids, y, validation=(shards, ids, y), suite=suite,
        )
        # Grouped AUC (mean of per-query AUCs) and precision@2 both present.
        assert set(history[-1]["validation"]) == {"auc", "precision@2"}
        assert 0 <= history[-1]["validation"]["precision@2"] <= 1

        # Driver-level: evaluator_group_column in the JSON config.
        rows = []
        for i in range(400):
            rows.append({
                "uid": f"r{i}", "response": float(y[i]), "weight": None,
                "offset": None,
                "ids": {"userId": ids["userId"][i], "queryId": queries[i]},
                "features": {
                    "global": [
                        {"name": f"g{j}", "term": "", "value": float(Xg[i, j])}
                        for j in range(3)
                    ],
                    "userFeatures": [{"name": "b", "term": "", "value": 1.0}],
                },
            })
        train = str(tmp_path / "t.avro")
        val = str(tmp_path / "v.avro")
        write_game_avro(train, rows[:300])
        write_game_avro(val, rows[300:])
        cfg = {
            "task": "logistic", "iterations": 1,
            "evaluators": ["auc"],
            "evaluator_group_column": "queryId",
            "coordinates": [
                {"name": "fixed", "type": "fixed", "feature_shard": "global",
                 "optimizer": "lbfgs", "max_iters": 25, "reg_type": "l2",
                 "reg_weight": 0.5, "compute_variances": True},
                {"name": "per_user", "type": "random",
                 "feature_shard": "userFeatures", "entity_key": "userId",
                 "optimizer": "lbfgs", "max_iters": 20, "reg_type": "l2",
                 "reg_weight": 0.5},
            ],
        }
        cfgp = str(tmp_path / "c.json")
        with open(cfgp, "w") as f:
            json.dump(cfg, f)
        result = game_training_driver.run([
            "--train-data", train, "--validate-data", val,
            "--config", cfgp, "--output-dir", str(tmp_path / "out"),
        ])
        # Per-query mean AUC is a valid number in (0, 1].
        assert 0 < result["validation_metric"] <= 1
