"""FactoredRandomEffectCoordinate: w_e = V u_e through a shared low-rank
projection (the reference's factored random effects, SURVEY.md §2)."""

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from photon_ml_tpu.data.dataset import make_glm_data
from photon_ml_tpu.game.coordinates import (
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.data import (
    FixedEffectDataset,
    build_random_effect_dataset,
)
from photon_ml_tpu.game.descent import CoordinateDescent
from photon_ml_tpu.game.factored import FactoredRandomEffectCoordinate
from photon_ml_tpu.optim.problem import GlmOptimizationConfig, OptimizerConfig
from photon_ml_tpu.optim.regularization import RegularizationContext


def _rank1_problem(rng, n_entities=60, rows=6, d=12):
    """Entities whose TRUE coefficients share one direction: w_e = a_e * v."""
    v = rng.normal(size=d).astype(np.float32)
    v /= np.linalg.norm(v)
    a = rng.normal(size=n_entities).astype(np.float32) * 2.0
    n = n_entities * rows
    users = np.repeat(np.array([f"u{i}" for i in range(n_entities)]), rows)
    X = rng.normal(size=(n, d)).astype(np.float32)
    margins = np.sum(X * (a[:, None] * v[None, :])[np.repeat(
        np.arange(n_entities), rows)], axis=1)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margins))).astype(np.float32)
    return users, X, y, v


@pytest.fixture
def opt_config():
    return GlmOptimizationConfig(
        optimizer=OptimizerConfig(max_iters=25, tolerance=1e-8),
        regularization=RegularizationContext.l2(),
    )


class TestFactoredCoordinate:
    def test_rank1_recovers_shared_direction(self, rng, opt_config):
        users, X, y, v_true = _rank1_problem(rng)
        ds = build_random_effect_dataset(
            users, sp.csr_matrix(X), y, np.ones(len(y), np.float32)
        )
        coord = FactoredRandomEffectCoordinate(
            "fre", ds, "logistic", opt_config, rank=1,
            reg_weight=0.1, alternations=3, entity_key="userId",
        )
        state = coord.train(jnp.zeros(len(y), jnp.float32))
        _, V = state
        v_learned = np.asarray(V)[:, 0]
        cos = abs(
            v_learned @ v_true
            / max(np.linalg.norm(v_learned) * np.linalg.norm(v_true), 1e-12)
        )
        assert cos > 0.8, f"projection direction not recovered (cos={cos:.3f})"

    def test_full_rank_matches_plain_random_effect_quality(
        self, rng, opt_config
    ):
        from sklearn.metrics import roc_auc_score

        users, X, y, _ = _rank1_problem(rng, n_entities=40, rows=10, d=6)
        w = np.ones(len(y), np.float32)
        ds = build_random_effect_dataset(users, sp.csr_matrix(X), y, w)
        base = jnp.zeros(len(y), jnp.float32)

        plain = RandomEffectCoordinate(
            "re", ds, "logistic", opt_config, reg_weight=0.5,
            entity_key="userId",
        )
        s_plain = np.asarray(plain.score(plain.train(base)))

        factored = FactoredRandomEffectCoordinate(
            "fre", ds, "logistic", opt_config, rank=6,
            reg_weight=0.5, alternations=3, entity_key="userId",
        )
        s_fact = np.asarray(factored.score(factored.train(base)))

        auc_plain = roc_auc_score(y, s_plain)
        auc_fact = roc_auc_score(y, s_fact)
        # Full-rank factorization spans the same model space; quality must
        # be comparable (parametrization/regularization differ slightly).
        assert auc_fact > auc_plain - 0.03, (auc_fact, auc_plain)

    def test_low_rank_beats_independent_fits_on_sparse_entities(
        self, rng, opt_config
    ):
        from sklearn.metrics import roc_auc_score

        # 4 training rows per entity in 10-d with rank-1 truth, evaluated
        # on HELD-OUT rows of the SAME entities: independent per-entity
        # fits can't borrow strength across entities; the factored
        # coordinate learns the shared direction from everyone.  (With
        # fewer rows per entity the alternation can land in a local
        # optimum that fits train rows through a wrong direction —
        # inherent to alternating factorizations, not tested.)
        users, X, y, _ = _rank1_problem(rng, n_entities=120, rows=8, d=10)
        rows = 8
        n_ent = 120
        idx = np.arange(len(y)).reshape(n_ent, rows)
        train_i = idx[:, :4].ravel()
        test_i = idx[:, 4:].ravel()
        w = np.ones(len(train_i), np.float32)
        ds = build_random_effect_dataset(
            users[train_i], sp.csr_matrix(X[train_i]), y[train_i], w
        )
        base = jnp.zeros(len(train_i), jnp.float32)

        plain = RandomEffectCoordinate(
            "re", ds, "logistic", opt_config, reg_weight=1.0,
            entity_key="userId",
        )
        factored = FactoredRandomEffectCoordinate(
            "fre", ds, "logistic", opt_config, rank=1,
            reg_weight=1.0, alternations=6, entity_key="userId",
        )
        m_plain = plain.finalize(plain.train(base))
        m_fact = factored.finalize(factored.train(base))

        def score_model(model, which):
            out = np.zeros(len(which))
            for j, i in enumerate(which):
                ent = model.coefficients.get(users[i])
                if ent is None:
                    continue
                cols, vals = ent
                out[j] = float(np.sum(X[i][cols] * vals))
            return out

        auc_plain = roc_auc_score(y[test_i], score_model(m_plain, test_i))
        auc_fact = roc_auc_score(y[test_i], score_model(m_fact, test_i))
        assert auc_fact > auc_plain + 0.02, (auc_fact, auc_plain)

    def test_finalize_matches_score_on_training_rows(self, rng, opt_config):
        users, X, y, _ = _rank1_problem(rng, n_entities=30, rows=5, d=8)
        w = np.ones(len(y), np.float32)
        ds = build_random_effect_dataset(users, sp.csr_matrix(X), y, w)
        coord = FactoredRandomEffectCoordinate(
            "fre", ds, "logistic", opt_config, rank=2,
            reg_weight=0.3, alternations=2, entity_key="userId",
        )
        state = coord.train(jnp.zeros(len(y), jnp.float32))
        device_scores = np.asarray(coord.score(state))
        model = coord.finalize(state)
        for i in rng.choice(len(y), size=20, replace=False):
            cols, vals = model.coefficients[users[i]]
            host = float(np.sum(X[i][cols] * vals))
            np.testing.assert_allclose(host, device_scores[i], rtol=2e-4,
                                       atol=1e-5)

    def test_warm_start_and_cd_integration(self, rng, opt_config):
        users, X, y, _ = _rank1_problem(rng, n_entities=50, rows=4, d=8)
        n = len(y)
        w = np.ones(n, np.float32)
        Xg = sp.csr_matrix(
            rng.normal(size=(n, 16)).astype(np.float32)
        )
        fixed = FixedEffectCoordinate(
            "fixed",
            FixedEffectDataset(data=make_glm_data(Xg, y), n_global_rows=n),
            "logistic", opt_config, reg_weight=0.5,
        )
        ds = build_random_effect_dataset(users, sp.csr_matrix(X), y, w)
        factored = FactoredRandomEffectCoordinate(
            "fre", ds, "logistic", opt_config, rank=2,
            reg_weight=0.5, alternations=1, entity_key="userId",
        )
        cd = CoordinateDescent([fixed, factored])
        result = cd.run(jnp.zeros(n, jnp.float32), n_iterations=2)
        total = np.asarray(result.scores["fixed"] + result.scores["fre"])
        assert np.all(np.isfinite(total))
        # Warm start: training again from the final state stays finite and
        # reuses the state structure.
        st = result.states["fre"]
        st2 = factored.train(result.scores["fixed"], warm_state=st)
        assert len(st2) == 2 and len(st2[0]) == len(st[0])

    def test_bad_rank_raises(self, rng, opt_config):
        users, X, y, _ = _rank1_problem(rng, n_entities=5, rows=3, d=4)
        ds = build_random_effect_dataset(
            users, sp.csr_matrix(X), y, np.ones(len(y), np.float32)
        )
        with pytest.raises(ValueError, match="rank"):
            FactoredRandomEffectCoordinate(
                "fre", ds, "logistic", opt_config, rank=0,
            )


class TestEntityShardedFactored:
    """Factored random effects on a mesh: sharded block placement is the
    whole distribution — the latent step partitions communication-free
    across entity lanes, and the projection gradient's scatter into the
    replicated V gradient is the cross-shard reduction the shared fit
    needs (GSPMD inserts it)."""

    def test_mesh_matches_single_device(self, rng, opt_config, eight_devices):
        from photon_ml_tpu.game.distributed import (
            entity_sharded_factored_coordinate,
        )
        from photon_ml_tpu.parallel.distributed import data_mesh

        mesh = data_mesh(eight_devices)
        users, X, y, _v = _rank1_problem(rng, n_entities=50, rows=5)
        w = np.ones(len(y), np.float32)
        ds_plain = build_random_effect_dataset(users, sp.csr_matrix(X), y, w)
        ds_host = build_random_effect_dataset(
            users, sp.csr_matrix(X), y, w, device=False
        )
        single = FactoredRandomEffectCoordinate(
            "fre", ds_plain, "logistic", opt_config, rank=2,
            reg_weight=0.3, alternations=2, entity_key="userId",
        )
        sharded = entity_sharded_factored_coordinate(
            "fre", ds_host, mesh, "logistic", opt_config, rank=2,
            reg_weight=0.3, alternations=2, entity_key="userId",
        )
        offsets = jnp.zeros(len(y), jnp.float32)
        st_s = single.train(offsets)
        st_m = sharded.train(offsets)
        # Same tolerance class as the other sharded-vs-plain parity
        # tests: sharded lowering reorders float ops in the iterative
        # alternation.
        np.testing.assert_allclose(
            np.asarray(single.score(st_s)), np.asarray(sharded.score(st_m)),
            rtol=1e-2, atol=2e-3,
        )
        t_s = single.finalize(st_s).coefficients
        t_m = sharded.finalize(st_m).coefficients
        assert set(t_s) == set(t_m)  # padding lanes dropped

    def test_estimator_routes_factored_to_mesh(
        self, rng, opt_config, eight_devices
    ):
        from photon_ml_tpu.game.estimator import (
            FactoredRandomEffectCoordinateConfig,
            FixedEffectCoordinateConfig,
            GameEstimator,
        )
        from photon_ml_tpu.parallel.distributed import data_mesh

        mesh = data_mesh(eight_devices)
        users, X, y, _v = _rank1_problem(rng, n_entities=40, rows=4)
        shards = {
            "global": sp.csr_matrix(
                rng.normal(size=(len(y), 3)).astype(np.float32)
            ),
            "uf": sp.csr_matrix(X),
        }
        ids = {"userId": users}
        est = GameEstimator(
            "logistic",
            {
                "fixed": FixedEffectCoordinateConfig(
                    "global", opt_config, reg_weight=0.5
                ),
                "fre": FactoredRandomEffectCoordinateConfig(
                    "uf", "userId", rank=2, optimization=opt_config,
                    reg_weight=0.3,
                ),
            },
            n_iterations=2,
            mesh=mesh,
        )
        coords = est.build_coordinates(shards, ids, y)
        assert getattr(coords[1], "mesh", None) is mesh
        model, history = est.fit(shards, ids, y)
        assert "fre" in model.models
        assert np.isfinite(history[-1]["score_norm"])


class TestOutOfCoreFactored:
    """Out-of-core factored random effects (game/ooc_factored.py): the
    last coordinate-type x residency cell.  Entity blocks stream in
    budget-bounded groups; latent vectors host-resident between passes;
    the shared V fits by host-loop L-BFGS over streamed passes — so the
    trajectory must match the resident coordinate's alternation to float
    tolerance (same solvers, same math, different residency)."""

    def _coords(self, rng, opt_config, budget, **kw):
        from photon_ml_tpu.game.ooc_factored import (
            OutOfCoreFactoredRandomEffectCoordinate,
        )

        users, X, y, _v = _rank1_problem(rng, n_entities=50, rows=5)
        w = np.ones(len(y), np.float32)
        resident = FactoredRandomEffectCoordinate(
            "fre",
            build_random_effect_dataset(users, sp.csr_matrix(X), y, w),
            "logistic", opt_config, rank=2, reg_weight=0.3,
            alternations=2, entity_key="userId", **kw,
        )
        ooc = OutOfCoreFactoredRandomEffectCoordinate(
            "fre",
            build_random_effect_dataset(
                users, sp.csr_matrix(X), y, w, device=False
            ),
            "logistic", opt_config, rank=2, reg_weight=0.3,
            alternations=2, entity_key="userId",
            device_budget_bytes=budget, **kw,
        )
        return resident, ooc, y

    def test_parity_with_resident_across_budgets(self, rng, opt_config):
        resident, ooc, y = self._coords(rng, opt_config, 40_000)
        assert len(ooc.pass_plan) >= 2
        offsets = jnp.zeros(len(y), jnp.float32)
        st_r = resident.train(offsets)
        st_o = ooc.train(offsets)
        u_r, V_r = st_r
        u_o, V_o = st_o
        # Host-loop vs in-jit L-BFGS rounding compounds over the
        # alternations; same tolerance class as the other streamed-vs-
        # resident trajectory parity tests.
        np.testing.assert_allclose(
            np.asarray(V_r), np.asarray(V_o), rtol=1e-2, atol=3e-3
        )
        np.testing.assert_allclose(
            np.asarray(resident.score(st_r)), np.asarray(ooc.score(st_o)),
            rtol=1e-2, atol=5e-3,
        )
        # Warm restart round-trips host/device state shapes.
        st_o2 = ooc.train(offsets, warm_state=st_o)
        np.testing.assert_allclose(
            np.asarray(ooc.score(st_o2)),
            np.asarray(resident.score(resident.train(
                offsets, warm_state=st_r
            ))),
            rtol=1e-2, atol=5e-3,
        )

    def test_finalize_tables_match(self, rng, opt_config):
        resident, ooc, y = self._coords(rng, opt_config, 40_000)
        offsets = jnp.zeros(len(y), jnp.float32)
        t_r = resident.finalize(resident.train(offsets)).coefficients
        t_o = ooc.finalize(ooc.train(offsets)).coefficients
        assert set(t_r) == set(t_o)
        for k, (cols, vals) in t_r.items():
            np.testing.assert_array_equal(cols, t_o[k][0])
            np.testing.assert_allclose(vals, t_o[k][1], atol=5e-3)

    def test_budget_and_overlap_discipline(self, rng, opt_config):
        _, ooc, y = self._coords(rng, opt_config, 40_000)
        per_pass = (
            ooc.device_budget_bytes - ooc._budget_overhead_bytes()
        ) // 2
        for group in ooc.pass_plan:
            assert sum(s.bytes for s in group) <= per_pass
        ooc.train(jnp.zeros(len(y), jnp.float32))
        # The permit bound is exact (never 3); reaching 2 depends on the
        # producer thread winning the dispatch race, which a loaded
        # 1-CPU box does not guarantee.
        assert 1 <= ooc.live_groups_high_water <= 2

    def test_estimator_routes_ooc_factored(self, rng, opt_config):
        from photon_ml_tpu.game.estimator import (
            FactoredRandomEffectCoordinateConfig,
            FixedEffectCoordinateConfig,
            GameEstimator,
        )
        from photon_ml_tpu.game.ooc_factored import (
            OutOfCoreFactoredRandomEffectCoordinate,
        )

        users, X, y, _v = _rank1_problem(rng, n_entities=30, rows=4)
        shards = {
            "global": sp.csr_matrix(
                rng.normal(size=(len(y), 3)).astype(np.float32)
            ),
            "uf": sp.csr_matrix(X),
        }
        ids = {"userId": users}
        est = GameEstimator(
            "logistic",
            {
                "fixed": FixedEffectCoordinateConfig(
                    "global", opt_config, reg_weight=0.5
                ),
                "fre": FactoredRandomEffectCoordinateConfig(
                    "uf", "userId", rank=2, optimization=opt_config,
                    reg_weight=0.3, device_budget_bytes=60_000,
                ),
            },
            n_iterations=1,
        )
        coords = est.build_coordinates(shards, ids, y)
        assert isinstance(
            coords[1], OutOfCoreFactoredRandomEffectCoordinate
        )
        model, history = est.fit(shards, ids, y)
        assert "fre" in model.models
        assert np.isfinite(history[-1]["score_norm"])
