"""Continuous train->serve loop tests (ISSUE 12).

The load-bearing contracts:

- a delta diffed from two models and applied back is BITWISE-IDENTICAL
  to loading the target outright — for a plain GLM and for a
  multi-coordinate GAME model (modified, added, AND removed entities);
- a tampered or torn artifact is refused with a pointed error naming
  the file, and applying against the wrong base refuses BEFORE touching
  anything (whole-base checksum verification);
- a publish killed at EVERY record/rename boundary resumes exactly:
  either the publication is completed (artifact already durable) or
  cleanly aborted — subscribers never see a half-publish;
- retention (keep-last-K, ISSUE 13) prunes artifacts + compacts the
  journal crash-safely: never the newest commit, never an unsettled
  begin, never past a registered subscriber's ack, and sequence
  numbering survives compaction and reopen;
- the serving delta path (``swap_delta``) patches live replicas with
  shared compiled kernels, rides the version registry (one-step
  rollback), and rolls back on a bad artifact with the old version
  still serving — in-process and across process workers;
- online refinement is deterministic, only touches what the events
  touched, and publishes through the same artifact path;
- ``read_fingerprints`` answers cheaply on current stores and points
  legacy fingerprint-less saves at a re-save;
- the tuning executor seeds warm starts from an explicitly published
  model directory.
"""

import json
import os
import threading

import numpy as np
import pytest

from photon_ml_tpu import chaos
from photon_ml_tpu import telemetry
from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.freshness.applier import DeltaApplier
from photon_ml_tpu.freshness.delta import (
    DeltaBaseMismatchError,
    DeltaError,
    DeltaFormatError,
    apply_delta,
    diff_game_models,
    diff_model_dirs,
    model_table_checksums,
    read_delta,
    write_delta,
)
from photon_ml_tpu.freshness.online import (
    LabeledEvent,
    OnlineRefiner,
    RefinerConfig,
)
from photon_ml_tpu.freshness.publisher import (
    DeltaPublisher,
    PublishAborted,
    read_acks,
    read_publications,
    write_ack,
)
from photon_ml_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.io.game_store import save_game_model
from photon_ml_tpu.io import game_store, model_store
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.serving.batcher import BatcherConfig
from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
from photon_ml_tpu.serving.service import ScoringService
from photon_ml_tpu.serving.supervisor import ReplicaSupervisor
from photon_ml_tpu.serving.synthetic import SyntheticWorkload


@pytest.fixture(scope="module")
def workload():
    return SyntheticWorkload(n_entities=32, seed=7)


def _perturbed(seed=7, n_entities=32):
    """A copy of `workload`'s world with 5 modified, 1 added, and 1
    removed random-effect entity plus shifted fixed means — the shape
    of a real incremental retrain."""
    w = SyntheticWorkload(n_entities=n_entities, seed=seed)
    re = w.model.models["per_entity"]
    for k in [f"u{i}" for i in range(5)]:
        cols, vals = re.coefficients[k]
        re.coefficients[k] = (
            cols, (vals + np.float32(0.25)).astype(np.float32)
        )
    cols = np.arange(w.re_dim, dtype=np.int32)
    re.coefficients["brand_new"] = (
        cols, np.full(w.re_dim, 0.5, np.float32)
    )
    del re.coefficients[f"u{n_entities - 1}"]
    fixed = w.model.models["fixed"].model
    fixed.coefficients.means = (
        np.asarray(fixed.coefficients.means, np.float32) + np.float32(0.125)
    )
    return w


def _assert_bitwise_equal(got: GameModel, want: GameModel):
    assert model_table_checksums(got) == model_table_checksums(want)
    for name, coord in want.models.items():
        other = got.models[name]
        if isinstance(coord, RandomEffectModel):
            assert set(other.coefficients) == set(coord.coefficients)
            for k, (cols, vals) in coord.coefficients.items():
                assert other.coefficients[k][0].tobytes() == cols.tobytes()
                assert other.coefficients[k][1].tobytes() == vals.tobytes()
        else:
            assert (
                np.asarray(other.model.coefficients.means).tobytes()
                == np.asarray(coord.model.coefficients.means).tobytes()
            )


class TestDeltaRoundTrip:
    def test_game_multi_coordinate_bitwise(self, tmp_path, workload):
        target = _perturbed()
        delta = diff_game_models(
            workload.model, target.model, event_wall_epoch=123.0
        )
        names = {c.name for c in delta.changed_coordinates}
        assert names == {"fixed", "per_entity"}
        ddir = str(tmp_path / "delta")
        write_delta(delta, ddir)
        patched = apply_delta(workload.model, read_delta(ddir))
        _assert_bitwise_equal(patched, target.model)
        # The base was never mutated (apply builds new objects).
        assert "brand_new" not in workload.model.models[
            "per_entity"
        ].coefficients

    def test_diff_model_dirs_uses_fingerprints(self, tmp_path, workload):
        target = _perturbed()
        d1, d2 = str(tmp_path / "v1"), str(tmp_path / "v2")
        save_game_model(workload.model, workload.index_maps, d1)
        save_game_model(target.model, target.index_maps, d2)
        delta = diff_model_dirs(d1, d2, event_wall_epoch=5.0)
        assert delta.event_wall_epoch == 5.0
        base, _ = ScoringRuntime.load_model(d1)
        want, _ = ScoringRuntime.load_model(d2)
        ddir = str(tmp_path / "delta")
        write_delta(delta, ddir)
        _assert_bitwise_equal(apply_delta(base, read_delta(ddir)), want)

    def test_glm_avro_bitwise(self, tmp_path):
        imap = IndexMap.build(
            [feature_key(f"f{j}", "") for j in range(6)]
        )
        m1 = GeneralizedLinearModel(
            Coefficients(
                means=np.arange(1, 7, dtype=np.float32) * np.float32(0.3)
            ),
            "logistic",
        )
        m2 = GeneralizedLinearModel(
            Coefficients(
                means=np.asarray(m1.coefficients.means) + np.float32(0.5)
            ),
            "logistic",
        )
        p1, p2 = str(tmp_path / "m1.avro"), str(tmp_path / "m2.avro")
        model_store.save_glm_model(m1, imap, p1)
        model_store.save_glm_model(m2, imap, p2)
        delta = diff_model_dirs(p1, p2)
        ddir = str(tmp_path / "delta")
        write_delta(delta, ddir)
        base, _ = ScoringRuntime.load_model(p1)
        want, _ = ScoringRuntime.load_model(p2)
        patched = apply_delta(base, read_delta(ddir))
        _assert_bitwise_equal(patched, want)

    def test_identical_models_make_empty_delta(self, workload):
        w2 = SyntheticWorkload(n_entities=32, seed=7)
        delta = diff_game_models(workload.model, w2.model)
        assert delta.empty and delta.n_changed_rows == 0

    def test_structural_change_refused(self, workload):
        re = workload.model.models["per_entity"]
        other = GameModel(
            models={
                "fixed": workload.model.models["fixed"],
                "renamed": re,
            },
            task=workload.model.task,
        )
        with pytest.raises(DeltaError, match="coordinate"):
            diff_game_models(workload.model, other)


class TestArtifactIntegrity:
    def _delta_dir(self, tmp_path, workload) -> str:
        ddir = str(tmp_path / "delta")
        write_delta(
            diff_game_models(workload.model, _perturbed().model), ddir
        )
        return ddir

    def test_flipped_segment_byte_refused(self, tmp_path, workload):
        ddir = self._delta_dir(tmp_path, workload)
        seg = next(
            os.path.join(ddir, f) for f in os.listdir(ddir)
            if f.startswith("segment-")
        )
        with open(seg, "r+b") as f:
            f.seek(-8, os.SEEK_END)
            byte = f.read(1)
            f.seek(-8, os.SEEK_END)
            f.write(bytes([byte[0] ^ 0xFF]))
        with pytest.raises(DeltaFormatError, match=os.path.basename(seg)):
            read_delta(ddir)

    def test_truncated_manifest_refused(self, tmp_path, workload):
        ddir = self._delta_dir(tmp_path, workload)
        manifest = os.path.join(ddir, "delta.json")
        with open(manifest, "r+") as f:
            f.truncate(os.path.getsize(manifest) // 2)
        with pytest.raises(DeltaFormatError):
            read_delta(ddir)

    def test_edited_manifest_refused(self, tmp_path, workload):
        ddir = self._delta_dir(tmp_path, workload)
        manifest = os.path.join(ddir, "delta.json")
        with open(manifest) as f:
            body = json.load(f)
        body["task"] = "poisson"
        with open(manifest, "w") as f:
            json.dump(body, f)
        with pytest.raises(DeltaFormatError, match="digest"):
            read_delta(ddir)

    def test_wrong_base_refused_before_patching(self, tmp_path, workload):
        ddir = self._delta_dir(tmp_path, workload)
        stranger = SyntheticWorkload(n_entities=32, seed=9)
        before = model_table_checksums(stranger.model)
        with pytest.raises(DeltaBaseMismatchError, match="DIFFERENT base"):
            apply_delta(stranger.model, read_delta(ddir))
        assert model_table_checksums(stranger.model) == before


class TestPublisher:
    def _delta(self, workload):
        return diff_game_models(
            workload.model, _perturbed().model, event_wall_epoch=42.0
        )

    def test_publish_and_read_back(self, tmp_path, workload):
        root = str(tmp_path / "pubs")
        with telemetry.Telemetry(sinks=[]):
            with DeltaPublisher(root) as pub:
                p = pub.publish(self._delta(workload))
                assert p.seq == 1 and p.event_wall_epoch == 42.0
                assert pub.publications() == [p]
        # Read-only subscriber view agrees without touching the journal.
        assert read_publications(root) == [p]
        patched = apply_delta(workload.model, read_delta(p.path))
        _assert_bitwise_equal(patched, _perturbed().model)

    def test_crash_at_every_chaos_boundary_resumes(
        self, tmp_path, workload
    ):
        # Occurrences 0/1/2 of publish.delta bracket journal-begin,
        # artifact staging, and the commit record.  A kill at each must
        # resume to a settled root: completed iff the rename happened.
        for at, settled_as in ((0, "abort"), (1, "abort"), (2, "commit")):
            root = str(tmp_path / f"pubs{at}")
            with telemetry.Telemetry(sinks=[]):
                plan = chaos.FaultPlan([
                    chaos.FaultSpec(site="publish.delta", at=at),
                ])
                pub = DeltaPublisher(root)
                with plan:
                    with pytest.raises(Exception, match="chaos-injected"):
                        pub.publish(self._delta(workload))
                pub.close()
                resumed = DeltaPublisher(root)
                records = resumed._read()
                assert records[-1]["kind"] == settled_as, f"at={at}"
                assert records[-1]["resumed"] is True
                pubs = resumed.publications()
                if settled_as == "commit":
                    assert len(pubs) == 1
                    patched = apply_delta(
                        workload.model, read_delta(pubs[0].path)
                    )
                    _assert_bitwise_equal(patched, _perturbed().model)
                else:
                    assert pubs == []
                    assert not any(
                        f.endswith(".staging")
                        for f in os.listdir(root)
                    )
                # The sequence is claimed either way; publishing again
                # continues past it.
                p2 = resumed.publish(self._delta(workload))
                assert p2.seq == 2
                resumed.close()

    def test_abort_after_journal_record_sweep(self, tmp_path, workload):
        # The tuning/state.py-style abort hook kills the append itself:
        # abort_after=0 dies before `begin`, =1 dies on `commit` (the
        # artifact is already renamed, so resume must COMPLETE it).
        for abort_after, n_pubs in ((0, 0), (1, 1)):
            root = str(tmp_path / f"abort{abort_after}")
            with telemetry.Telemetry(sinks=[]):
                pub = DeltaPublisher(root, abort_after=abort_after)
                with pytest.raises(PublishAborted):
                    pub.publish(self._delta(workload))
                pub.close()
                resumed = DeltaPublisher(root)
                assert len(resumed.publications()) == n_pubs
                resumed.close()

    def test_mid_file_journal_corruption_raises(self, tmp_path, workload):
        root = str(tmp_path / "pubs")
        with telemetry.Telemetry(sinks=[]):
            with DeltaPublisher(root) as pub:
                pub.publish(self._delta(workload))
        journal = os.path.join(root, DeltaPublisher.JOURNAL)
        lines = open(journal).read().splitlines()
        lines[0] = lines[0][: len(lines[0]) // 2]
        with open(journal, "w") as f:
            f.write("\n".join(lines) + "\n")
        with pytest.raises(DeltaError, match="corrupt journal"):
            read_publications(root)


class TestRetention:
    def _publish_n(self, root, workload, n):
        delta = diff_game_models(
            workload.model, _perturbed().model, event_wall_epoch=42.0
        )
        pub = DeltaPublisher(root)
        for _ in range(n):
            pub.publish(delta)
        return pub

    def test_keep_last_boundaries(self, tmp_path, workload):
        root = str(tmp_path / "pubs")
        with telemetry.Telemetry(sinks=[]) as tel:
            pub = self._publish_n(root, workload, 5)
            # keep_last == count: nothing to prune.
            assert pub.retain(5) == {
                "pruned": [], "blocked": [], "blocking": {},
                "kept": [1, 2, 3, 4, 5],
            }
            # keep the newest 3.
            s = pub.retain(3)
            assert s["pruned"] == [1, 2] and s["kept"] == [3, 4, 5]
            assert [p.seq for p in pub.publications()] == [3, 4, 5]
            assert not os.path.isdir(os.path.join(root, "delta-000001"))
            assert os.path.isdir(os.path.join(root, "delta-000003"))
            # keep_last=1: everything but the newest goes; the newest
            # commit itself is NEVER prunable (keep_last >= 1 enforced).
            s = pub.retain(1)
            assert s["pruned"] == [3, 4] and s["kept"] == [5]
            with pytest.raises(ValueError, match="newest"):
                pub.retain(0)
            with pytest.raises(ValueError, match="newest"):
                DeltaPublisher(str(tmp_path / "x"), retain_last=0)
            # Sequence numbering survives compaction…
            p6 = pub.publish(diff_game_models(
                workload.model, _perturbed().model
            ))
            assert p6.seq == 6
            pub.close()
            # …and a reopened publisher continues the same sequence.
            pub2 = DeltaPublisher(root)
            assert pub2._next_seq == 7
            assert [p.seq for p in read_publications(root)] == [5, 6]
            pub2.close()
            snap = tel.snapshot()
        assert snap["counters"]["freshness_retention_pruned_total"] == 4

    def test_unsettled_begin_survives_retention(self, tmp_path, workload):
        root = str(tmp_path / "pubs")
        with telemetry.Telemetry(sinks=[]):
            pub = self._publish_n(root, workload, 2)
            # Simulate an in-flight publish: begin journaled, no settle.
            with pub._lock:
                pub._append({
                    "kind": "begin", "seq": 3,
                    "publish_wall_epoch": 1.0,
                })
            s = pub.retain(1)
            assert s["pruned"] == [1] and s["kept"] == [2]
            kinds = {(r["kind"], r["seq"]) for r in pub._read()}
            assert ("begin", 3) in kinds  # in-flight claim preserved
            assert ("commit", 1) not in kinds
            pub.close()
            # The next constructor settles seq 3 as an abort, and the
            # claimed sequence stays burned.
            resumed = DeltaPublisher(root)
            assert resumed._next_seq == 4
            assert [p.seq for p in resumed.publications()] == [2]
            resumed.close()

    def test_crash_between_compaction_and_artifact_removal(
        self, tmp_path, workload, monkeypatch
    ):
        root = str(tmp_path / "pubs")
        with telemetry.Telemetry(sinks=[]):
            pub = self._publish_n(root, workload, 3)
            import photon_ml_tpu.freshness.publisher as publisher_mod

            real_rmtree = publisher_mod.shutil.rmtree
            calls = []

            def dying_rmtree(path, **kwargs):
                calls.append(path)
                raise OSError("chaos: killed before artifact removal")

            monkeypatch.setattr(
                publisher_mod.shutil, "rmtree", dying_rmtree
            )
            with pytest.raises(OSError, match="chaos"):
                pub.retain(1)
            monkeypatch.setattr(
                publisher_mod.shutil, "rmtree", real_rmtree
            )
            # The journal compacted BEFORE the crash: subscribers
            # already see only the kept publication; the pruned
            # artifact dirs are orphans on disk.
            assert [p.seq for p in read_publications(root)] == [3]
            assert os.path.isdir(os.path.join(root, "delta-000001"))
            # The next retention sweeps the orphans even with nothing
            # newly prunable.
            s = pub.retain(1)
            assert s["pruned"] == [] and s["kept"] == [3]
            assert not os.path.isdir(os.path.join(root, "delta-000001"))
            assert not os.path.isdir(os.path.join(root, "delta-000002"))
            assert os.path.isdir(os.path.join(root, "delta-000003"))
            pub.close()

    def test_acks_block_and_release_pruning(self, tmp_path, workload):
        root = str(tmp_path / "pubs")
        with telemetry.Telemetry(sinks=[]):
            pub = self._publish_n(root, workload, 4)
            write_ack(root, "replica-a", 2)
            write_ack(root, "replica-b", 4)
            assert read_acks(root) == {"replica-a": 2, "replica-b": 4}
            # The SLOWEST ack gates: 3 is prunable by age but unacked.
            s = pub.retain(1)
            assert s["pruned"] == [1, 2] and s["blocked"] == [3]
            assert [p.seq for p in pub.publications()] == [3, 4]
            write_ack(root, "replica-a", 4)
            s = pub.retain(1)
            assert s["pruned"] == [3] and s["blocked"] == []
            assert [p.seq for p in pub.publications()] == [4]
            pub.close()

    def test_retain_last_prunes_on_publish(self, tmp_path, workload):
        root = str(tmp_path / "pubs")
        delta = diff_game_models(workload.model, _perturbed().model)
        with telemetry.Telemetry(sinks=[]):
            with DeltaPublisher(root, retain_last=2) as pub:
                for _ in range(5):
                    pub.publish(delta)
                assert [p.seq for p in pub.publications()] == [4, 5]

    def test_ack_hygiene(self, tmp_path):
        root = str(tmp_path / "pubs")
        os.makedirs(root)
        with pytest.raises(ValueError, match="safe filename"):
            write_ack(root, "../escape", 1)
        write_ack(root, "ok", 7)
        # Garbage in acks/ is skipped, never fatal (ack writes are
        # atomic, so torn files are not ours).
        with open(os.path.join(root, "acks", "junk.json"), "w") as f:
            f.write("{not json")
        assert read_acks(root) == {"ok": 7}


class TestApplierAcks:
    def test_applier_registers_and_advances_ack(self, tmp_path, workload):
        with telemetry.Telemetry(sinks=[]):
            root = str(tmp_path / "pubs")
            middle = _perturbed()
            keeper = DeltaPublisher(root)
            keeper.publish(diff_game_models(
                workload.model, middle.model, event_wall_epoch=1.0
            ))
            keeper.publish(diff_game_models(
                middle.model, workload.model, event_wall_epoch=2.0
            ))
            service = ScoringService(_runtime(workload))
            applier = DeltaApplier(
                service, root, subscriber_id="replica_0"
            )
            # Registration at construction pins the whole root…
            assert read_acks(root) == {"replica_0": 0}
            s = keeper.retain(1)
            assert s["pruned"] == [] and s["blocked"] == [1]
            with service:
                applier.poll_once()
            assert applier.stats()["subscriber_id"] == "replica_0"
            # …and the ack follows the applied high-water mark, which
            # releases the consumed publication for pruning.
            assert read_acks(root) == {"replica_0": 2}
            s = keeper.retain(1)
            assert s["pruned"] == [1] and s["blocked"] == []
            keeper.close()

    def test_failed_applies_are_acked(self, tmp_path, workload):
        # Failed sequences are never retried, so the applier acks past
        # them — otherwise one poisoned delta would pin the root forever.
        with telemetry.Telemetry(sinks=[]):
            root, p = _publish_one(tmp_path, workload)
            stranger = SyntheticWorkload(n_entities=32, seed=9)
            service = ScoringService(_runtime(stranger))
            applier = DeltaApplier(service, root, subscriber_id="sub")
            with service:
                applier.poll_once()
            assert applier.failed == [p.seq]
            assert read_acks(root) == {"sub": p.seq}


def _runtime(workload, **kwargs):
    cfg = RuntimeConfig(
        **{"max_batch_size": 8, "hot_entities": 8, **kwargs}
    )
    return ScoringRuntime(workload.model, workload.index_maps, cfg)


def _publish_one(tmp_path, workload):
    root = str(tmp_path / "pubs")
    with DeltaPublisher(root) as pub:
        p = pub.publish(diff_game_models(
            workload.model, _perturbed().model, event_wall_epoch=1.0
        ))
    return root, p


class TestSwapDelta:
    def test_in_process_apply_parity_and_rollback(
        self, tmp_path, workload
    ):
        target = _perturbed()
        requests = [workload.request(i) for i in range(8)]
        want = np.asarray(
            [
                _runtime(target)
                .score_rows([_runtime(target).parse_request(r)])[0][0]
                for r in requests
            ],
            np.float32,
        )
        with telemetry.Telemetry(sinks=[]):
            root, p = _publish_one(tmp_path, workload)
            service = ScoringService(_runtime(workload))
            with service:
                before = service.batcher.runtime
                result = service.reload(p.path, mode="delta")
                assert result.status == "swapped", result
                assert service.swapper.version == 2
                after = service.batcher.runtime
                # Kernel shared by geometry, no recompiles on the patch.
                assert after._kernel is before._kernel
                assert after.warmup_compiles == 0
                got = np.asarray(
                    [
                        np.float32(service.score(r)["score"])
                        for r in requests
                    ],
                    np.float32,
                )
                assert got.tobytes() == want.tobytes()
                rb = service.reload(rollback=True)
                assert rb.status == "swapped" or rb.version_after == 1
                assert service.swapper.version == 1
                assert service.batcher.runtime is before

    def test_distinct_equal_base_objects_apply(self, tmp_path, workload):
        # Factory-restarted replicas hold different (bitwise-equal)
        # model objects; the delta still applies to every one.
        with telemetry.Telemetry(sinks=[]):
            root, p = _publish_one(tmp_path, workload)
            v1_dir = str(tmp_path / "v1")
            save_game_model(workload.model, workload.index_maps, v1_dir)
            cfg = RuntimeConfig(max_batch_size=8, hot_entities=8)
            supervisor = ReplicaSupervisor(
                lambda: ScoringRuntime.load(v1_dir, cfg), n_replicas=2,
                probe_interval_s=3600.0,
            )
            service = ScoringService(supervisor, BatcherConfig(
                max_batch_size=8, max_wait_us=1_000, max_queue=64,
            ))
            with service:
                models = {
                    id(r.batcher.runtime.model)
                    for r in supervisor.replicas
                }
                assert len(models) == 2  # genuinely distinct objects
                result = service.reload(p.path, mode="delta")
                assert result.status == "swapped", result
                want = model_table_checksums(_perturbed().model)
                for rep in supervisor.replicas:
                    assert model_table_checksums(
                        rep.batcher.runtime.model
                    ) == want

    def test_diverged_base_rolls_back(self, tmp_path, workload):
        with telemetry.Telemetry(sinks=[]):
            root, p = _publish_one(tmp_path, workload)
            stranger = SyntheticWorkload(n_entities=32, seed=9)
            service = ScoringService(_runtime(stranger))
            with service:
                result = service.reload(p.path, mode="delta")
                assert result.status == "rolled_back"
                assert "base" in result.reason
                assert service.swapper.version == 1

    def test_tampered_artifact_rolls_back(self, tmp_path, workload):
        with telemetry.Telemetry(sinks=[]):
            root, p = _publish_one(tmp_path, workload)
            seg = next(
                os.path.join(p.path, f) for f in os.listdir(p.path)
                if f.startswith("segment-")
            )
            with open(seg, "r+b") as f:
                f.seek(-8, os.SEEK_END)
                byte = f.read(1)
                f.seek(-8, os.SEEK_END)
                f.write(bytes([byte[0] ^ 0xFF]))
            service = ScoringService(_runtime(workload))
            with service:
                result = service.reload(p.path, mode="delta")
                assert result.status == "rolled_back"
                assert result.stage == "load"
                assert service.swapper.version == 1

    def test_chaos_verify_failure_restores(self, tmp_path, workload):
        requests = [workload.request(i) for i in range(4)]
        ref = np.asarray(
            [
                _runtime(workload)
                .score_rows([_runtime(workload).parse_request(r)])[0][0]
                for r in requests
            ],
            np.float32,
        )
        with telemetry.Telemetry(sinks=[]):
            root, p = _publish_one(tmp_path, workload)
            service = ScoringService(_runtime(workload))
            plan = chaos.FaultPlan([
                chaos.FaultSpec(site="publish.apply", at=2),
            ])
            with service:
                with plan:
                    result = service.reload(p.path, mode="delta")
                assert result.status == "rolled_back"
                assert result.stage == "verify"
                assert service.swapper.version == 1
                got = np.asarray(
                    [
                        np.float32(service.score(r)["score"])
                        for r in requests
                    ],
                    np.float32,
                )
                assert got.tobytes() == ref.tobytes()

    def test_unknown_reload_mode_raises(self, workload):
        with telemetry.Telemetry(sinks=[]):
            service = ScoringService(_runtime(workload))
            with service:
                with pytest.raises(ValueError, match="mode"):
                    service.reload("/nowhere", mode="sideways")


class TestApplier:
    def test_poll_applies_in_order_and_skips_failed(
        self, tmp_path, workload
    ):
        with telemetry.Telemetry(sinks=[]) as tel:
            root = str(tmp_path / "pubs")
            middle = _perturbed()
            final = _perturbed()
            re = final.model.models["per_entity"]
            cols, vals = re.coefficients["u0"]
            re.coefficients["u0"] = (
                cols, (vals + np.float32(1.0)).astype(np.float32)
            )
            with DeltaPublisher(root) as pub:
                p1 = pub.publish(diff_game_models(
                    workload.model, middle.model, event_wall_epoch=1.0
                ))
                p2 = pub.publish(diff_game_models(
                    middle.model, final.model, event_wall_epoch=2.0
                ))
            service = ScoringService(_runtime(workload))
            applier = DeltaApplier(service, root)
            with service:
                results = applier.poll_once()
                assert [r.status for r in results] == [
                    "swapped", "swapped"
                ]
                assert applier.applied == 2 and not applier.failed
                assert service.swapper.version == 3
                assert model_table_checksums(
                    service.batcher.runtime.model
                ) == model_table_checksums(final.model)
                # Nothing pending; a second poll is a no-op.
                assert applier.poll_once() == []
            snap = tel.snapshot()
        assert snap["counters"]["freshness_deltas_applied_total"] == 2
        assert (
            snap["histograms"]["freshness_event_to_servable_seconds"][
                "count"
            ] == 2
        )
        assert snap["gauges"]["freshness_model_age_seconds"] >= 0.0

    def test_failed_apply_recorded_not_retried(self, tmp_path, workload):
        with telemetry.Telemetry(sinks=[]) as tel:
            root, p = _publish_one(tmp_path, workload)
            stranger = SyntheticWorkload(n_entities=32, seed=9)
            service = ScoringService(_runtime(stranger))
            applier = DeltaApplier(service, root)
            with service:
                results = applier.poll_once()
                assert [r.status for r in results] == ["rolled_back"]
                assert applier.failed == [p.seq]
                assert applier.poll_once() == []  # no retry storm
            snap = tel.snapshot()
        assert snap["counters"]["freshness_apply_failures_total"] == 1

    def test_background_thread_lifecycle(self, tmp_path, workload):
        with telemetry.Telemetry(sinks=[]):
            root, p = _publish_one(tmp_path, workload)
            service = ScoringService(_runtime(workload))
            with service:
                applier = DeltaApplier(
                    service, root, poll_interval_s=0.01
                )
                with applier:
                    deadline = 100
                    while applier.applied < 1 and deadline:
                        deadline -= 1
                        threading.Event().wait(0.05)
                assert applier.applied == 1
                assert service.swapper.version == 2


class TestOnlineRefiner:
    def _events(self, workload, n=30, seed=5):
        rng = np.random.default_rng(seed)
        return [
            LabeledEvent(
                features={
                    workload.fixed_shard: rng.normal(
                        size=workload.fixed_dim
                    ).astype(np.float32),
                    workload.re_shard: rng.normal(
                        size=workload.re_dim
                    ).astype(np.float32),
                },
                ids={workload.entity_key: f"u{rng.integers(6)}"},
                label=float(rng.integers(2)),
                wall_epoch=float(10 + rng.integers(5)),
            )
            for _ in range(n)
        ]

    def test_deterministic_and_touch_scoped(self, workload):
        with telemetry.Telemetry(sinks=[]):
            events = self._events(workload)
            a = OnlineRefiner(workload.model, RefinerConfig(seed=1))
            b = OnlineRefiner(workload.model, RefinerConfig(seed=1))
            a.consume(events)
            b.consume(events)
            ra, rb = a.refined_model(), b.refined_model()
            assert model_table_checksums(ra) == model_table_checksums(rb)
            touched = set(a.touched["per_entity"])
            assert touched  # events reached entities
            base_re = workload.model.models["per_entity"]
            out_re = ra.models["per_entity"]
            for k, pair in base_re.coefficients.items():
                if k not in touched:
                    # Untouched rows share the base arrays outright.
                    assert out_re.coefficients[k] is pair
            assert a.latest_event_wall == max(
                e.wall_epoch for e in events
            )

    def test_delta_roundtrips_through_publish(self, tmp_path, workload):
        with telemetry.Telemetry(sinks=[]):
            ref = OnlineRefiner(workload.model, RefinerConfig(seed=2))
            ref.consume(self._events(workload))
            with DeltaPublisher(str(tmp_path / "pubs")) as pub:
                p = ref.publish(pub)
            patched = apply_delta(workload.model, read_delta(p.path))
            _assert_bitwise_equal(patched, ref.refined_model())
            assert p.event_wall_epoch == ref.latest_event_wall

    def test_sgd_moves_toward_labels(self, workload):
        # A LEARNABLE signal (one entity, one repeated feature vector,
        # fixed label): each step must shrink the error on that event.
        with telemetry.Telemetry(sinks=[]):
            cfg = RefinerConfig(algorithm="sgd", learning_rate=0.5)
            ref = OnlineRefiner(workload.model, cfg)
            rng = np.random.default_rng(3)
            event = LabeledEvent(
                features={
                    workload.fixed_shard: rng.normal(
                        size=workload.fixed_dim
                    ).astype(np.float32),
                    workload.re_shard: rng.normal(
                        size=workload.re_dim
                    ).astype(np.float32),
                },
                ids={workload.entity_key: "u0"},
                label=1.0,
            )
            errs = ref.consume([event] * 30)
            assert abs(errs[-1]) < abs(errs[0])
            assert abs(errs[-1]) < 0.1  # converged onto the label

    def test_chaos_site_fires(self, workload):
        with telemetry.Telemetry(sinks=[]):
            ref = OnlineRefiner(workload.model)
            plan = chaos.FaultPlan([
                chaos.FaultSpec(site="online.step", at=0),
            ])
            with plan:
                with pytest.raises(Exception, match="chaos-injected"):
                    ref.step(self._events(workload, n=1)[0])
            assert [f["site"] for f in plan.fired] == ["online.step"]

    def test_unknown_algorithm_refused(self, workload):
        with pytest.raises(ValueError, match="algorithm"):
            OnlineRefiner(
                workload.model, RefinerConfig(algorithm="newton")
            )


class TestReadFingerprints:
    def test_game_store_roundtrip(self, tmp_path, workload):
        d = str(tmp_path / "m")
        save_game_model(workload.model, workload.index_maps, d)
        fps = game_store.read_fingerprints(d)
        assert set(fps) == {"fixed", "per_entity"}

    def test_game_store_legacy_pointed_error(self, tmp_path, workload):
        d = str(tmp_path / "m")
        save_game_model(workload.model, workload.index_maps, d)
        meta = os.path.join(d, "metadata.json")
        with open(meta) as f:
            body = json.load(f)
        body.pop("fingerprints", None)
        with open(meta, "w") as f:
            json.dump(body, f)
        with pytest.raises(ValueError, match="re-save"):
            game_store.read_fingerprints(d)

    def test_model_store_roundtrip_and_legacy(self, tmp_path):
        imap = IndexMap.build([feature_key("f0", "")])
        glm = GeneralizedLinearModel(
            Coefficients(means=np.array([1.0], np.float32)), "logistic"
        )
        p = str(tmp_path / "m.avro")
        model_store.save_glm_model(glm, imap, p)
        assert model_store.read_fingerprints(p)
        os.remove(p + ".meta.json")
        with pytest.raises(ValueError, match="re-save"):
            model_store.read_fingerprints(p)


class TestExecutorWarmStartDir:
    def _published(self, tmp_path):
        imap = IndexMap.build(
            [feature_key(f"f{j}", "") for j in range(3)]
        )
        glm = GeneralizedLinearModel(
            Coefficients(means=np.array([1.0, 2.0, 3.0], np.float32)),
            "logistic",
        )
        path = str(tmp_path / "published.avro")
        model_store.save_glm_model(glm, imap, path)
        return path

    def test_seeds_trials_before_any_completion(self, tmp_path):
        from photon_ml_tpu.tuning.executor import (
            TuningConfig, TuningOrchestrator,
        )
        from photon_ml_tpu.tuning.scheduler import (
            RandomProposer, SearchSpace,
        )
        from photon_ml_tpu.tuning.state import TuningJournal

        path = self._published(tmp_path)
        sp = SearchSpace.create([(0.0, 1.0)])
        seen = []
        lock = threading.Lock()

        def fn(p, r, w):
            with lock:
                seen.append(None if w is None else np.asarray(w).copy())
            return float(p[0])

        with telemetry.Telemetry(sinks=[]):
            journal = TuningJournal(str(tmp_path / "j"))
            res = TuningOrchestrator(
                sp, fn, RandomProposer(sp, seed=1),
                TuningConfig(
                    max_trials=3, workers=1, warm_start_dir=path,
                ),
                journal,
            ).run()
            journal.close()
        assert res.completed == 3
        # No trial returned coefficients, so every trial fell through to
        # the published seed.
        assert all(
            w is not None and w.tobytes()
            == np.array([1.0, 2.0, 3.0], np.float32).tobytes()
            for w in seen
        )

    def test_resume_refuses_changed_warm_start_dir(self, tmp_path):
        from photon_ml_tpu.tuning.executor import (
            TuningConfig, TuningOrchestrator,
        )
        from photon_ml_tpu.tuning.scheduler import (
            RandomProposer, SearchSpace,
        )
        from photon_ml_tpu.tuning.state import (
            ResumeMismatch, TuningJournal,
        )

        path = self._published(tmp_path)
        sp = SearchSpace.create([(0.0, 1.0)])
        with telemetry.Telemetry(sinks=[]):
            journal = TuningJournal(str(tmp_path / "j"))
            TuningOrchestrator(
                sp, lambda p, r, w: float(p[0]),
                RandomProposer(sp, seed=1),
                TuningConfig(max_trials=2, workers=1), journal,
            ).run()
            journal.close()
            journal2 = TuningJournal(str(tmp_path / "j"))
            with pytest.raises(ResumeMismatch, match="warm_start_dir"):
                TuningOrchestrator(
                    sp, lambda p, r, w: float(p[0]),
                    RandomProposer(sp, seed=1),
                    TuningConfig(
                        max_trials=2, workers=1, warm_start_dir=path,
                    ),
                    journal2,
                ).run(resume=True)
            journal2.close()
