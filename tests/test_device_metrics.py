"""Device-side metrics match the host evaluators (VERDICT weak #8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from photon_ml_tpu.evaluation.device import (
    device_auc,
    device_pointwise_metric,
)
from photon_ml_tpu.evaluation.evaluators import (
    AreaUnderROCCurveEvaluator,
    LogisticLossEvaluator,
    PoissonLossEvaluator,
    RMSEEvaluator,
    SquaredLossEvaluator,
)


@pytest.fixture
def arrays(rng):
    n = 5000
    scores = rng.normal(size=n).astype(np.float32)
    scores = np.round(scores, 1)  # many exact ties → tie-averaging path
    labels = (rng.uniform(size=n) < 0.4).astype(np.float32)
    weights = rng.uniform(0.0, 2.0, size=n).astype(np.float32)
    weights[rng.uniform(size=n) < 0.1] = 0.0  # padding rows
    return scores, labels, weights


class TestPointwiseParity:
    @pytest.mark.parametrize(
        "kind,host",
        [
            ("logistic_loss", LogisticLossEvaluator()),
            ("poisson_loss", PoissonLossEvaluator()),
            ("squared_loss", SquaredLossEvaluator()),
            ("rmse", RMSEEvaluator()),
        ],
    )
    def test_matches_host(self, arrays, kind, host):
        scores, labels, weights = arrays
        got = float(
            device_pointwise_metric(
                jnp.asarray(scores), jnp.asarray(labels),
                jnp.asarray(weights), kind=kind,
            )
        )
        want = host.evaluate(scores, labels, weights)
        assert got == pytest.approx(want, rel=2e-5)

    def test_psum_over_mesh(self, arrays):
        """Row-sharded metric inside shard_map == whole-array metric."""
        scores, labels, weights = arrays
        n_dev = len(jax.devices())
        n = (len(scores) // n_dev) * n_dev
        scores, labels, weights = scores[:n], labels[:n], weights[:n]
        mesh = Mesh(np.array(jax.devices()), ("data",))

        def spmd(s, y, w):
            return device_pointwise_metric(
                s, y, w, kind="logistic_loss", axis_name="data"
            )

        sharded = jax.jit(
            jax.shard_map(
                spmd, mesh=mesh,
                in_specs=(P("data"), P("data"), P("data")),
                out_specs=P(),
                check_vma=False,
            )
        )(jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights))
        whole = device_pointwise_metric(
            jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights),
            kind="logistic_loss",
        )
        assert float(sharded) == pytest.approx(float(whole), rel=1e-5)


class TestAucParity:
    def test_matches_host_with_ties_and_weights(self, arrays):
        scores, labels, weights = arrays
        got = float(device_auc(
            jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights)
        ))
        want = AreaUnderROCCurveEvaluator().evaluate(scores, labels, weights)
        assert got == pytest.approx(want, abs=1e-6)

    def test_single_class_nan(self):
        scores = jnp.asarray(np.random.default_rng(0).normal(size=10))
        ones = jnp.ones(10)
        assert np.isnan(float(device_auc(scores, ones)))

    def test_perfect_separation(self):
        scores = jnp.asarray([3.0, 2.0, -1.0, -2.0])
        labels = jnp.asarray([1.0, 1.0, 0.0, 0.0])
        assert float(device_auc(scores, labels)) == 1.0
