"""Device-side metrics match the host evaluators (VERDICT weak #8)."""

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.parallel.compat import shard_map
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from photon_ml_tpu.evaluation.device import (
    device_auc,
    device_pointwise_metric,
)
from photon_ml_tpu.evaluation.evaluators import (
    AreaUnderROCCurveEvaluator,
    LogisticLossEvaluator,
    PoissonLossEvaluator,
    RMSEEvaluator,
    SquaredLossEvaluator,
)


@pytest.fixture
def arrays(rng):
    n = 5000
    scores = rng.normal(size=n).astype(np.float32)
    scores = np.round(scores, 1)  # many exact ties → tie-averaging path
    labels = (rng.uniform(size=n) < 0.4).astype(np.float32)
    weights = rng.uniform(0.0, 2.0, size=n).astype(np.float32)
    weights[rng.uniform(size=n) < 0.1] = 0.0  # padding rows
    return scores, labels, weights


class TestPointwiseParity:
    @pytest.mark.parametrize(
        "kind,host",
        [
            ("logistic_loss", LogisticLossEvaluator()),
            ("poisson_loss", PoissonLossEvaluator()),
            ("squared_loss", SquaredLossEvaluator()),
            ("rmse", RMSEEvaluator()),
        ],
    )
    def test_matches_host(self, arrays, kind, host):
        scores, labels, weights = arrays
        got = float(
            device_pointwise_metric(
                jnp.asarray(scores), jnp.asarray(labels),
                jnp.asarray(weights), kind=kind,
            )
        )
        want = host.evaluate(scores, labels, weights)
        assert got == pytest.approx(want, rel=2e-5)

    def test_psum_over_mesh(self, arrays):
        """Row-sharded metric inside shard_map == whole-array metric."""
        scores, labels, weights = arrays
        n_dev = len(jax.devices())
        n = (len(scores) // n_dev) * n_dev
        scores, labels, weights = scores[:n], labels[:n], weights[:n]
        mesh = Mesh(np.array(jax.devices()), ("data",))

        def spmd(s, y, w):
            return device_pointwise_metric(
                s, y, w, kind="logistic_loss", axis_name="data"
            )

        sharded = jax.jit(
            shard_map(
                spmd, mesh=mesh,
                in_specs=(P("data"), P("data"), P("data")),
                out_specs=P(),
                check_vma=False,
            )
        )(jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights))
        whole = device_pointwise_metric(
            jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights),
            kind="logistic_loss",
        )
        assert float(sharded) == pytest.approx(float(whole), rel=1e-5)


class TestAucParity:
    def test_matches_host_with_ties_and_weights(self, arrays):
        scores, labels, weights = arrays
        got = float(device_auc(
            jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights)
        ))
        want = AreaUnderROCCurveEvaluator().evaluate(scores, labels, weights)
        assert got == pytest.approx(want, abs=1e-6)

    def test_single_class_nan(self):
        scores = jnp.asarray(np.random.default_rng(0).normal(size=10))
        ones = jnp.ones(10)
        assert np.isnan(float(device_auc(scores, ones)))

    def test_perfect_separation(self):
        scores = jnp.asarray([3.0, 2.0, -1.0, -2.0])
        labels = jnp.asarray([1.0, 1.0, 0.0, 0.0])
        assert float(device_auc(scores, labels)) == 1.0


class TestDeviceValidationWiring:
    """VERDICT r4 missing #4: device metrics were built but unwired — now
    the estimator (device_metrics=True), the training driver
    (--device-metrics), and the scoring driver (incl. streamed scalar
    accumulation) all validate on device, pulling back scalars only."""

    @staticmethod
    def _fit(device_metrics, suite=None):
        import scipy.sparse as sp

        from photon_ml_tpu.game.estimator import (
            FixedEffectCoordinateConfig,
            GameEstimator,
            RandomEffectCoordinateConfig,
        )
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        rng = np.random.default_rng(7)
        n, d = 300, 4
        X = rng.normal(size=(n, d)).astype(np.float32)
        users = np.asarray([f"u{rng.integers(12)}" for _ in range(n)])
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-X[:, 0]))).astype(
            np.float32
        )
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=15),
            regularization=RegularizationContext.l2(),
        )
        shards = {
            "global": sp.csr_matrix(X),
            "u": sp.csr_matrix(np.ones((n, 1), np.float32)),
        }
        ids = {"userId": users}
        est = GameEstimator(
            "logistic",
            {
                "fixed": FixedEffectCoordinateConfig(
                    "global", opt, reg_weight=0.5
                ),
                "per_user": RandomEffectCoordinateConfig(
                    "u", "userId", opt, reg_weight=0.5
                ),
            },
            n_iterations=2,
            device_metrics=device_metrics,
        )
        val = (shards, ids, y)
        _, history = est.fit(
            shards, ids, y, validation=val, suite=suite
        )
        return history

    def test_estimator_metrics_match_host_path(self):
        h_host = self._fit(False)
        h_dev = self._fit(True)
        assert len(h_host) == len(h_dev)
        for a, b in zip(h_host, h_dev):
            assert a["train_metric"] == pytest.approx(
                b["train_metric"], abs=1e-5
            )
            assert a["validation_metric"] == pytest.approx(
                b["validation_metric"], abs=1e-5
            )

    def test_history_metrics_materialized_to_floats(self):
        """Device metrics ride the CD flush as 0-d device scalars
        (estimator passes materialize=False) — but by the time fit()
        returns, every history value must be a plain host float, nested
        validation dicts included."""
        for entry in self._fit(True):
            for key in ("train_metric", "validation_metric", "score_norm"):
                assert type(entry[key]) is float, (key, type(entry[key]))
            for name, val in entry["validation"].items():
                assert type(val) is float, (name, type(val))

    def test_mixed_suite_host_fallback(self):
        """Evaluators WITHOUT a device implementation still evaluate via
        one shared host pullback, alongside device ones.  Every built-in
        ungrouped evaluator has a device fn, so a custom host-only
        evaluator pins the fallback branch."""
        import dataclasses as _dc

        from photon_ml_tpu.evaluation.evaluators import Evaluator
        from photon_ml_tpu.evaluation.suite import EvaluationSuite

        @_dc.dataclass(frozen=True)
        class MeanScoreEvaluator(Evaluator):
            def _compute(self, scores, labels, weights, group_ids):
                return float(np.average(scores, weights=weights))

        suite = EvaluationSuite.from_specs(
            ["auc", "logistic_loss", MeanScoreEvaluator()]
        )
        from photon_ml_tpu.evaluation.device import device_evaluator_fn

        assert device_evaluator_fn(MeanScoreEvaluator()) is None
        h_host = self._fit(False, suite=suite)
        h_dev = self._fit(True, suite=suite)
        for a, b in zip(h_host, h_dev):
            for name in ("auc", "logistic_loss", "MeanScoreEvaluator"):
                assert a["validation"][name] == pytest.approx(
                    b["validation"][name], abs=1e-5
                )

    def test_grouped_suite_rejected(self):
        from photon_ml_tpu.evaluation.suite import EvaluationSuite

        suite = EvaluationSuite.from_specs(
            ["auc"], group_column="userId"
        )
        with pytest.raises(ValueError, match="group_column"):
            self._fit(True, suite=suite)
