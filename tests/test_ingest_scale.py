"""Streaming ingest + vectorized scoring paths (VERDICT round 1, item 3).

Covers: block-streaming Avro iteration, the specialized GAME block decoder
(parity with the generic datum decoder), the packed vectorized per-entity
coefficient lookup, and the GameTransformer prepared-scoring cache.
"""

import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.data.game_reader import (
    GAME_EXAMPLE_SCHEMA,
    read_game_avro,
    write_game_avro,
)
from photon_ml_tpu.game.model import RandomEffectModel
from photon_ml_tpu.io import avro


def _rows(rng, n, n_users=9, shards=("global", "userFeatures")):
    out = []
    for i in range(n):
        feats = {
            "global": [
                {"name": f"g{j}", "term": "t", "value": float(rng.normal())}
                for j in range(rng.integers(1, 5))
            ],
        }
        if "userFeatures" in shards:
            feats["userFeatures"] = [
                {"name": "bias", "term": "", "value": 1.0}
            ]
        out.append({
            "uid": f"r{i}" if i % 3 else None,
            "response": float(rng.uniform() < 0.5),
            "weight": float(rng.uniform(0.5, 2.0)) if i % 2 else None,
            "offset": float(rng.normal()) if i % 5 == 0 else None,
            "ids": {"userId": f"u{rng.integers(n_users)}"},
            "features": feats,
        })
    return out


class TestStreamingAvro:
    def test_iter_blocks_streams(self, tmp_path):
        path = str(tmp_path / "x.avro")
        rng = np.random.default_rng(0)
        rows = _rows(rng, 500)
        avro.write_container(
            path, GAME_EXAMPLE_SCHEMA, rows, records_per_block=64
        )
        blocks = list(avro.iter_blocks(path))
        assert len(blocks) == -(-500 // 64)  # ceil: true block-by-block
        assert sum(c for _, c, _ in blocks) == 500

    def test_iter_container_matches_read_container(self, tmp_path):
        path = str(tmp_path / "x.avro")
        rng = np.random.default_rng(1)
        rows = _rows(rng, 200)
        avro.write_container(path, GAME_EXAMPLE_SCHEMA, rows,
                             records_per_block=37)
        _, recs = avro.read_container(path)
        assert list(avro.iter_container(path)) == recs
        assert avro.read_schema(path) == GAME_EXAMPLE_SCHEMA


class TestFastGameDecoder:
    def test_fast_path_matches_generic(self, tmp_path, monkeypatch):
        """The specialized block decoder and the generic datum decoder must
        produce identical outputs on the same file."""
        import photon_ml_tpu.data.game_reader as gr

        path = str(tmp_path / "g.avro")
        rng = np.random.default_rng(2)
        write_game_avro(path, _rows(rng, 300))

        # Pin the PYTHON flat decoder (native parity is TestNativeDecoder's
        # job) against the generic datum decoder.
        monkeypatch.setenv("PHOTON_NO_NATIVE", "1")
        fast = read_game_avro(path)
        monkeypatch.setattr(gr, "_is_game_schema", lambda s: False)
        slow = read_game_avro(path)

        f_shards, f_ids, f_resp, f_w, f_off, f_uids, f_maps = fast
        s_shards, s_ids, s_resp, s_w, s_off, s_uids, s_maps = slow
        assert f_uids == s_uids
        np.testing.assert_array_equal(f_resp, s_resp)
        np.testing.assert_array_equal(f_w, s_w)
        np.testing.assert_array_equal(f_off, s_off)
        assert set(f_shards) == set(s_shards)
        for k in f_shards:
            assert (f_shards[k] != s_shards[k]).nnz == 0
            assert dict(f_maps[k]) == dict(s_maps[k])
        for k in f_ids:
            np.testing.assert_array_equal(f_ids[k], s_ids[k])

    def test_fast_path_scoring_drops(self, tmp_path):
        """Scoring-path semantics (supplied index maps, unseen features and
        shards dropped with a count) survive the fast decoder."""
        path = str(tmp_path / "g.avro")
        rng = np.random.default_rng(3)
        write_game_avro(path, _rows(rng, 50))
        *_, imaps = read_game_avro(path)

        path2 = str(tmp_path / "g2.avro")
        rows2 = _rows(rng, 20)
        rows2[0]["features"]["global"].append(
            {"name": "UNSEEN", "term": "", "value": 1.0}
        )
        rows2[1]["features"]["brandNewShard"] = [
            {"name": "x", "term": "", "value": 2.0}
        ]
        write_game_avro(path2, rows2)
        shards, *_ = read_game_avro(path2, index_maps=imaps)
        assert "brandNewShard" not in shards
        assert shards["global"].shape[1] == len(imaps["global"])


class TestPackedCoefficientLookup:
    def _brute_force(self, model, col_map, entity_ids):
        E, D = col_map.shape
        out = np.zeros((E, D), np.float32)
        for lane, key in enumerate(entity_ids):
            entry = model.coefficients.get(key)
            if entry is None or len(entry[0]) == 0:
                continue
            cols, vals = entry
            for k in range(D):
                c = col_map[lane, k]
                if c < 0:
                    continue
                j = np.searchsorted(cols, c)
                if j < len(cols) and cols[j] == c:
                    out[lane, k] = vals[j]
        return out

    def test_matches_brute_force(self):
        rng = np.random.default_rng(4)
        nf = 40
        table = {}
        for e in range(30):
            k = rng.integers(1, 10)
            cols = np.sort(
                rng.choice(nf, size=k, replace=False).astype(np.int32)
            )
            table[f"u{e}"] = (cols, rng.normal(size=k).astype(np.float32))
        table["empty"] = (
            np.empty(0, np.int32), np.empty(0, np.float32)
        )
        model = RandomEffectModel(
            coefficients=table, feature_shard="s", entity_key="userId",
            task="logistic", n_features=nf,
        )
        # Lanes include unseen entities, the empty entity, and -1 padding.
        entity_ids = ["u3", "nope", "u7", "empty", "u0", "zz"]
        col_map = rng.integers(-1, nf, size=(len(entity_ids), 12)).astype(
            np.int32
        )
        got = model.coefficient_matrix_for(col_map, entity_ids)
        want = self._brute_force(model, col_map, entity_ids)
        np.testing.assert_array_equal(got, want)

    def test_empty_table(self):
        model = RandomEffectModel(
            coefficients={}, feature_shard="s", entity_key="userId",
            task="logistic", n_features=5,
        )
        out = model.coefficient_matrix_for(
            np.zeros((2, 3), np.int32), ["a", "b"]
        )
        np.testing.assert_array_equal(out, 0.0)


class TestTransformerCache:
    def test_grouping_built_once_per_dataset(self, monkeypatch):
        import photon_ml_tpu.game.estimator as est_mod
        from photon_ml_tpu.game.estimator import (
            FixedEffectCoordinateConfig,
            GameEstimator,
            GameTransformer,
            RandomEffectCoordinateConfig,
        )
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        rng = np.random.default_rng(5)
        n = 200
        users = np.array([f"u{u}" for u in rng.integers(8, size=n)])
        shards = {
            "global": sp.csr_matrix(
                rng.normal(size=(n, 3)).astype(np.float32)
            ),
            "userFeatures": sp.csr_matrix(np.ones((n, 1), np.float32)),
        }
        ids = {"userId": users}
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=15),
            regularization=RegularizationContext.l2(),
        )
        est = GameEstimator("logistic", {
            "fixed": FixedEffectCoordinateConfig("global", opt, 0.5),
            "per_user": RandomEffectCoordinateConfig(
                "userFeatures", "userId", opt, 0.5
            ),
        })
        model, _ = est.fit(shards, ids, y)

        calls = {"n": 0}
        orig = est_mod.build_random_effect_dataset

        def counting(*a, **k):
            calls["n"] += 1
            return orig(*a, **k)

        monkeypatch.setattr(est_mod, "build_random_effect_dataset", counting)
        t = GameTransformer(model)
        s1 = t.transform(shards, ids)
        s2 = t.transform(shards, ids)
        assert calls["n"] == 1  # grouping happened ONCE for two transforms
        np.testing.assert_array_equal(s1, s2)

        # Explicit prepare() handle also short-circuits the grouping.
        prep = t.prepare(shards, ids)
        calls["n"] = 0
        t2 = GameTransformer(model)
        t2.transform(shards, ids, prepared=prep)
        assert calls["n"] == 0


class TestReviewRegressions:
    def test_all_features_dropped_shard_still_materializes(self, tmp_path):
        """Feature-drifted scoring data: every feature unseen → the shard
        must come back as an all-zero (n, d) matrix, not a missing key."""
        path = str(tmp_path / "train.avro")
        rng = np.random.default_rng(7)
        write_game_avro(path, _rows(rng, 30))
        *_, imaps = read_game_avro(path)

        drifted = str(tmp_path / "drift.avro")
        rows = _rows(rng, 10)
        for r in rows:
            for f in r["features"]["global"]:
                f["name"] = "DRIFTED_" + f["name"]
        write_game_avro(drifted, rows)
        shards, *_ = read_game_avro(drifted, index_maps=imaps)
        assert "global" in shards
        assert shards["global"].shape == (10, len(imaps["global"]))
        assert shards["global"].nnz == 0

    def test_schema_type_mismatch_falls_back_to_generic(self, tmp_path):
        """Same field NAMES but uid typed plain string (no union): the flat
        decoder must not run; the generic path parses it correctly."""
        schema = {
            "type": "record",
            "name": "Variant",
            "fields": [
                {"name": "uid", "type": "string"},  # NOT a union
                {"name": "response", "type": "double"},
                {"name": "weight", "type": ["null", "double"]},
                {"name": "offset", "type": ["null", "double"]},
                {"name": "ids", "type": {"type": "map", "values": "string"}},
                {"name": "features", "type": {
                    "type": "map",
                    "values": {"type": "array", "items": {
                        "type": "record", "name": "F",
                        "fields": [
                            {"name": "name", "type": "string"},
                            {"name": "term", "type": "string"},
                            {"name": "value", "type": "double"},
                        ]}},
                }},
            ],
        }
        rows = [{
            "uid": "ab", "response": 1.0, "weight": None, "offset": None,
            "ids": {"userId": "u1"},
            "features": {"global": [
                {"name": "g0", "term": "", "value": 3.0}
            ]},
        }]
        path = str(tmp_path / "variant.avro")
        avro.write_container(path, schema, rows)
        shards, ids, resp, *_ = read_game_avro(path)
        assert resp[0] == 1.0
        assert ids["userId"][0] == "u1"
        assert shards["global"][0, 0] == 3.0

    def test_prepared_row_mismatch_raises(self):
        from photon_ml_tpu.game.estimator import GameTransformer
        from photon_ml_tpu.game.model import GameModel

        rng = np.random.default_rng(8)
        nf = 10
        table = {"u0": (np.array([1], np.int32), np.array([2.0], np.float32))}
        model = GameModel(models={"re": RandomEffectModel(
            table, "s", "userId", "logistic", nf)}, task="logistic")
        shards_a = {"s": sp.csr_matrix(np.ones((5, nf), np.float32))}
        ids_a = {"userId": np.array(["u0"] * 5)}
        shards_b = {"s": sp.csr_matrix(np.ones((7, nf), np.float32))}
        ids_b = {"userId": np.array(["u0"] * 7)}
        t = GameTransformer(model)
        prep = t.prepare(shards_a, ids_a)
        with pytest.raises(ValueError, match="prepared scoring set"):
            t.transform(shards_b, ids_b, prepared=prep)


class TestTransformerCacheStaleness:
    def test_replaced_dict_values_miss_cache(self, monkeypatch):
        """Mutating the VALUES inside the same shards/ids dicts must rebuild
        the grouping (identity of the arrays, not the dicts, is the key)."""
        import photon_ml_tpu.game.estimator as est_mod
        from photon_ml_tpu.game.estimator import GameTransformer
        from photon_ml_tpu.game.model import GameModel

        nf = 6
        table = {"u0": (np.array([0], np.int32), np.array([5.0], np.float32)),
                 "u1": (np.array([0], np.int32), np.array([-3.0], np.float32))}
        model = GameModel(models={"re": RandomEffectModel(
            table, "s", "userId", "logistic", nf)}, task="logistic")

        shards = {"s": sp.csr_matrix(np.ones((4, nf), np.float32))}
        ids = {"userId": np.array(["u0", "u0", "u1", "u1"])}
        t = GameTransformer(model)
        s1 = t.transform(shards, ids)
        np.testing.assert_array_equal(s1, [5.0, 5.0, -3.0, -3.0])

        # Same dict objects, swapped values (same shapes): batch 2.
        shards["s"] = sp.csr_matrix(np.ones((4, nf), np.float32))
        ids["userId"] = np.array(["u1", "u1", "u0", "u0"])
        s2 = t.transform(shards, ids)
        np.testing.assert_array_equal(s2, [-3.0, -3.0, 5.0, 5.0])

    def test_cache_cleared_when_source_dies(self):
        import gc

        from photon_ml_tpu.game.estimator import GameTransformer
        from photon_ml_tpu.game.model import GameModel

        nf = 3
        model = GameModel(models={"re": RandomEffectModel(
            {"u0": (np.array([0], np.int32), np.array([1.0], np.float32))},
            "s", "userId", "logistic", nf)}, task="logistic")
        t = GameTransformer(model)
        shards = {"s": sp.csr_matrix(np.ones((2, nf), np.float32))}
        ids = {"userId": np.array(["u0", "u0"])}
        t.transform(shards, ids)
        assert t._cache is not None
        del shards, ids
        gc.collect()
        assert t._cache is None  # weakref callbacks released the blocks


class TestNativeDecoder:
    """C++ session decoder parity with the Python paths (the native
    component replacing the reference's JVM Avro ingest)."""

    @pytest.fixture()
    def native_lib(self):
        from photon_ml_tpu.native import load_game_decoder

        lib = load_game_decoder()
        if lib is None:
            pytest.skip("native decoder unavailable (no g++ or build failed)")
        return lib

    def test_native_matches_python(self, tmp_path, monkeypatch, native_lib):
        import photon_ml_tpu.data.game_reader as gr

        path = str(tmp_path / "n.avro")
        rng = np.random.default_rng(21)
        write_game_avro(path, _rows(rng, 400))

        native = read_game_avro(path)
        monkeypatch.setenv("PHOTON_NO_NATIVE", "1")
        python = read_game_avro(path)

        n_shards, n_ids, n_resp, n_w, n_off, n_uids, n_maps = native
        p_shards, p_ids, p_resp, p_w, p_off, p_uids, p_maps = python
        assert n_uids == p_uids
        np.testing.assert_array_equal(n_resp, p_resp)
        np.testing.assert_array_equal(n_w, p_w)
        np.testing.assert_array_equal(n_off, p_off)
        assert set(n_shards) == set(p_shards)
        for k in n_shards:
            assert (n_shards[k] != p_shards[k]).nnz == 0
            assert dict(n_maps[k]) == dict(p_maps[k])
        for k in n_ids:
            np.testing.assert_array_equal(n_ids[k], p_ids[k])

    def test_native_scoring_drops_match(self, tmp_path, monkeypatch,
                                        native_lib):
        path = str(tmp_path / "t.avro")
        rng = np.random.default_rng(22)
        write_game_avro(path, _rows(rng, 60))
        *_, imaps = read_game_avro(path)

        path2 = str(tmp_path / "s.avro")
        rows2 = _rows(rng, 25)
        rows2[0]["features"]["global"].append(
            {"name": "NEW", "term": "x", "value": 1.5}
        )
        rows2[1]["features"]["mysteryShard"] = [
            {"name": "m", "term": "", "value": 2.0}
        ]
        for f in rows2[2]["features"]["global"]:
            f["name"] = "GONE_" + f["name"]
        write_game_avro(path2, rows2)

        n = read_game_avro(path2, index_maps=imaps)
        monkeypatch.setenv("PHOTON_NO_NATIVE", "1")
        p = read_game_avro(path2, index_maps=imaps)
        assert set(n[0]) == set(p[0])
        for k in n[0]:
            assert (n[0][k] != p[0][k]).nnz == 0
        assert "mysteryShard" not in n[0]

    def test_native_malformed_raises(self, tmp_path, native_lib):
        """Truncated payload must raise, not crash or hang."""
        import photon_ml_tpu.data.game_reader as gr

        path = str(tmp_path / "m.avro")
        rng = np.random.default_rng(23)
        write_game_avro(path, _rows(rng, 10))
        acc = gr._Accumulator(True, {})
        import photon_ml_tpu.io.avro as avro_mod

        blocks = list(avro_mod.iter_blocks(path))
        schema, count, payload = blocks[0]

        from photon_ml_tpu.native import load_game_decoder
        lib = load_game_decoder()
        h = lib.gd_new(1)
        try:
            rc = lib.gd_decode_block(h, payload[: len(payload) // 2],
                                     len(payload) // 2, count)
            assert rc == -1
            assert b"malformed" in lib.gd_error(h)
        finally:
            lib.gd_free(h)


class TestFeatureSummaryStore:
    def test_host_summary_matches_device_and_round_trips(self, tmp_path):
        import jax.numpy as jnp

        from photon_ml_tpu.data.dataset import make_glm_data
        from photon_ml_tpu.data.index_map import IndexMap
        from photon_ml_tpu.data.stats import summarize, summarize_host
        from photon_ml_tpu.io.summary_store import (
            load_feature_summary,
            save_feature_summary,
        )

        rng = np.random.default_rng(31)
        n, d = 200, 12
        X = sp.random(n, d, density=0.3, random_state=6, format="csr")
        w = rng.uniform(0.0, 2.0, size=n).astype(np.float32)
        w[rng.uniform(size=n) < 0.1] = 0.0
        y = np.zeros(n, np.float32)

        dev = summarize(make_glm_data(X, y, weights=w))
        host = summarize_host(X, w)
        np.testing.assert_allclose(
            np.asarray(host.mean), np.asarray(dev.mean), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(host.variance), np.asarray(dev.variance),
            rtol=1e-4, atol=1e-7,
        )
        np.testing.assert_array_equal(
            np.asarray(host.nnz), np.asarray(dev.nnz)
        )
        np.testing.assert_allclose(
            np.asarray(host.min), np.asarray(dev.min), rtol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(host.max), np.asarray(dev.max), rtol=1e-6
        )

        imap = IndexMap.build([f"f{j}" for j in range(d)])
        path = str(tmp_path / "summary.avro")
        save_feature_summary(host, imap, path)
        recs = load_feature_summary(path)
        assert len(recs) == d
        assert recs[3]["name"] == "f3"
        assert recs[3]["mean"] == pytest.approx(float(host.mean[3]))
        assert recs[3]["nonzeroCount"] == int(host.nnz[3])

    def test_game_driver_writes_shard_summaries(self, tmp_path):
        import json
        import os

        from photon_ml_tpu.drivers import game_training_driver
        from photon_ml_tpu.io.summary_store import load_feature_summary

        rng = np.random.default_rng(32)
        write_game_avro(str(tmp_path / "t.avro"), _rows(rng, 80))
        cfg = {
            "task": "logistic", "iterations": 1, "feature_summaries": True,
            "coordinates": [
                {"name": "fixed", "type": "fixed", "feature_shard": "global",
                 "optimizer": "lbfgs", "max_iters": 10, "reg_type": "l2",
                 "reg_weight": 0.5},
            ],
        }
        cfgp = str(tmp_path / "c.json")
        with open(cfgp, "w") as f:
            json.dump(cfg, f)
        game_training_driver.run([
            "--train-data", str(tmp_path / "t.avro"),
            "--config", cfgp, "--output-dir", str(tmp_path / "out"),
        ])
        path = os.path.join(
            str(tmp_path / "out"), "feature-summaries", "global.avro"
        )
        recs = load_feature_summary(path)
        assert len(recs) > 0 and all("mean" in r for r in recs)
