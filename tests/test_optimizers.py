"""Optimizer convergence tests.

Mirrors the reference's optimizer unit-test strategy (SURVEY.md §4):
convergence on small convex objectives with known minima, plus parity
against scipy oracles (the stand-in for Breeze until the reference tree is
readable).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.optimize

from photon_ml_tpu.data.dataset import make_glm_data
from photon_ml_tpu.ops import losses
from photon_ml_tpu.optim.lbfgs import LBFGSConfig, lbfgs_solve
from photon_ml_tpu.optim.objective import GlmObjective
from photon_ml_tpu.optim.owlqn import OWLQNConfig, owlqn_solve
from photon_ml_tpu.optim.tron import TRONConfig, tron_solve


def _logistic_problem(rng, n=200, d=10, dtype=np.float64):
    X = rng.normal(size=(n, d)).astype(dtype)
    w_true = rng.normal(size=d).astype(dtype)
    p = 1.0 / (1.0 + np.exp(-X @ w_true))
    y = (rng.uniform(size=n) < p).astype(dtype)
    data = make_glm_data(X, y, dtype=jnp.float64)
    obj = GlmObjective(losses.logistic)
    return X, y, data, obj


def _scipy_logistic_min(X, y, l2):
    def f(w):
        m = X @ w
        val = np.sum(np.logaddexp(0, m) - y * m) + 0.5 * l2 * w @ w
        g = X.T @ (1 / (1 + np.exp(-m)) - y) + l2 * w
        return val, g

    res = scipy.optimize.minimize(
        f, np.zeros(X.shape[1]), jac=True, method="L-BFGS-B",
        options={"maxiter": 500, "ftol": 1e-14, "gtol": 1e-10},
    )
    return res


class TestLBFGS:
    def test_quadratic_exact(self):
        d = 20
        diag = jnp.linspace(1.0, 50.0, d)
        target = jnp.arange(1.0, d + 1.0)

        def vg(w):
            r = w - target
            return 0.5 * jnp.vdot(r, diag * r), diag * r

        res = lbfgs_solve(vg, jnp.zeros(d), LBFGSConfig(tolerance=1e-10))
        np.testing.assert_allclose(np.asarray(res.w), np.asarray(target), atol=1e-6)
        assert bool(res.converged)

    def test_logistic_matches_scipy(self, rng):
        X, y, data, obj = _logistic_problem(rng)
        l2 = 0.1

        def vg(w):
            return obj.value_and_grad(w, data, l2_weight=l2)

        res = lbfgs_solve(vg, jnp.zeros(X.shape[1], jnp.float64),
                          LBFGSConfig(tolerance=1e-9, max_iters=200))
        oracle = _scipy_logistic_min(X, y, l2)
        assert float(res.value) <= oracle.fun + 1e-6
        np.testing.assert_allclose(np.asarray(res.w), oracle.x, atol=1e-3)

    def test_jit_and_tracker(self, rng):
        X, y, data, obj = _logistic_problem(rng, n=50, d=5)

        @jax.jit
        def solve(w0):
            return lbfgs_solve(
                lambda w: obj.value_and_grad(w, data, l2_weight=1.0),
                w0,
                LBFGSConfig(max_iters=50),
            )

        res = solve(jnp.zeros(5, jnp.float64))
        vals = np.asarray(res.values)
        vals = vals[~np.isnan(vals)]
        # Objective decreases monotonically under Wolfe line search.
        assert np.all(np.diff(vals) <= 1e-10)
        assert len(vals) == int(res.iterations) + 1

    def test_vmap_batched_solves(self, rng):
        # The random-effect pattern: many independent small problems at once.
        B, n, d = 4, 30, 3
        Xs = rng.normal(size=(B, n, d))
        ys = (rng.uniform(size=(B, n)) < 0.5).astype(np.float64)

        def solve_one(X, y):
            def vg(w):
                m = X @ w
                val = jnp.sum(jax.nn.softplus(m) - y * m) + 0.5 * jnp.vdot(w, w)
                g = X.T @ (jax.nn.sigmoid(m) - y) + w
                return val, g

            return lbfgs_solve(vg, jnp.zeros(d, jnp.float64),
                               LBFGSConfig(max_iters=50)).w

    # noqa: solve each batch member independently and compare with vmap
        batched = jax.vmap(solve_one)(jnp.asarray(Xs), jnp.asarray(ys))
        for b in range(B):
            single = solve_one(jnp.asarray(Xs[b]), jnp.asarray(ys[b]))
            np.testing.assert_allclose(
                np.asarray(batched[b]), np.asarray(single), atol=1e-5
            )


class TestOWLQN:
    def test_soft_threshold_closed_form(self):
        # min ½‖w - a‖² + λ‖w‖₁ has solution soft(a, λ).
        a = jnp.array([3.0, -2.0, 0.5, -0.1, 0.0])
        lam = 1.0

        def vg(w):
            r = w - a
            return 0.5 * jnp.vdot(r, r), r

        res = owlqn_solve(vg, jnp.zeros(5, jnp.float64), lam,
                          OWLQNConfig(tolerance=1e-10))
        expected = np.sign(np.asarray(a)) * np.maximum(np.abs(np.asarray(a)) - lam, 0)
        np.testing.assert_allclose(np.asarray(res.w), expected, atol=1e-6)

    def test_l1_logistic_sparsity_and_optimality(self, rng):
        X, y, data, obj = _logistic_problem(rng, n=300, d=20)
        lam = 20.0

        def vg(w):
            return obj.value_and_grad(w, data)

        res = owlqn_solve(vg, jnp.zeros(20, jnp.float64), lam,
                          OWLQNConfig(max_iters=300, tolerance=1e-9))
        w = np.asarray(res.w)
        # Strong L1 ⇒ some exact zeros.
        assert np.sum(w == 0.0) > 0
        # KKT: |grad_i| <= lam where w_i == 0; grad_i = -lam*sign(w_i) otherwise.
        _, g = obj.value_and_grad(res.w, data)
        g = np.asarray(g)
        assert np.all(np.abs(g[w == 0.0]) <= lam + 1e-4)
        np.testing.assert_allclose(
            g[w != 0.0], -lam * np.sign(w[w != 0.0]), atol=1e-4
        )

    def test_l1_mask_exempts_intercept(self, rng):
        # With a huge penalty on all-but-intercept, only intercept survives.
        n = 200
        X = np.concatenate(
            [np.ones((n, 1)), rng.normal(size=(n, 3))], axis=1
        )
        y = (rng.uniform(size=n) < 0.8).astype(np.float64)
        data = make_glm_data(X, y, dtype=jnp.float64)
        obj = GlmObjective(losses.logistic)
        mask = jnp.array([0.0, 1.0, 1.0, 1.0])

        res = owlqn_solve(
            lambda w: obj.value_and_grad(w, data),
            jnp.zeros(4, jnp.float64),
            1e4,
            OWLQNConfig(max_iters=200),
            l1_mask=mask,
        )
        w = np.asarray(res.w)
        np.testing.assert_allclose(w[1:], 0.0, atol=1e-8)
        # Intercept ≈ logit of base rate.
        expected = np.log(np.mean(y) / (1 - np.mean(y)))
        np.testing.assert_allclose(w[0], expected, atol=1e-2)


class TestProblemDispatch:
    def test_l1_routes_to_owlqn_regardless_of_optimizer(self, rng):
        # Regression: '--reg-type l1 --optimizer lbfgs' must NOT silently
        # train unregularized; any L1 component routes to OWL-QN.
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            GlmOptimizationProblem,
            OptimizerConfig,
            OptimizerType,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        X, y, data, obj = _logistic_problem(rng, n=200, d=15)
        for opt_type in (OptimizerType.LBFGS, OptimizerType.TRON):
            problem = GlmOptimizationProblem(
                "logistic",
                GlmOptimizationConfig(
                    optimizer=OptimizerConfig(optimizer=opt_type, max_iters=200),
                    regularization=RegularizationContext.l1(),
                ),
            )
            res = problem.solve(data, reg_weight=15.0,
                                w0=jnp.zeros(15, jnp.float64))
            w = np.asarray(res.w)
            assert np.sum(w == 0.0) > 0, f"{opt_type}: L1 was dropped"


class TestTRON:
    def test_quadratic_one_newton_step(self):
        d = 10
        diag = jnp.linspace(1.0, 10.0, d)
        target = jnp.ones(d)

        def vg(w):
            r = w - target
            return 0.5 * jnp.vdot(r, diag * r), diag * r

        def hvp(w, v, aux):
            return diag * v

        res = tron_solve(vg, hvp, jnp.zeros(d, jnp.float64),
                         TRONConfig(tolerance=1e-10))
        np.testing.assert_allclose(np.asarray(res.w), np.asarray(target), atol=1e-6)
        # Inexact CG (forcing tol 0.1·||g||) needs a handful of outer steps.
        assert int(res.iterations) <= 15

    def test_logistic_matches_lbfgs(self, rng):
        X, y, data, obj = _logistic_problem(rng)
        l2 = 0.5

        def vg(w):
            return obj.value_and_grad(w, data, l2_weight=l2)

        res_tron = tron_solve(
            vg,
            lambda w, v, aux: obj.hvp(w, v, data, l2_weight=l2, d2w=aux),
            jnp.zeros(X.shape[1], jnp.float64),
            TRONConfig(tolerance=1e-9, max_iters=100),
            d2_fn=lambda w: obj.d2_weights(w, data),
        )
        oracle = _scipy_logistic_min(X, y, l2)
        assert float(res_tron.value) <= oracle.fun + 1e-6
        np.testing.assert_allclose(np.asarray(res_tron.w), oracle.x, atol=1e-3)
        assert bool(res_tron.converged)

    def test_hvp_matches_finite_difference(self, rng):
        X, y, data, obj = _logistic_problem(rng, n=60, d=6)
        w = jnp.asarray(rng.normal(size=6))
        v = jnp.asarray(rng.normal(size=6))
        eps = 1e-6
        _, g_plus = obj.value_and_grad(w + eps * v, data)
        _, g_minus = obj.value_and_grad(w - eps * v, data)
        fd = (np.asarray(g_plus) - np.asarray(g_minus)) / (2 * eps)
        hvp = np.asarray(obj.hvp(w, v, data))
        np.testing.assert_allclose(hvp, fd, rtol=1e-5, atol=1e-5)


class TestSPGBoxConstraints:
    """Box-constrained solves (the reference's optimizer-layer constraint
    map) via spectral projected gradient, vs scipy L-BFGS-B oracles."""

    def _bounded_oracle(self, X, y, l2, bounds):
        def f(w):
            m = X @ w
            val = np.sum(np.logaddexp(0, m) - y * m) + 0.5 * l2 * w @ w
            g = X.T @ (1 / (1 + np.exp(-m)) - y) + l2 * w
            return val, g

        res = scipy.optimize.minimize(
            f, np.zeros(X.shape[1]), jac=True, method="L-BFGS-B",
            bounds=bounds, options={"maxiter": 500, "ftol": 1e-14,
                                    "gtol": 1e-10},
        )
        return res.x

    def test_matches_scipy_lbfgsb(self, rng):
        from photon_ml_tpu.optim.projected import SPGConfig, spg_solve

        X, y, data, obj = _logistic_problem(rng)
        l2 = 0.3
        d = X.shape[1]
        lower = np.full(d, -0.25)
        upper = np.full(d, 0.25)
        # Leave a couple of coefficients unconstrained on one side.
        lower[0], upper[1] = -np.inf, np.inf
        res = spg_solve(
            lambda w: obj.value_and_grad(w, data, l2_weight=l2),
            jnp.zeros(d, jnp.float64),
            jnp.asarray(lower), jnp.asarray(upper),
            SPGConfig(max_iters=300, tolerance=1e-10),
        )
        oracle = self._bounded_oracle(
            X, y, l2, list(zip(lower, upper))
        )
        # Terminated before max_iters: either true stationarity or an
        # honest ftol plateau (converged no longer claims the latter).
        assert bool(res.converged) or bool(res.stalled)
        np.testing.assert_allclose(np.asarray(res.w), oracle, atol=2e-5)
        assert np.all(np.asarray(res.w) >= lower - 1e-12)
        assert np.all(np.asarray(res.w) <= upper + 1e-12)
        # The box must actually bind somewhere for this to test anything.
        assert np.any(np.isclose(np.abs(oracle[2:]), 0.25, atol=1e-8))

    def test_inactive_bounds_match_unconstrained(self, rng):
        from photon_ml_tpu.optim.projected import SPGConfig, spg_solve

        X, y, data, obj = _logistic_problem(rng)
        l2 = 0.5
        d = X.shape[1]
        vg = lambda w: obj.value_and_grad(w, data, l2_weight=l2)
        free = lbfgs_solve(
            vg, jnp.zeros(d, jnp.float64),
            LBFGSConfig(max_iters=300, tolerance=1e-10),
        )
        boxed = spg_solve(
            vg, jnp.zeros(d, jnp.float64),
            jnp.full(d, -np.inf), jnp.full(d, np.inf),
            SPGConfig(max_iters=300, tolerance=1e-10),
        )
        np.testing.assert_allclose(
            np.asarray(boxed.w), np.asarray(free.w), atol=1e-6
        )

    def test_problem_routes_bounds_and_rejects_l1(self, rng):
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            GlmOptimizationProblem,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            GlmOptimizationProblem,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        X, y, data, obj = _logistic_problem(rng)
        d = X.shape[1]
        bounds = (jnp.full(d, -0.2), jnp.full(d, 0.2))
        prob = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(max_iters=300, tolerance=1e-10),
                regularization=RegularizationContext.l2(),
            ),
        )
        res = prob.solve_single_device(data, 0.3, bounds=bounds)
        oracle = self._bounded_oracle(X, y, 0.3, [(-0.2, 0.2)] * d)
        np.testing.assert_allclose(np.asarray(res.w), oracle, atol=2e-5)

        l1_prob = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                regularization=RegularizationContext.l1(),
            ),
        )
        with pytest.raises(NotImplementedError, match="box constraints"):
            l1_prob.solve(data, 0.1, bounds=bounds)

    def test_ftol_plateau_reports_stalled_not_converged(self):
        """ADVICE r5: an objective-plateau exit that never met the
        projected-gradient tolerance must not claim converged=True —
        it surfaces as the distinct ``stalled`` flag."""
        from photon_ml_tpu.optim.projected import SPGConfig, spg_solve

        # Linear term riding a huge constant: the accepted step's
        # decrease (~4e-8) is absorbed by f ≈ 1e8 in f32, so rel_impr
        # reads 0 (an ftol plateau) while the projected-gradient norm
        # stays at 2e-4 ≫ tolerance·scale.
        g = jnp.full((4,), 1e-4, jnp.float32)
        res = spg_solve(
            lambda w: (1e8 + jnp.vdot(g, w), g),
            jnp.ones((4,), jnp.float32),
            jnp.full((4,), -1e6), jnp.full((4,), 1e6),
            SPGConfig(max_iters=50, tolerance=1e-8),
        )
        assert not bool(res.converged)
        assert bool(res.stalled)

    def test_converged_solve_is_not_stalled(self):
        from photon_ml_tpu.optim.projected import SPGConfig, spg_solve

        # Quadratic with identity Hessian: the first BB step lands
        # exactly on the interior optimum, pg hits 0, and the solve
        # reports true convergence with no stall.
        res = spg_solve(
            lambda w: (0.5 * jnp.vdot(w, w), w),
            jnp.ones((4,), jnp.float32),
            jnp.full((4,), -5.0), jnp.full((4,), 5.0),
            SPGConfig(max_iters=50, tolerance=1e-6),
        )
        assert bool(res.converged)
        assert not bool(res.stalled)

    def test_bounds_with_variances_rejected(self, rng):
        """Diag-inverse-Hessian variances assume an interior optimum;
        combining them with box constraints must refuse loudly (solve
        AND run_grid)."""
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            GlmOptimizationProblem,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        X, y, data, obj = _logistic_problem(rng)
        d = X.shape[1]
        bounds = (jnp.full(d, -0.2), jnp.full(d, 0.2))
        prob = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                regularization=RegularizationContext.l2(),
                compute_variances=True,
            ),
        )
        with pytest.raises(ValueError, match="compute_variances"):
            prob.solve(data, 0.3, bounds=bounds)
        with pytest.raises(ValueError, match="compute_variances"):
            prob.run_grid(data, [0.3], bounds=bounds)

    def test_nan_trial_backtracks_poisson(self, rng):
        """An overflowing Poisson trial (exp of a huge margin -> NaN)
        must be rejected by the Armijo backtrack, not adopted."""
        from photon_ml_tpu.optim.projected import SPGConfig, spg_solve

        X = rng.normal(size=(100, 5)) * 30.0  # big features: easy overflow
        yc = rng.poisson(1.0, size=100).astype(np.float64)
        data = make_glm_data(X, yc, dtype=jnp.float64)
        obj = GlmObjective(losses.poisson)
        res = spg_solve(
            lambda w: obj.value_and_grad(w, data, l2_weight=1.0),
            jnp.zeros(5, jnp.float64),
            jnp.full(5, -2.0), jnp.full(5, 2.0),
            SPGConfig(max_iters=200, tolerance=1e-8),
        )
        assert np.all(np.isfinite(np.asarray(res.w)))
        assert np.isfinite(float(res.value))
