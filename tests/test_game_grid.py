"""Config-grid fitting, per-iteration validation, and evaluation suites.

Mirrors the reference's GameEstimator behavior (SURVEY.md §3.2): fit every
coordinate-config combination, track a validation EvaluationSuite after
every coordinate update, select the best model by the primary validation
metric.
"""

import json

import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.evaluation.evaluators import (
    AreaUnderROCCurveEvaluator,
    LogisticLossEvaluator,
)
from photon_ml_tpu.evaluation.suite import EvaluationSuite
from photon_ml_tpu.game.estimator import (
    FixedEffectCoordinateConfig,
    GameEstimator,
    GameTransformer,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.optim.problem import (
    GlmOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.optim.regularization import RegularizationContext


def _synthetic_game(rng, n_rows, n_users=15, uid_start=0):
    """Global linear signal + per-user bias, logistic response."""
    user_effect = rng.normal(scale=2.0, size=n_users)
    Xg = rng.normal(size=(n_rows, 4)).astype(np.float32)
    users = rng.integers(n_users, size=n_rows)
    margin = 1.2 * Xg[:, 0] - 0.8 * Xg[:, 1] + user_effect[users]
    y = (rng.uniform(size=n_rows) < 1 / (1 + np.exp(-margin))).astype(
        np.float32
    )
    shards = {
        "global": sp.csr_matrix(Xg),
        "userFeatures": sp.csr_matrix(np.ones((n_rows, 1), np.float32)),
    }
    ids = {"userId": np.array([f"u{u}" for u in users])}
    return shards, ids, y, user_effect, users


@pytest.fixture(scope="module")
def game_data():
    rng = np.random.default_rng(7)
    n_users = 15
    user_effect = rng.normal(scale=2.0, size=n_users)

    def make(n):
        Xg = rng.normal(size=(n, 4)).astype(np.float32)
        users = rng.integers(n_users, size=n)
        margin = 1.2 * Xg[:, 0] - 0.8 * Xg[:, 1] + user_effect[users]
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(np.float32)
        shards = {
            "global": sp.csr_matrix(Xg),
            "userFeatures": sp.csr_matrix(np.ones((n, 1), np.float32)),
        }
        ids = {"userId": np.array([f"u{u}" for u in users])}
        return shards, ids, y

    return make(500), make(250)


def _configs(reg_fixed=0.5, reg_user=0.5):
    opt = GlmOptimizationConfig(
        optimizer=OptimizerConfig(max_iters=40, tolerance=1e-7),
        regularization=RegularizationContext.l2(),
    )
    return {
        "fixed": FixedEffectCoordinateConfig(
            feature_shard="global", optimization=opt, reg_weight=reg_fixed
        ),
        "per_user": RandomEffectCoordinateConfig(
            feature_shard="userFeatures",
            entity_key="userId",
            optimization=opt,
            reg_weight=reg_user,
        ),
    }


class TestEvaluationSuite:
    def test_from_specs_and_primary(self):
        suite = EvaluationSuite.from_specs(["auc", "logistic_loss"])
        assert suite.primary == "auc"
        assert isinstance(suite.primary_evaluator, AreaUnderROCCurveEvaluator)
        assert isinstance(dict(suite.evaluators)["logistic_loss"],
                          LogisticLossEvaluator)

    def test_evaluate_all_metrics(self):
        suite = EvaluationSuite.from_specs(["auc", "logistic_loss", "rmse"])
        scores = np.array([2.0, -1.0, 0.5, -0.5])
        labels = np.array([1.0, 0.0, 1.0, 0.0])
        out = suite.evaluate(scores, labels)
        assert set(out) == {"auc", "logistic_loss", "rmse"}
        assert out["auc"] == 1.0

    def test_better_than_direction_and_none(self):
        auc_suite = EvaluationSuite.from_specs(["auc"])
        assert auc_suite.better_than(0.9, 0.8)
        assert not auc_suite.better_than(0.8, 0.9)
        loss_suite = EvaluationSuite.from_specs(["logistic_loss"])
        assert loss_suite.better_than(0.3, 0.5)
        assert auc_suite.better_than(0.5, None)
        assert not auc_suite.better_than(None, 0.5)

    def test_bad_primary_rejected(self):
        with pytest.raises(ValueError, match="primary"):
            EvaluationSuite.from_specs(["auc"], primary="rmse")


class TestPerIterationValidation:
    def test_history_carries_validation_suite(self, game_data):
        (tr_shards, tr_ids, tr_y), (v_shards, v_ids, v_y) = game_data
        est = GameEstimator("logistic", _configs(), n_iterations=2)
        suite = EvaluationSuite.from_specs(["auc", "logistic_loss"])
        model, history = est.fit(
            tr_shards, tr_ids, tr_y,
            validation=(v_shards, v_ids, v_y),
            suite=suite,
        )
        # One entry per (iteration, coordinate) = 2 * 2.
        assert len(history) == 4
        for entry in history:
            assert set(entry["validation"]) == {"auc", "logistic_loss"}
            assert entry["validation_metric"] == entry["validation"]["auc"]
        # Per-iteration validation must see the random effect help.
        assert history[-1]["validation_metric"] > history[0]["validation_metric"]

    def test_validation_scorer_matches_transformer(self, game_data):
        """The incremental device-state scorer and the finalized-model
        transformer must produce identical validation scores."""
        (tr_shards, tr_ids, tr_y), (v_shards, v_ids, v_y) = game_data
        est = GameEstimator("logistic", _configs(), n_iterations=2)
        model, history = est.fit(
            tr_shards, tr_ids, tr_y,
            validation=(v_shards, v_ids, v_y),
        )
        t_scores = GameTransformer(model).transform(v_shards, v_ids)
        ev = AreaUnderROCCurveEvaluator()
        assert history[-1]["validation_metric"] == pytest.approx(
            ev.evaluate(t_scores, v_y), abs=1e-5
        )

    def test_unseen_validation_entities_score_zero(self, game_data):
        (tr_shards, tr_ids, tr_y), (v_shards, v_ids, v_y) = game_data
        est = GameEstimator("logistic", _configs(), n_iterations=1)
        coords = est.build_coordinates(tr_shards, tr_ids, tr_y)
        re_coord = coords[1]
        state = re_coord.train(np.zeros(len(tr_y), np.float32))
        # All-new entities: every validation row must score exactly 0.
        new_ids = {"userId": np.array(["zz%d" % i for i in range(len(v_y))])}
        scorer = re_coord.make_validation_scorer(v_shards, new_ids)
        np.testing.assert_array_equal(np.asarray(scorer.score(state)), 0.0)


class TestConfigGrid:
    def test_grid_selects_best_by_validation(self, game_data):
        (tr_shards, tr_ids, tr_y), (v_shards, v_ids, v_y) = game_data
        # A hugely over-regularized point must lose to a reasonable one.
        grid = [_configs(1e6, 1e6), _configs(0.5, 0.5)]
        est = GameEstimator("logistic", _configs(), n_iterations=2)
        model, results = est.fit_grid(
            grid, tr_shards, tr_ids, tr_y,
            validation=(v_shards, v_ids, v_y),
        )
        assert len(results) == 2
        assert results[1]["best"] and not results[0]["best"]
        assert results[1]["metric"] > results[0]["metric"]
        assert results[0]["selected_by"] == "validation_metric"

    def test_grid_shares_datasets(self, game_data):
        (tr_shards, tr_ids, tr_y), _ = game_data
        grid = [_configs(0.1, 0.1), _configs(1.0, 1.0), _configs(10.0, 10.0)]
        est = GameEstimator("logistic", _configs(), n_iterations=1)
        cache: dict = {}
        coords_a = est._build_coordinates(
            grid[0], tr_shards, tr_ids, tr_y, None, None, dataset_cache=cache
        )
        coords_b = est._build_coordinates(
            grid[1], tr_shards, tr_ids, tr_y, None, None, dataset_cache=cache
        )
        # Same dataset objects, different coordinate objects.
        assert coords_a[0].dataset is coords_b[0].dataset
        assert coords_a[1].dataset is coords_b[1].dataset

    def test_grid_without_validation_selects_by_train(self, game_data):
        (tr_shards, tr_ids, tr_y), _ = game_data
        grid = [_configs(1e6, 1e6), _configs(0.5, 0.5)]
        est = GameEstimator("logistic", _configs(), n_iterations=1)
        model, results = est.fit_grid(grid, tr_shards, tr_ids, tr_y)
        assert results[0]["selected_by"] == "train_metric"
        assert results[1]["best"]


class TestDriverGrid:
    def test_driver_reg_weight_grid(self, tmp_path):
        from photon_ml_tpu.data.game_reader import write_game_avro
        from photon_ml_tpu.drivers import game_training_driver

        rng = np.random.default_rng(3)
        user_effect = {f"u{u}": rng.normal(scale=2.0) for u in range(12)}

        def rows(n, start):
            out = []
            for i in range(start, start + n):
                u = f"u{rng.integers(len(user_effect))}"
                xg = rng.normal(size=3)
                margin = 1.5 * xg[0] - 1.0 * xg[1] + user_effect[u]
                y = float(rng.uniform() < 1 / (1 + np.exp(-margin)))
                out.append({
                    "uid": f"row{i}", "response": y, "weight": None,
                    "offset": None, "ids": {"userId": u},
                    "features": {
                        "global": [
                            {"name": f"g{j}", "term": "", "value": float(xg[j])}
                            for j in range(3)
                        ],
                        "userFeatures": [
                            {"name": "bias", "term": "", "value": 1.0}
                        ],
                    },
                })
            return out

        train = str(tmp_path / "train.avro")
        val = str(tmp_path / "val.avro")
        write_game_avro(train, rows(400, 0))
        write_game_avro(val, rows(150, 400))
        config = {
            "task": "logistic",
            "iterations": 2,
            "evaluators": ["auc", "logistic_loss"],
            "coordinates": [
                {"name": "fixed", "type": "fixed", "feature_shard": "global",
                 "optimizer": "lbfgs", "max_iters": 40, "reg_type": "l2",
                 "reg_weights": [1e5, 0.5]},
                {"name": "per_user", "type": "random",
                 "feature_shard": "userFeatures", "entity_key": "userId",
                 "optimizer": "lbfgs", "max_iters": 30, "reg_type": "l2",
                 "reg_weight": 0.5},
            ],
        }
        config_path = str(tmp_path / "config.json")
        with open(config_path, "w") as f:
            json.dump(config, f)
        out = str(tmp_path / "out")
        result = game_training_driver.run([
            "--train-data", train, "--validate-data", val,
            "--config", config_path, "--output-dir", out,
        ])
        assert len(result["grid"]) == 2
        best = next(g for g in result["grid"] if g["best"])
        assert best["reg_weights"]["fixed"] == 0.5
        assert result["per_iteration_validation"]
        assert set(result["validation_suite"]) == {"auc", "logistic_loss"}
        # History from the best grid point carries per-update validation.
        assert all("validation" in h for h in result["history"])
        assert result["validation_metric"] > 0.6
