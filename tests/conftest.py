"""Test fixtures.

Mirrors the reference's test strategy (SURVEY.md §4): the reference runs its
"distributed" integration tests on `local[*]` Spark with multiple partitions;
the TPU-native analogue is a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count=8``, which exercises real psum /
sharding semantics without TPU hardware.
"""

import os

# Must be set before jax initializes any backend.
os.environ["JAX_PLATFORMS"] = "cpu"

# Tier-1 is compile-bound on 1-core CI boxes: most of the suite's wall
# clock is XLA compiling thousands of tiny per-test programs, and a warm
# persistent compilation cache (the same machinery drivers default to,
# utils/compile_cache.py) cuts a rerun ~4x — the difference between
# fitting the tier-1 wall budget and timing out.  Tests get their OWN
# stable dir (not the drivers' ~/.cache/photon_ml_tpu/jax_cache) so
# test-shaped executables never mix into a real driver cache;
# min_compile_secs=0.0 because the win here IS the sub-second compiles.
# $PHOTON_COMPILE_CACHE overrides the dir; set it empty to disable.
# tests/test_aux.py's TestCompileCache mutates this process-global config
# and restores it via its autouse fixture.
if "PHOTON_COMPILE_CACHE" not in os.environ:
    os.environ["PHOTON_COMPILE_CACHE"] = os.path.join(
        os.path.expanduser("~"), ".cache", "photon_ml_tpu",
        "jax_cache_tests",
    )
_cache_dir = os.environ["PHOTON_COMPILE_CACHE"]
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The env var alone is not honored in this environment (an "axon" TPU plugin
# wins platform selection); the config flag is.
jax.config.update("jax_platforms", "cpu")

# Float64 for finite-difference oracles and scipy parity checks.  Library
# data paths pin float32 explicitly, so this only affects test-constructed
# float64 arrays.
jax.config.update("jax_enable_x64", True)

if _cache_dir:
    from photon_ml_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(_cache_dir, min_compile_secs=0.0)

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: wall-clock-bound tests (load generators); excluded from "
        "tier-1 via -m 'not slow'",
    )


@pytest.fixture
def rng():
    return np.random.default_rng(20260729)


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 virtual CPU devices, got {len(devices)}"
    return devices[:8]
