"""Test fixtures.

Mirrors the reference's test strategy (SURVEY.md §4): the reference runs its
"distributed" integration tests on `local[*]` Spark with multiple partitions;
the TPU-native analogue is a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count=8``, which exercises real psum /
sharding semantics without TPU hardware.
"""

import os
import tempfile

# Must be set before jax initializes any backend.
os.environ["JAX_PLATFORMS"] = "cpu"

# Drivers enable the persistent compilation cache by default ('auto');
# keep test-shaped executables out of the real ~/.cache.  The dir must be
# chosen before jax initializes (so no tmp_path fixture), but it can still
# be cleaned up at interpreter exit.
if "PHOTON_COMPILE_CACHE" not in os.environ:
    import atexit
    import shutil

    _cache_tmp = tempfile.mkdtemp(prefix="photon_test_jax_cache_")
    os.environ["PHOTON_COMPILE_CACHE"] = _cache_tmp
    atexit.register(shutil.rmtree, _cache_tmp, ignore_errors=True)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The env var alone is not honored in this environment (an "axon" TPU plugin
# wins platform selection); the config flag is.
jax.config.update("jax_platforms", "cpu")

# Float64 for finite-difference oracles and scipy parity checks.  Library
# data paths pin float32 explicitly, so this only affects test-constructed
# float64 arrays.
jax.config.update("jax_enable_x64", True)

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: wall-clock-bound tests (load generators); excluded from "
        "tier-1 via -m 'not slow'",
    )


@pytest.fixture
def rng():
    return np.random.default_rng(20260729)


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 virtual CPU devices, got {len(devices)}"
    return devices[:8]
