"""Chaos harness tests: deterministic fault injection + verified recovery.

The bar (ISSUE 6): recovery is PROVEN by killing runs mid-flight, not
asserted.  Crash-at-every-boundary matrices drive the GLM λ-grid and the
GAME CD loop through scripted kills at EVERY checkpoint boundary and
require the resumed result to be bitwise identical to the uninterrupted
one; the serving tests require a lost device to degrade (zero request
errors) and the breaker to re-promote.
"""

import json
import threading

import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu import chaos
from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.chaos import CircuitBreaker
from photon_ml_tpu.io.checkpoint import (
    CoordinateDescentCheckpointer,
    GridCheckpointer,
)
from photon_ml_tpu.optim.problem import (
    GlmOptimizationConfig,
    GlmOptimizationProblem,
    OptimizerConfig,
)
from photon_ml_tpu.optim.regularization import RegularizationContext
from photon_ml_tpu.utils.watchdog import (
    RetryPolicy,
    RetryStats,
    run_with_retries,
)


def _bitwise_equal(a, b):
    a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
    return a.shape == b.shape and a.tobytes() == b.tobytes()


# ---------------------------------------------------------------------------
# FaultPlan mechanics
# ---------------------------------------------------------------------------

class TestFaultPlan:
    def test_disabled_is_noop(self):
        assert chaos.current_plan() is None
        chaos.maybe_fail("grid.point", reg_weight=1.0)  # no plan: no-op

    def test_unknown_site_refused(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            chaos.FaultSpec(site="no.such.site")

    def test_bad_spec_fields_refused(self):
        with pytest.raises(ValueError, match="action"):
            chaos.FaultSpec(site="grid.point", action="explode")
        with pytest.raises(ValueError, match="exception"):
            chaos.FaultSpec(site="grid.point", exception="KeyboardInterrupt")
        with pytest.raises(ValueError, match="count"):
            chaos.FaultSpec(site="grid.point", count=0)

    def test_occurrence_targeting_and_window(self):
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="grid.point", at=2, count=2),
        ])
        fired = []
        with plan:
            for i in range(6):
                try:
                    chaos.maybe_fail("grid.point", i=i)
                except chaos.InjectedFault:
                    fired.append(i)
        assert fired == [2, 3]
        assert plan.occurrences("grid.point") == 6
        assert [f["occurrence"] for f in plan.fired_at("grid.point")] == [2, 3]

    def test_forever_window(self):
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="cd.iteration", at=1, count=-1),
        ])
        with plan:
            chaos.maybe_fail("cd.iteration")  # occurrence 0: clean
            for _ in range(3):
                with pytest.raises(chaos.InjectedFault):
                    chaos.maybe_fail("cd.iteration")

    def test_counts_survive_reinstall(self):
        """The kill/resume idiom: the same plan object re-installed (or
        left installed across a watchdog retry) keeps counting, so an
        armed occurrence fires ONCE and the resumed run sails past."""
        plan = chaos.FaultPlan([chaos.FaultSpec(site="grid.point", at=0)])
        with plan:
            with pytest.raises(chaos.InjectedFault):
                chaos.maybe_fail("grid.point")
        with plan:
            chaos.maybe_fail("grid.point")  # occurrence 1: clean

    def test_delay_action(self):
        plan = chaos.FaultPlan([
            chaos.FaultSpec(
                site="serving.batch", action="delay", delay_seconds=0.01
            ),
        ])
        import time

        with plan:
            t0 = time.perf_counter()
            chaos.maybe_fail("serving.batch")
            assert time.perf_counter() - t0 >= 0.01

    def test_json_round_trip(self):
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="grid.point", at=1,
                            exception="InjectedDeviceLost"),
            chaos.FaultSpec(site="serving.device", action="delay",
                            delay_seconds=0.5),
        ])
        plan2 = chaos.FaultPlan.from_json(plan.to_json())
        assert plan2.faults == plan.faults
        with pytest.raises(ValueError, match="unknown fault site"):
            chaos.FaultPlan.from_json(json.dumps([{"site": "nope"}]))

    def test_exclusive_installation(self):
        a = chaos.FaultPlan([])
        b = chaos.FaultPlan([])
        with a:
            with pytest.raises(RuntimeError, match="already installed"):
                b.install()
        b.install()
        b.uninstall()

    def test_default_message_is_watchdog_transient(self):
        spec = chaos.FaultSpec(site="tuning.trial")
        exc = spec.build_exception(0)
        verdict = RetryPolicy().classify(exc)
        assert verdict.transient and verdict.matched == "UNAVAILABLE"

    def test_injection_counted_in_telemetry(self):
        with telemetry_mod.Telemetry(enabled=True, sinks=[]) as tel:
            plan = chaos.FaultPlan([
                chaos.FaultSpec(site="grid.point", at=0),
            ])
            with plan:
                with pytest.raises(chaos.InjectedFault):
                    chaos.maybe_fail("grid.point")
            assert tel.counter("chaos_faults_injected").value == 1

    def test_thread_safe_occurrence_counting(self):
        plan = chaos.FaultPlan([])
        with plan:
            threads = [
                threading.Thread(
                    target=lambda: [
                        chaos.maybe_fail("prefetch.pack") for _ in range(200)
                    ]
                )
                for _ in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert plan.occurrences("prefetch.pack") == 800


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------

class TestCircuitBreaker:
    def test_state_machine(self):
        clock = [0.0]
        br = CircuitBreaker(cooldown_seconds=10.0, clock=lambda: clock[0])
        assert br.state == chaos.CLOSED and br.allow_request()
        br.record_failure()
        assert br.state == chaos.OPEN
        assert not br.allow_request()  # cooldown not elapsed
        clock[0] = 9.9
        assert not br.allow_request()
        clock[0] = 10.0
        assert br.allow_request()  # admits THE probe
        assert br.state == chaos.HALF_OPEN
        br.record_failure()  # probe failed: re-open, cooldown restarts
        assert br.state == chaos.OPEN
        assert not br.allow_request()
        clock[0] = 20.0
        assert br.allow_request()
        br.record_success()
        assert br.state == chaos.CLOSED
        assert br.reclosures == 1 and br.opens == 2 and br.probes == 2

    def test_failure_threshold(self):
        clock = [0.0]
        br = CircuitBreaker(
            cooldown_seconds=1.0, failure_threshold=3,
            clock=lambda: clock[0],
        )
        br.record_failure()
        br.record_failure()
        assert br.state == chaos.CLOSED  # under threshold
        br.record_success()  # resets the consecutive run
        br.record_failure()
        br.record_failure()
        assert br.state == chaos.CLOSED
        br.record_failure()
        assert br.state == chaos.OPEN

    def test_validation(self):
        with pytest.raises(ValueError):
            CircuitBreaker(cooldown_seconds=-1.0)
        with pytest.raises(ValueError):
            CircuitBreaker(failure_threshold=0)


# ---------------------------------------------------------------------------
# Crash-at-every-boundary: GLM λ grid
# ---------------------------------------------------------------------------

def _glm_fixture():
    rng = np.random.default_rng(5)
    X = sp.csr_matrix(rng.normal(size=(200, 8)).astype(np.float32))
    w_true = rng.normal(size=8).astype(np.float32)
    y = (np.asarray(X @ w_true).ravel() > 0).astype(np.float32)
    from photon_ml_tpu.data.dataset import make_glm_data

    data = make_glm_data(X, y)
    problem = GlmOptimizationProblem(
        "logistic",
        GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=30),
            regularization=RegularizationContext.l2(),
        ),
    )
    return problem, data


class TestGridCrashEveryBoundary:
    def test_resume_bitwise_at_every_boundary(self, tmp_path):
        """Kill after EVERY grid-point checkpoint; each resumed grid must
        be bitwise identical to the uninterrupted one (mirrors
        test_tuning's every-abort-point journal tests, driven through
        the chaos harness + the watchdog)."""
        problem, data = _glm_fixture()
        lams = [10.0, 1.0, 0.1]
        full = problem.run_grid(data, lams)
        ref = {lam: np.asarray(m.coefficients.means) for lam, m, _ in full}

        for boundary in range(len(lams)):
            ckpt = GridCheckpointer(str(tmp_path / f"b{boundary}"))
            plan = chaos.FaultPlan([
                chaos.FaultSpec(site="grid.point", at=boundary),
            ])

            def train(attempt, ckpt=ckpt):
                solved = ckpt.load() if attempt else {}
                acc = dict(solved)

                def on_solved(lam, w):
                    acc[lam] = np.asarray(w)
                    ckpt.save(acc)

                return problem.run_grid(
                    data, lams, solved=solved, on_solved=on_solved
                )

            stats = RetryStats()
            with plan:
                resumed = run_with_retries(
                    train, RetryPolicy(max_retries=1),
                    sleep=lambda s: None, stats=stats,
                )
            assert stats.retries == 1
            assert len(plan.fired_at("grid.point")) == 1
            restored = sum(1 for _, _, r in resumed if r is None)
            assert restored == boundary + 1  # solved-before-kill λs skip
            for lam, model, _ in resumed:
                assert _bitwise_equal(ref[lam], model.coefficients.means), (
                    f"boundary {boundary}, λ={lam}: resumed grid diverged"
                )

    def test_non_transient_kill_propagates(self, tmp_path):
        """A fault NOT matching the transient vocabulary must not be
        retried — the watchdog hands it straight up."""
        problem, data = _glm_fixture()
        plan = chaos.FaultPlan([
            chaos.FaultSpec(
                site="grid.point",
                message="INVALID_ARGUMENT: chaos says no",
            ),
        ])
        with plan:
            with pytest.raises(chaos.InjectedFault):
                run_with_retries(
                    lambda a: problem.run_grid(data, [1.0]),
                    RetryPolicy(max_retries=3),
                    sleep=lambda s: None,
                )
        assert len(plan.fired_at("grid.point")) == 1  # no retry happened


# ---------------------------------------------------------------------------
# Crash-at-every-boundary: GAME coordinate descent
# ---------------------------------------------------------------------------

def _game_fixture(seed=13, n=300, n_users=10):
    rng = np.random.default_rng(seed)
    user_effect = rng.normal(scale=2.0, size=n_users)
    Xg = rng.normal(size=(n, 3)).astype(np.float32)
    users = rng.integers(n_users, size=n)
    margin = 1.3 * Xg[:, 0] - 0.7 * Xg[:, 1] + user_effect[users]
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(np.float32)
    shards = {
        "global": sp.csr_matrix(Xg),
        "userFeatures": sp.csr_matrix(np.ones((n, 1), np.float32)),
    }
    ids = {"userId": np.array([f"u{u}" for u in users])}
    return shards, ids, y


def _game_configs():
    from photon_ml_tpu.game.estimator import (
        FixedEffectCoordinateConfig,
        RandomEffectCoordinateConfig,
    )

    opt = GlmOptimizationConfig(
        optimizer=OptimizerConfig(max_iters=25, tolerance=1e-7),
        regularization=RegularizationContext.l2(),
    )
    return {
        "fixed": FixedEffectCoordinateConfig(
            feature_shard="global", optimization=opt, reg_weight=0.5
        ),
        "per_user": RandomEffectCoordinateConfig(
            feature_shard="userFeatures", entity_key="userId",
            optimization=opt, reg_weight=0.5,
        ),
    }


class TestCdCrashEveryBoundary:
    N_ITERS = 3

    def test_resume_bitwise_at_every_boundary(self, tmp_path):
        from photon_ml_tpu.game.estimator import GameEstimator

        shards, ids, y = _game_fixture()
        model_full, hist_full = GameEstimator(
            "logistic", _game_configs(), n_iterations=self.N_ITERS
        ).fit(shards, ids, y)
        w_full = np.asarray(model_full["fixed"].model.coefficients.means)
        cf = model_full["per_user"].coefficients

        for boundary in range(self.N_ITERS):
            ck = CoordinateDescentCheckpointer(str(tmp_path / f"b{boundary}"))
            plan = chaos.FaultPlan([
                chaos.FaultSpec(site="cd.iteration", at=boundary),
            ])

            def attempt(a, ck=ck):
                return GameEstimator(
                    "logistic", _game_configs(), n_iterations=self.N_ITERS
                ).fit(shards, ids, y, checkpointer=ck)

            stats = RetryStats()
            with plan:
                model_res, hist_res = run_with_retries(
                    attempt, RetryPolicy(max_retries=1),
                    sleep=lambda s: None, stats=stats,
                )
            assert stats.retries == 1
            w_res = np.asarray(
                model_res["fixed"].model.coefficients.means
            )
            assert _bitwise_equal(w_full, w_res), (
                f"boundary {boundary}: fixed-effect coefficients diverged"
            )
            cr = model_res["per_user"].coefficients
            assert set(cf) == set(cr)
            for k in cf:
                assert _bitwise_equal(cf[k][1], cr[k][1]), (
                    f"boundary {boundary}: per-entity {k} diverged"
                )
            assert len(hist_res) == len(hist_full)


# ---------------------------------------------------------------------------
# Streaming pipeline faults: teardown, propagation, no leaks
# ---------------------------------------------------------------------------

def _small_stream(n=160, d=10, chunk_rows=40):
    from photon_ml_tpu.data.streaming import make_streaming_glm_data

    rng = np.random.default_rng(11)
    X = sp.random(n, d, density=0.5, random_state=2, format="csr",
                  dtype=np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    return make_streaming_glm_data(X, y, chunk_rows=chunk_rows,
                                   use_pallas=False)


class TestStreamingFaults:
    @pytest.mark.parametrize(
        "site", ["prefetch.pack", "prefetch.transfer", "staging.put",
                 "streaming.carry_sync"],
    )
    def test_fault_propagates_and_next_pass_is_clean(self, site):
        """A fault on ANY pipeline stage surfaces on the caller thread,
        tears the pack/transfer threads down without leaking them, and
        the next clean pass over the same objective is bit-identical to
        a never-faulted pass (donated accumulators uncorrupted)."""
        import jax.numpy as jnp

        from photon_ml_tpu.optim.streaming import StreamingObjective

        stream = _small_stream()
        sobj = StreamingObjective("logistic", stream)
        w = jnp.zeros((stream.n_features,), jnp.float32)
        v0, g0 = sobj.value_and_grad(w, 1.0)
        v0, g0 = np.asarray(v0), np.asarray(g0)

        with telemetry_mod.Telemetry(enabled=True, sinks=[]) as tel:
            plan = chaos.FaultPlan([chaos.FaultSpec(site=site, at=1)])
            with plan:
                with pytest.raises(chaos.InjectedFault):
                    sobj.value_and_grad(w, 1.0)
            assert len(plan.fired_at(site)) == 1
            assert tel.counter("prefetch_thread_leak").value == 0

        v1, g1 = sobj.value_and_grad(w, 1.0)
        assert _bitwise_equal(v0, np.asarray(v1))
        assert _bitwise_equal(g0, np.asarray(g1))

    @pytest.mark.parametrize(
        # staging.decode fires per item (fault the 2nd); cache_evict
        # fires once per accumulation pass (fault its only occurrence).
        "site,at", [("staging.decode", 1), ("streaming.cache_evict", 0)],
    )
    def test_transfer_avoidance_fault_next_pass_clean(self, site, at):
        """Faults on the transfer-avoidance seams — the in-program
        dequant dispatch of a compressed item, and the working-set
        cache's replan — surface on the caller, leak no pipeline
        threads, leave the cache internally consistent (the evict fault
        clears it before propagating), and the next pass is bitwise
        identical to a never-faulted uncompressed, uncached pass."""
        import jax.numpy as jnp

        from photon_ml_tpu.optim.streaming import StreamingObjective

        stream = _small_stream()
        ref = StreamingObjective("logistic", _small_stream())
        w = jnp.zeros((stream.n_features,), jnp.float32)
        v0, g0 = ref.value_and_grad(w, 1.0)
        v0, g0 = np.asarray(v0), np.asarray(g0)

        sobj = StreamingObjective(
            "logistic", stream, compress="lossless",
            hot_budget_bytes=1 << 30,
        )
        with telemetry_mod.Telemetry(enabled=True, sinks=[]) as tel:
            # Two clean passes first: pass 1 replans, pass 2 admits —
            # so the faulted pass exercises hot hits + the cache paths.
            for _ in range(2):
                sobj.value_and_grad(w, 1.0)
            plan = chaos.FaultPlan([chaos.FaultSpec(site=site, at=at)])
            with plan:
                with pytest.raises(chaos.InjectedFault):
                    sobj.value_and_grad(w, 1.0)
            assert len(plan.fired_at(site)) == 1
            assert tel.counter("prefetch_thread_leak").value == 0
        if site == "streaming.cache_evict":
            # The fault fired inside replan: the cache must have been
            # cleared (no half-applied plan survives into later passes).
            assert len(sobj._hot_cache) == 0
            assert sobj._hot_cache.resident_bytes == 0

        v1, g1 = sobj.value_and_grad(w, 1.0)
        assert _bitwise_equal(v0, np.asarray(v1))
        assert _bitwise_equal(g0, np.asarray(g1))

    def test_streamed_grid_kill_resume_bitwise(self, tmp_path):
        """The streamed flavor of the grid boundary matrix (one boundary
        — the full matrix runs on the resident path above; the selfcheck
        covers a second streamed boundary)."""
        from photon_ml_tpu.optim.streaming import streaming_run_grid

        stream = _small_stream()
        problem = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(max_iters=20),
                regularization=RegularizationContext.l2(),
            ),
        )
        lams = [2.0, 0.5]
        full = streaming_run_grid(problem, stream, lams)
        ref = {lam: np.asarray(m.coefficients.means) for lam, m, _ in full}

        ckpt = GridCheckpointer(str(tmp_path / "sg"))
        plan = chaos.FaultPlan([chaos.FaultSpec(site="grid.point", at=0)])

        def train(attempt):
            solved = ckpt.load() if attempt else {}
            acc = dict(solved)

            def on_solved(lam, w):
                acc[lam] = np.asarray(w)
                ckpt.save(acc)

            return streaming_run_grid(
                problem, stream, lams, solved=solved, on_solved=on_solved
            )

        with plan:
            resumed = run_with_retries(
                train, RetryPolicy(max_retries=1), sleep=lambda s: None
            )
        for lam, model, _ in resumed:
            assert _bitwise_equal(ref[lam], model.coefficients.means)


class TestPrefetchThreadLeak:
    def test_wedged_thread_counted_not_silent(self, monkeypatch):
        """A pipeline thread that outlives the join timeout is COUNTED
        (prefetch_thread_leak) — the old code returned as if nothing
        happened.  Here the transfer thread wedges inside put() while
        the consumer's failure is propagating, so the original exception
        keeps priority and the leak lands on the counter."""
        import time

        from photon_ml_tpu.data import prefetch as prefetch_mod

        monkeypatch.setattr(prefetch_mod, "JOIN_TIMEOUT_SECONDS", 0.01)
        release = threading.Event()

        def put(item):
            if item == 1:
                release.wait(5.0)  # wedged until the test releases it
            return item

        def consume(k, dev):
            raise ValueError("consumer dies while transfer is wedged")

        with telemetry_mod.Telemetry(enabled=True, sinks=[]) as tel:
            with pytest.raises(ValueError, match="consumer dies"):
                prefetch_mod.run_prefetched(
                    3, lambda k: k, put, consume, depth=2,
                )
            # Give the wedge a beat to be observed as alive by join().
            assert tel.counter("prefetch_thread_leak").value >= 1
        release.set()
        time.sleep(0.05)  # let the daemon thread drain before exit

    def test_healthy_pipeline_counts_no_leak(self):
        from photon_ml_tpu.data import prefetch as prefetch_mod

        with telemetry_mod.Telemetry(enabled=True, sinks=[]) as tel:
            seen = []
            prefetch_mod.run_prefetched(
                4, lambda k: k, lambda x: x,
                lambda k, dev: seen.append(k), depth=2,
            )
            assert seen == [0, 1, 2, 3]
            assert tel.counter("prefetch_thread_leak").value == 0


# ---------------------------------------------------------------------------
# Serving: degrade on device loss, zero errors, breaker re-promotion
# ---------------------------------------------------------------------------

def _serving_runtime(**cfg_kw):
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.synthetic import SyntheticWorkload

    workload = SyntheticWorkload(n_entities=24, seed=9)
    cfg_kw.setdefault("max_batch_size", 4)
    cfg_kw.setdefault("hot_entities", 8)
    cfg_kw.setdefault("breaker_cooldown_s", 0.0)
    runtime = ScoringRuntime(
        workload.model, workload.index_maps, RuntimeConfig(**cfg_kw)
    )
    return workload, runtime


class TestServingDegrade:
    def test_device_lost_degrades_and_repromotes(self):
        workload, runtime = _serving_runtime()
        rows = [runtime.parse_request(workload.request(i)) for i in range(10)]
        ref = np.asarray(
            [runtime.score_rows([r])[0][0] for r in rows], np.float32
        )
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="serving.device", at=0, count=3,
                            exception="InjectedDeviceLost"),
        ])
        got = np.zeros(len(rows), np.float32)
        degraded_during = []
        with plan:
            for i, r in enumerate(rows):
                m, mu = runtime.score_rows([r])
                got[i] = m[0]
                degraded_during.append(runtime.degraded)
        assert degraded_during[0] is True  # first fault flips the flag
        assert runtime.degraded is False  # fault cleared: re-promoted
        assert runtime.breaker.state == chaos.CLOSED
        assert runtime.degraded_batches == 3
        assert runtime.repromotions == 1
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_open_breaker_skips_device_entirely(self):
        """While OPEN (cooldown pending), batches go straight to the host
        path — the dead device is not probed per batch."""
        workload, runtime = _serving_runtime(breaker_cooldown_s=1e9)
        rows = [runtime.parse_request(workload.request(i)) for i in range(6)]
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="serving.device", at=0, count=-1,
                            exception="InjectedDeviceLost"),
        ])
        with plan:
            for r in rows:
                runtime.score_rows([r])
        # Only the FIRST batch touched the device seam; the breaker held
        # the other five off it.
        assert plan.occurrences("serving.device") == 1
        assert runtime.degraded and runtime.breaker.state == chaos.OPEN
        assert runtime.degraded_batches == 6

    def test_non_transient_device_error_propagates(self):
        """A programming error on the device path must NOT degrade —
        masking it as availability would hide real bugs."""
        workload, runtime = _serving_runtime()
        row = runtime.parse_request(workload.request(0))
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="serving.device",
                            message="INVALID_ARGUMENT: shape mismatch"),
        ])
        with plan:
            with pytest.raises(chaos.InjectedFault):
                runtime.score_rows([row])
        assert not runtime.degraded

    def test_service_healthz_and_stats_carry_degraded(self):
        from photon_ml_tpu.serving.batcher import BatcherConfig
        from photon_ml_tpu.serving.service import ScoringService

        workload, runtime = _serving_runtime(breaker_cooldown_s=1e9)
        service = ScoringService(runtime, BatcherConfig(
            max_batch_size=4, max_wait_us=0, max_queue=16,
        ))
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="serving.device", at=0, count=-1,
                            exception="InjectedDeviceLost"),
        ])
        with service, plan:
            assert service.healthz()["degraded"] is False
            result = service.score(workload.request(0))
            assert "error" not in result
            hz = service.healthz()
            assert hz["degraded"] is True and hz["status"] == "degraded"
            assert hz["breaker"] == chaos.OPEN
            st = service.stats()
            assert st["runtime"]["degraded"] is True
            assert st["runtime"]["breaker"]["state"] == chaos.OPEN

    def test_batcher_site_fails_requests_cleanly(self):
        """A fault at the serving.batch seam (before the runtime is
        reached) rides the batcher's per-request failure path: futures
        get the exception, counters classify it transient."""
        from photon_ml_tpu.serving.batcher import BatcherConfig
        from photon_ml_tpu.serving.service import ScoringService

        workload, runtime = _serving_runtime()
        service = ScoringService(runtime, BatcherConfig(
            max_batch_size=4, max_wait_us=0, max_queue=16,
        ))
        plan = chaos.FaultPlan([chaos.FaultSpec(site="serving.batch")])
        with service, plan:
            fut = service.submit(workload.request(0))
            with pytest.raises(chaos.InjectedFault):
                fut.result(timeout=10)
            stats = service.batcher.stats()
            assert stats["failed"] == 1
            assert stats["failed_transient"] == 1


# ---------------------------------------------------------------------------
# Tuning: injected trial faults ride the executor's retry vocabulary
# ---------------------------------------------------------------------------

class TestTuningTrialFaults:
    def test_transient_trial_fault_retries_in_place(self, tmp_path):
        from photon_ml_tpu.tuning.executor import (
            TuningConfig,
            TuningOrchestrator,
        )
        from photon_ml_tpu.tuning.scheduler import GridProposer, SearchSpace
        from photon_ml_tpu.tuning.state import TuningJournal

        space = SearchSpace.create([(0.0, 1.0)])
        journal = TuningJournal(str(tmp_path))
        plan = chaos.FaultPlan([chaos.FaultSpec(site="tuning.trial", at=1)])
        with plan:
            res = TuningOrchestrator(
                space, lambda p, r, w: float(p[0]),
                GridProposer(space, [[0.1], [0.5], [0.9]]),
                TuningConfig(
                    max_trials=3, workers=1,
                    retry=RetryPolicy(max_retries=1),
                    sleep=lambda s: None,
                ),
                journal,
            ).run()
        journal.close()
        assert res.completed == 3 and res.failed == 0
        assert sum(t["retries"] for t in res.trials) == 1
        assert len(plan.fired_at("tuning.trial")) == 1


# ---------------------------------------------------------------------------
# Checkpoint save-boundary kill through the chaos site
# ---------------------------------------------------------------------------

class TestCheckpointSaveKill:
    def test_kill_before_rename_preserves_previous(self, tmp_path):
        ck = GridCheckpointer(str(tmp_path))
        ck.save({1.0: np.ones(3, np.float32)})
        plan = chaos.FaultPlan([chaos.FaultSpec(site="checkpoint.save")])
        with plan:
            with pytest.raises(chaos.InjectedFault):
                ck.save({1.0: np.ones(3, np.float32),
                         0.5: np.zeros(3, np.float32)})
        # The published checkpoint is still the previous complete one.
        assert sorted(ck.load()) == [1.0]

    def test_restore_site_fires(self, tmp_path):
        ck = GridCheckpointer(str(tmp_path))
        ck.save({1.0: np.ones(3, np.float32)})
        plan = chaos.FaultPlan([
            chaos.FaultSpec(
                site="checkpoint.restore",
                message="UNAVAILABLE: injected restore-path failure",
            ),
        ])
        with plan:
            with pytest.raises(chaos.InjectedFault):
                ck.load()
        assert sorted(ck.load()) == [1.0]  # clean restore afterwards
