"""High-availability serving tests (ISSUE 9).

The load-bearing contracts:

- a scripted replica kill costs ZERO failed requests (resubmission);
- a model hot-swap under concurrent traffic is invisible: every score is
  bit-identical to EITHER the pre-swap or the post-swap single-runtime
  reference, never a mix within one row;
- a tampered model directory (payload or ``.meta.json`` sidecar) rolls
  back automatically with the previous version still serving;
- swap while degraded DEFERS (the pinned decision — see
  serving/swap.py);
- the tiered admission controller sheds low-priority and over-deadline
  work before rejecting everything, and journals tier transitions;
- liveness (/livez) and readiness (/readyz) are distinct verdicts.
"""

import json
import os
import shutil
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from photon_ml_tpu import chaos
from photon_ml_tpu import telemetry
from photon_ml_tpu.io.game_store import save_game_model
from photon_ml_tpu.serving.batcher import (
    BatcherConfig,
    MicroBatcher,
    RejectedError,
    TIER_ACCEPT,
    TIER_REJECT,
    TIER_SHED,
)
from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
from photon_ml_tpu.serving.service import ScoringService, start_http_server
from photon_ml_tpu.serving.supervisor import ReplicaSupervisor
from photon_ml_tpu.serving.swap import HotSwapper, SwapInProgressError
from photon_ml_tpu.serving.synthetic import SyntheticWorkload


@pytest.fixture(scope="module")
def workload():
    # No unknown entities: requests must parse/score identically on any
    # replica and across model versions.
    return SyntheticWorkload(n_entities=32, seed=7)


@pytest.fixture(scope="module")
def workload_v2():
    # Same shard shapes as `workload`, different coefficients — a request
    # stream valid on both, scoring differently.
    return SyntheticWorkload(n_entities=32, seed=8)


def _runtime(workload, **kwargs):
    cfg = RuntimeConfig(**{"max_batch_size": 8, "hot_entities": 8, **kwargs})
    return ScoringRuntime(workload.model, workload.index_maps, cfg)


def _reference(workload, requests):
    """Scores from a fresh single runtime, one row at a time."""
    runtime = _runtime(workload)
    return np.asarray(
        [
            runtime.score_rows([runtime.parse_request(r)])[0][0]
            for r in requests
        ],
        np.float32,
    )


def _wait_until(predicate, timeout_s=10.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


# ---------------------------------------------------------------------------
# Replica supervision
# ---------------------------------------------------------------------------

class TestSupervisor:
    def test_kill_replica_zero_failed_requests(self, workload):
        sup = ReplicaSupervisor(
            lambda: _runtime(workload), n_replicas=2,
            probe_interval_s=0.05,
        )
        with sup:
            requests = [workload.request(i) for i in range(48)]
            rows = [sup.parse_request(r) for r in requests]
            futures = [sup.submit(r) for r in rows[:24]]
            sup.kill_replica(0)
            futures += [sup.submit(r) for r in rows[24:]]
            results = [f.result(timeout=30) for f in futures]
            assert all(np.isfinite(r["score"]) for r in results)
            # The killed replica restarts and rejoins.
            assert _wait_until(lambda: sup.healthy_count == 2), (
                sup.stats()
            )
            assert sup.stats()["replicas"][0]["restarts"] == 1

    def test_kill_costs_zero_errors_under_load(self, workload):
        from photon_ml_tpu.serving import loadgen

        sup = ReplicaSupervisor(
            lambda: _runtime(workload), n_replicas=2,
            probe_interval_s=0.05,
        )
        service = ScoringService(sup)
        with service:
            killer = threading.Timer(
                0.3, lambda: sup.kill_replica(1)
            )
            killer.start()
            report = loadgen.open_loop(
                service.submit, workload.request,
                rate_rps=150.0, duration_s=1.5,
            )
            killer.join()
        assert report.errors == 0, report.snapshot()
        assert report.rejected == 0, report.snapshot()
        assert report.completed > 50

    def test_chaos_replica_site_reroutes(self, workload):
        """A FaultPlan-scripted kill at the routing seam: the victim is
        marked down, the request resubmits and still succeeds."""
        sup = ReplicaSupervisor(
            lambda: _runtime(workload), n_replicas=2,
            probe_interval_s=10.0,  # keep probes out of the script
        )
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="serving.replica", at=0),
        ])
        with sup:
            row = sup.parse_request(workload.request(0))
            with plan:
                result = sup.submit(row).result(timeout=30)
            assert np.isfinite(result["score"])
            assert plan.fired and \
                plan.fired[0]["site"] == "serving.replica"
            assert sup.healthy_count == 1  # victim awaits restart

    def test_probes_detect_poisoned_replica_and_restart(self, workload):
        sup = ReplicaSupervisor(
            lambda: _runtime(workload), n_replicas=2,
            probe_interval_s=0.05, probe_failure_threshold=2,
        )
        with sup:
            class _Wedged:
                degraded = False

                def score_rows(self, rows):
                    raise RuntimeError("UNAVAILABLE: wedged")

                def bucket_for(self, n):
                    return n

            sup.replicas[0].batcher.runtime = _Wedged()
            assert _wait_until(
                lambda: sup.replicas[0].restarts >= 1
            ), sup.stats()
            assert sup.healthy_count == 2 or _wait_until(
                lambda: sup.healthy_count == 2
            )

    def test_restart_backoff_is_decorrelated_jitter(self, workload):
        """Consecutive restart delays follow the watchdog's decorrelated
        walk: within [base, 3*previous] and capped."""
        from photon_ml_tpu.utils.watchdog import RetryPolicy

        policy = RetryPolicy(
            backoff_seconds=0.1, max_backoff_seconds=5.0,
            jitter="decorrelated",
        )
        sup = ReplicaSupervisor(
            lambda: _runtime(workload), n_replicas=1,
            restart_policy=policy,
        )
        # Exercise the scheduling math without starting threads.
        import random

        rng = random.Random(42)
        prev = None
        for attempt in range(6):
            delay = policy.backoff(attempt, rng=rng, previous=prev)
            assert 0.1 <= delay <= 5.0
            if prev is not None:
                assert delay <= max(3 * prev, 0.1) + 1e-9
            prev = delay
        assert sup.restart_policy.jitter == "decorrelated"

    def test_no_healthy_replica_rejects_transiently(self, workload):
        sup = ReplicaSupervisor(
            lambda: _runtime(workload), n_replicas=1,
            probe_interval_s=10.0,
            restart_policy=__import__(
                "photon_ml_tpu.utils.watchdog", fromlist=["RetryPolicy"]
            ).RetryPolicy(backoff_seconds=30.0),
        )
        with sup:
            row = sup.parse_request(workload.request(0))
            sup.kill_replica(0)
            with pytest.raises(RejectedError):
                sup.submit(row).result(timeout=30)


# ---------------------------------------------------------------------------
# Hot swap + rollback
# ---------------------------------------------------------------------------

@pytest.fixture()
def model_dirs(tmp_path, workload, workload_v2):
    v1 = str(tmp_path / "v1")
    v2 = str(tmp_path / "v2")
    save_game_model(workload.model, workload.index_maps, v1)
    save_game_model(workload_v2.model, workload_v2.index_maps, v2)
    return v1, v2


class TestHotSwap:
    def test_swap_bit_parity_under_concurrent_traffic(
        self, workload, workload_v2, model_dirs, tmp_path
    ):
        """Every score observed during a hot swap matches EITHER the
        pre-swap or the post-swap single-runtime reference, bitwise —
        no request ever sees a half-swapped runtime."""
        _v1, v2_dir = model_dirs
        requests = [workload.request(i) for i in range(16)]
        ref_v1 = _reference(workload, requests)
        ref_v2 = _reference(workload_v2, requests)
        assert ref_v1.tobytes() != ref_v2.tobytes()

        service = ScoringService(_runtime(workload))
        scores: list[tuple[int, np.float32]] = []
        errors: list[str] = []
        stop = threading.Event()

        def traffic():
            i = 0
            while not stop.is_set():
                try:
                    r = service.score(requests[i % 16], timeout=30)
                    scores.append((i % 16, np.float32(r["score"])))
                except Exception as exc:  # noqa: BLE001
                    errors.append(repr(exc))
                i += 1

        with service:
            threads = [
                threading.Thread(target=traffic) for _ in range(3)
            ]
            for t in threads:
                t.start()
            time.sleep(0.2)
            result = service.reload(v2_dir)
            time.sleep(0.2)
            stop.set()
            for t in threads:
                t.join()
        assert result.status == "swapped", result
        assert result.version_after == 2
        assert not errors, errors[:3]
        assert len(scores) > 20
        for idx, score in scores:
            assert score.tobytes() in (
                np.float32(ref_v1[idx]).tobytes(),
                np.float32(ref_v2[idx]).tobytes(),
            ), f"request {idx} scored {score!r}, matching neither version"
        # Post-swap: everything scores as v2.
        with service:
            post = np.asarray(
                [
                    np.float32(service.score(r)["score"])
                    for r in requests
                ],
                np.float32,
            )
        assert post.tobytes() == ref_v2.tobytes()

    def test_tampered_payload_rolls_back_with_zero_errors(
        self, workload, model_dirs, tmp_path
    ):
        v1_dir, v2_dir = model_dirs
        bad_dir = str(tmp_path / "bad")
        shutil.copytree(v2_dir, bad_dir)
        # Swap in v1's payload under v2's fingerprints: the file is
        # structurally valid avro, only the CONTENT is wrong — exactly
        # what a silent corruption or botched copy looks like.
        rel = os.path.join("random-effect", "per_entity", "coefficients.avro")
        shutil.copyfile(
            os.path.join(v1_dir, rel), os.path.join(bad_dir, rel)
        )
        requests = [workload.request(i) for i in range(8)]
        ref = _reference(workload, requests)
        service = ScoringService(_runtime(workload))
        with service:
            result = service.reload(bad_dir)
            assert result.status == "rolled_back", result
            assert result.stage in ("load", "prepare")
            assert result.version_after == 1
            assert "checksum" in result.reason
            got = np.asarray(
                [np.float32(service.score(r)["score"]) for r in requests],
                np.float32,
            )
        assert got.tobytes() == ref.tobytes()  # v1 still serving

    def test_tampered_meta_sidecar_rolls_back(
        self, workload, model_dirs, tmp_path
    ):
        _v1, v2_dir = model_dirs
        bad_dir = str(tmp_path / "badmeta")
        shutil.copytree(v2_dir, bad_dir)
        meta = os.path.join(
            bad_dir, "fixed-effect", "fixed", "coefficients.avro.meta.json"
        )
        with open(meta) as f:
            payload = json.load(f)
        payload["fingerprint"]["coefficient_checksum"] = "0" * 64
        with open(meta, "w") as f:
            json.dump(payload, f)
        service = ScoringService(_runtime(workload))
        with service:
            result = service.reload(bad_dir)
        assert result.status == "rolled_back", result
        assert service.swapper.version == 1

    def test_swap_while_degraded_defers(self, workload, model_dirs):
        """The pinned decision: no swap commits through a degraded
        runtime; the result is 'deferred' and nothing changes."""
        _v1, v2_dir = model_dirs
        service = ScoringService(_runtime(workload))
        with service:
            service.batcher.runtime.degraded = True
            result = service.reload(v2_dir)
            assert result.status == "deferred", result
            assert service.swapper.version == 1
            service.batcher.runtime.degraded = False
            assert service.reload(v2_dir).status == "swapped"

    def test_chaos_verify_stage_rolls_back_post_commit(
        self, workload, model_dirs
    ):
        """A fault AFTER the commit (verify stage = occurrence 2 of
        serving.swap) restores the previous runtimes."""
        _v1, v2_dir = model_dirs
        requests = [workload.request(i) for i in range(8)]
        ref = _reference(workload, requests)
        service = ScoringService(_runtime(workload))
        plan = chaos.FaultPlan([
            chaos.FaultSpec(site="serving.swap", at=2),
        ])
        with service:
            with plan:
                result = service.reload(v2_dir)
            assert result.status == "rolled_back", result
            assert result.stage == "verify"
            assert service.swapper.version == 1
            got = np.asarray(
                [np.float32(service.score(r)["score"]) for r in requests],
                np.float32,
            )
        assert got.tobytes() == ref.tobytes()
        assert [f["site"] for f in plan.fired] == ["serving.swap"]

    def test_manual_rollback_and_version_monotonicity(
        self, workload, workload_v2, model_dirs
    ):
        _v1, v2_dir = model_dirs
        requests = [workload.request(i) for i in range(4)]
        ref_v1 = _reference(workload, requests)
        service = ScoringService(_runtime(workload))
        with service:
            assert service.reload(v2_dir).version_after == 2
            back = service.reload(rollback=True)
            assert back.status == "rolled_back"
            assert back.version_after == 1
            got = np.asarray(
                [np.float32(service.score(r)["score"]) for r in requests],
                np.float32,
            )
            assert got.tobytes() == ref_v1.tobytes()
            # Version numbers are never reused: the next swap is v3.
            assert service.reload(v2_dir).version_after == 3

    def test_concurrent_swap_raises_in_progress(self, workload, model_dirs):
        _v1, v2_dir = model_dirs
        service = ScoringService(_runtime(workload))
        with service:
            assert service.swapper._swap_lock.acquire(blocking=False)
            try:
                with pytest.raises(SwapInProgressError):
                    service.reload(v2_dir)
            finally:
                service.swapper._swap_lock.release()

    def test_supervisor_swap_rolls_all_replicas(
        self, workload, workload_v2, model_dirs
    ):
        _v1, v2_dir = model_dirs
        requests = [workload.request(i) for i in range(8)]
        ref_v2 = _reference(workload_v2, requests)
        sup = ReplicaSupervisor(
            lambda: _runtime(workload), n_replicas=2,
            probe_interval_s=0.05,
        )
        service = ScoringService(sup)
        with service:
            result = service.reload(v2_dir)
            assert result.status == "swapped"
            assert result.targets == 2
            got = np.asarray(
                [np.float32(service.score(r)["score"]) for r in requests],
                np.float32,
            )
            assert got.tobytes() == ref_v2.tobytes()
            # Restarts come back on the committed version.
            sup.kill_replica(0)
            assert _wait_until(lambda: sup.healthy_count == 2)
            versions = {
                r["model_version"] for r in sup.stats()["replicas"]
            }
            assert versions == {2}, sup.stats()


# ---------------------------------------------------------------------------
# Tiered admission control
# ---------------------------------------------------------------------------

def _idle_batcher(workload, **cfg_kwargs):
    """A batcher whose dispatch thread is NOT running — queue depth is
    fully controlled by the test."""
    runtime = _runtime(workload)
    cfg = BatcherConfig(**{
        "max_batch_size": 8, "max_queue": 20, "max_wait_us": 1000,
        **cfg_kwargs,
    })
    return MicroBatcher(runtime, cfg), runtime


class TestTieredAdmission:
    def test_accept_below_watermarks(self, workload):
        batcher, runtime = _idle_batcher(workload)
        row = runtime.parse_request(workload.request(0))
        batcher.submit(row)
        assert batcher.admission_tier() == TIER_ACCEPT

    def test_low_priority_shed_at_shed_tier(self, workload):
        batcher, runtime = _idle_batcher(
            workload, shed_watermark=0.25, reject_watermark=0.9
        )
        normal = runtime.parse_request(workload.request(0))
        low = runtime.parse_request(
            {**workload.request(1), "priority": "low"}
        )
        for _ in range(6):  # depth 6/20 = 0.3 >= 0.25
            batcher.submit(normal)
        assert batcher.admission_tier() == TIER_SHED
        with pytest.raises(RejectedError, match="load shed"):
            batcher.submit(low)
        # Normal-priority traffic still flows at the shed tier.
        batcher.submit(normal)

    def test_reject_tier_sheds_everything(self, workload):
        batcher, runtime = _idle_batcher(
            workload, shed_watermark=0.2, reject_watermark=0.5
        )
        row = runtime.parse_request(workload.request(0))
        for _ in range(10):  # depth 10/20 = 0.5
            batcher.submit(row)
        assert batcher.admission_tier() == TIER_REJECT
        with pytest.raises(RejectedError, match="load shed"):
            batcher.submit(row)

    def test_bypass_admission_flows_at_reject_tier(self, workload):
        batcher, runtime = _idle_batcher(
            workload, shed_watermark=0.2, reject_watermark=0.5
        )
        row = runtime.parse_request(workload.request(0))
        for _ in range(10):
            batcher.submit(row)
        assert batcher.admission_tier() == TIER_REJECT
        batcher.submit(row, bypass_admission=True)  # probes keep flowing

    def test_p99_slo_breach_sheds_over_deadline_work(self, workload):
        with telemetry.Telemetry(sinks=[]) as tel:
            hist = tel.histogram("serving_request_latency_seconds")
            for _ in range(100):
                hist.observe(0.5)  # p99 ~ 500 ms
            batcher, runtime = _idle_batcher(
                workload, p99_slo_ms=100.0, admission_interval_s=0.0
            )
            row = runtime.parse_request(workload.request(0))
            assert batcher.admission_tier() == TIER_SHED
            with pytest.raises(RejectedError, match="p99"):
                # Deadline budget far under the observed p99: it would
                # expire in the queue — shed it now.
                batcher.submit(row, timeout_ms=10.0)
            batcher.submit(row, timeout_ms=5_000.0)  # enough budget

    def test_tier_transitions_are_journaled(self, workload):
        with telemetry.Telemetry(sinks=[]) as tel:
            batcher, runtime = _idle_batcher(
                workload, shed_watermark=0.25, reject_watermark=0.9
            )
            row = runtime.parse_request(workload.request(0))
            for _ in range(6):
                batcher.submit(row)
            with pytest.raises(RejectedError):
                batcher.submit(
                    runtime.parse_request(
                        {**workload.request(1), "priority": "low"}
                    )
                )
            snap = tel.snapshot()
            assert snap["counters"]["serving_tier_transitions_total"] >= 1
            assert snap["counters"]["serving_shed_total"] >= 1
            assert snap["counters"]["serving_shed_low_priority_total"] >= 1
            assert snap["gauges"]["serving_shed_tier"] == TIER_SHED
            assert batcher.stats()["tier"] == "shed"

    def test_priority_validation(self, workload):
        runtime = _runtime(workload)
        with pytest.raises(ValueError, match="priority"):
            runtime.parse_request(
                {**workload.request(0), "priority": "urgent"}
            )

    def test_watermark_validation(self, workload):
        runtime = _runtime(workload)
        with pytest.raises(ValueError):
            MicroBatcher(runtime, BatcherConfig(
                shed_watermark=0.9, reject_watermark=0.5
            ))


# ---------------------------------------------------------------------------
# Liveness / readiness split
# ---------------------------------------------------------------------------

def _get(port, route):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{route}", timeout=10
        ) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestHealthSplit:
    def test_livez_readyz_routes(self, workload):
        service = ScoringService(_runtime(workload))
        with service:
            server, _ = start_http_server(service, port=0)
            port = server.server_address[1]
            try:
                assert _get(port, "/livez")[0] == 200
                status, body = _get(port, "/readyz")
                assert (status, body["status"]) == (200, "ready")
                status, health = _get(port, "/healthz")
                assert health["status"] == "ok"
                assert health["model_version"] == 1

                # Mid-swap: alive but NOT ready.
                service.swapper.in_progress = True
                try:
                    assert _get(port, "/livez")[0] == 200
                    status, body = _get(port, "/readyz")
                    assert (status, body["status"]) == (503, "not_ready")
                    assert _get(port, "/healthz")[1]["status"] == \
                        "not_ready"
                finally:
                    service.swapper.in_progress = False

                # Warming runtime: same split.
                service.batcher.runtime.ready = False
                try:
                    status, body = _get(port, "/readyz")
                    assert (status, body["status"]) == (503, "not_ready")
                    assert _get(port, "/livez")[0] == 200
                finally:
                    service.batcher.runtime.ready = True
            finally:
                server.shutdown()
                server.server_close()

    def test_reload_endpoint_over_http(
        self, workload, model_dirs, tmp_path
    ):
        _v1, v2_dir = model_dirs
        service = ScoringService(_runtime(workload))
        with service:
            server, _ = start_http_server(service, port=0)
            port = server.server_address[1]
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/reload",
                    data=json.dumps({"model_dir": v2_dir}).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=60) as resp:
                    body = json.loads(resp.read())
                    assert resp.status == 200
                assert body["status"] == "swapped"
                assert body["version_after"] == 2
                assert _get(port, "/healthz")[1]["model_version"] == 2
            finally:
                server.shutdown()
                server.server_close()

    def test_exporter_readiness_split(self):
        from photon_ml_tpu.telemetry.exporter import MetricsExporter

        verdict = {"ready": False}
        with telemetry.Telemetry(sinks=[]) as tel:
            exporter = MetricsExporter(
                tel, port=0,
                readiness=lambda: (verdict["ready"], "warming up"),
            ).start()
            try:
                port = exporter.port
                # Liveness stays "ok" regardless (pre-split semantics).
                assert _get(port, "/healthz")[1]["status"] == "ok"
                assert _get(port, "/livez")[1]["status"] == "ok"
                status, body = _get(port, "/readyz")
                assert (status, body["status"]) == (503, "not_ready")
                assert body["reason"] == "warming up"
                verdict["ready"] = True
                assert _get(port, "/readyz")[0] == 200
            finally:
                exporter.close()


# ---------------------------------------------------------------------------
# Unverified legacy loads (satellite: io stores)
# ---------------------------------------------------------------------------

class TestUnverifiedLoads:
    def test_glm_without_sidecar_warns_and_counts(self, tmp_path):
        from photon_ml_tpu.data.index_map import IndexMap, feature_key
        from photon_ml_tpu.io.model_store import (
            load_glm_model, save_glm_model,
        )
        from photon_ml_tpu.models.glm import (
            Coefficients, GeneralizedLinearModel,
        )

        imap = IndexMap.build([feature_key(f"f{i}", "") for i in range(4)])
        glm = GeneralizedLinearModel(
            Coefficients(means=np.ones(4, np.float32)), "logistic"
        )
        path = str(tmp_path / "legacy.avro")
        save_glm_model(glm, imap, path)
        os.remove(path + ".meta.json")  # pre-fingerprint file
        with telemetry.Telemetry(sinks=[]) as tel:
            with pytest.warns(UserWarning, match="UNVERIFIED"):
                load_glm_model(path)
            snap = tel.snapshot()
            assert snap["counters"]["model_load_unverified_total"] == 1

    def test_game_dir_without_fingerprints_warns(
        self, tmp_path, workload
    ):
        from photon_ml_tpu.io.game_store import load_game_model

        directory = str(tmp_path / "legacy_game")
        save_game_model(workload.model, workload.index_maps, directory)
        # Strip the manifest fingerprints AND the GLM sidecars: the
        # pre-fingerprint on-disk layout.
        meta_path = os.path.join(directory, "metadata.json")
        with open(meta_path) as f:
            manifest = json.load(f)
        del manifest["fingerprints"]
        with open(meta_path, "w") as f:
            json.dump(manifest, f)
        for root, _dirs, files in os.walk(directory):
            for name in files:
                if name.endswith(".meta.json"):
                    os.remove(os.path.join(root, name))
        with telemetry.Telemetry(sinks=[]) as tel:
            with pytest.warns(UserWarning, match="UNVERIFIED"):
                load_game_model(directory)
            # One count per unverified coordinate (fixed + random).
            assert (
                tel.snapshot()["counters"]["model_load_unverified_total"]
                == 2
            )

    def test_verified_load_stays_silent(self, tmp_path, workload):
        import warnings as warnings_mod

        from photon_ml_tpu.io.game_store import load_game_model

        directory = str(tmp_path / "verified_game")
        save_game_model(workload.model, workload.index_maps, directory)
        with telemetry.Telemetry(sinks=[]) as tel:
            with warnings_mod.catch_warnings():
                warnings_mod.simplefilter("error")
                load_game_model(directory)
            assert (
                tel.snapshot()["counters"].get(
                    "model_load_unverified_total", 0
                ) == 0
            )


# ---------------------------------------------------------------------------
# Loadgen scenarios
# ---------------------------------------------------------------------------

class TestScenarios:
    def test_catalog_has_the_issue_scenarios(self):
        from photon_ml_tpu.serving import loadgen

        assert set(loadgen.SCENARIOS) >= {
            "diurnal", "skew_shift", "swap_under_load", "replica_kill",
        }

    def test_unwired_action_raises_up_front(self):
        from photon_ml_tpu.serving import loadgen

        with pytest.raises(ValueError, match="kill_replica"):
            loadgen.run_scenario(
                lambda row: None, lambda i, phase: {},
                loadgen.SCENARIOS["replica_kill"],
            )

    def test_scenario_runs_phases_and_fires_action(self, workload):
        from photon_ml_tpu.serving import loadgen

        service = ScoringService(_runtime(workload))
        fired = []
        scenario = loadgen.Scenario("mini", "test", [
            loadgen.ScenarioPhase("a", 0.3, rate_multiplier=1.0),
            loadgen.ScenarioPhase(
                "b", 0.3, action="poke", entity_pool=(0.5, 1.0)
            ),
        ])
        pools = []

        def make_request(i, phase):
            pools.append(phase.entity_pool)
            return workload.request(i)

        with service:
            report = loadgen.run_scenario(
                service.submit, make_request, scenario,
                base_rate_rps=60.0,
                actions={"poke": lambda: fired.append(1) or "ok"},
            )
        assert [name for name, _ in report.phases] == ["a", "b"]
        assert fired == [1]
        assert report.actions == {"poke": "ok"}
        assert report.errors == 0
        assert (0.5, 1.0) in pools
        snap = report.snapshot()
        assert snap["phases"]["a"]["completed"] > 0
        assert snap["latency_p99_ms"] is not None
