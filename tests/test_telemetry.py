"""Telemetry subsystem: spans, metrics registry, sinks, driver wiring.

Covers the PR-2 acceptance surface: span nesting (incl. across threads),
registry snapshot round-trip, JSONL/Chrome-trace output validity (every
event parses; the trace is a valid trace-event array), the one-branch
disabled path, the selfcheck entry point, PhotonLogger lifecycle, and
end-to-end driver runs producing events.jsonl + trace.json +
metrics.json with nested run/coordinate/solver spans.
"""

import json
import logging
import os
import threading
import time

import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry.__main__ import selfcheck, validate_outputs


def read_events(out_dir):
    path = os.path.join(out_dir, "events.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f]


def span_records(records):
    return [r for r in records if r.get("type") == "span"]


class TestSpans:
    def test_nesting_parent_links(self, tmp_path):
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            with tel.span("run"):
                with tel.span("outer", k=1):
                    with tel.span("inner"):
                        pass
                with tel.span("sibling"):
                    pass
        spans = {r["name"]: r for r in span_records(read_events(tmp_path))}
        assert spans["run"]["parent"] is None
        assert spans["outer"]["parent"] == spans["run"]["id"]
        assert spans["inner"]["parent"] == spans["outer"]["id"]
        assert spans["sibling"]["parent"] == spans["run"]["id"]
        assert spans["outer"]["attrs"] == {"k": 1}
        # Children close before parents; durations nest.
        assert spans["inner"]["dur"] <= spans["outer"]["dur"]
        assert spans["outer"]["ts"] >= spans["run"]["ts"]

    def test_set_attaches_mid_span_attrs(self, tmp_path):
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            with tel.span("solver") as sp_:
                sp_.set(iterations=12, converged=True)
        (rec,) = span_records(read_events(tmp_path))
        assert rec["attrs"] == {"iterations": 12, "converged": True}

    def test_events_carry_enclosing_span(self, tmp_path):
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            with tel.span("run") as run_span:
                tel.event("checkpoint.save", path="x")
                run_id = run_span.span_id
        records = read_events(tmp_path)
        (ev,) = [r for r in records if r.get("type") == "event"]
        assert ev["name"] == "checkpoint.save"
        assert ev["parent"] == run_id
        assert ev["attrs"]["path"] == "x"

    def test_threads_get_independent_stacks(self, tmp_path):
        """A span opened on another thread must not nest under the main
        thread's current span (each thread owns its stack), and
        concurrent emission must not corrupt the JSONL."""
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            with tel.span("run"):
                def worker(i):
                    for k in range(20):
                        with tel.span("chunk", worker=i, k=k):
                            pass

                threads = [
                    threading.Thread(target=worker, args=(i,))
                    for i in range(4)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
        records = read_events(tmp_path)  # every line parses
        chunks = [r for r in span_records(records) if r["name"] == "chunk"]
        assert len(chunks) == 80
        assert all(c["parent"] is None for c in chunks)
        # ids unique across threads
        ids = [c["id"] for c in chunks]
        assert len(set(ids)) == len(ids)

    def test_mismatched_exit_does_not_corrupt_stack(self, tmp_path):
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            with tel.span("run") as run_span:
                inner = tel.span("inner")
                inner.__enter__()
                # Caller error: exits the OUTER before the inner...
                run_span.__exit__(None, None, None)
                # ...later spans must still be recordable as roots.
                with tel.span("after"):
                    pass
        names = {r["name"] for r in span_records(read_events(tmp_path))}
        assert "after" in names

    def test_exception_recorded_on_span(self, tmp_path):
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            with pytest.raises(ValueError):
                with tel.span("boom"):
                    raise ValueError("induced")
        (rec,) = span_records(read_events(tmp_path))
        assert "ValueError" in rec["error"]


class TestMetricsRegistry:
    def test_counter_gauge_histogram_snapshot(self):
        reg = telemetry.MetricsRegistry()
        reg.counter("retries").inc()
        reg.counter("retries").inc(2)
        reg.gauge("gbps").set(3.5)
        for v in (1.0, 3.0, 2.0):
            reg.histogram("lat").observe(v)
        snap = reg.snapshot()
        assert snap["counters"] == {"retries": 3}
        assert snap["gauges"] == {"gbps": 3.5}
        h = snap["histograms"]["lat"]
        assert h["count"] == 3 and h["min"] == 1.0 and h["max"] == 3.0
        assert h["mean"] == pytest.approx(2.0) and h["last"] == 2.0

    def test_snapshot_json_round_trip(self, tmp_path):
        tel = telemetry.Telemetry(
            output_dir=str(tmp_path), sinks=[], enabled=True
        )
        tel.counter("c").inc(7)
        tel.gauge("g").set(1.5)
        tel.histogram("h").observe(0.25)
        path = tel.write_snapshot()
        loaded = json.load(open(path))
        live = tel.snapshot()
        for kind in ("counters", "gauges", "histograms"):
            assert loaded[kind] == live[kind]

    def test_threaded_counters_do_not_lose_increments(self):
        reg = telemetry.MetricsRegistry()

        def bump():
            for _ in range(1000):
                reg.counter("n").inc()

        threads = [threading.Thread(target=bump) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.snapshot()["counters"]["n"] == 8000

    def test_disabled_registry_returns_noop(self):
        reg = telemetry.MetricsRegistry(enabled=False)
        reg.counter("x").inc()
        reg.gauge("y").set(1)
        reg.histogram("z").observe(2.0)
        snap = reg.snapshot()
        assert snap == {"counters": {}, "gauges": {}, "histograms": {}}


class TestSinkOutputs:
    def test_selfcheck_passes(self):
        assert selfcheck() == 0

    def test_validate_outputs_catches_corruption(self, tmp_path):
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            with tel.span("run"):
                pass
            snap = tel.snapshot()
        assert validate_outputs(str(tmp_path), snap) == []
        with open(os.path.join(tmp_path, "trace.json"), "w") as f:
            f.write("{not json")
        assert any(
            "trace.json" in msg
            for msg in validate_outputs(str(tmp_path), snap)
        )

    def test_chrome_trace_is_valid_event_array(self, tmp_path):
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            with tel.span("run"):
                with tel.span("coordinate", coordinate="fixed"):
                    pass
                tel.event("marker")
            tel.counter("n_things").inc(3)
        trace = json.load(open(os.path.join(tmp_path, "trace.json")))
        assert isinstance(trace, list)
        by_ph = {}
        for ev in trace:
            assert {"name", "ph", "ts", "pid", "tid"} <= set(ev)
            by_ph.setdefault(ev["ph"], []).append(ev)
        assert all("dur" in ev for ev in by_ph["X"])
        # Counter sample rides the trace.
        assert any(
            ev["name"] == "n_things" and ev["args"]["value"] == 3
            for ev in by_ph.get("C", [])
        )
        # Microsecond timestamps: the span ts/dur must be finite floats.
        for ev in by_ph["X"]:
            assert ev["dur"] >= 0.0

    def test_device_arrays_never_materialized_in_attrs(self, tmp_path):
        """Attribute sanitization must not pull device arrays to host —
        a large jax array attribute records as a placeholder."""
        import jax.numpy as jnp

        big = jnp.zeros((4096,), jnp.float32)
        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            tel.event("e", arr=big)
        records = read_events(tmp_path)
        (ev,) = [r for r in records if r.get("type") == "event"]
        assert isinstance(ev["attrs"]["arr"], str)
        assert "4096" in ev["attrs"]["arr"]

    def test_logger_summary_sink_logs_through_photon_logger(self, tmp_path):
        from photon_ml_tpu.utils.logging import PhotonLogger

        with PhotonLogger(str(tmp_path / "log")) as logger:
            with telemetry.Telemetry(
                output_dir=str(tmp_path / "tel"), logger=logger
            ) as tel:
                with tel.span("run"):
                    pass
        text = open(tmp_path / "log" / "photon.log").read()
        assert "telemetry summary" in text


class TestDisabledPath:
    def test_disabled_hub_is_noop_and_writes_nothing(self, tmp_path):
        tel = telemetry.Telemetry(
            output_dir=str(tmp_path / "off"), enabled=False
        )
        with tel:
            with tel.span("run") as sp_:
                sp_.set(x=1)
                tel.event("e")
            tel.counter("c").inc()
        assert not os.path.exists(tmp_path / "off" / "events.jsonl")
        assert not os.path.exists(tmp_path / "off" / "trace.json")

    def test_disabled_span_is_shared_singleton(self):
        tel = telemetry.Telemetry(enabled=False, sinks=[])
        assert tel.span("a") is tel.span("b")

    def test_disabled_overhead_smoke(self):
        """The disabled path must stay branch-cheap: 100k span+event+metric
        calls well under a second (~µs each)."""
        tel = telemetry.Telemetry(enabled=False, sinks=[])
        t0 = time.perf_counter()
        for _ in range(100_000):
            with tel.span("s"):
                pass
            tel.event("e", k=1)
            tel.counter("c").inc()
        elapsed = time.perf_counter() - t0
        assert elapsed < 2.0, f"disabled path too slow: {elapsed:.3f}s"

    def test_current_defaults_to_disabled_null(self):
        assert telemetry.current() is telemetry.NULL or not (
            telemetry.current().active
        )

    def test_install_restore_nesting(self, tmp_path):
        before = telemetry.current()
        with telemetry.Telemetry(output_dir=str(tmp_path / "a")) as a:
            assert telemetry.current() is a
            with telemetry.Telemetry(output_dir=str(tmp_path / "b")) as b:
                assert telemetry.current() is b
            assert telemetry.current() is a
        assert telemetry.current() is before


class TestPhotonLoggerLifecycle:
    def test_close_detaches_handlers_and_unregisters(self, tmp_path):
        from photon_ml_tpu.utils.logging import PhotonLogger

        logger = PhotonLogger(str(tmp_path))
        inner = logger._logger
        name = logger._name
        assert len(inner.handlers) == 2  # console + file
        logger.info("hello")
        logger.close()
        assert inner.handlers == []
        assert name not in logging.Logger.manager.loggerDict
        logger.close()  # idempotent

    def test_repeated_instances_leak_no_handles(self, tmp_path):
        """100 context-managed loggers leave zero registered photon
        loggers and zero open handlers behind — the repeated-driver
        (hyperparameter search) shape that used to leak file handles."""
        from photon_ml_tpu.utils.logging import PhotonLogger

        before = {
            n for n in logging.Logger.manager.loggerDict
            if n.startswith("photon_ml_tpu.")
        }
        for i in range(100):
            with PhotonLogger(str(tmp_path / f"d{i}")) as logger:
                logger.info("run %d", i)
        after = {
            n for n in logging.Logger.manager.loggerDict
            if n.startswith("photon_ml_tpu.")
        }
        assert after == before

    def test_unique_names_across_instances(self, tmp_path):
        from photon_ml_tpu.utils.logging import PhotonLogger

        a = PhotonLogger(str(tmp_path / "a"))
        b = PhotonLogger(str(tmp_path / "b"))
        try:
            assert a._name != b._name
            assert a._logger is not b._logger
        finally:
            a.close()
            b.close()

    def test_exception_path_closes_logger(self, tmp_path):
        from photon_ml_tpu.utils.logging import PhotonLogger

        with pytest.raises(RuntimeError):
            with PhotonLogger(str(tmp_path)) as logger:
                raise RuntimeError("induced")
        assert logger.closed


class TestDriverTelemetry:
    @pytest.fixture
    def glm_files(self, tmp_path, rng):
        from photon_ml_tpu.data import libsvm

        n, d = 200, 30
        X = sp.random(n, d, density=0.2, random_state=3, format="csr")
        X.data[:] = 1.0
        y = np.where(rng.uniform(size=n) < 0.5, 1.0, -1.0)
        train = str(tmp_path / "t.libsvm")
        libsvm.write_libsvm(train, X, y)
        return train, d

    def test_glm_driver_produces_valid_telemetry(self, tmp_path, glm_files):
        from photon_ml_tpu.drivers import glm_driver

        train, d = glm_files
        out = str(tmp_path / "out")
        res = glm_driver.run([
            "--train-data", train, "--output-dir", out,
            "--task", "logistic", "--reg-type", "l2",
            "--reg-weights", "0.5,5.0", "--n-features", str(d),
        ])
        for fname in ("events.jsonl", "trace.json", "metrics.json"):
            assert os.path.exists(os.path.join(out, fname)), fname
        records = read_events(out)
        names = {r["name"] for r in span_records(records)}
        assert {"run", "read", "summarize", "train", "solver",
                "validate", "write"} <= names
        # solver spans nest under train under run
        spans = {r["id"]: r for r in span_records(records)}
        solver = [r for r in span_records(records) if r["name"] == "solver"]
        assert solver
        for s in solver:
            chain = []
            cur = s
            while cur["parent"] is not None:
                cur = spans[cur["parent"]]
                chain.append(cur["name"])
            assert chain == ["train", "run"]
            assert s["attrs"]["iterations"] > 0
        trace = json.load(open(os.path.join(out, "trace.json")))
        assert isinstance(trace, list) and any(
            e.get("ph") == "X" for e in trace
        )
        snap = json.load(open(os.path.join(out, "metrics.json")))
        assert snap["counters"]["solver_iterations"] > 0
        # Wall-clock satellite: per-λ solver walls in the result and real
        # (non-NaN) wall on the solve path.
        assert set(res["solver_wall_seconds"]) == {"0.5", "5.0"}
        assert all(w > 0 for w in res["solver_wall_seconds"].values())

    def test_glm_driver_telemetry_off_writes_nothing(
        self, tmp_path, glm_files
    ):
        from photon_ml_tpu.drivers import glm_driver

        train, d = glm_files
        out = str(tmp_path / "out_off")
        glm_driver.run([
            "--train-data", train, "--output-dir", out,
            "--task", "logistic", "--reg-weights", "0.5",
            "--n-features", str(d), "--telemetry", "off",
        ])
        assert not os.path.exists(os.path.join(out, "events.jsonl"))
        assert not os.path.exists(os.path.join(out, "trace.json"))
        # ...and the run still trains a model.
        assert any(
            f.startswith("model_lambda") for f in os.listdir(out)
        )

    def test_game_driver_produces_nested_coordinate_spans(self, tmp_path):
        from photon_ml_tpu.data.game_reader import write_game_avro
        from photon_ml_tpu.drivers import game_training_driver

        rng = np.random.default_rng(11)
        n = 200
        records = [
            {
                "uid": f"row{i}",
                "response": float(rng.integers(2)),
                "weight": None,
                "offset": None,
                "ids": {"userId": f"u{rng.integers(12)}"},
                "features": {
                    "global": [
                        {"name": f"g{j}", "term": "",
                         "value": float(rng.normal())}
                        for j in range(3)
                    ],
                    "userFeatures": [
                        {"name": "bias", "term": "", "value": 1.0}
                    ],
                },
            }
            for i in range(n)
        ]
        train = str(tmp_path / "game.avro")
        write_game_avro(train, records)
        config = {
            "task": "logistic",
            "iterations": 2,
            "coordinates": [
                {"name": "fixed", "type": "fixed",
                 "feature_shard": "global", "reg_type": "l2",
                 "reg_weight": 1.0, "max_iters": 5},
                {"name": "per_user", "type": "random",
                 "feature_shard": "userFeatures", "entity_key": "userId",
                 "reg_type": "l2", "reg_weight": 1.0, "max_iters": 5},
            ],
        }
        cfg = str(tmp_path / "cfg.json")
        with open(cfg, "w") as f:
            json.dump(config, f)
        out = str(tmp_path / "out")
        res = game_training_driver.run([
            "--train-data", train, "--config", cfg, "--output-dir", out,
        ])
        records_ = read_events(out)
        spans = {r["id"]: r for r in span_records(records_)}

        def ancestry(rec):
            chain = []
            while rec["parent"] is not None:
                rec = spans[rec["parent"]]
                chain.append(rec["name"])
            return chain

        solver = [
            r for r in span_records(records_) if r["name"] == "solver"
        ]
        # 2 CD iterations x 2 coordinates
        assert len(solver) == 4
        for s in solver:
            assert ancestry(s) == [
                "coordinate", "cd_iteration", "train", "run"
            ]
        coords = {
            r["attrs"]["coordinate"]
            for r in span_records(records_) if r["name"] == "coordinate"
        }
        assert coords == {"fixed", "per_user"}
        # CD history entries carry wall-clock attribution.
        assert all("wall_seconds" in h for h in res["history"])
        assert all(h["wall_seconds"] > 0 for h in res["history"])
        snap = json.load(open(os.path.join(out, "metrics.json")))
        assert snap["histograms"]["cd_iteration_seconds"]["count"] == 2
        assert snap["counters"]["checkpoint_saves"] == 2
        trace = json.load(open(os.path.join(out, "trace.json")))
        assert isinstance(trace, list) and len(trace) > 0


class TestPrefetchTelemetry:
    def test_prefetch_pass_feeds_gauges_and_counters(self, tmp_path):
        from photon_ml_tpu.data.prefetch import TransferStats, run_prefetched

        with telemetry.Telemetry(output_dir=str(tmp_path)) as tel:
            stats = TransferStats()
            consumed = []
            run_prefetched(
                n_items=5,
                get_item=lambda k: np.full(1024, k, np.float32),
                put=lambda host: host,
                consume=lambda k, dev: consumed.append(k),
                depth=2,
                stats=stats,
            )
            snap = tel.snapshot()
        assert consumed == list(range(5))
        assert snap["counters"]["h2d_chunks_total"] == 5
        assert snap["counters"]["h2d_bytes_total"] == 5 * 1024 * 4
        assert snap["counters"]["prefetch_passes"] == 1
        assert "h2d_gbps" in snap["gauges"]
        assert snap["gauges"]["prefetch_max_live"] <= 2
        # The pass event rode the JSONL sink.
        events = [
            r for r in read_events(tmp_path)
            if r.get("type") == "event" and r["name"] == "prefetch.pass"
        ]
        assert len(events) == 1
        assert events[0]["attrs"]["chunks"] == 5

    def test_prefetch_without_hub_costs_one_branch(self):
        """No installed hub: run_prefetched must not record anything (the
        NULL hub is disabled) and must still stream correctly."""
        from photon_ml_tpu.data.prefetch import TransferStats, run_prefetched

        stats = TransferStats()
        out = []
        run_prefetched(
            n_items=3,
            get_item=lambda k: np.zeros(8, np.float32),
            put=lambda h: h,
            consume=lambda k, d: out.append(k),
            stats=stats,
        )
        assert out == [0, 1, 2]
        assert telemetry.current().snapshot()["counters"] == {}
