"""Feature-dim (tensor-parallel) fixed-effect training (VERDICT item 6).

The bar: a (data × feature) mesh trains a wide synthetic GLM to the same
coefficients as the single-device solver.  Runs on the 8-virtual-CPU-device
mesh from conftest (the `local[*]` analogue — SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.data.dataset import make_glm_data
from photon_ml_tpu.optim.lbfgs import LBFGSConfig
from photon_ml_tpu.optim.problem import (
    GlmOptimizationConfig,
    GlmOptimizationProblem,
    OptimizerConfig,
)
from photon_ml_tpu.optim.regularization import RegularizationContext
from photon_ml_tpu.parallel.tensor import (
    dp_tp_mesh,
    shard_glm_data_dp_tp,
    tp_lbfgs_solve,
)


def _wide_problem(rng, n=600, d=500, density=0.05, task="logistic"):
    X = sp.random(
        n, d, density=density, random_state=7, format="csr", dtype=np.float32
    )
    w_true = (rng.normal(size=d) * (rng.uniform(size=d) < 0.2)).astype(
        np.float32
    )
    margin = np.asarray(X @ w_true).ravel()
    if task == "logistic":
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(
            np.float32
        )
    else:
        y = (margin + 0.1 * rng.normal(size=n)).astype(np.float32)
    return X, y


def _single_device_solution(X, y, task, lam, max_iters=80):
    problem = GlmOptimizationProblem(
        task,
        GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=max_iters),
            regularization=RegularizationContext.l2(),
        ),
    )
    res = problem.solve(make_glm_data(X, y), lam)
    return np.asarray(res.w), float(res.value)


class TestTensorParallel:
    @pytest.mark.parametrize("dp,tp", [(2, 4), (4, 2), (1, 8), (8, 1)])
    def test_sparse_parity_all_mesh_shapes(self, rng, dp, tp):
        """Every (dp, tp) factorization reproduces the single-device fit."""
        X, y = _wide_problem(rng)
        lam = 0.7
        w_ref, v_ref = _single_device_solution(X, y, "logistic", lam)

        mesh = dp_tp_mesh(dp, tp)
        feats, lab, wts, off, d = shard_glm_data_dp_tp(X, y, mesh)
        res = tp_lbfgs_solve(
            "logistic", feats, lab, wts, off, mesh, reg_weight=lam,
            config=LBFGSConfig(max_iters=80),
        )
        w = np.asarray(res.w)[:d]
        # Padded columns never see data and carry no regularization pull
        # away from 0 beyond l2*0.
        np.testing.assert_array_equal(np.asarray(res.w)[d:], 0.0)
        assert float(res.value) == pytest.approx(v_ref, rel=1e-5)
        np.testing.assert_allclose(w, w_ref, atol=2e-3)

    def test_dense_path(self, rng):
        X, y = _wide_problem(rng, n=300, d=200, task="squared")
        Xd = np.asarray(X.todense(), np.float32)
        lam = 1.3
        w_ref, v_ref = _single_device_solution(Xd, y, "squared", lam)
        mesh = dp_tp_mesh(2, 4)
        feats, lab, wts, off, d = shard_glm_data_dp_tp(Xd, y, mesh)
        res = tp_lbfgs_solve(
            "squared", feats, lab, wts, off, mesh, reg_weight=lam,
            config=LBFGSConfig(max_iters=80),
        )
        assert float(res.value) == pytest.approx(v_ref, rel=1e-5)
        np.testing.assert_allclose(np.asarray(res.w)[:d], w_ref, atol=2e-3)

    def test_weights_and_offsets(self, rng):
        """Weighted rows + nonzero offsets flow through the sharded path."""
        X, y = _wide_problem(rng, n=400, d=300)
        weights = rng.uniform(0.5, 2.0, size=400).astype(np.float32)
        offsets = rng.normal(size=400).astype(np.float32) * 0.3
        lam = 0.5
        problem = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(max_iters=60),
                regularization=RegularizationContext.l2(),
            ),
        )
        ref = problem.solve(
            make_glm_data(X, y, weights=weights, offsets=offsets), lam
        )
        mesh = dp_tp_mesh(2, 4)
        feats, lab, wts, off, d = shard_glm_data_dp_tp(
            X, y, mesh, weights=weights, offsets=offsets
        )
        res = tp_lbfgs_solve(
            "logistic", feats, lab, wts, off, mesh, reg_weight=lam,
            config=LBFGSConfig(max_iters=60),
        )
        assert float(res.value) == pytest.approx(float(ref.value), rel=1e-5)
        np.testing.assert_allclose(
            np.asarray(res.w)[:d], np.asarray(ref.w), atol=2e-3
        )

    def test_traced_reg_weight_no_recompile(self, rng):
        """reg_weight is a traced argument and the solver program is
        memoized: a λ sweep reuses ONE compiled program."""
        from photon_ml_tpu.parallel import tensor as tensor_mod

        X, y = _wide_problem(rng, n=200, d=150)
        mesh = dp_tp_mesh(2, 4)
        feats, lab, wts, off, d = shard_glm_data_dp_tp(X, y, mesh)
        cfg = LBFGSConfig(max_iters=30)
        factory_misses0 = tensor_mod._make_tp_solver.cache_info().misses
        r1 = tp_lbfgs_solve(
            "logistic", feats, lab, wts, off, mesh, reg_weight=0.1,
            config=cfg,
        )
        fn = tensor_mod._make_tp_solver(
            "logistic", mesh, cfg
        )  # same cached callable the solve used
        traces_after_first = fn._cache_size()
        r2 = tp_lbfgs_solve(
            "logistic", feats, lab, wts, off, mesh, reg_weight=10.0,
            config=cfg,
        )
        # One factory miss for this (task, mesh, config)...
        assert (
            tensor_mod._make_tp_solver.cache_info().misses
            == factory_misses0 + 1
        )
        # ...and the second λ added NO new trace to the jitted program.
        assert fn._cache_size() == traces_after_first == 1
        # Stronger regularization → smaller coefficients.
        assert np.linalg.norm(np.asarray(r2.w)) < np.linalg.norm(
            np.asarray(r1.w)
        )


class TestTensorParallelOwlqn:
    def test_l1_parity_and_sparsity(self, rng):
        """Sharded OWL-QN reproduces the single-device L1 fit, including the
        exact sparsity pattern (zeros land on the same coordinates)."""
        from photon_ml_tpu.optim.owlqn import OWLQNConfig
        from photon_ml_tpu.optim.regularization import RegularizationContext
        from photon_ml_tpu.parallel.tensor import tp_owlqn_solve

        # d deliberately NOT a multiple of tp: the padded-columns-stay-zero
        # assertion below must check a non-empty slice.
        X, y = _wide_problem(rng, n=500, d=397)
        lam = 2.0
        problem = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(max_iters=80),
                regularization=RegularizationContext.l1(),
            ),
        )
        ref = problem.solve(make_glm_data(X, y), lam)
        w_ref = np.asarray(ref.w)
        assert np.sum(w_ref == 0) > 100  # the L1 fit is genuinely sparse

        mesh = dp_tp_mesh(2, 4)
        feats, lab, wts, off, d = shard_glm_data_dp_tp(X, y, mesh)
        res = tp_owlqn_solve(
            "logistic", feats, lab, wts, off, mesh, l1_weight=lam,
            config=OWLQNConfig(max_iters=80),
        )
        w = np.asarray(res.w)[:d]
        np.testing.assert_array_equal(np.asarray(res.w)[d:], 0.0)
        assert float(res.value) == pytest.approx(float(ref.value), rel=1e-4)
        np.testing.assert_allclose(w, w_ref, atol=3e-3)
        # Sparsity pattern agreement (allow a few borderline coords).
        disagree = np.sum((w == 0) != (w_ref == 0))
        assert disagree <= max(2, int(0.01 * d))

    def test_elastic_net_with_mask(self, rng):
        """Elastic net + an intercept-exempt l1_mask on the sharded path."""
        from photon_ml_tpu.optim.owlqn import OWLQNConfig
        from photon_ml_tpu.optim.regularization import RegularizationContext
        from photon_ml_tpu.parallel.tensor import tp_owlqn_solve

        X, y = _wide_problem(rng, n=300, d=200)
        lam, alpha = 1.5, 0.5
        problem = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(max_iters=60),
                regularization=RegularizationContext.elastic_net(alpha),
            ),
        )
        import jax.numpy as jnp

        mask_ref = jnp.ones((200,), jnp.float32).at[0].set(0.0)
        ref = problem.solve(make_glm_data(X, y), lam, l1_mask=mask_ref)

        mesh = dp_tp_mesh(4, 2)
        feats, lab, wts, off, d = shard_glm_data_dp_tp(X, y, mesh)
        d_padded = feats.n_cols * 2
        mask = np.ones(d_padded, np.float32)
        mask[0] = 0.0
        res = tp_owlqn_solve(
            "logistic", feats, lab, wts, off, mesh,
            l1_weight=alpha * lam, l2_weight=(1 - alpha) * lam,
            config=OWLQNConfig(max_iters=60), l1_mask=mask,
        )
        assert float(res.value) == pytest.approx(float(ref.value), rel=1e-4)
        np.testing.assert_allclose(
            np.asarray(res.w)[:d], np.asarray(ref.w), atol=3e-3
        )


class TestTensorParallelTron:
    def test_tron_parity(self, rng):
        """Sharded trust-region Newton reproduces the single-device TRON."""
        from photon_ml_tpu.optim.problem import OptimizerType
        from photon_ml_tpu.optim.tron import TRONConfig
        from photon_ml_tpu.parallel.tensor import tp_tron_solve

        X, y = _wide_problem(rng, n=500, d=350)
        lam = 0.8
        problem = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(
                    optimizer=OptimizerType.TRON, max_iters=50
                ),
                regularization=RegularizationContext.l2(),
            ),
        )
        ref = problem.solve(make_glm_data(X, y), lam)
        mesh = dp_tp_mesh(2, 4)
        feats, lab, wts, off, d = shard_glm_data_dp_tp(X, y, mesh)
        res = tp_tron_solve(
            "logistic", feats, lab, wts, off, mesh, reg_weight=lam,
            config=TRONConfig(max_iters=50),
        )
        assert float(res.value) == pytest.approx(float(ref.value), rel=1e-5)
        np.testing.assert_allclose(
            np.asarray(res.w)[:d], np.asarray(ref.w), atol=2e-3
        )


class TestMeshVariancesFixedEffect:
    def test_distributed_game_fixed_variances_match(self, rng):
        """Row-sharded GAME fixed effects now compute variances; they must
        match the single-device path."""
        import scipy.sparse as sp

        from photon_ml_tpu.game.estimator import (
            FixedEffectCoordinateConfig,
            GameEstimator,
        )
        from photon_ml_tpu.parallel.distributed import data_mesh

        n = 320
        Xg = rng.normal(size=(n, 5)).astype(np.float32)
        y = (rng.uniform(size=n) <
             1 / (1 + np.exp(-Xg[:, 0]))).astype(np.float32)
        shards = {"global": sp.csr_matrix(Xg)}
        ids = {}
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=40),
            regularization=RegularizationContext.l2(),
            compute_variances=True,
        )
        configs = {"fixed": FixedEffectCoordinateConfig("global", opt, 0.6)}
        m_single, _ = GameEstimator("logistic", configs, 1).fit(
            shards, ids, y
        )
        m_dist, _ = GameEstimator(
            "logistic", configs, 1, mesh=data_mesh()
        ).fit(shards, ids, y)
        v1 = np.asarray(m_single["fixed"].model.coefficients.variances)
        v2 = np.asarray(m_dist["fixed"].model.coefficients.variances)
        assert v2 is not None
        np.testing.assert_allclose(v2, v1, rtol=1e-3)
