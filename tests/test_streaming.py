"""Out-of-core streaming trainer vs the resident solvers.

The contract (VERDICT round 2, item 1): a chunked dataset must train to the
SAME solution as the resident path — the streamed pass is the reference's
``treeAggregate`` full-data scan rebuilt as a double-buffered device_put
stream (SURVEY.md §3.1, §7 "Host→device ingest bandwidth").
"""

import os

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

os.environ.setdefault("PHOTON_PALLAS_INTERPRET", "1")

from photon_ml_tpu.data.dataset import make_glm_data
from photon_ml_tpu.data.streaming import (
    StreamingGlmData,
    make_streaming_glm_data,
    streaming_from_blocks,
)
from photon_ml_tpu.optim.lbfgs import LBFGSConfig, lbfgs_solve
from photon_ml_tpu.optim.objective import GlmObjective
from photon_ml_tpu.optim.problem import (
    GlmOptimizationConfig,
    GlmOptimizationProblem,
    OptimizerConfig,
    OptimizerType,
)
from photon_ml_tpu.optim.regularization import RegularizationContext
from photon_ml_tpu.optim.streaming import (
    StreamingObjective,
    streaming_lbfgs_solve,
    streaming_run_grid,
)
from photon_ml_tpu.ops import losses


def _logistic_problem(rng, n, d, density=0.01, seed=3):
    X = sp.random(n, d, density=density, random_state=seed, format="csr",
                  dtype=np.float32)
    X = sp.hstack(
        [sp.csr_matrix(np.ones((n, 1), np.float32)), X]
    ).tocsr()
    w_true = (rng.normal(size=d + 1) *
              (rng.uniform(size=d + 1) < 0.3)).astype(np.float32)
    logits = X @ w_true
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-logits))).astype(
        np.float32
    )
    return X, y


class TestStreamingObjective:
    @pytest.mark.parametrize("accumulate", ["f32", "kahan"])
    def test_value_and_grad_matches_resident(self, rng, accumulate):
        n, d = 900, 40
        X, y = _logistic_problem(rng, n, d - 1, density=0.1)
        stream = make_streaming_glm_data(
            X, y, chunk_rows=256, use_pallas=False
        )
        assert stream.n_chunks == 4  # last chunk row-padded
        sobj = StreamingObjective("logistic", stream, accumulate=accumulate)
        data = make_glm_data(X, y)
        obj = GlmObjective(losses.logistic)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v_s, g_s = sobj.value_and_grad(w, l2_weight=0.5)
        v_r, g_r = obj.value_and_grad(w, data, l2_weight=0.5)
        assert float(jnp.abs(v_s - v_r)) < 1e-3 * max(1.0, abs(float(v_r)))
        assert float(jnp.abs(g_s - g_r).max()) < 1e-3

    def test_dense_features(self, rng):
        n, d = 300, 12
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        stream = make_streaming_glm_data(X, y, chunk_rows=128)
        sobj = StreamingObjective("logistic", stream)
        obj = GlmObjective(losses.logistic)
        data = make_glm_data(X, y)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v_s, g_s = sobj.value_and_grad(w)
        v_r, g_r = obj.value_and_grad(w, data)
        np.testing.assert_allclose(float(v_s), float(v_r), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(g_s), np.asarray(g_r), atol=1e-4
        )

    def test_scores_match_resident(self, rng):
        n, d = 500, 30
        X, y = _logistic_problem(rng, n, d - 1, density=0.1)
        stream = make_streaming_glm_data(
            X, y, chunk_rows=200, use_pallas=False
        )
        sobj = StreamingObjective("logistic", stream)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        scores = sobj.scores(w)
        assert scores.shape == (n,)
        np.testing.assert_allclose(
            scores, np.asarray(X @ np.asarray(w)).ravel(), atol=1e-4
        )

    def test_kahan_beats_f32_on_adversarial_stream(self, rng):
        """Many chunks of alternating huge/tiny contributions: compensated
        accumulation must track the f64 oracle much more tightly."""
        n, d = 4096, 4
        X = np.zeros((n, d), np.float32)
        X[:, 0] = 1.0
        y = np.zeros(n, np.float32)
        # Weights spanning 7 orders of magnitude force f32 cancellation
        # across the 32-chunk stream.
        w_rows = np.where(
            np.arange(n) % 2 == 0, 1e7, 1.0
        ).astype(np.float32)
        sq = make_streaming_glm_data(
            X, y, weights=w_rows, chunk_rows=128
        )
        w = jnp.asarray(np.array([1e-3, 0, 0, 0], np.float32))
        v32, _ = StreamingObjective(
            "linear", sq, accumulate="f32"
        ).value_and_grad(w)
        vk, _ = StreamingObjective(
            "linear", sq, accumulate="kahan"
        ).value_and_grad(w)
        # f64 oracle on host
        margins = (X @ np.asarray(w, np.float64))
        oracle = float(np.sum(
            w_rows.astype(np.float64) * 0.5 * margins**2
        ))
        err32 = abs(float(v32) - oracle)
        errk = abs(float(vk) - oracle)
        assert errk <= err32
        assert errk <= 1e-6 * abs(oracle) + 1e-6


class TestStreamingLBFGS:
    def test_matches_resident_solver(self, rng):
        n, d = 1200, 50
        X, y = _logistic_problem(rng, n, d - 1, density=0.1)
        data = make_glm_data(X, y)
        obj = GlmObjective(losses.logistic)
        cfg = LBFGSConfig(max_iters=200, tolerance=1e-9)
        res_r = lbfgs_solve(
            lambda w: obj.value_and_grad(w, data, l2_weight=1.0),
            jnp.zeros(d, jnp.float32), cfg,
        )
        stream = make_streaming_glm_data(
            X, y, chunk_rows=400, use_pallas=False
        )
        sobj = StreamingObjective("logistic", stream)
        res_s = streaming_lbfgs_solve(
            lambda w: sobj.value_and_grad(w, 1.0),
            jnp.zeros(d, jnp.float32), cfg,
        )
        # Same optimum to optimizer tolerance (summation order differs; the
        # converged FLAG may differ by one stalled step — host f64 vs device
        # f32 Armijo arithmetic — so the contract is the solution itself).
        np.testing.assert_allclose(
            float(res_s.value), float(res_r.value), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(res_s.w), np.asarray(res_r.w), atol=5e-3
        )

    def test_single_chunk_mirrors_resident_trajectory(self, rng):
        """With ONE chunk the streamed solver runs the identical math; the
        per-iteration objective trace must match the resident solver
        closely, not just the endpoint."""
        n, d = 400, 20
        X, y = _logistic_problem(rng, n, d - 1, density=0.15)
        data = make_glm_data(X, y)
        obj = GlmObjective(losses.logistic)
        cfg = LBFGSConfig(max_iters=40, tolerance=1e-9)
        res_r = lbfgs_solve(
            lambda w: obj.value_and_grad(w, data, l2_weight=0.3),
            jnp.zeros(d, jnp.float32), cfg,
        )
        stream = make_streaming_glm_data(X, y, chunk_rows=n, use_pallas=False)
        sobj = StreamingObjective("logistic", stream)
        res_s = streaming_lbfgs_solve(
            lambda w: sobj.value_and_grad(w, 0.3),
            jnp.zeros(d, jnp.float32), cfg,
        )
        vr = np.asarray(res_r.values)
        vs = np.asarray(res_s.values)
        k = min(5, int(res_r.iterations), int(res_s.iterations))
        np.testing.assert_allclose(vs[: k + 1], vr[: k + 1], rtol=1e-4)


class TestStreamingPallasChunks:
    def test_pallas_chunks_match_coo_stream(self, rng):
        """Uniformized tiled layouts as chunk features: same objective as
        the COO chunk store (kernel parity through the streaming path)."""
        n, d = 700, 300
        X, y = _logistic_problem(rng, n, d - 1, density=0.05)
        s_coo = make_streaming_glm_data(
            X, y, chunk_rows=256, use_pallas=False
        )
        s_pal = make_streaming_glm_data(
            X, y, chunk_rows=256, use_pallas=True, depth_cap=16
        )
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v1, g1 = StreamingObjective("logistic", s_coo).value_and_grad(w)
        v2, g2 = StreamingObjective("logistic", s_pal).value_and_grad(w)
        np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(g1), np.asarray(g2), atol=1e-4
        )

    def test_sharded_pallas_chunks_match_coo_stream(self, rng):
        """Tiled Pallas layouts on SHARDED streams (VERDICT r3 #4): one
        per-shard layout each, uniformized across chunks × shards, stacked
        on the shard axis — the streamed-DP shard_map program must match
        the COO-layout stream bit-for-tolerance, offsets included."""
        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
        n_dev = mesh.devices.size
        n, d = 700, 300
        X, y = _logistic_problem(rng, n, d - 1, density=0.05)
        offs = rng.normal(size=n).astype(np.float32)
        s_coo = make_streaming_glm_data(
            X, y, chunk_rows=256, use_pallas=False, n_shards=n_dev
        )
        s_pal = make_streaming_glm_data(
            X, y, chunk_rows=256, use_pallas=True, n_shards=n_dev,
            depth_cap=16,
        )
        assert s_pal.n_shards == n_dev
        o_coo = StreamingObjective("logistic", s_coo, mesh=mesh)
        o_pal = StreamingObjective("logistic", s_pal, mesh=mesh)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v1, g1 = o_coo.value_and_grad(w, 0.5, offsets=offs)
        v2, g2 = o_pal.value_and_grad(w, 0.5, offsets=offs)
        np.testing.assert_allclose(float(v2), float(v1), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(g2), np.asarray(g1), atol=1e-3)
        v = jnp.asarray(rng.normal(size=d).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(o_pal.hvp(w, v, 0.5, offsets=offs)),
            np.asarray(o_coo.hvp(w, v, 0.5, offsets=offs)),
            atol=1e-3,
        )

    def test_dropped_host_coo_fails_loudly(self, rng):
        n, d = 300, 200
        X, y = _logistic_problem(rng, n, d - 1, density=0.05)
        s = make_streaming_glm_data(X, y, chunk_rows=128, use_pallas=True)
        with pytest.raises(RuntimeError, match="dropped"):
            s.chunks[0].features.host_coo.col_nnz()


class TestStreamingGrid:
    def test_grid_matches_resident_grid(self, rng):
        n, d = 800, 30
        X, y = _logistic_problem(rng, n, d - 1, density=0.1)
        problem = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(max_iters=150, tolerance=1e-9),
                regularization=RegularizationContext.l2(),
            ),
        )
        lams = [0.5, 2.0]
        data = make_glm_data(X, y)
        grid_r = problem.run_grid(data, lams)
        stream = make_streaming_glm_data(
            X, y, chunk_rows=256, use_pallas=False
        )
        grid_s = streaming_run_grid(problem, stream, lams)
        for (lam_r, model_r, _), (lam_s, model_s, _) in zip(grid_r, grid_s):
            assert lam_r == lam_s
            np.testing.assert_allclose(
                np.asarray(model_s.coefficients.means),
                np.asarray(model_r.coefficients.means),
                atol=5e-3,
            )

    def test_variances_match_resident(self, rng):
        n, d = 400, 15
        X, y = _logistic_problem(rng, n, d - 1, density=0.2)
        problem = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(max_iters=100, tolerance=1e-8),
                regularization=RegularizationContext.l2(),
                compute_variances=True,
            ),
        )
        data = make_glm_data(X, y)
        grid_r = problem.run_grid(data, [1.0])
        stream = make_streaming_glm_data(
            X, y, chunk_rows=128, use_pallas=False
        )
        grid_s = streaming_run_grid(problem, stream, [1.0])
        v_r = np.asarray(grid_r[0][1].coefficients.variances)
        v_s = np.asarray(grid_s[0][1].coefficients.variances)
        np.testing.assert_allclose(v_s, v_r, rtol=2e-2)

    def test_l1_grid_matches_resident(self, rng):
        """Streamed OWL-QN: L1 grid lands on the resident solution with
        the same sparsity pattern."""
        n, d = 700, 30
        X, y = _logistic_problem(rng, n, d - 1, density=0.15)
        problem = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(max_iters=200, tolerance=1e-9),
                regularization=RegularizationContext.l1(),
            ),
        )
        data = make_glm_data(X, y)
        grid_r = problem.run_grid(data, [2.0])
        stream = make_streaming_glm_data(
            X, y, chunk_rows=256, use_pallas=False
        )
        grid_s = streaming_run_grid(problem, stream, [2.0])
        w_r = np.asarray(grid_r[0][1].coefficients.means)
        w_s = np.asarray(grid_s[0][1].coefficients.means)
        np.testing.assert_allclose(w_s, w_r, atol=5e-3)
        # L1 must actually sparsify, identically on both paths.
        assert np.sum(w_r == 0.0) > d // 4
        np.testing.assert_array_equal(w_s == 0.0, w_r == 0.0)

    def test_tron_grid_matches_resident(self, rng):
        """Smooth TRON streams (VERDICT r3 #2: the last optimizer ×
        residency cell): the streamed grid lands on the resident TRON
        solution."""
        n, d = 800, 30
        X, y = _logistic_problem(rng, n, d - 1, density=0.1)
        problem = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(
                    optimizer=OptimizerType.TRON,
                    max_iters=100,
                    tolerance=1e-8,
                ),
                regularization=RegularizationContext.l2(),
            ),
        )
        lams = [0.5, 2.0]
        data = make_glm_data(X, y)
        grid_r = problem.run_grid(data, lams)
        stream = make_streaming_glm_data(
            X, y, chunk_rows=256, use_pallas=False
        )
        grid_s = streaming_run_grid(problem, stream, lams)
        for (lam_r, model_r, _), (lam_s, model_s, _) in zip(grid_r, grid_s):
            assert lam_r == lam_s
            np.testing.assert_allclose(
                np.asarray(model_s.coefficients.means),
                np.asarray(model_r.coefficients.means),
                atol=5e-3,
            )


class TestStreamingNormalization:
    def test_normalized_objective_matches_resident(self, rng):
        """NormalizationContext composes with the streamed objective the
        same way it does resident: value/grad/HVP parity under a
        standardization context (the reference applies normalization
        inside the optimizer against unscaled data — SURVEY.md §2)."""
        from photon_ml_tpu.data.normalization import (
            NormalizationContext,
            NormalizationType,
            build_normalization,
        )
        from photon_ml_tpu.data.stats import summarize

        n, d = 600, 20
        X, y = _logistic_problem(rng, n, d - 1, density=0.2)
        data = make_glm_data(X, y)
        norm = build_normalization(
            NormalizationType.STANDARDIZATION, summarize(data),
            intercept_index=0,
        )
        obj = GlmObjective(losses.logistic, norm)
        stream = make_streaming_glm_data(
            X, y, chunk_rows=200, use_pallas=False
        )
        sobj = StreamingObjective(obj, stream)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v_r, g_r = obj.value_and_grad(w, data, l2_weight=0.5)
        v_s, g_s = sobj.value_and_grad(w, 0.5)
        np.testing.assert_allclose(float(v_s), float(v_r), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(g_s), np.asarray(g_r),
                                   atol=1e-3)
        vv = jnp.asarray(rng.normal(size=d).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(sobj.hvp(w, vv, 0.5)),
            np.asarray(obj.hvp(w, vv, data, l2_weight=0.5)),
            atol=1e-3,
        )


class TestStreamingTRON:
    def test_hvp_matches_resident(self, rng):
        """One streamed HVP pass == the resident Hessian-vector product
        (the HessianVectorAggregator treeAggregate analogue)."""
        n, d = 900, 40
        X, y = _logistic_problem(rng, n, d - 1, density=0.1)
        data = make_glm_data(X, y)
        obj = GlmObjective(losses.logistic)
        stream = make_streaming_glm_data(
            X, y, chunk_rows=256, use_pallas=False
        )
        sobj = StreamingObjective("logistic", stream)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v = jnp.asarray(rng.normal(size=d).astype(np.float32))
        h_r = obj.hvp(w, v, data, l2_weight=0.7)
        h_s = sobj.hvp(w, v, l2_weight=0.7)
        np.testing.assert_allclose(
            np.asarray(h_s), np.asarray(h_r), atol=1e-3
        )
        # The kahan accumulator must carry through the HVP pass too (its
        # compensation pair changes the carry structure, not the result).
        h_k = StreamingObjective(
            "logistic", stream, accumulate="kahan"
        ).hvp(w, v, l2_weight=0.7)
        np.testing.assert_allclose(
            np.asarray(h_k), np.asarray(h_r), atol=1e-3
        )

    def test_single_chunk_mirrors_resident_trajectory(self, rng):
        """With ONE chunk the streamed trust-region solver runs identical
        math (same radius updates, same CG, same acceptance): the
        per-iteration objective trace must track the resident solver."""
        from photon_ml_tpu.optim.streaming import streaming_tron_solve
        from photon_ml_tpu.optim.tron import TRONConfig, tron_solve

        n, d = 400, 20
        X, y = _logistic_problem(rng, n, d - 1, density=0.15)
        data = make_glm_data(X, y)
        obj = GlmObjective(losses.logistic)
        cfg = TRONConfig(max_iters=40, tolerance=1e-9)
        res_r = tron_solve(
            lambda w: obj.value_and_grad(w, data, l2_weight=0.3),
            lambda w, v, aux: obj.hvp(w, v, data, l2_weight=0.3, d2w=aux),
            jnp.zeros(d, jnp.float32),
            cfg,
            d2_fn=lambda w: obj.d2_weights(w, data),
        )
        stream = make_streaming_glm_data(X, y, chunk_rows=n, use_pallas=False)
        sobj = StreamingObjective("logistic", stream)
        res_s = streaming_tron_solve(
            lambda w: sobj.value_and_grad(w, 0.3),
            lambda w, v: sobj.hvp(w, v, 0.3),
            jnp.zeros(d, jnp.float32),
            cfg,
        )
        vr = np.asarray(res_r.values)
        vs = np.asarray(res_s.values)
        k = min(5, int(res_r.iterations), int(res_s.iterations))
        np.testing.assert_allclose(vs[: k + 1], vr[: k + 1], rtol=1e-4)
        np.testing.assert_allclose(
            float(res_s.value), float(res_r.value), rtol=1e-5
        )

    def test_multi_chunk_matches_resident_solution(self, rng):
        from photon_ml_tpu.optim.streaming import streaming_tron_solve
        from photon_ml_tpu.optim.tron import TRONConfig, tron_solve

        n, d = 1200, 50
        X, y = _logistic_problem(rng, n, d - 1, density=0.1)
        data = make_glm_data(X, y)
        obj = GlmObjective(losses.logistic)
        cfg = TRONConfig(max_iters=100, tolerance=1e-9)
        res_r = tron_solve(
            lambda w: obj.value_and_grad(w, data, l2_weight=1.0),
            lambda w, v, aux: obj.hvp(w, v, data, l2_weight=1.0, d2w=aux),
            jnp.zeros(d, jnp.float32),
            cfg,
            d2_fn=lambda w: obj.d2_weights(w, data),
        )
        stream = make_streaming_glm_data(
            X, y, chunk_rows=400, use_pallas=False
        )
        sobj = StreamingObjective("logistic", stream)
        res_s = streaming_tron_solve(
            lambda w: sobj.value_and_grad(w, 1.0),
            lambda w, v: sobj.hvp(w, v, 1.0),
            jnp.zeros(d, jnp.float32),
            cfg,
        )
        np.testing.assert_allclose(
            float(res_s.value), float(res_r.value), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(res_s.w), np.asarray(res_r.w), atol=5e-3
        )

    def test_tron_l1_still_routes_to_owlqn(self, rng):
        """A TRON config carrying L1 routes to streamed OWL-QN (static
        routing parity with the resident problem.solve)."""
        n, d = 400, 20
        X, y = _logistic_problem(rng, n, d - 1, density=0.15)
        problem = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(
                    optimizer=OptimizerType.TRON,
                    max_iters=150,
                    tolerance=1e-9,
                ),
                regularization=RegularizationContext.elastic_net(0.5),
            ),
        )
        data = make_glm_data(X, y)
        grid_r = problem.run_grid(data, [1.0])
        stream = make_streaming_glm_data(
            X, y, chunk_rows=128, use_pallas=False
        )
        grid_s = streaming_run_grid(problem, stream, [1.0])
        w_r = np.asarray(grid_r[0][1].coefficients.means)
        w_s = np.asarray(grid_s[0][1].coefficients.means)
        np.testing.assert_allclose(w_s, w_r, atol=5e-3)
        np.testing.assert_array_equal(w_s == 0.0, w_r == 0.0)


class TestStreamingDataParallel:
    def test_sharded_stream_matches_single_device(self, rng):
        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
        n_dev = mesh.devices.size
        n, d = 960, 25
        X, y = _logistic_problem(rng, n, d - 1, density=0.1)
        stream1 = make_streaming_glm_data(
            X, y, chunk_rows=320, use_pallas=False
        )
        streamN = make_streaming_glm_data(
            X, y, chunk_rows=320, use_pallas=False, n_shards=n_dev
        )
        sobj1 = StreamingObjective("logistic", stream1)
        sobjN = StreamingObjective("logistic", streamN, mesh=mesh)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v1, g1 = sobj1.value_and_grad(w, 0.7)
        vN, gN = sobjN.value_and_grad(w, 0.7)
        np.testing.assert_allclose(float(vN), float(v1), rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(gN), np.asarray(g1), atol=1e-3
        )
        # HVP parity under the mesh too (streamed-DP TRON's inner pass).
        v = jnp.asarray(rng.normal(size=d).astype(np.float32))
        h1 = sobj1.hvp(w, v, 0.7)
        hN = sobjN.hvp(w, v, 0.7)
        np.testing.assert_allclose(
            np.asarray(hN), np.asarray(h1), atol=1e-3
        )

    def test_sharded_grid_fit(self, rng):
        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
        n, d = 640, 20
        X, y = _logistic_problem(rng, n, d - 1, density=0.15)
        problem = GlmOptimizationProblem(
            "logistic",
            GlmOptimizationConfig(
                optimizer=OptimizerConfig(max_iters=120, tolerance=1e-9),
                regularization=RegularizationContext.l2(),
            ),
        )
        data = make_glm_data(X, y)
        grid_r = problem.run_grid(data, [1.0])
        streamN = make_streaming_glm_data(
            X, y, chunk_rows=160, use_pallas=False,
            n_shards=mesh.devices.size,
        )
        grid_s = streaming_run_grid(problem, streamN, [1.0], mesh=mesh)
        np.testing.assert_allclose(
            np.asarray(grid_s[0][1].coefficients.means),
            np.asarray(grid_r[0][1].coefficients.means),
            atol=5e-3,
        )

    def test_sharded_row_offsets_match_single_device(self, rng):
        """Per-row CD offsets under the mesh (VERDICT r3 #3): each chunk's
        offset slice rides SHARDED next to the chunk, and value/grad/HVP/
        hessian-diagonal all match the single-device streamed pass."""
        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
        n_dev = mesh.devices.size
        n, d = 960, 25
        X, y = _logistic_problem(rng, n, d - 1, density=0.1)
        offs = rng.normal(size=n).astype(np.float32)
        stream1 = make_streaming_glm_data(
            X, y, chunk_rows=320, use_pallas=False
        )
        streamN = make_streaming_glm_data(
            X, y, chunk_rows=320, use_pallas=False, n_shards=n_dev
        )
        sobj1 = StreamingObjective("logistic", stream1)
        sobjN = StreamingObjective("logistic", streamN, mesh=mesh)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v1, g1 = sobj1.value_and_grad(w, 0.5, offsets=offs)
        vN, gN = sobjN.value_and_grad(w, 0.5, offsets=offs)
        np.testing.assert_allclose(float(vN), float(v1), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(gN), np.asarray(g1), atol=1e-3)
        v = jnp.asarray(rng.normal(size=d).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(sobjN.hvp(w, v, 0.5, offsets=offs)),
            np.asarray(sobj1.hvp(w, v, 0.5, offsets=offs)),
            atol=1e-3,
        )
        np.testing.assert_allclose(
            np.asarray(sobjN.hessian_diagonal(w, offsets=offs)),
            np.asarray(sobj1.hessian_diagonal(w, offsets=offs)),
            atol=1e-3,
        )

    def test_streamed_game_cd_on_mesh(self, rng):
        """BASELINE config 5's minimum viable shape: streaming AND
        multi-device AND GAME simultaneously — a mesh-sharded streamed
        fixed effect composed with a resident random effect in one
        coordinate descent, matching the single-device streamed run."""
        from photon_ml_tpu.game.coordinates import RandomEffectCoordinate
        from photon_ml_tpu.game.data import build_random_effect_dataset
        from photon_ml_tpu.game.descent import CoordinateDescent
        from photon_ml_tpu.game.streaming import (
            StreamingFixedEffectCoordinate,
        )

        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
        n_dev = mesh.devices.size
        n, d, n_users = 640, 16, 12
        X = sp.random(n, d, density=0.15, random_state=9, format="csr",
                      dtype=np.float32)
        users = np.array(
            [f"u{rng.integers(n_users)}" for _ in range(n)], dtype=object
        )
        margin = X @ rng.normal(size=d).astype(np.float32)
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(
            np.float32
        )
        bias = sp.csr_matrix(np.ones((n, 1), np.float32))
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=50, tolerance=1e-8),
            regularization=RegularizationContext.l2(),
        )

        def run_cd(fixed_coord):
            re = RandomEffectCoordinate(
                "per_user",
                build_random_effect_dataset(
                    users, bias, y, np.ones(n, np.float32)
                ),
                "logistic", opt, reg_weight=1.0, entity_key="userId",
            )
            return CoordinateDescent([fixed_coord, re]).run(
                jnp.zeros(n, jnp.float32), n_iterations=2
            )

        stream1 = make_streaming_glm_data(
            X, y, chunk_rows=160, use_pallas=False
        )
        streamN = make_streaming_glm_data(
            X, y, chunk_rows=160, use_pallas=False, n_shards=n_dev
        )
        single = run_cd(StreamingFixedEffectCoordinate(
            "fixed", stream1, "logistic", opt, reg_weight=0.5,
        ))
        meshed = run_cd(StreamingFixedEffectCoordinate(
            "fixed", streamN, "logistic", opt, reg_weight=0.5, mesh=mesh,
        ))
        np.testing.assert_allclose(
            np.asarray(meshed.states["fixed"]),
            np.asarray(single.states["fixed"]),
            atol=5e-3,
        )
        np.testing.assert_allclose(
            np.asarray(meshed.scores["fixed"]),
            np.asarray(single.scores["fixed"]),
            atol=5e-3,
        )
        # Downstream coordinate: trained against the streamed-DP scores,
        # so psum-order f32 drift compounds once more — slightly looser.
        for b_m, b_s in zip(
            meshed.states["per_user"], single.states["per_user"]
        ):
            np.testing.assert_allclose(
                np.asarray(b_m), np.asarray(b_s), atol=1e-2
            )

    def test_estimator_mesh_plus_streaming(self, rng):
        """GameEstimator accepts mesh + streaming_chunk_rows together now
        (the round-3 rejection at game/estimator.py:198 is lifted)."""
        from photon_ml_tpu.game.estimator import (
            FixedEffectCoordinateConfig,
            GameEstimator,
            RandomEffectCoordinateConfig,
        )

        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
        n, d, n_users = 512, 12, 10
        X = sp.random(n, d, density=0.2, random_state=3, format="csr",
                      dtype=np.float32)
        users = np.array(
            [f"u{rng.integers(n_users)}" for _ in range(n)], dtype=object
        )
        margin = X @ rng.normal(size=d).astype(np.float32)
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(
            np.float32
        )
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=40, tolerance=1e-7),
            regularization=RegularizationContext.l2(),
        )
        configs = {
            "fixed": FixedEffectCoordinateConfig(
                feature_shard="global", optimization=opt, reg_weight=0.5,
                streaming_chunk_rows=128,
            ),
            "per_user": RandomEffectCoordinateConfig(
                feature_shard="global", entity_key="userId",
                optimization=opt, reg_weight=1.0,
            ),
        }
        shards = {"global": X}
        ids = {"userId": users}

        fit_m = GameEstimator(
            "logistic", configs, n_iterations=2, mesh=mesh
        ).fit(shards, ids, y)
        fit_1 = GameEstimator(
            "logistic", configs, n_iterations=2
        ).fit(shards, ids, y)
        w_m = np.asarray(
            fit_m[0].models["fixed"].model.coefficients.means
        )
        w_1 = np.asarray(
            fit_1[0].models["fixed"].model.coefficients.means
        )
        np.testing.assert_allclose(w_m, w_1, atol=5e-3)


class TestStreamingMeshGuards:
    def test_one_device_mesh_rejected(self, rng):
        """Single-shard chunks carry no shard axis; the mesh path's x[0]
        unstack would strip a DATA axis and silently return wrong
        values/gradients — construction must refuse loudly instead."""
        mesh1 = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("data",))
        X, y = _logistic_problem(rng, 100, 10)
        stream = make_streaming_glm_data(
            X, y, chunk_rows=64, use_pallas=False
        )
        with pytest.raises(ValueError, match="no shard axis"):
            StreamingObjective("logistic", stream, mesh=mesh1)


class TestChunkStoreShapes:
    def test_uniform_chunk_shapes(self, rng):
        X, y = _logistic_problem(rng, 1000, 64)
        stream = make_streaming_glm_data(
            X, y, chunk_rows=300, use_pallas=False
        )
        assert stream.n_chunks == 4
        shapes = [
            [leaf.shape for leaf in jax.tree.leaves(c)]
            for c in stream.chunks
        ]
        assert all(s == shapes[0] for s in shapes)
        # weight padding: total weight equals real row count
        assert stream.weight_sum == pytest.approx(1000.0)
        assert stream.nbytes() > 0

    def test_from_blocks(self, rng):
        X, y = _logistic_problem(rng, 500, 32)
        blocks = [
            (X[i * 100:(i + 1) * 100], y[i * 100:(i + 1) * 100])
            for i in range(5)
        ]
        stream = streaming_from_blocks(
            blocks, n_features=X.shape[1], chunk_rows=150, use_pallas=False
        )
        assert stream.n_rows == 500
        sobj = StreamingObjective("logistic", stream)
        data = make_glm_data(X, y)
        obj = GlmObjective(losses.logistic)
        w = jnp.asarray(rng.normal(size=X.shape[1]).astype(np.float32))
        v_s, _ = sobj.value_and_grad(w)
        v_r, _ = obj.value_and_grad(w, data)
        np.testing.assert_allclose(float(v_s), float(v_r), rtol=1e-5)


class TestStreamingGameCoordinate:
    """StreamingFixedEffectCoordinate inside coordinate descent: same
    result as the resident fixed effect, composed with a random effect."""

    def _game_problem(self, rng, n=600, d=20, n_users=15):
        X = sp.random(n, d, density=0.15, random_state=7, format="csr",
                      dtype=np.float32)
        users = np.array(
            [f"u{rng.integers(n_users)}" for _ in range(n)], dtype=object
        )
        user_eff = {f"u{u}": rng.normal() for u in range(n_users)}
        w_true = rng.normal(size=d).astype(np.float32)
        margin = X @ w_true + np.array([user_eff[u] for u in users])
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(
            np.float32
        )
        return X, users, y

    def test_cd_matches_resident_fixed_effect(self, rng):
        import jax.numpy as jnp

        from photon_ml_tpu.game.coordinates import (
            FixedEffectCoordinate,
            RandomEffectCoordinate,
        )
        from photon_ml_tpu.game.data import (
            FixedEffectDataset,
            build_random_effect_dataset,
        )
        from photon_ml_tpu.game.descent import CoordinateDescent
        from photon_ml_tpu.game.streaming import (
            StreamingFixedEffectCoordinate,
        )
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        X, users, y = self._game_problem(rng)
        n, d = X.shape
        bias = sp.csr_matrix(np.ones((n, 1), np.float32))
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=50, tolerance=1e-8),
            regularization=RegularizationContext.l2(),
        )

        def run_cd(fixed_coord):
            re = RandomEffectCoordinate(
                "per_user",
                build_random_effect_dataset(
                    users, bias, y, np.ones(n, np.float32)
                ),
                "logistic", opt, reg_weight=1.0, entity_key="userId",
            )
            return CoordinateDescent([fixed_coord, re]).run(
                jnp.zeros(n, jnp.float32), n_iterations=2
            )

        resident = run_cd(FixedEffectCoordinate(
            "fixed",
            FixedEffectDataset(data=make_glm_data(X, y), n_global_rows=n),
            "logistic", opt, reg_weight=0.5,
        ))
        stream = make_streaming_glm_data(
            X, y, chunk_rows=200, use_pallas=False
        )
        streamed = run_cd(StreamingFixedEffectCoordinate(
            "fixed", stream, "logistic", opt, reg_weight=0.5,
        ))

        np.testing.assert_allclose(
            np.asarray(streamed.states["fixed"]),
            np.asarray(resident.states["fixed"]),
            atol=5e-3,
        )
        np.testing.assert_allclose(
            np.asarray(streamed.scores["fixed"]),
            np.asarray(resident.scores["fixed"]),
            atol=5e-3,
        )
        # The OTHER coordinate's solution must agree too (it trains
        # against the streamed coordinate's scores).
        for b_s, b_r in zip(
            streamed.states["per_user"], resident.states["per_user"]
        ):
            np.testing.assert_allclose(
                np.asarray(b_s), np.asarray(b_r), atol=5e-3
            )

    def test_finalize_variances_and_model(self, rng):
        import jax.numpy as jnp

        from photon_ml_tpu.game.streaming import (
            StreamingFixedEffectCoordinate,
        )
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        X, _, y = self._game_problem(rng, n=300, d=10)
        stream = make_streaming_glm_data(
            X, y, chunk_rows=128, use_pallas=False
        )
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=40, tolerance=1e-7),
            regularization=RegularizationContext.l2(),
            compute_variances=True,
        )
        coord = StreamingFixedEffectCoordinate(
            "fixed", stream, "logistic", opt, reg_weight=1.0,
        )
        offsets = jnp.zeros(stream.n_rows, jnp.float32)
        w = coord.train(offsets)
        model = coord.finalize(w, offsets=offsets)
        assert model.model.task == "logistic"
        v = np.asarray(model.model.coefficients.variances)
        assert v.shape == (10,) and np.all(v > 0)

    def test_nonzero_chunk_offsets_rejected(self, rng):
        from photon_ml_tpu.game.streaming import (
            StreamingFixedEffectCoordinate,
        )
        from photon_ml_tpu.optim.problem import GlmOptimizationConfig

        X, _, y = self._game_problem(rng, n=200, d=8)
        stream = make_streaming_glm_data(
            X, y, offsets=np.ones(X.shape[0], np.float32),
            chunk_rows=100, use_pallas=False,
        )
        with pytest.raises(ValueError, match="zero offsets"):
            StreamingFixedEffectCoordinate(
                "fixed", stream, "logistic", GlmOptimizationConfig(),
            )

    def test_streamed_game_l1_fixed_effect(self, rng):
        """L1 on the STREAMED GAME fixed effect inside coordinate descent:
        same solution and sparsity pattern as the resident coordinate
        (exercises OWL-QN's orthant-projected trials against per-chunk
        CD offsets and the l1 = l1_frac * reg_weight scaling)."""
        import jax.numpy as jnp

        from photon_ml_tpu.game.coordinates import (
            FixedEffectCoordinate,
            RandomEffectCoordinate,
        )
        from photon_ml_tpu.game.data import (
            FixedEffectDataset,
            build_random_effect_dataset,
        )
        from photon_ml_tpu.game.descent import CoordinateDescent
        from photon_ml_tpu.game.streaming import (
            StreamingFixedEffectCoordinate,
        )
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        X, users, y = self._game_problem(rng, n=500, d=30)
        n, d = X.shape
        bias = sp.csr_matrix(np.ones((n, 1), np.float32))
        l1_opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=60, tolerance=1e-8),
            regularization=RegularizationContext.l1(),
        )
        re_opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=30, tolerance=1e-7),
            regularization=RegularizationContext.l2(),
        )

        def run_cd(fixed_coord):
            re = RandomEffectCoordinate(
                "per_user",
                build_random_effect_dataset(
                    users, bias, y, np.ones(n, np.float32)
                ),
                "logistic", re_opt, reg_weight=1.0, entity_key="userId",
            )
            return CoordinateDescent([fixed_coord, re]).run(
                jnp.zeros(n, jnp.float32), n_iterations=2
            )

        resident = run_cd(FixedEffectCoordinate(
            "fixed",
            FixedEffectDataset(data=make_glm_data(X, y), n_global_rows=n),
            "logistic", l1_opt, reg_weight=2.0,
        ))
        stream = make_streaming_glm_data(
            X, y, chunk_rows=180, use_pallas=False
        )
        streamed = run_cd(StreamingFixedEffectCoordinate(
            "fixed", stream, "logistic", l1_opt, reg_weight=2.0,
        ))
        w_r = np.asarray(resident.states["fixed"])
        w_s = np.asarray(streamed.states["fixed"])
        assert np.sum(w_r == 0.0) > 0  # the penalty actually pruned
        np.testing.assert_allclose(w_s, w_r, atol=5e-3)
        np.testing.assert_array_equal(w_s == 0.0, w_r == 0.0)

    def test_streamed_game_tron_fixed_effect(self, rng):
        """Smooth TRON on the STREAMED GAME fixed effect: exercises the
        streamed HVP against per-chunk CD offsets (the d2 weights depend
        on the other coordinates' scores through the margin)."""
        import jax.numpy as jnp

        from photon_ml_tpu.game.coordinates import (
            FixedEffectCoordinate,
            RandomEffectCoordinate,
        )
        from photon_ml_tpu.game.data import (
            FixedEffectDataset,
            build_random_effect_dataset,
        )
        from photon_ml_tpu.game.descent import CoordinateDescent
        from photon_ml_tpu.game.streaming import (
            StreamingFixedEffectCoordinate,
        )
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        X, users, y = self._game_problem(rng, n=500, d=20)
        n, d = X.shape
        bias = sp.csr_matrix(np.ones((n, 1), np.float32))
        tron_opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(
                optimizer=OptimizerType.TRON, max_iters=50, tolerance=1e-8
            ),
            regularization=RegularizationContext.l2(),
        )
        re_opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=30, tolerance=1e-7),
            regularization=RegularizationContext.l2(),
        )

        def run_cd(fixed_coord):
            re = RandomEffectCoordinate(
                "per_user",
                build_random_effect_dataset(
                    users, bias, y, np.ones(n, np.float32)
                ),
                "logistic", re_opt, reg_weight=1.0, entity_key="userId",
            )
            return CoordinateDescent([fixed_coord, re]).run(
                jnp.zeros(n, jnp.float32), n_iterations=2
            )

        resident = run_cd(FixedEffectCoordinate(
            "fixed",
            FixedEffectDataset(data=make_glm_data(X, y), n_global_rows=n),
            "logistic", tron_opt, reg_weight=0.5,
        ))
        stream = make_streaming_glm_data(
            X, y, chunk_rows=180, use_pallas=False
        )
        streamed = run_cd(StreamingFixedEffectCoordinate(
            "fixed", stream, "logistic", tron_opt, reg_weight=0.5,
        ))
        np.testing.assert_allclose(
            np.asarray(streamed.states["fixed"]),
            np.asarray(resident.states["fixed"]),
            atol=5e-3,
        )


class TestDoubleBufferStructure:
    """VERDICT r3 weak #3: the overlap claim, pinned by structure instead
    of arithmetic.  Rewritten for the windowed-async pipeline: the
    consumer dispatches chunk k's program and blocks only on the carry a
    ``prefetch_depth``-deep WINDOW behind, so (a) the number of blocking
    syncs per pass is ``n_chunks - window + 1`` (each carry synced once,
    plus the drain), (b) transfer k+1 is never gated on compute k's sync
    (the pin is a handshake: every sync WAITS for the next transfer to
    have been dispatched — deadlock-free exactly when the transfer
    thread is not gated on that sync), and (c) HBM liveness stays
    bounded by ``2·prefetch_depth`` chunks (``prefetch_depth``
    transferred-not-consumed by permit accounting + the window of
    dispatched-not-synced programs pinning their buffers)."""

    def test_transfer_overlaps_compute_and_hbm_bound(
        self, rng, monkeypatch
    ):
        import gc
        import threading
        import weakref

        n, d = 600, 10
        X, y = _logistic_problem(rng, n, d - 1, density=0.2)
        stream = make_streaming_glm_data(
            X, y, chunk_rows=100, use_pallas=False
        )
        assert stream.n_chunks == 6
        n_chunks = stream.n_chunks
        depth = 2
        sobj = StreamingObjective("logistic", stream, prefetch_depth=depth)

        put_done = [threading.Event() for _ in range(n_chunks)]
        live_refs = []
        hbm_violations = []
        orig_put = sobj._put
        put_idx = [0]

        def tracked_put(chunk):
            k = put_idx[0]
            put_idx[0] += 1
            dev = orig_put(chunk)
            live_refs.append(weakref.ref(jax.tree.leaves(dev)[0]))
            # HBM-residency bound: at the moment chunk k lands, the
            # permit-held transfers (≤ depth) plus the window of
            # dispatched-but-unsynced programs (≤ depth) may pin chunk
            # buffers.  (Recorded, not asserted: this runs on the
            # transfer thread.)
            gc.collect()
            alive = sum(1 for r in live_refs if r() is not None)
            if alive > 2 * depth:
                hbm_violations.append((k, alive))
            put_done[k].set()
            return dev

        monkeypatch.setattr(sobj, "_put", tracked_put)

        orig_block = jax.block_until_ready
        block_count = [0]

        def tracked_block(x):
            block_count[0] += 1
            # Syncs run a window of ``depth`` carries behind dispatch, so
            # by the time ANY sync runs, the transfer thread must have
            # been able to dispatch at least the next chunk without it —
            # if the pipeline ever serialized transfer k+1 behind
            # compute k's sync, this wait could only time out.
            k_ahead = min(
                block_count[0] - 1 + depth + 1, n_chunks - 1
            )
            assert put_done[k_ahead].wait(timeout=60.0), (
                f"transfer {k_ahead} was not dispatched while an "
                f"earlier compute sync was still pending — no overlap"
            )
            return orig_block(x)

        monkeypatch.setattr(jax, "block_until_ready", tracked_block)

        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v, g = sobj.value_and_grad(w, 0.3)
        monkeypatch.undo()
        assert np.isfinite(float(v))
        assert put_idx[0] == n_chunks
        # Windowed backpressure: one blocking sync per chunk beyond the
        # window, plus the end-of-pass drain.
        assert block_count[0] == n_chunks - depth + 1
        assert not hbm_violations, (
            f"chunks alive in device memory beyond the pipeline bound: "
            f"{hbm_violations}"
        )
        assert sobj.transfer_stats.max_live <= depth

    def test_depth_one_syncs_every_chunk(self, rng, monkeypatch):
        """prefetch_depth=1 is the fully-serial measurement baseline:
        window 0, one blocking sync per chunk."""
        n, d = 400, 8
        X, y = _logistic_problem(rng, n, d - 1, density=0.2)
        stream = make_streaming_glm_data(
            X, y, chunk_rows=100, use_pallas=False
        )
        sobj = StreamingObjective("logistic", stream, prefetch_depth=1)
        orig_block = jax.block_until_ready
        count = [0]

        def tracked(x):
            count[0] += 1
            return orig_block(x)

        monkeypatch.setattr(jax, "block_until_ready", tracked)
        sobj.value_and_grad(jnp.zeros(d, jnp.float32))
        monkeypatch.undo()
        assert count[0] == stream.n_chunks


class TestDiskBackedStore:
    """storage_dir: the chunk store spills to .npy and trains from
    memmap leaves — the MEMORY_AND_DISK rung of the residency ladder
    (host RAM stops bounding trainable size, disk does).  Parity is
    bit-for-bit: the spill is a pure re-residency of the same arrays."""

    @staticmethod
    def _data(seed=0, n=700, d=12):
        rng = np.random.default_rng(seed)
        X = sp.random(n, d, density=0.4, random_state=seed, format="csr",
                      dtype=np.float32)
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        return X, y

    @pytest.mark.parametrize("mode,n_shards", [
        ("coo", 1), ("coo", 4), ("pallas", 1), ("dense", 1), ("dense", 4),
    ])
    def test_bit_identical_to_ram_store(self, tmp_path, mode, n_shards):
        X, y = self._data()
        if mode == "dense":
            X = np.asarray(X.toarray(), np.float32)
        kw = dict(
            chunk_rows=256, use_pallas=(mode == "pallas"),
            n_shards=n_shards,
        )
        ram = make_streaming_glm_data(X, y, **kw)
        disk = make_streaming_glm_data(
            X, y, storage_dir=str(tmp_path / "store"), **kw
        )
        assert disk.n_chunks == ram.n_chunks
        for cr, cd in zip(ram.chunks, disk.chunks):
            leaves_r = jax.tree_util.tree_leaves(cr)
            leaves_d = jax.tree_util.tree_leaves(cd)
            assert any(
                isinstance(l, np.memmap) for l in leaves_d
            ), "no leaf is disk-backed"
            for a, b in zip(leaves_r, leaves_d):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_training_from_disk_matches_ram(self, tmp_path):
        X, y = self._data(seed=3)
        ram = make_streaming_glm_data(X, y, chunk_rows=256, use_pallas=False)
        disk = make_streaming_glm_data(
            X, y, chunk_rows=256, use_pallas=False,
            storage_dir=str(tmp_path / "store"),
        )
        cfg = LBFGSConfig(max_iters=30, tolerance=1e-9)
        w_ram = streaming_lbfgs_solve(
            lambda w: StreamingObjective("logistic", ram).value_and_grad(
                w, 1.0
            ),
            jnp.zeros(X.shape[1], jnp.float32), cfg,
        ).w
        w_disk = streaming_lbfgs_solve(
            lambda w: StreamingObjective("logistic", disk).value_and_grad(
                w, 1.0
            ),
            jnp.zeros(X.shape[1], jnp.float32), cfg,
        ).w
        np.testing.assert_array_equal(np.asarray(w_ram), np.asarray(w_disk))

    def test_spilled_random_effect_dataset_trains(self, tmp_path):
        from photon_ml_tpu.data.streaming import spill_random_effect_dataset
        from photon_ml_tpu.game.data import build_random_effect_dataset
        from photon_ml_tpu.game.ooc_random import (
            OutOfCoreRandomEffectCoordinate,
        )
        from photon_ml_tpu.optim.problem import (
            GlmOptimizationConfig, OptimizerConfig,
        )
        from photon_ml_tpu.optim.regularization import RegularizationContext

        rng = np.random.default_rng(5)
        n_ent, rows, d = 40, 4, 5
        n = n_ent * rows
        users = np.repeat([f"u{i}" for i in range(n_ent)], rows)
        Xe = sp.csr_matrix(rng.normal(size=(n, d)).astype(np.float32))
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        w = np.ones(n, np.float32)
        host = build_random_effect_dataset(users, Xe, y, w, device=False)
        spilled = spill_random_effect_dataset(
            build_random_effect_dataset(users, Xe, y, w, device=False),
            str(tmp_path / "re"),
        )
        assert any(
            isinstance(l, np.memmap)
            for b in spilled.blocks
            for l in jax.tree_util.tree_leaves(b)
        )
        opt = GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=20, tolerance=1e-7),
            regularization=RegularizationContext.l2(),
        )
        offsets = jnp.zeros(n, jnp.float32)
        st_ram = OutOfCoreRandomEffectCoordinate(
            "re", host, "logistic", opt, reg_weight=0.5,
            device_budget_bytes=20_000,
        ).train(offsets)
        st_disk = OutOfCoreRandomEffectCoordinate(
            "re", spilled, "logistic", opt, reg_weight=0.5,
            device_budget_bytes=20_000,
        ).train(offsets)
        for a, b in zip(st_ram, st_disk):
            np.testing.assert_array_equal(a, b)

    def test_nonempty_storage_dir_refused(self, tmp_path):
        X, y = self._data()
        store = tmp_path / "store"
        store.mkdir()
        (store / "stale.npy").write_bytes(b"x")
        with pytest.raises(ValueError, match="not empty"):
            make_streaming_glm_data(
                X, y, chunk_rows=256, use_pallas=False,
                storage_dir=str(store),
            )


class TestPipelineParity:
    """ISSUE 5 parity pins for the windowed-async pipeline: depth>1
    (windowed carry sync + donated accumulators) must be BIT-IDENTICAL
    on f32 to the ``prefetch_depth=1`` serial baseline for value/grad,
    HVP and scores (float-close on kahan — same order, but donation-free
    vs donated buffers may round identically anyway); chunk fusion must
    preserve the accumulation order including the ragged tail; batched
    line-search trials must evaluate the exact single-trial graph; a
    failed pass must leave the objective reusable (no use-after-donate);
    and the stall counters must stay monotone across passes."""

    @staticmethod
    def _stream4(rng, n=640, d=24, chunk_rows=160):
        X, y = _logistic_problem(rng, n, d - 1, density=0.15)
        stream = make_streaming_glm_data(
            X, y, chunk_rows=chunk_rows, use_pallas=False
        )
        return X, y, stream

    def test_async_window_bit_identical_to_sync_f32(self, rng):
        """The check.sh --fast parity smoke: tiny 4-chunk store,
        async (depth 3) == sync (depth 1), bitwise."""
        _, _, stream = self._stream4(rng)
        assert stream.n_chunks == 4
        w = jnp.asarray(rng.normal(size=stream.n_features), jnp.float32)
        v = jnp.asarray(rng.normal(size=stream.n_features), jnp.float32)
        sync = StreamingObjective("logistic", stream, prefetch_depth=1)
        asyn = StreamingObjective("logistic", stream, prefetch_depth=3)
        vs, gs = sync.value_and_grad(w, 0.5)
        va, ga = asyn.value_and_grad(w, 0.5)
        np.testing.assert_array_equal(np.asarray(vs), np.asarray(va))
        np.testing.assert_array_equal(np.asarray(gs), np.asarray(ga))
        np.testing.assert_array_equal(
            np.asarray(sync.hvp(w, v, 0.5)), np.asarray(asyn.hvp(w, v, 0.5))
        )
        np.testing.assert_array_equal(sync.scores(w), asyn.scores(w))
        np.testing.assert_array_equal(
            np.asarray(sync.hessian_diagonal(w)),
            np.asarray(asyn.hessian_diagonal(w)),
        )

    def test_async_window_kahan_close_to_sync(self, rng):
        _, _, stream = self._stream4(rng)
        w = jnp.asarray(rng.normal(size=stream.n_features), jnp.float32)
        sync = StreamingObjective(
            "logistic", stream, prefetch_depth=1, accumulate="kahan"
        )
        asyn = StreamingObjective(
            "logistic", stream, prefetch_depth=3, accumulate="kahan"
        )
        vs, gs = sync.value_and_grad(w, 0.5)
        va, ga = asyn.value_and_grad(w, 0.5)
        np.testing.assert_allclose(float(vs), float(va), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(gs), np.asarray(ga), rtol=1e-6, atol=1e-7
        )

    @pytest.mark.parametrize("fuse", [2, 3, 99])
    def test_fused_chunks_match_unfused(self, rng, fuse):
        """chunk_fuse folds chunks into one lax.scan dispatch; the
        accumulation order is unchanged, including the RAGGED TAIL group
        (4 chunks at fuse=3 → groups of 3 and 1; fuse=99 → one group)."""
        _, _, stream = self._stream4(rng)
        w = jnp.asarray(rng.normal(size=stream.n_features), jnp.float32)
        v = jnp.asarray(rng.normal(size=stream.n_features), jnp.float32)
        ref = StreamingObjective("logistic", stream, chunk_fuse=1)
        fused = StreamingObjective("logistic", stream, chunk_fuse=fuse)
        if fuse == 3:
            assert [len(g) for g in fused._groups] == [3, 1]
        vr, gr = ref.value_and_grad(w, 0.5)
        vf, gf = fused.value_and_grad(w, 0.5)
        np.testing.assert_allclose(float(vr), float(vf), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gf), rtol=1e-6, atol=1e-7
        )
        np.testing.assert_allclose(
            np.asarray(ref.hvp(w, v, 0.5)),
            np.asarray(fused.hvp(w, v, 0.5)),
            rtol=1e-6, atol=1e-7,
        )
        np.testing.assert_allclose(
            ref.scores(w), fused.scores(w), rtol=1e-6, atol=1e-7
        )

    def test_fused_solve_matches_unfused(self, rng):
        _, _, stream = self._stream4(rng)
        cfg = LBFGSConfig(max_iters=30, tolerance=1e-8)
        w0 = jnp.zeros(stream.n_features, jnp.float32)
        ref = StreamingObjective("logistic", stream, chunk_fuse=1)
        fused = StreamingObjective("logistic", stream, chunk_fuse=3)
        res_r = streaming_lbfgs_solve(
            lambda w: ref.value_and_grad(w, 0.5), w0, cfg
        )
        res_f = streaming_lbfgs_solve(
            lambda w: fused.value_and_grad(w, 0.5), w0, cfg
        )
        np.testing.assert_allclose(
            np.asarray(res_r.w), np.asarray(res_f.w), atol=1e-4
        )

    def test_fuse_rejects_mesh_and_invalid(self, rng):
        _, _, stream = self._stream4(rng)
        with pytest.raises(ValueError, match="chunk_fuse"):
            StreamingObjective("logistic", stream, chunk_fuse=0)
        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
        with pytest.raises(ValueError, match="single-device"):
            StreamingObjective(
                "logistic", stream, mesh=mesh, chunk_fuse=2
            )

    def test_batched_vg_rows_match_single(self, rng):
        """value_and_grad_batch unrolls the exact single-w graph per
        candidate — each row must equal the separate pass BITWISE (the
        property the batched line search's trajectory pin rests on)."""
        _, _, stream = self._stream4(rng)
        sobj = StreamingObjective("logistic", stream)
        ws = jnp.asarray(
            rng.normal(size=(3, stream.n_features)).astype(np.float32)
        )
        vb, gb = sobj.value_and_grad_batch(ws, 0.7)
        for i in range(3):
            vi, gi = sobj.value_and_grad(ws[i], 0.7)
            np.testing.assert_array_equal(
                np.asarray(vb[i]), np.asarray(vi)
            )
            np.testing.assert_array_equal(
                np.asarray(gb[i]), np.asarray(gi)
            )

    def test_batched_linesearch_same_trajectory(self, rng):
        """The speculative batched Wolfe search examines the identical
        candidate sequence, so iteration count AND solution must match
        the unbatched solver."""
        _, _, stream = self._stream4(rng)
        cfg = LBFGSConfig(max_iters=40, tolerance=1e-8)
        w0 = jnp.zeros(stream.n_features, jnp.float32)
        sobj = StreamingObjective("logistic", stream)
        res_seq = streaming_lbfgs_solve(
            lambda w: sobj.value_and_grad(w, 0.3), w0, cfg
        )
        passes_before = sobj.transfer_stats.passes
        res_bat = streaming_lbfgs_solve(
            lambda w: sobj.value_and_grad(w, 0.3), w0, cfg,
            value_and_grad_batch=lambda ws: sobj.value_and_grad_batch(
                ws, 0.3
            ),
        )
        passes_batched = sobj.transfer_stats.passes - passes_before
        assert int(res_bat.iterations) == int(res_seq.iterations)
        np.testing.assert_array_equal(
            np.asarray(res_bat.w), np.asarray(res_seq.w)
        )
        # The batched solver must not stream MORE passes than the
        # sequential one (one pass per cache miss, each covering the
        # trial plus its successors).
        assert passes_batched <= passes_before

    def test_batched_linesearch_owlqn_same_trajectory(self, rng):
        from photon_ml_tpu.optim.owlqn import OWLQNConfig
        from photon_ml_tpu.optim.streaming import streaming_owlqn_solve

        _, _, stream = self._stream4(rng)
        cfg = OWLQNConfig(max_iters=30, tolerance=1e-8)
        w0 = jnp.zeros(stream.n_features, jnp.float32)
        sobj = StreamingObjective("logistic", stream)
        res_seq = streaming_owlqn_solve(
            lambda w: sobj.value_and_grad(w, 0.1), w0, 0.05, cfg
        )
        res_bat = streaming_owlqn_solve(
            lambda w: sobj.value_and_grad(w, 0.1), w0, 0.05, cfg,
            value_and_grad_batch=lambda ws: sobj.value_and_grad_batch(
                ws, 0.1
            ),
        )
        assert int(res_bat.iterations) == int(res_seq.iterations)
        np.testing.assert_array_equal(
            np.asarray(res_bat.w), np.asarray(res_seq.w)
        )

    def test_donation_safety_after_failed_pass(self, rng, monkeypatch):
        """A pass that dies mid-stream (producer failure) must not leave
        the objective poisoned: the next pass starts from fresh carries
        and produces the same answer as an undisturbed objective — no
        use-after-donate, no stale ring state."""
        _, _, stream = self._stream4(rng)
        sobj = StreamingObjective("logistic", stream, prefetch_depth=2)
        w = jnp.asarray(rng.normal(size=stream.n_features), jnp.float32)
        ref_v, ref_g = StreamingObjective(
            "logistic", stream
        ).value_and_grad(w, 0.5)

        orig = sobj._host_item

        def exploding(k):
            if k == 2:
                raise RuntimeError("ingest exploded mid-pass")
            return orig(k)

        monkeypatch.setattr(sobj, "_host_item", exploding)
        with pytest.raises(RuntimeError, match="ingest exploded"):
            sobj.value_and_grad(w, 0.5)
        monkeypatch.undo()
        v2, g2 = sobj.value_and_grad(w, 0.5)
        np.testing.assert_array_equal(np.asarray(v2), np.asarray(ref_v))
        np.testing.assert_array_equal(np.asarray(g2), np.asarray(ref_g))

    def test_stall_counters_monotone(self, rng):
        """Counters only ever accumulate across passes (bench resets
        around measurement windows; a decrement would corrupt deltas)."""
        _, _, stream = self._stream4(rng)
        sobj = StreamingObjective("logistic", stream)
        w = jnp.zeros(stream.n_features, jnp.float32)
        prev = (0, 0, 0.0, 0.0, 0.0, 0.0, 0)
        for _ in range(3):
            sobj.value_and_grad(w, 0.5)
            st = sobj.transfer_stats
            cur = (
                st.consumer_stalls, st.producer_stalls,
                st.consumer_stall_seconds, st.producer_stall_seconds,
                st.pack_seconds, st.h2d_seconds, st.chunks,
            )
            assert all(c >= p for c, p in zip(cur, prev))
            prev = cur
        assert st.passes == 3
        assert st.chunks == 3 * stream.n_chunks


class TestTransferAvoidance:
    """ISSUE 14 pins: compressed wire formats + the importance-aware hot
    working-set cache must be BITWISE NEUTRAL on the f32 path — across
    prefetch depth, chunk fusion and hot-budget settings, over multiple
    passes (the cache admits on pass 2 and hits from pass 3) — while
    actually moving fewer wire bytes; cache admission must be
    deterministic under tied importance scores."""

    @staticmethod
    def _problem(rng, n=640, d=24):
        return _logistic_problem(rng, n, d - 1, density=0.15)

    @staticmethod
    def _stream4(X, y, chunk_rows=160):
        return make_streaming_glm_data(
            X, y, chunk_rows=chunk_rows, use_pallas=False
        )

    def test_fast_lane_compressed_cached_parity(self, rng):
        """The check.sh --fast transfer-avoidance smoke: a 4-chunk
        store streamed compressed (lossless) + cached is bitwise the
        raw uncached stream — value/grad, batched trials, HVP, diag and
        scores — and the wire actually shrank."""
        X, y = self._problem(rng)
        stream = self._stream4(X, y)
        assert stream.n_chunks == 4
        w = jnp.asarray(rng.normal(size=stream.n_features), jnp.float32)
        v = jnp.asarray(rng.normal(size=stream.n_features), jnp.float32)
        ws = jnp.stack([w, 0.5 * w, 2.0 * w])
        raw = StreamingObjective("logistic", stream)
        ta = StreamingObjective(
            "logistic", self._stream4(X, y), compress="lossless",
            hot_budget_bytes=1 << 30,
        )
        assert ta._codec is not None and ta._codec.ratio > 1.0
        v0, g0 = raw.value_and_grad(w, 0.5)
        vb0, gb0 = raw.value_and_grad_batch(ws, 0.5)
        for _ in range(3):  # pass 2 admits, pass 3 hits
            v1, g1 = ta.value_and_grad(w, 0.5)
        assert ta._hot_cache.hits > 0
        np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
        np.testing.assert_array_equal(np.asarray(g0), np.asarray(g1))
        vb1, gb1 = ta.value_and_grad_batch(ws, 0.5)
        np.testing.assert_array_equal(np.asarray(vb0), np.asarray(vb1))
        np.testing.assert_array_equal(np.asarray(gb0), np.asarray(gb1))
        np.testing.assert_array_equal(
            np.asarray(raw.hvp(w, v, 0.5)), np.asarray(ta.hvp(w, v, 0.5))
        )
        np.testing.assert_array_equal(
            np.asarray(raw.hessian_diagonal(w)),
            np.asarray(ta.hessian_diagonal(w)),
        )
        np.testing.assert_array_equal(raw.scores(w), ta.scores(w))
        # Wire vs logical accounting: the compressed stream recorded
        # fewer wire bytes than the decoded bytes it stood for.
        s = ta.transfer_stats
        assert s.logical_bytes > s.bytes > 0
        assert s.compression_ratio > 1.0

    @pytest.mark.parametrize("depth", [1, 2])
    @pytest.mark.parametrize("fuse", [1, 2])
    @pytest.mark.parametrize("budget", ["zero", "half", "huge"])
    def test_cached_vs_uncached_bitwise_grid(self, rng, depth, fuse,
                                             budget):
        """The full knob grid: hot-budget {0, ~half the store, huge} ×
        prefetch_depth × chunk_fuse, three passes each — every cell
        bitwise the uncached raw baseline."""
        X, y = self._problem(rng)
        stream = self._stream4(X, y)
        w = jnp.asarray(rng.normal(size=stream.n_features), jnp.float32)
        raw = StreamingObjective("logistic", stream)
        v0, g0 = raw.value_and_grad(w, 0.5)
        codec_bytes = StreamingObjective(
            "logistic", self._stream4(X, y), compress="lossless"
        )._codec.wire_nbytes
        budget_bytes = {
            "zero": 0,
            # room for 2 of the 4 chunks (×fuse items per group)
            "half": 2 * codec_bytes * fuse + 1,
            "huge": 1 << 30,
        }[budget]
        ta = StreamingObjective(
            "logistic", self._stream4(X, y), compress="lossless",
            hot_budget_bytes=budget_bytes, prefetch_depth=depth,
            chunk_fuse=fuse,
        )
        for _ in range(3):
            v1, g1 = ta.value_and_grad(w, 0.5)
        np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
        np.testing.assert_array_equal(np.asarray(g0), np.asarray(g1))
        if budget == "half":
            cache = ta._hot_cache
            assert 0 < cache.resident_bytes <= budget_bytes
            assert cache.hits > 0
        if budget == "huge":
            # Everything fits: from pass 3 on, zero wire transfers.
            chunks_before = ta.transfer_stats.chunks
            v1, g1 = ta.value_and_grad(w, 0.5)
            assert ta.transfer_stats.chunks == chunks_before
            np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
            np.testing.assert_array_equal(np.asarray(g0), np.asarray(g1))

    def test_admission_determinism_under_tie(self):
        """Tied importance scores break by ascending item index, so the
        wanted set — and therefore admission — is deterministic."""
        from photon_ml_tpu.optim.streaming import HotChunkCache

        nbytes = 100
        cache = HotChunkCache(budget_bytes=250)  # fits exactly 2 items
        scores = {i: 1.0 for i in range(6)}  # fully tied
        cache.replan(scores, lambda i: nbytes)
        admitted = [
            i for i in range(6)
            if cache.maybe_admit(i, object(), nbytes)
        ]
        assert admitted == [0, 1]
        # A strictly-higher score displaces the highest tied index on
        # the next replan (and evicts its resident entry).
        scores[5] = 2.0
        cache.replan(scores, lambda i: nbytes)
        assert cache.maybe_admit(5, object(), nbytes)
        assert not cache.maybe_admit(2, object(), nbytes)
        assert cache.evictions == 1
        assert len(cache) == 2 and cache.resident_bytes == 200

    def test_compress_requires_staged_and_single_host(self, rng):
        """Pointed construction errors: unknown mode, negative budget."""
        X, y = self._problem(rng)
        stream = self._stream4(X, y)
        with pytest.raises(ValueError, match="compress must be one of"):
            StreamingObjective("logistic", stream, compress="zstd")
        with pytest.raises(ValueError, match="hot_budget_bytes"):
            StreamingObjective(
                "logistic", stream, hot_budget_bytes=-1
            )
