"""Out-of-core random effects: bounded-HBM entity-block streaming.

The last dataset axis that had to fit device memory (VERDICT r4 missing
#2): entity blocks now stream through HBM in budget-bounded pass groups
while per-entity coefficients stay host-resident.  Parity discipline
matches the streamed fixed effect: the SAME memoized block solver runs on
each slice, so resident and out-of-core trajectories must agree to
float tolerance, and the pass plan itself is pinned structurally
(every group within budget, oversized blocks split, ≤2 groups live).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.game.coordinates import RandomEffectCoordinate
from photon_ml_tpu.game.data import build_random_effect_dataset
from photon_ml_tpu.game.ooc_random import OutOfCoreRandomEffectCoordinate
from photon_ml_tpu.optim.problem import (
    GlmOptimizationConfig,
    OptimizerConfig,
    OptimizerType,
)
from photon_ml_tpu.optim.regularization import (
    RegularizationContext,
    RegularizationType,
)


def _zipf_data(seed=3, n_entities=60, d=5, max_rows=40):
    """Long-tailed per-entity row counts — several bucket shapes."""
    rng = np.random.default_rng(seed)
    keys, rows, labels = [], [], []
    true_w = rng.normal(size=(n_entities, d))
    for e in range(n_entities):
        n_e = int(np.clip(rng.zipf(1.7), 1, max_rows))
        for _ in range(n_e):
            x = np.zeros(d, np.float32)
            nz = rng.choice(d, size=rng.integers(1, d + 1), replace=False)
            x[nz] = rng.normal(size=len(nz)).astype(np.float32)
            m = float(x @ true_w[e])
            keys.append(f"e{e}")
            rows.append(x)
            labels.append(float(rng.uniform() < 1 / (1 + np.exp(-m))))
    X = sp.csr_matrix(np.asarray(rows, np.float32))
    y = np.asarray(labels, np.float32)
    w = np.ones_like(y)
    return keys, X, y, w


def _config(optimizer="lbfgs", reg="l2"):
    return GlmOptimizationConfig(
        optimizer=OptimizerConfig(
            optimizer=OptimizerType(optimizer), max_iters=25, tolerance=1e-7
        ),
        regularization=RegularizationContext(RegularizationType(reg)),
    )


def _datasets(keys, X, y, w, **kw):
    resident = build_random_effect_dataset(keys, X, y, w, **kw)
    host = build_random_effect_dataset(keys, X, y, w, device=False, **kw)
    return resident, host


def _coords(task, config, resident, host, budget, mesh=None, reg_weight=0.7):
    res = RandomEffectCoordinate(
        "re", resident, task, config, reg_weight=reg_weight
    )
    ooc = OutOfCoreRandomEffectCoordinate(
        "re", host, task, config, reg_weight=reg_weight,
        device_budget_bytes=budget, mesh=mesh,
    )
    return res, ooc


class TestParity:
    def test_train_and_score_match_resident(self):
        keys, X, y, w = _zipf_data()
        resident, host = _datasets(keys, X, y, w)
        res, ooc = _coords("logistic", _config(), resident, host, 1 << 30)
        offsets = jnp.asarray(
            np.random.default_rng(0).normal(size=len(y)).astype(np.float32)
        )
        st_res = res.train(offsets)
        st_ooc = ooc.train(offsets)
        assert len(st_res) == len(st_ooc)
        for a, b in zip(st_res, st_ooc):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            )
        np.testing.assert_allclose(
            np.asarray(res.score(st_res)), np.asarray(ooc.score(st_ooc)),
            atol=1e-4,
        )

    def test_tiny_budget_forces_splits_and_still_matches(self):
        """A budget far below the dataset size: every block splits along
        the entity axis, many pass groups run — numerics must not move
        (slicing/padding never changes a lane's math)."""
        keys, X, y, w = _zipf_data(seed=5)
        resident, host = _datasets(keys, X, y, w)
        total = sum(
            sum(leaf.nbytes for leaf in jax.tree.leaves(b))
            for b in host.blocks
        )
        budget = max(total // 6, 6000)
        res, ooc = _coords("logistic", _config(), resident, host, budget)
        assert len(ooc.pass_plan) >= 3
        assert any(
            s.lane_lo > 0 for g in ooc.pass_plan for s in g
        ), "expected at least one entity-axis split"
        offsets = jnp.zeros(len(y), jnp.float32)
        st_res = res.train(offsets)
        st_ooc = ooc.train(offsets)
        for a, b in zip(st_res, st_ooc):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            )
        np.testing.assert_allclose(
            np.asarray(res.score(st_res)), np.asarray(ooc.score(st_ooc)),
            atol=1e-4,
        )

    @pytest.mark.parametrize("optimizer,reg", [
        ("tron", "l2"), ("owlqn", "elastic_net"),
    ])
    def test_other_optimizers_match(self, optimizer, reg):
        keys, X, y, w = _zipf_data(seed=7, n_entities=25)
        resident, host = _datasets(keys, X, y, w)
        cfg = _config(optimizer, reg)
        res, ooc = _coords("logistic", cfg, resident, host, 20_000)
        offsets = jnp.zeros(len(y), jnp.float32)
        st_res, st_ooc = res.train(offsets), ooc.train(offsets)
        for a, b in zip(st_res, st_ooc):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            )

    def test_warm_start_parity(self):
        keys, X, y, w = _zipf_data(seed=9, n_entities=30)
        resident, host = _datasets(keys, X, y, w)
        res, ooc = _coords("logistic", _config(), resident, host, 30_000)
        offsets = jnp.zeros(len(y), jnp.float32)
        st_res = res.train(offsets)
        st_ooc = ooc.train(offsets)
        # Second train warm-started from the first (the CD pattern);
        # resume-style device arrays must also be accepted as warm state.
        st_res2 = res.train(offsets, warm_state=st_res)
        st_ooc2 = ooc.train(
            offsets, warm_state=[jnp.asarray(s) for s in st_ooc]
        )
        for a, b in zip(st_res2, st_ooc2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            )

    def test_active_passive_split_scored(self):
        """max_rows_per_entity: passive rows are scored (not trained),
        matching resident semantics block for block."""
        keys, X, y, w = _zipf_data(seed=11, max_rows=60)
        resident, host = _datasets(keys, X, y, w, max_rows_per_entity=8)
        assert any(b is not None for b in host.passive_blocks)
        res, ooc = _coords("logistic", _config(), resident, host, 25_000)
        offsets = jnp.zeros(len(y), jnp.float32)
        st_res, st_ooc = res.train(offsets), ooc.train(offsets)
        np.testing.assert_allclose(
            np.asarray(res.score(st_res)), np.asarray(ooc.score(st_ooc)),
            atol=1e-4,
        )

    def test_variances_budget_bounded_and_match(self):
        """compute_variances must not break the budget: the OOC override
        computes the variance Hessian per plan-shaped slice, matching the
        resident whole-block einsum."""
        keys, X, y, w = _zipf_data(seed=29, n_entities=30)
        resident, host = _datasets(keys, X, y, w)
        cfg = dataclasses.replace(_config(), compute_variances=True)
        res, ooc = _coords("logistic", cfg, resident, host, 20_000)
        offsets = jnp.asarray(
            np.random.default_rng(2).normal(size=len(y)).astype(np.float32)
        )
        m_res = res.finalize(res.train(offsets), offsets=offsets)
        m_ooc = ooc.finalize(ooc.train(offsets), offsets=offsets)
        assert m_res.variances is not None and m_ooc.variances is not None
        assert set(m_res.variances) == set(m_ooc.variances)
        for k, v in m_res.variances.items():
            np.testing.assert_allclose(v, m_ooc.variances[k], rtol=1e-3)

    def test_finalize_model_tables_match(self):
        keys, X, y, w = _zipf_data(seed=13, n_entities=20)
        resident, host = _datasets(keys, X, y, w)
        res, ooc = _coords("logistic", _config(), resident, host, 20_000)
        offsets = jnp.zeros(len(y), jnp.float32)
        m_res = res.finalize(res.train(offsets))
        m_ooc = ooc.finalize(ooc.train(offsets))
        assert set(m_res.coefficients) == set(m_ooc.coefficients)
        for k, (cols, vals) in m_res.coefficients.items():
            cols2, vals2 = m_ooc.coefficients[k]
            np.testing.assert_array_equal(cols, cols2)
            np.testing.assert_allclose(vals, vals2, atol=1e-5)


class TestBoundedMemory:
    def test_plan_respects_budget(self):
        keys, X, y, w = _zipf_data(seed=15)
        _, host = _datasets(keys, X, y, w)
        budget = 24_000
        ooc = OutOfCoreRandomEffectCoordinate(
            "re", host, "logistic", _config(),
            device_budget_bytes=budget,
        )
        per_pass = budget // 2
        for group in ooc.pass_plan:
            assert sum(s.bytes for s in group) <= per_pass
        # Every lane of every block is covered exactly once.
        seen = {}
        for group in ooc.pass_plan:
            for s in group:
                seen.setdefault(s.block_idx, []).append(
                    (s.lane_lo, s.lane_hi)
                )
        for bi, block in enumerate(host.blocks):
            spans = sorted(seen[bi])
            assert spans[0][0] == 0
            assert spans[-1][1] == block.n_entities
            for (a, b), (c, d) in zip(spans, spans[1:]):
                assert b == c

    def test_uniform_slice_shapes_per_block(self):
        """Every slice of one block shares a padded_e — one compiled
        program per original block shape, not one per slice."""
        keys, X, y, w = _zipf_data(seed=17)
        _, host = _datasets(keys, X, y, w)
        ooc = OutOfCoreRandomEffectCoordinate(
            "re", host, "logistic", _config(), device_budget_bytes=16_000,
        )
        per_block = {}
        for group in ooc.pass_plan:
            for s in group:
                per_block.setdefault(s.block_idx, set()).add(s.padded_e)
        assert all(len(v) == 1 for v in per_block.values())

    def test_at_most_two_groups_live(self):
        keys, X, y, w = _zipf_data(seed=19)
        _, host = _datasets(keys, X, y, w)
        ooc = OutOfCoreRandomEffectCoordinate(
            "re", host, "logistic", _config(), device_budget_bytes=16_000,
        )
        assert len(ooc.pass_plan) >= 3
        ooc.train(jnp.zeros(host.n_global_rows, jnp.float32))
        # ≤ prefetch_depth is the hard bound (the permit accounting);
        # hitting exactly 2 needs the producer thread to win the
        # dispatch race, which a loaded box does not guarantee.
        assert 1 <= ooc.live_groups_high_water <= 2
        ooc.score(ooc.train(jnp.zeros(host.n_global_rows, jnp.float32)))
        assert 1 <= ooc.live_groups_high_water <= 2

    def test_transfer_ordering_never_holds_three_groups(self):
        """Group g+2's transfer may be dispatched only AFTER group g was
        consumed: at every put, the number of dispatched-but-unconsumed
        groups must stay ≤ prefetch_depth (=2).  The prefetch pipeline's
        permit is acquired before the put and released only after
        consume returns, so this count is exact even though the put runs
        on the producer thread (a pre-pipeline yield-based runner kept
        three groups alive at the put, making peak memory 1.5x the
        budget)."""
        keys, X, y, w = _zipf_data(seed=31)
        _, host = _datasets(keys, X, y, w)
        ooc = OutOfCoreRandomEffectCoordinate(
            "re", host, "logistic", _config(), device_budget_bytes=8_000,
        )
        assert len(ooc.pass_plan) >= 3
        counts = {"put": 0, "consume": 0}
        violations = []
        orig_put = ooc._put_group

        def tracked_put(group, payloads, pack_to_default=False):
            counts["put"] += 1
            if counts["put"] - counts["consume"] > 2:
                violations.append(dict(counts))
            return orig_put(group, payloads, pack_to_default)

        ooc._put_group = tracked_put

        def consume(group, dev):
            counts["consume"] += 1

        ooc._run_groups(lambda group: [], consume)
        assert not violations, violations
        assert counts["put"] == len(ooc.pass_plan)
        assert counts["consume"] == len(ooc.pass_plan)
        assert ooc.live_groups_high_water <= 2

    def test_budget_too_small_fails_loudly(self):
        keys, X, y, w = _zipf_data(seed=21)
        _, host = _datasets(keys, X, y, w)
        with pytest.raises(ValueError, match="per-pass budget"):
            OutOfCoreRandomEffectCoordinate(
                "re", host, "logistic", _config(), device_budget_bytes=64,
            )

    def test_device_resident_dataset_rejected(self):
        keys, X, y, w = _zipf_data(seed=23, n_entities=10)
        resident, _ = _datasets(keys, X, y, w)
        with pytest.raises(ValueError, match="device=False"):
            OutOfCoreRandomEffectCoordinate(
                "re", resident, "logistic", _config(),
                device_budget_bytes=1 << 30,
            )


class TestMesh:
    def test_mesh_parity_and_quantum(self, eight_devices):
        from photon_ml_tpu.parallel.distributed import data_mesh

        mesh = data_mesh(eight_devices)
        keys, X, y, w = _zipf_data(seed=25)
        resident, host = _datasets(keys, X, y, w)
        res, ooc = _coords(
            "logistic", _config(), resident, host, 200_000, mesh=mesh
        )
        # Hierarchical placement: split slices are padded to mesh-size
        # multiples (shardable lanes); packed slices run whole on one
        # device and carry no mesh-quantum padding.
        assert ooc.bucket_plan is not None
        for group in ooc.pass_plan:
            for s in group:
                if s.placement[0] == "split":
                    assert s.padded_e % 8 == 0
                else:
                    assert s.placement[0] == "pack"
                    assert 0 <= s.placement[1] < 8
        offsets = jnp.zeros(len(y), jnp.float32)
        st_res, st_ooc = res.train(offsets), ooc.train(offsets)
        # Sharded lowering reorders float ops inside the iterative solver
        # vs the unsharded resident program; same tolerance class as the
        # distributed-fixed parity test.
        for a, b in zip(st_res, st_ooc):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3
            )
        np.testing.assert_allclose(
            np.asarray(res.score(st_res)), np.asarray(ooc.score(st_ooc)),
            atol=1e-3,
        )


class TestEstimatorIntegration:
    def test_estimator_ooc_matches_resident(self):
        from photon_ml_tpu.game.estimator import (
            FixedEffectCoordinateConfig,
            GameEstimator,
            RandomEffectCoordinateConfig,
        )

        keys, X, y, w = _zipf_data(seed=27, n_entities=40, d=4)
        rng = np.random.default_rng(1)
        Xg = rng.normal(size=(len(y), 3)).astype(np.float32)
        shards = {"global": sp.csr_matrix(Xg), "entity": X}
        ids = {"eid": np.asarray(keys)}

        def run(budget):
            est = GameEstimator(
                "logistic",
                {
                    "fixed": FixedEffectCoordinateConfig(
                        feature_shard="global", optimization=_config(),
                        reg_weight=0.5,
                    ),
                    "re": RandomEffectCoordinateConfig(
                        feature_shard="entity", entity_key="eid",
                        optimization=_config(), reg_weight=0.5,
                        device_budget_bytes=budget,
                    ),
                },
                n_iterations=2,
            )
            model, result = est.fit(shards, ids, y)
            return model, result

        m_res, r_res = run(0)
        m_ooc, r_ooc = run(60_000)
        tbl_res = m_res.models["re"].coefficients
        tbl_ooc = m_ooc.models["re"].coefficients
        assert set(tbl_res) == set(tbl_ooc)
        for k, (cols, vals) in tbl_res.items():
            np.testing.assert_array_equal(cols, tbl_ooc[k][0])
            np.testing.assert_allclose(vals, tbl_ooc[k][1], atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(m_res.models["fixed"].model.coefficients.means),
            np.asarray(m_ooc.models["fixed"].model.coefficients.means),
            atol=1e-4,
        )
